// gbridge: agent-side bridge client to the TPU gossip plane.
//
// The second native artifact SURVEY.md §2.1 calls for (the cgo→bridge
// role between the real agent and the TPU sidecar; the reference's
// only native component is LMDB behind cgo).  The agent's LIVENESS
// signal must not depend on the Python event loop: a GIL-held FSM
// apply or a long jit compile would otherwise read as a lapsed
// heartbeat and get the agent declared dead by the kernel.  So the
// transport runs here: a writer-locked socket, a reader thread that
// reassembles length-prefixed frames into a queue the host polls, and
// a heartbeat thread that keeps sending the preframed heartbeat buffer
// on schedule no matter what Python is doing.
//
// Wire format (shared with consul_tpu/gossip/plane.py): 4-byte
// big-endian length + msgpack payload.  This library moves bytes and
// owns timing; msgpack encode/decode stays on the host.
//
// Plain C ABI for ctypes (no pybind11 in the image):
//   gb_connect(host, port, unix_path)        -> handle (>0) | -errno
//   gb_send(h, buf, len)                     -> 0 | -1
//   gb_set_heartbeat(h, buf, len, period_ms) -> 0   (len 0 stops)
//   gb_poll(h, buf, cap)                     -> nbytes | 0 none | -1 closed
//   gb_connected(h)                          -> 1 | 0
//   gb_close(h)

#include <arpa/inet.h>
#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <netdb.h>
#include <string>
#include <sys/socket.h>
#include <sys/un.h>
#include <thread>
#include <unistd.h>
#include <unordered_map>
#include <vector>

namespace {

struct Conn {
    int fd = -1;
    std::mutex wmu;                      // serializes writes (hb vs host)
    std::thread reader;
    std::thread hb;
    std::mutex qmu;
    std::deque<std::vector<uint8_t>> q;  // parsed incoming frames
    std::mutex hbmu;
    std::vector<uint8_t> hb_frame;       // preframed heartbeat bytes
    int hb_period_ms = 0;
    // Read/written across the reader, heartbeat, and host threads.
    std::atomic<bool> closing{false};
    std::atomic<bool> dead{false};       // reader saw EOF/error/overflow
};

std::mutex g_mu;
std::unordered_map<int64_t, Conn*> g_conns;
int64_t g_next = 1;

Conn* get(int64_t h) {
    std::lock_guard<std::mutex> lk(g_mu);
    auto it = g_conns.find(h);
    return it == g_conns.end() ? nullptr : it->second;
}

bool write_all(Conn* c, const uint8_t* buf, size_t len) {
    std::lock_guard<std::mutex> lk(c->wmu);
    size_t off = 0;
    while (off < len) {
        ssize_t n = ::send(c->fd, buf + off, len - off, MSG_NOSIGNAL);
        if (n <= 0) {
            if (n < 0 && (errno == EINTR)) continue;
            c->dead = true;
            return false;
        }
        off += static_cast<size_t>(n);
    }
    return true;
}

bool read_exact(int fd, uint8_t* buf, size_t len) {
    size_t off = 0;
    while (off < len) {
        ssize_t n = ::recv(fd, buf + off, len - off, 0);
        if (n <= 0) {
            if (n < 0 && errno == EINTR) continue;
            return false;
        }
        off += static_cast<size_t>(n);
    }
    return true;
}

void reader_loop(Conn* c) {
    for (;;) {
        uint8_t hdr[4];
        if (!read_exact(c->fd, hdr, 4)) break;
        uint32_t ln = (uint32_t(hdr[0]) << 24) | (uint32_t(hdr[1]) << 16) |
                      (uint32_t(hdr[2]) << 8) | uint32_t(hdr[3]);
        if (ln > (1u << 20)) break;  // oversized frame: protocol error
        std::vector<uint8_t> frame(ln);
        if (ln && !read_exact(c->fd, frame.data(), ln)) break;
        {
            std::lock_guard<std::mutex> lk(c->qmu);
            c->q.push_back(std::move(frame));
            if (c->q.size() > 4096) {
                // The host stopped polling and the protocol pushes
                // INCREMENTAL events — silently dropping any frame
                // would desync the membership view forever.  Kill the
                // connection instead: the client redials and gets a
                // fresh welcome snapshot (an explicit resync).
                break;
            }
        }
    }
    c->dead = true;
}

void hb_loop(Conn* c) {
    for (;;) {
        std::vector<uint8_t> frame;
        int period;
        {
            std::lock_guard<std::mutex> lk(c->hbmu);
            if (c->closing) return;
            frame = c->hb_frame;
            period = c->hb_period_ms;
        }
        if (frame.empty() || period <= 0) {
            if (c->closing) return;
            ::usleep(20 * 1000);
            continue;
        }
        if (!write_all(c, frame.data(), frame.size())) return;
        int slept = 0;
        while (slept < period) {
            if (c->closing) return;
            int step = period - slept < 20 ? period - slept : 20;
            ::usleep(step * 1000);
            slept += step;
        }
    }
}

}  // namespace

extern "C" {

int64_t gb_connect(const char* host, int port, const char* unix_path) {
    int fd = -1;
    if (unix_path && unix_path[0]) {
        fd = ::socket(AF_UNIX, SOCK_STREAM, 0);
        if (fd < 0) return -errno;
        sockaddr_un sa{};
        sa.sun_family = AF_UNIX;
        std::strncpy(sa.sun_path, unix_path, sizeof(sa.sun_path) - 1);
        if (::connect(fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)) < 0) {
            int e = errno; ::close(fd); return -e;
        }
    } else {
        fd = ::socket(AF_INET, SOCK_STREAM, 0);
        if (fd < 0) return -errno;
        sockaddr_in sa{};
        sa.sin_family = AF_INET;
        sa.sin_port = htons(static_cast<uint16_t>(port));
        if (::inet_pton(AF_INET, host, &sa.sin_addr) != 1) {
            ::close(fd); return -EINVAL;
        }
        if (::connect(fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)) < 0) {
            int e = errno; ::close(fd); return -e;
        }
        int one = 1;
        ::setsockopt(fd, IPPROTO_TCP, 1 /*TCP_NODELAY*/, &one, sizeof(one));
    }
    Conn* c = new Conn();
    c->fd = fd;
    c->reader = std::thread(reader_loop, c);
    c->hb = std::thread(hb_loop, c);
    std::lock_guard<std::mutex> lk(g_mu);
    int64_t h = g_next++;
    g_conns[h] = c;
    return h;
}

int gb_send(int64_t h, const uint8_t* buf, int len) {
    Conn* c = get(h);
    if (!c || c->dead || len < 0) return -1;
    uint8_t hdr[4] = {uint8_t(len >> 24), uint8_t(len >> 16),
                      uint8_t(len >> 8), uint8_t(len)};
    std::vector<uint8_t> framed;
    framed.reserve(4 + len);
    framed.insert(framed.end(), hdr, hdr + 4);
    framed.insert(framed.end(), buf, buf + len);
    return write_all(c, framed.data(), framed.size()) ? 0 : -1;
}

int gb_set_heartbeat(int64_t h, const uint8_t* buf, int len, int period_ms) {
    Conn* c = get(h);
    if (!c) return -1;
    std::vector<uint8_t> framed;
    if (len > 0) {
        uint8_t hdr[4] = {uint8_t(len >> 24), uint8_t(len >> 16),
                          uint8_t(len >> 8), uint8_t(len)};
        framed.reserve(4 + len);
        framed.insert(framed.end(), hdr, hdr + 4);
        framed.insert(framed.end(), buf, buf + len);
    }
    std::lock_guard<std::mutex> lk(c->hbmu);
    c->hb_frame = std::move(framed);
    c->hb_period_ms = period_ms;
    return 0;
}

int gb_poll(int64_t h, uint8_t* buf, int cap) {
    Conn* c = get(h);
    if (!c) return -1;
    {
        std::lock_guard<std::mutex> lk(c->qmu);
        if (!c->q.empty()) {
            std::vector<uint8_t>& f = c->q.front();
            if (static_cast<int>(f.size()) > cap) return -2;  // grow buffer
            int n = static_cast<int>(f.size());
            std::memcpy(buf, f.data(), f.size());
            c->q.pop_front();
            return n;
        }
    }
    return c->dead ? -1 : 0;
}

int gb_connected(int64_t h) {
    Conn* c = get(h);
    return (c && !c->dead) ? 1 : 0;
}

void gb_close(int64_t h) {
    Conn* c = nullptr;
    {
        std::lock_guard<std::mutex> lk(g_mu);
        auto it = g_conns.find(h);
        if (it == g_conns.end()) return;
        c = it->second;
        g_conns.erase(it);
    }
    {
        std::lock_guard<std::mutex> lk(c->hbmu);
        c->closing = true;
    }
    ::shutdown(c->fd, SHUT_RDWR);
    if (c->hb.joinable()) c->hb.join();
    if (c->reader.joinable()) c->reader.join();
    ::close(c->fd);
    delete c;
}

}  // extern "C"
