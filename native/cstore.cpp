// consul-tpu native store: a single-writer / multi-reader MVCC KV store
// over an mmap'd append-only segment.
//
// Role: the reference's one native dependency is LMDB (mmap B-tree,
// consul/state_store.go:15 via armon/gomdb) used for MVCC tables, and
// BoltDB (mmap B-tree) for the raft log (consul/server.go:357-368).
// This store plays both parts for the TPU-native framework:
//   - ordered key space with prefix scans (LMDB's id_prefix indexes)
//   - snapshot isolation for readers against a single writer (LMDB MVCC)
//   - append-only durable segment with CRC framing + fsync batching
//     (the raft-log role; durability of *state* still comes from the
//     Raft log above, mirroring the reference's NOSYNC stance,
//     state_store.go:190-196)
//
// Design: records append to a segment file that is mmap'd for reads.
// An in-memory ordered index (std::map) holds per-key version chains
// (seq, offset, len, tombstone).  Readers pin a snapshot sequence; a
// version is visible to snapshot S if its seq <= S and it is the
// newest such version.  Old versions are pruned on compaction, which
// rewrites live records and remaps.
//
// Concurrency: one writer at a time (callers serialize; the Python
// host plane is a single event loop), any number of readers under
// shared_mutex.  All exported symbols use a C ABI for ctypes.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <set>
#include <shared_mutex>
#include <string>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMagic = 0x43545053;  // "CTPS"
constexpr uint8_t kOpPut = 1;
constexpr uint8_t kOpDel = 2;

#pragma pack(push, 1)
struct RecHdr {
  uint32_t len;   // bytes after this header (body)
  uint32_t crc;   // crc32 of body
};
struct RecBody {
  uint64_t seq;
  uint8_t op;
  uint16_t klen;
  uint32_t vlen;
  // key bytes, then value bytes
};
#pragma pack(pop)

uint32_t crc32(const uint8_t* data, size_t n) {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = c & 1 ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    init = true;
  }
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; i++) c = table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

struct Version {
  uint64_t seq;
  uint64_t off;    // offset of value bytes inside the segment
  uint32_t vlen;
  bool tombstone;
};

struct Store {
  std::string path;
  int fd = -1;
  uint8_t* map = nullptr;
  size_t map_len = 0;     // mapped bytes
  size_t file_len = 0;    // written bytes
  uint64_t seq = 0;
  std::map<std::string, std::vector<Version>> index;
  std::multiset<uint64_t> snapshots;  // pinned reader sequences
  // Retired mappings, unmapped only at compaction/close: a reader's
  // pointer from cs_get/cs_scan_next must stay valid while it copies
  // (the contract is "valid until the next compaction"), so growth
  // keeps the old region alive.  Doubling sizes bound the waste to
  // ~2x the live mapping.
  std::vector<std::pair<uint8_t*, size_t>> retired;
  std::shared_mutex mu;
  std::string err;

  void drop_retired() {
    for (auto& [p, n] : retired) munmap(p, n);
    retired.clear();
  }

  bool remap() {
    size_t want = std::max<size_t>(file_len, 1);
    if (map && map_len >= want) return true;
    size_t new_len = 1;
    while (new_len < want) new_len <<= 1;
    new_len = std::max<size_t>(new_len, 1 << 20);
    if (map) retired.emplace_back(map, map_len);
    void* m = mmap(nullptr, new_len, PROT_READ, MAP_SHARED, fd, 0);
    if (m == MAP_FAILED) { map = nullptr; map_len = 0; err = "mmap failed"; return false; }
    map = static_cast<uint8_t*>(m);
    map_len = new_len;
    return true;
  }

  bool append_record(uint8_t op, const std::string& key,
                     const uint8_t* val, uint32_t vlen, uint64_t* out_voff) {
    RecBody body{};
    body.seq = ++seq;
    body.op = op;
    body.klen = static_cast<uint16_t>(key.size());
    body.vlen = vlen;
    size_t body_len = sizeof(RecBody) + key.size() + vlen;
    std::vector<uint8_t> buf(sizeof(RecHdr) + body_len);
    auto* hdr = reinterpret_cast<RecHdr*>(buf.data());
    uint8_t* b = buf.data() + sizeof(RecHdr);
    memcpy(b, &body, sizeof(RecBody));
    memcpy(b + sizeof(RecBody), key.data(), key.size());
    if (vlen) memcpy(b + sizeof(RecBody) + key.size(), val, vlen);
    hdr->len = static_cast<uint32_t>(body_len);
    hdr->crc = crc32(b, body_len);
    ssize_t wrote = pwrite(fd, buf.data(), buf.size(), file_len);
    if (wrote != static_cast<ssize_t>(buf.size())) { err = "short write"; --seq; return false; }
    *out_voff = file_len + sizeof(RecHdr) + sizeof(RecBody) + key.size();
    file_len += buf.size();
    // Growing the file keeps existing mapping valid for old offsets;
    // remap lazily when a read needs the new tail.
    return true;
  }

  bool replay() {
    struct stat st{};
    if (fstat(fd, &st) != 0) { err = "fstat failed"; return false; }
    file_len = 0;
    size_t end = static_cast<size_t>(st.st_size);
    if (end == 0) return true;
    if (!remap_for(end)) return false;
    size_t pos = 0;
    while (pos + sizeof(RecHdr) <= end) {
      auto* hdr = reinterpret_cast<RecHdr*>(map + pos);
      if (hdr->len == 0 || pos + sizeof(RecHdr) + hdr->len > end) break;
      const uint8_t* b = map + pos + sizeof(RecHdr);
      if (crc32(b, hdr->len) != hdr->crc) break;  // torn tail
      RecBody body{};
      memcpy(&body, b, sizeof(RecBody));
      if (sizeof(RecBody) + body.klen + body.vlen != hdr->len) break;
      std::string key(reinterpret_cast<const char*>(b + sizeof(RecBody)),
                      body.klen);
      uint64_t voff = pos + sizeof(RecHdr) + sizeof(RecBody) + body.klen;
      index[key].push_back(Version{body.seq, voff, body.vlen,
                                   body.op == kOpDel});
      seq = std::max(seq, body.seq);
      pos += sizeof(RecHdr) + hdr->len;
    }
    file_len = pos;
    if (pos != end) {
      // torn tail: truncate to the last good record
      if (ftruncate(fd, static_cast<off_t>(pos)) != 0) { err = "truncate failed"; return false; }
    }
    return true;
  }

  bool remap_for(size_t want) {
    size_t save = file_len;
    file_len = want;
    bool ok = remap();
    file_len = save;
    return ok;
  }

  uint64_t min_pinned() const {
    return snapshots.empty() ? UINT64_MAX : *snapshots.begin();
  }

  const Version* visible(const std::vector<Version>& chain,
                         uint64_t snap) const {
    const Version* best = nullptr;
    for (const auto& v : chain)
      if (v.seq <= snap && (!best || v.seq > best->seq)) best = &v;
    return best;
  }
};

struct ScanIter {
  Store* s;
  uint64_t snap;
  std::string prefix;
  std::map<std::string, std::vector<Version>>::const_iterator it;
};

bool has_prefix(const std::string& s, const std::string& p) {
  return s.size() >= p.size() && memcmp(s.data(), p.data(), p.size()) == 0;
}

}  // namespace

extern "C" {

Store* cs_open(const char* path) {
  auto* s = new Store();
  s->path = path;
  s->fd = open(path, O_RDWR | O_CREAT, 0644);
  if (s->fd < 0) { delete s; return nullptr; }
  if (!s->replay()) { close(s->fd); delete s; return nullptr; }
  s->remap();
  return s;
}

void cs_close(Store* s) {
  if (!s) return;
  s->drop_retired();
  if (s->map) munmap(s->map, s->map_len);
  if (s->fd >= 0) close(s->fd);
  delete s;
}

const char* cs_error(Store* s) { return s ? s->err.c_str() : "null store"; }

uint64_t cs_last_seq(Store* s) {
  std::shared_lock lk(s->mu);
  return s->seq;
}

int64_t cs_put(Store* s, const uint8_t* key, uint32_t klen,
               const uint8_t* val, uint32_t vlen) {
  if (klen > UINT16_MAX) { s->err = "key too long"; return -1; }
  std::unique_lock lk(s->mu);
  std::string k(reinterpret_cast<const char*>(key), klen);
  uint64_t voff = 0;
  if (!s->append_record(kOpPut, k, val, vlen, &voff)) return -1;
  s->remap();  // writer owns the lock: readers never grow the mapping
  auto& chain = s->index[k];
  // prune versions invisible to every pinned snapshot
  uint64_t keep = std::min(s->min_pinned(), s->seq - 1);
  const Version* vis = s->visible(chain, keep);
  uint64_t vis_seq = vis ? vis->seq : 0;
  chain.erase(std::remove_if(chain.begin(), chain.end(),
                             [&](const Version& v) { return v.seq < vis_seq; }),
              chain.end());
  chain.push_back(Version{s->seq, voff, vlen, false});
  return static_cast<int64_t>(s->seq);
}

int64_t cs_del(Store* s, const uint8_t* key, uint32_t klen) {
  std::unique_lock lk(s->mu);
  std::string k(reinterpret_cast<const char*>(key), klen);
  auto it = s->index.find(k);
  if (it == s->index.end()) return static_cast<int64_t>(s->seq);
  uint64_t voff = 0;
  if (!s->append_record(kOpDel, k, nullptr, 0, &voff)) return -1;
  s->remap();
  it->second.push_back(Version{s->seq, voff, 0, true});
  return static_cast<int64_t>(s->seq);
}

uint64_t cs_snapshot(Store* s) {
  std::unique_lock lk(s->mu);
  s->snapshots.insert(s->seq);
  return s->seq;
}

void cs_release(Store* s, uint64_t snap) {
  std::unique_lock lk(s->mu);
  auto it = s->snapshots.find(snap);
  if (it != s->snapshots.end()) s->snapshots.erase(it);
}

// Returns 0 found (out/out_len set; pointer into the mmap, valid until
// the next compaction), 1 not found, -1 error.
int cs_get(Store* s, uint64_t snap, const uint8_t* key, uint32_t klen,
           const uint8_t** out, uint32_t* out_len) {
  std::shared_lock lk(s->mu);
  if (snap == 0) snap = s->seq;
  std::string k(reinterpret_cast<const char*>(key), klen);
  auto it = s->index.find(k);
  if (it == s->index.end()) return 1;
  const Version* v = s->visible(it->second, snap);
  if (!v || v->tombstone) return 1;
  if (v->off + v->vlen > s->map_len) return -1;  // writer remaps, not us
  *out = s->map + v->off;
  *out_len = v->vlen;
  return 0;
}

ScanIter* cs_scan_begin(Store* s, uint64_t snap, const uint8_t* prefix,
                        uint32_t plen) {
  auto* iter = new ScanIter();
  iter->s = s;
  std::unique_lock lk(s->mu);
  iter->snap = snap == 0 ? s->seq : snap;
  // Pin the scan's view: compaction (which would invalidate both the
  // index iterator and value pointers) refuses while pinned.
  s->snapshots.insert(iter->snap);
  iter->prefix.assign(reinterpret_cast<const char*>(prefix), plen);
  iter->it = s->index.lower_bound(iter->prefix);
  return iter;
}

// Returns 0 with key/value set, 1 at end.  Skips tombstones.
int cs_scan_next(ScanIter* iter, const uint8_t** key, uint32_t* klen,
                 const uint8_t** val, uint32_t* vlen) {
  Store* s = iter->s;
  std::shared_lock lk(s->mu);
  while (iter->it != s->index.end() &&
         has_prefix(iter->it->first, iter->prefix)) {
    const Version* v = s->visible(iter->it->second, iter->snap);
    const auto& k = iter->it->first;
    ++iter->it;
    if (!v || v->tombstone) continue;
    if (v->off + v->vlen > s->map_len) return -1;
    *key = reinterpret_cast<const uint8_t*>(k.data());
    *klen = static_cast<uint32_t>(k.size());
    *val = s->map + v->off;
    *vlen = v->vlen;
    return 0;
  }
  return 1;
}

void cs_scan_end(ScanIter* iter) {
  Store* s = iter->s;
  {
    std::unique_lock lk(s->mu);
    auto it = s->snapshots.find(iter->snap);
    if (it != s->snapshots.end()) s->snapshots.erase(it);
  }
  delete iter;
}

int cs_sync(Store* s) {
  std::shared_lock lk(s->mu);
  return fsync(s->fd) == 0 ? 0 : -1;
}

uint64_t cs_count(Store* s) {
  std::shared_lock lk(s->mu);
  uint64_t n = 0;
  for (const auto& [k, chain] : s->index) {
    const Version* v = s->visible(chain, s->seq);
    if (v && !v->tombstone) n++;
  }
  return n;
}

// Rewrite live (visible-at-head, non-tombstone) records into a fresh
// segment; drops history.  Requires no pinned snapshots.
int cs_compact(Store* s) {
  std::unique_lock lk(s->mu);
  if (!s->snapshots.empty()) { s->err = "snapshots pinned"; return -1; }
  std::string tmp_path = s->path + ".compact";
  int tfd = open(tmp_path.c_str(), O_RDWR | O_CREAT | O_TRUNC, 0644);
  if (tfd < 0) { s->err = "compact open failed"; return -1; }

  if (!s->remap()) { close(tfd); return -1; }
  std::map<std::string, std::vector<Version>> new_index;
  size_t new_len = 0;
  uint64_t new_seq = 0;
  for (const auto& [k, chain] : s->index) {
    const Version* v = s->visible(chain, s->seq);
    if (!v || v->tombstone) continue;
    RecBody body{};
    body.seq = ++new_seq;
    body.op = kOpPut;
    body.klen = static_cast<uint16_t>(k.size());
    body.vlen = v->vlen;
    size_t body_len = sizeof(RecBody) + k.size() + v->vlen;
    std::vector<uint8_t> buf(sizeof(RecHdr) + body_len);
    auto* hdr = reinterpret_cast<RecHdr*>(buf.data());
    uint8_t* b = buf.data() + sizeof(RecHdr);
    memcpy(b, &body, sizeof(RecBody));
    memcpy(b + sizeof(RecBody), k.data(), k.size());
    if (v->vlen) memcpy(b + sizeof(RecBody) + k.size(), s->map + v->off, v->vlen);
    hdr->len = static_cast<uint32_t>(body_len);
    hdr->crc = crc32(b, body_len);
    if (pwrite(tfd, buf.data(), buf.size(), new_len)
        != static_cast<ssize_t>(buf.size())) {
      close(tfd); unlink(tmp_path.c_str()); s->err = "compact write failed";
      return -1;
    }
    new_index[k].push_back(Version{
        new_seq, new_len + sizeof(RecHdr) + sizeof(RecBody) + k.size(),
        v->vlen, false});
    new_len += buf.size();
  }
  if (fsync(tfd) != 0 || rename(tmp_path.c_str(), s->path.c_str()) != 0) {
    close(tfd); unlink(tmp_path.c_str()); s->err = "compact swap failed";
    return -1;
  }
  s->drop_retired();
  if (s->map) { munmap(s->map, s->map_len); s->map = nullptr; s->map_len = 0; }
  close(s->fd);
  s->fd = tfd;
  s->file_len = new_len;
  s->index = std::move(new_index);
  // seq keeps monotonically increasing across compactions so pinned
  // snapshot numbering stays meaningful to callers.
  s->remap();
  return 0;
}

}  // extern "C"
