# Canonical build/test entry points — the role of the reference's
# Makefile (`/root/reference/Makefile:3-52`: test = verify_no_uuid +
# per-package go test + vet; integ = INTEG_TESTS=yes; Travis runs
# `make integ`, `.travis.yml:10-11`).
#
# The full gate a contributor (or the driver) runs before shipping:
#
#     make ci        # vet + unit/integration suite + black-box tiers
#
# Tests force JAX onto an 8-device virtual CPU mesh (tests/conftest.py);
# no TPU access is needed for any target except `bench`.

PYTHON ?= python
PYTEST ?= $(PYTHON) -m pytest -q

# Fast-ish tier: everything in-process (includes the determinism guard,
# the role of scripts/verify_no_uuid.sh).
UNIT_ARGS = --ignore=tests/test_blackbox.py --ignore=tests/test_linearizability.py

.PHONY: default ci test integ vet vet-fast vet-diff vet-dyn obs-smoke chaos chaos-fast tune tune-check bench bench-serve bench-watch bench-fuse bench-fuse-fast dryrun clean

default: test

ci: vet vet-dyn test integ chaos-fast tune-check bench-fuse-fast

# Unit + in-process integration tests (multi-node simulated in one
# process with compressed timers, SURVEY.md §4).
test: vet
	$(PYTEST) tests/ $(UNIT_ARGS)

# Black-box tiers: fork/exec real agents over HTTP/DNS/IPC
# (testutil.TestServer role) + the Jepsen-role linearizability checker.
integ:
	$(PYTEST) tests/test_blackbox.py tests/test_linearizability.py

# Static checks: byte-compile every source file, then the
# eighteen-pass analyzer (tools/vet/: names, async-safety, JAX
# tracer-purity, wire-schema drift, exception hygiene, donation
# safety, shard-exactness, carry-contract, overflow, pallas-safety,
# table-drift, fork-safety, interleave, role-transition, and the four
# cancel-safety passes Q01-Q04 — the `go vet`
# role in an image without a Python linter).  Exit codes: 0 clean,
# 1 findings, 2 parse error or time-guard trip.  Suppress per line
# with `# noqa: CODE[,CODE]` or per finding in tools/vet/baseline.txt.
# `vet` writes the machine-readable vet_report.json CI artifact (incl.
# per-pass wall times; the driver prints the slowest passes) and arms
# --time-guard: exit 2 when total analyzer time exceeds 1.5x the
# previously recorded report's total, naming the two slowest passes;
# `vet-fast` skips the flow-sensitive JAX passes for the inner loop;
# `vet-diff` vets only git-touched files plus their cross-file
# partners (same exit-code contract) for pre-commit; `vet-dyn` runs
# the dynamic sanitizer harness (tools/vet/dyn.py: debug_nans +
# asyncio debug + warnings-as-errors + fd/thread/task leak audit over
# the fast tier-1 slice, a forced-interleave re-run of the
# lease/barrier + anti-entropy slices with a task switch at every
# await, a cancel-injection sweep cancelling a victim task at every
# distinct await point over the confirm-batch / reconcile-flush /
# blocking-query scenarios, then a checkify smoke of one dissemination
# round per strategy).  `make ci` runs vet-dyn right after vet.
VET_PATHS = consul_tpu tests tools demo bench.py __graft_entry__.py
vet:
	$(PYTHON) -m compileall -q $(VET_PATHS)
	$(PYTHON) -m tools.vet $(VET_PATHS) --report vet_report.json --time-guard
	JAX_PLATFORMS=cpu $(PYTHON) -m tools.store_crossval --fast
	JAX_PLATFORMS=cpu $(PYTHON) -m tools.fused_crossval --fast
	$(MAKE) obs-smoke

vet-fast:
	$(PYTHON) -m tools.vet $(VET_PATHS) --fast

vet-diff:
	$(PYTHON) -m tools.vet $(VET_PATHS) --changed

vet-dyn:
	JAX_PLATFORMS=cpu $(PYTHON) -m tools.vet.dyn

# Observability gate: boot a small CPU plane + one kernel-backed agent,
# scrape /v1/agent/metrics?format=prometheus, and hold every line to
# the strict text-format checker (tools/check_prom.py) — including the
# detection-latency histogram families and the /v1/agent/slo shell.
obs-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) -m tools.obs_smoke

# Consensus-plane chaos campaign (consul_tpu/chaos/): one fresh
# 3-node cluster + seeded fault schedule per scenario, gated on
# linearizability (tests/linearize.py), lease safety (single holder +
# no deposed-leader serve), and fault *detectability* in the raft
# observatory; per-scenario prom scrape held to tools/check_prom.py.
# Report: CHAOS.json; debug bundles under chaos_debug/.  `chaos` runs
# the full catalog (incl. the fork/exec worker-crash leg); chaos-fast
# runs the cheap subset TWICE and insists the verdicts match — the
# fixed-seed determinism guard CI rides on.
chaos:
	JAX_PLATFORMS=cpu $(PYTHON) -m tools.chaos_campaign --seed 1234 \
	  --out CHAOS.json --debug-dir chaos_debug

chaos-fast:
	JAX_PLATFORMS=cpu $(PYTHON) -m tools.chaos_campaign --fast --seed 1234 \
	  --out CHAOS.json --debug-dir chaos_debug
	JAX_PLATFORMS=cpu $(PYTHON) -m tools.chaos_campaign --fast --seed 1234 \
	  --out CHAOS2.json --debug-dir chaos_debug
	$(PYTHON) -c "import json; \
	  v = lambda p: [(r.get('scenario'), r.get('pass'), r.get('gates'), \
	  (r.get('detection') or {}).get('detected')) \
	  for r in json.load(open(p))['scenarios']]; \
	  assert v('CHAOS.json') == v('CHAOS2.json'), \
	  'chaos-fast verdicts differ between seeded runs'; \
	  print('chaos-fast: verdicts deterministic under seed 1234')"
	rm -f CHAOS2.json

# Autotune control plane (obs/tuner.py + tools/autotune.py): settle
# the knob registry against the checked-in observatory artifacts
# (bench regime cache, BENCH_WATCH.json, BENCH_SERVE.json, CHAOS.json)
# and persist the per-platform verdict next to the XLA compile cache.
# Planes/agents boot with explicit flag > persisted verdict > default.
tune:
	JAX_PLATFORMS=cpu $(PYTHON) -m tools.autotune

# Determinism gate CI rides on (mirrors chaos-fast): two independent
# settles over the same artifacts must be byte-identical.
tune-check:
	JAX_PLATFORMS=cpu $(PYTHON) -m tools.autotune --platform cpu \
	  --devices 8 --out TUNE1.json
	JAX_PLATFORMS=cpu $(PYTHON) -m tools.autotune --platform cpu \
	  --devices 8 --out TUNE2.json
	$(PYTHON) -c "a = open('TUNE1.json','rb').read(); \
	  b = open('TUNE2.json','rb').read(); \
	  assert a == b, 'tune-check: verdicts differ between settles'; \
	  print('tune-check: verdict deterministic (%d bytes)' % len(a))"
	rm -f TUNE1.json TUNE2.json

# North-star benchmark (needs the real chip; emits one JSON line).
bench:
	$(PYTHON) bench.py

# Serving-plane microbench (CPU-only): forks one agent per worker
# count and drives keep-alive HTTP load over the KV hot path
# (stale/default/consistent legs); JSON to stdout, numbers land in
# BENCH_NOTES.md §9.
bench-serve:
	$(PYTHON) tools/bench_serve.py --requests 8000 --concurrency 32 \
	  --workers 1,4

# Watch-matching storm (CPU-only): device matcher vs host radix walk
# A/B over correlated invalidation bursts at >=10^4 standing watches;
# medians-of-3 land in BENCH_WATCH.json (BENCH_NOTES.md section 12).
bench-watch:
	JAX_PLATFORMS=cpu $(PYTHON) -m tools.watchstorm --watches 10000

# Fused-planes reconcile A/B (CPU-only): batched vs per-agent catalog
# writes over an in-process 3-node cluster; entries/transition +
# detection->visible p50/p99 land in BENCH_FUSE.json (feeds the
# reconcile_batch_max autotune rule; BENCH_NOTES.md section 16).
bench-fuse:
	JAX_PLATFORMS=cpu $(PYTHON) tools/bench_fuse.py

# CI smoke: 2 rounds, batch=64 only, gates the >=10x raft-entry
# reduction without touching the BENCH_FUSE.json artifact.
bench-fuse-fast:
	JAX_PLATFORMS=cpu $(PYTHON) tools/bench_fuse.py --fast

# Multi-chip sharding dry-run on the 8-device virtual CPU mesh —
# exactly what the driver validates.
dryrun:
	JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	  $(PYTHON) -c "import __graft_entry__ as g; g.dryrun_multichip(8); print('dryrun ok')"

clean:
	find . -name __pycache__ -type d -prune -exec rm -rf {} +
	rm -rf .jax_cache
	rm -rf chaos_debug
	rm -f vet_report.json CHAOS.json CHAOS2.json TUNE1.json TUNE2.json
