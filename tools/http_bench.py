"""HTTP KV load bench — the reference's headline workload.

Mirrors ``bench/Makefile`` in the reference: 20,480 requests at 64
concurrency against ``/v1/kv/bench`` (PUT, then GET in default /
stale / consistent modes), reported as req/s + latency percentiles.
Reference numbers to beat (BASELINE.md, 3 servers on 4x DO-16GB,
1Gbps): PUT 4,092 req/s; GET default 10,470; stale 10,948;
consistent 10,246; PUT avg 15.6ms / p90 21.8ms.

Topology matches the reference: a 3-server cluster (forked daemons,
loopback RPC mesh + gossip), load driven at ONE server.  Run:

    python tools/http_bench.py [--requests 20480] [--concurrency 64]
                               [--single]   # 1-server variant
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))
os.environ.pop("PALLAS_AXON_POOL_IPS", None)


async def drive(base: str, method: str, path: str, body, total: int,
                concurrency: int):
    import aiohttp

    latencies = []
    errors = [0]
    sample_err = [None]
    sem_queue = asyncio.Queue()
    for _ in range(total):
        sem_queue.put_nowait(None)

    conn = aiohttp.TCPConnector(limit=concurrency)
    async with aiohttp.ClientSession(connector=conn) as sess:
        async def worker():
            while True:
                try:
                    sem_queue.get_nowait()
                except asyncio.QueueEmpty:
                    return
                t0 = time.perf_counter()
                try:
                    async with sess.request(method, base + path,
                                            data=body) as r:
                        text = await r.read()
                        if r.status >= 400:
                            errors[0] += 1
                            if sample_err[0] is None:
                                sample_err[0] = f"{r.status}: {text[:200]!r}"
                except Exception as e:
                    errors[0] += 1
                    if sample_err[0] is None:
                        sample_err[0] = f"exc: {type(e).__name__}: {e}"
                latencies.append((time.perf_counter() - t0) * 1000)

        t0 = time.perf_counter()
        await asyncio.gather(*[worker() for _ in range(concurrency)])
        wall = time.perf_counter() - t0
    lat = sorted(latencies)

    def pct(q):
        return lat[min(len(lat) - 1, int(q / 100 * len(lat)))]

    out = {
        "requests": total, "errors": errors[0],
        "req_per_sec": round(total / wall, 1),
        "avg_ms": round(statistics.mean(lat), 2),
        "p50_ms": round(pct(50), 2), "p90_ms": round(pct(90), 2),
        "p99_ms": round(pct(99), 2),
    }
    if sample_err[0] is not None:
        out["sample_error"] = sample_err[0]
    return out


async def bench(requests: int, concurrency: int, single: bool):
    from blackbox_util import TestServer

    servers = []
    try:
        if single:
            s1 = TestServer("hb1").start()
            servers = [s1]
            s1.wait_for_api()
            s1.wait_for_leader()
        else:
            s1 = TestServer("hb1", bootstrap=False, bootstrap_expect=3).start()
            servers = [s1]
            s1.wait_for_api()
            for name in ("hb2", "hb3"):
                s = TestServer(name, bootstrap=False, bootstrap_expect=3,
                               retry_join=[s1.lan_addr]).start()
                servers.append(s)
                s.wait_for_api()
            for s in servers:
                s.wait_for_leader(60)
        base = f"http://127.0.0.1:{s1.ports['http']}"
        results = {"topology": "1 server" if single else "3-server cluster",
                   "concurrency": concurrency}
        print(f"[bench] PUT x{requests} @ {concurrency}", file=sys.stderr)
        results["kv_put"] = await drive(base, "PUT", "/v1/kv/bench",
                                        b"74a31e96-1d0f-4fa7-aa14-7212a326986e",
                                        requests, concurrency)
        print(f"[bench] GET default x{requests}", file=sys.stderr)
        results["kv_get"] = await drive(base, "GET", "/v1/kv/bench", None,
                                        requests, concurrency)
        print(f"[bench] GET stale x{requests}", file=sys.stderr)
        results["kv_get_stale"] = await drive(base, "GET",
                                              "/v1/kv/bench?stale", None,
                                              requests, concurrency)
        print(f"[bench] GET consistent x{requests}", file=sys.stderr)
        results["kv_get_consistent"] = await drive(
            base, "GET", "/v1/kv/bench?consistent", None,
            requests, concurrency)
        return results
    finally:
        for s in servers:
            s.stop()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=20480)
    ap.add_argument("--concurrency", type=int, default=64)
    ap.add_argument("--single", action="store_true")
    args = ap.parse_args()
    out = asyncio.run(bench(args.requests, args.concurrency, args.single))
    out["reference_v03"] = {
        "kv_put_req_per_sec": 4092, "kv_get_req_per_sec": 10470,
        "kv_get_stale_req_per_sec": 10948,
        "kv_get_consistent_req_per_sec": 10246,
        "workload": "boom 20480 reqs @64, 3 servers on 4x DO-16GB/1Gbps",
    }
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
