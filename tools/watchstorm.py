"""Watch-matching storm bench: device matcher vs host radix walk, A/B.

The device-resident state store (state/device_store.py) evaluates the
whole registered watch set against a committed batch in one device
pass.  This bench prices that pass against the host's per-event radix
walk (state/notify.py KVWatchSet.matched) on the SAME watch set and
the SAME correlated mutation bursts, so BENCH_NOTES can quote an
honest A/B instead of a synthetic kernel number.

Workload shape (the Consul deployment the paper talks about): W
standing prefix watches, one per service shard (``svc/<i>/``), plus a
broad ``svc/`` watch that fires on everything.  Each batch is a
correlated invalidation burst — all mutations land under a handful of
hot shards, the way a deploy or a node death invalidates one service's
keys at once rather than spraying the keyspace.

Per batch the bench times:

* host: ``watchset.matched(path)`` walked for every event in the
  batch (exactly what ``DeviceStoreBridge._fire_watches`` runs as the
  authoritative side);
* device: event encoding + the jitted matcher dispatch + fetching the
  fired vector (the production per-batch cost; watch-set encoding is
  amortised across batches exactly as in production and is excluded,
  but reported separately as ``encode_watches_ms``).

Both sides' fired sets are compared every batch — a mismatch fails the
run (the crossval contract, forward direction).  Timings are
median-of-``--trials`` (default 3) over the per-trial mean batch
latency.  Results land in BENCH_WATCH.json.

Run (the `make bench-watch` target):
    python -m tools.watchstorm --watches 10000
Storm tiers (slow, gated behind explicit opt-in):
    python -m tools.watchstorm --watches 10000,100000,1000000
Crossover sweep (replaces the WATCH_DEVICE_MIN_CPU guess with a
measurement; consumed by obs/tuner.py as ``watch_device_min``):
    python -m tools.watchstorm --sweep
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402


class _Flag:
    """Inert NotifyGroup waiter (never awaited — matching only)."""

    def set(self) -> None:  # pragma: no cover - never fired here
        pass


def _build_watchset(n_watches: int):
    """W-1 shard watches + one broad ``svc/`` watch."""
    from consul_tpu.state.notify import KVWatchSet

    ws = KVWatchSet()
    ws.watch("svc/", _Flag())
    for i in range(n_watches - 1):
        ws.watch(f"svc/{i:07d}/", _Flag())
    return ws


def _bursts(n_batches: int, batch: int, n_watches: int, seed: int,
            hot_shards: int = 4):
    """Correlated invalidation bursts: each batch mutates keys under
    ``hot_shards`` randomly chosen shards.  Events are the capture
    notify tuples _fire_watches consumes: (kind, path, prefix, index)."""
    rng = np.random.default_rng(seed)
    out = []
    index = 100
    for _ in range(n_batches):
        hot = rng.integers(0, max(n_watches - 1, 1), size=hot_shards)
        evs = []
        for k in range(batch):
            shard = int(hot[k % hot_shards])
            index += 1
            evs.append(("kv", f"svc/{shard:07d}/key/{int(rng.integers(64))}",
                        False, index))
        out.append(evs)
    return out


def _host_pass(ws, batches):
    """Radix walk per event, deduped per batch — the authoritative side
    of _fire_watches, minus the firing."""
    fired_sets = []
    t0 = time.perf_counter()
    for evs in batches:
        seen = set()
        for _, path, prefix, _idx in evs:
            for p, _g in ws.matched(path, prefix):
                seen.add(p)
        fired_sets.append(seen)
    return (time.perf_counter() - t0), fired_sets


def _device_pass(bridge, groups, batches):
    """Encode + dispatch + fetch per batch (production per-batch cost)."""
    fired_sets = []
    t0 = time.perf_counter()
    for evs in batches:
        events = bridge._encode_events(evs)
        fired, _packed = bridge._match(*bridge._w_arrays, events)
        fired = np.asarray(fired)[: len(groups)]
        fired_sets.append({groups[i][0] for i in np.nonzero(fired)[0]})
    return (time.perf_counter() - t0), fired_sets


def run_tier(n_watches: int, batch: int, n_batches: int, trials: int,
             seed: int) -> dict:
    from consul_tpu.state.device_store import DeviceStoreBridge

    ws = _build_watchset(n_watches)
    bridge = DeviceStoreBridge(capacity=64, stats=None)
    t0 = time.perf_counter()
    bridge._encode_watches(ws)
    encode_ms = (time.perf_counter() - t0) * 1e3
    groups = bridge._w_groups

    batches = _bursts(n_batches, batch, n_watches, seed)
    # Warmup: compiles the matcher for this (W, B) shape.
    _device_pass(bridge, groups, batches[:1])

    host_ms, dev_ms = [], []
    for _ in range(trials):
        h_s, h_fired = _host_pass(ws, batches)
        d_s, d_fired = _device_pass(bridge, groups, batches)
        for b, (hf, df) in enumerate(zip(h_fired, d_fired)):
            if hf != df:
                raise SystemExit(
                    f"[watchstorm] A/B DISAGREE at W={n_watches} batch {b}: "
                    f"host-only={sorted(hf - df)[:3]} "
                    f"device-only={sorted(df - hf)[:3]}")
        host_ms.append(h_s * 1e3 / n_batches)
        dev_ms.append(d_s * 1e3 / n_batches)

    h_med, d_med = statistics.median(host_ms), statistics.median(dev_ms)
    evals = n_watches * batch  # watch evaluations per device pass
    return {
        "watches": n_watches,
        "events_per_batch": batch,
        "batches": n_batches,
        "trials": trials,
        "host_ms_per_batch": round(h_med, 4),
        "device_ms_per_batch": round(d_med, 4),
        "device_evals_per_sec": round(evals / (d_med / 1e3)),
        "host_speedup_over_device": round(d_med / h_med, 2),
        "encode_watches_ms": round(encode_ms, 2),
        "agreement": True,
    }


def _sweep(lo: int, hi: int, batch: int, n_batches: int, trials: int,
           seed: int) -> dict:
    """Host-vs-device crossover search: geometric climb from ``lo``
    (doubling) until the device pass first beats the host walk, then
    bisect the bracketing interval.  Every measured tier is recorded so
    the evidence behind the verdict stays auditable.  ``crossover``
    stays null when the device never wins below ``hi`` — the tuner then
    floors ``watch_device_min`` above the sweep cap instead of
    pretending it measured a break-even."""
    tiers = []

    def wins(w: int) -> bool:
        r = run_tier(w, batch, n_batches, trials, seed)
        tiers.append(r)
        side = ("device" if r["device_ms_per_batch"]
                <= r["host_ms_per_batch"] else "host")
        print(f"[watchstorm]   sweep W={w}: host "
              f"{r['host_ms_per_batch']}ms device "
              f"{r['device_ms_per_batch']}ms/batch -> {side}", flush=True)
        return side == "device"

    first_win, prev = None, None
    w = lo
    while w <= hi:
        if wins(w):
            first_win = w
            break
        prev = w
        w *= 2
    cross = None
    if first_win is not None:
        cross = first_win
        if prev is not None:
            # Bisect (prev, first_win]; stop once the bracket is within
            # ~12% (or 1024 watches) — crossover precision beyond that
            # is noise on a shared host.
            lo_w, hi_w = prev, first_win
            while hi_w - lo_w > max(lo_w // 8, 1024):
                mid = (lo_w + hi_w) // 2
                if wins(mid):
                    hi_w = mid
                else:
                    lo_w = mid
            cross = hi_w
    return {"lo": lo, "hi": hi, "crossover_watches": cross,
            "tiers": tiers}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--watches", default="10000",
                    help="comma-separated watch-count tiers (default 10000)")
    ap.add_argument("--events", type=int, default=256,
                    help="mutations per burst batch")
    ap.add_argument("--batches", type=int, default=20)
    ap.add_argument("--trials", type=int, default=3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--sweep", action="store_true",
                    help="also binary-search the host-vs-device "
                         "crossover and record it (tuner evidence)")
    ap.add_argument("--sweep-lo", type=int, default=8192,
                    help="sweep start watch count (doubles upward)")
    ap.add_argument("--sweep-max", type=int, default=65536,
                    help="sweep cap; no device win below it records "
                         "crossover null")
    ap.add_argument("--out", default=os.path.join(REPO, "BENCH_WATCH.json"))
    args = ap.parse_args(argv)

    import jax
    tiers = [int(w) for w in str(args.watches).split(",")]
    results = []
    for w in tiers:
        print(f"[watchstorm] tier W={w} B={args.events} "
              f"({args.batches} batches x {args.trials} trials)...",
              flush=True)
        r = run_tier(w, args.events, args.batches, args.trials, args.seed)
        print(f"[watchstorm]   host {r['host_ms_per_batch']}ms/batch  "
              f"device {r['device_ms_per_batch']}ms/batch  "
              f"({r['device_evals_per_sec']:,} evals/s device)", flush=True)
        results.append(r)

    out = {
        "bench": "watchstorm",
        "platform": jax.default_backend(),
        "device_count": jax.device_count(),
        "tiers": results,
    }
    if args.sweep:
        print(f"[watchstorm] crossover sweep {args.sweep_lo}.."
              f"{args.sweep_max}...", flush=True)
        sweep = _sweep(args.sweep_lo, args.sweep_max, args.events,
                       args.batches, args.trials, args.seed)
        cross = sweep["crossover_watches"]
        print(f"[watchstorm]   crossover: "
              + (f"{cross} watches" if cross is not None
                 else f"none below {args.sweep_max} (host wins the "
                      "whole sweep)"), flush=True)
        out["sweep"] = sweep
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print(f"[watchstorm] wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
