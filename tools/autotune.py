"""Offline autotune settle: observatory artifacts -> persisted verdict.

The closing of the observability loop (obs/tuner.py): this CLI gathers
every evidence artifact the checkout holds — the bench regime cache
(``.bench_last_success.json`` / ``BENCH_r*.json``), the watch-storm
A/B + crossover sweep (``BENCH_WATCH.json``), the serving-plane
microbench (``BENCH_SERVE.json``), and the chaos campaign report
(``CHAOS.json``) — settles the knob registry against them, and
persists the per-platform verdict next to the XLA compile cache
(``~/.cache/consul_tpu_jax_cache/autotune/verdict-<platform>.json``,
or ``$CONSUL_TPU_AUTOTUNE_DIR``).

Planes and agents pick the verdict up at boot with explicit flag >
persisted verdict > registry default resolution, and re-settle it
automatically when the backend fingerprint (platform x topology x jax
version) changes.

Run (the `make tune` target):
    python -m tools.autotune
Offline/CI (no jax import; fingerprint supplied by hand):
    python -m tools.autotune --platform cpu --devices 8 --out TUNE.json
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from consul_tpu.obs import tuner  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--platform", default="",
                    help="settle for this backend platform without "
                         "importing jax (offline/CI mode)")
    ap.add_argument("--devices", type=int, default=0,
                    help="device count for the fingerprint (only with "
                         "--platform; default 1)")
    ap.add_argument("--repo", default=REPO,
                    help="artifact root to gather evidence from")
    ap.add_argument("--out", default="",
                    help="write the verdict here instead of the "
                         "per-platform file next to the compile cache")
    ap.add_argument("--print", dest="print_verdict", action="store_true",
                    help="dump the full verdict JSON to stdout")
    args = ap.parse_args(argv)

    if args.platform:
        fp = tuner.fingerprint(args.platform, args.devices or 1)
    else:
        # Imports jax: the verdict is scoped to the backend that will
        # consume it.
        fp = tuner.fingerprint()

    rows = tuner.gather_evidence(args.repo)
    verdict = tuner.settle(rows, fp)

    print(f"[autotune] fingerprint: {fp['platform']} "
          f"x{fp['device_count']} jax {fp['jax']}")
    print(f"[autotune] evidence: {verdict['evidence_rows']} admissible "
          f"row(s), {len(verdict['rejected_rows'])} rejected "
          f"(stale/foreign-platform)")
    for name in sorted(verdict["knobs"]):
        row = verdict["knobs"][name]
        print(f"[autotune]   {name:<22} = {row['value']!r:<10} "
              f"[{row['source']}] {row['reason']}")

    if args.print_verdict:
        sys.stdout.write(tuner.verdict_bytes(verdict).decode())

    path = tuner.save_verdict(verdict, args.out or None)
    if path is None:
        print("[autotune] WARNING: verdict not persisted "
              "(cache dir unwritable); boot resolution will re-settle",
              file=sys.stderr)
        return 1
    print(f"[autotune] wrote {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
