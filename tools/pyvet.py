"""Back-compat shim: the two original pyvet passes (undefined names +
unused imports) now live in ``tools/vet/names.py`` on the shared
single-parse walker, honoring the package's ``# noqa: CODE``
convention (blanket ``# noqa`` still suppresses everything on a line).

``python tools/pyvet.py <paths>`` runs ONLY those two passes — the
historical contract.  The full six-pass analyzer (async-safety,
tracer-purity, wire-schema, exception-hygiene) is what ``make vet``
runs:  ``python -m tools.vet <paths>``.
"""

from __future__ import annotations

import sys
from pathlib import Path
from typing import List, Optional, Sequence

# runnable as a script: tools/pyvet.py puts tools/ first on sys.path
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tools.vet.driver import LEGACY_PASSES, run_vet  # noqa: E402


def main(argv: Optional[Sequence[str]] = None) -> int:
    roots: List[str] = list(argv) if argv else ["consul_tpu", "tests"]
    result = run_vet(roots, passes=list(LEGACY_PASSES),
                     baseline_path=None)
    for f in result.parse_errors + result.findings:
        print(f.render())
    if result.rc == 0:
        print(f"pyvet: {result.files} files clean", file=sys.stderr)
    return result.rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
