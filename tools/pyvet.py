"""DEPRECATED back-compat shim — use ``python -m tools.vet`` instead.

The two original pyvet passes (undefined names + unused imports) live
in ``tools/vet/names.py`` on the shared single-parse walker, honoring
the package's ``# noqa: CODE`` convention (blanket ``# noqa`` still
suppresses everything on a line).

``python tools/pyvet.py <paths>`` runs ONLY those two passes — the
historical contract, kept so old scripts keep their exit-code
behavior.  The full ten-pass analyzer (async-safety, tracer-purity,
wire-schema, exception-hygiene, donation, shard-exactness,
carry-contract, overflow) is what ``make vet`` runs:
``python -m tools.vet <paths>``.

Removal window: this shim emits a DeprecationWarning now and will be
deleted two PRs after the analyzer PR that deprecated it (keep
``tests/test_vet.py::test_legacy_pyvet_cli_still_names_only`` green
until then — delete the test together with the shim).
"""

from __future__ import annotations

import sys
import warnings
from pathlib import Path
from typing import List, Optional, Sequence

# runnable as a script: tools/pyvet.py puts tools/ first on sys.path
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tools.vet.driver import LEGACY_PASSES, run_vet  # noqa: E402


def main(argv: Optional[Sequence[str]] = None) -> int:
    warnings.warn(
        "tools/pyvet.py is deprecated (names-only shim; scheduled for "
        "removal): run `python -m tools.vet <paths>` for the full "
        "analyzer",
        DeprecationWarning, stacklevel=2)
    roots: List[str] = list(argv) if argv else ["consul_tpu", "tests"]
    result = run_vet(roots, passes=list(LEGACY_PASSES),
                     baseline_path=None)
    for f in result.parse_errors + result.findings:
        print(f.render())
    if result.rc == 0:
        print(f"pyvet: {result.files} files clean", file=sys.stderr)
    return result.rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
