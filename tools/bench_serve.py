"""Serving-plane KV microbench — stale / default / consistent legs.

Forks a single local server (``tests/blackbox_util.TestServer``) and
drives ``/v1/kv/bench`` over raw asyncio HTTP/1.1 keep-alive
connections — no client-side HTTP framework, so on a shared-core box
the measurement tracks the *server's* cost per request rather than
the client's.  Legs:

    kv_put             PUT through raft group-commit
    kv_get             default consistency (leader-local read)
    kv_get_stale       ?stale (any-server local read)
    kv_get_consistent  ?consistent (lease short-circuit or ReadIndex)

``--workers 1,4`` repeats the run at each ``http_workers`` setting
(SO_REUSEPORT worker processes in front of the agent core); when the
value is 1 the key is omitted from the forked config so the bench
also runs against builds that predate it.  Output is one JSON object
with GET/s and p50/p99 per leg per worker count.

Child processes are terminated by tracked PID only (TestServer.stop
sends SIGTERM to its own Popen handle, then SIGKILL after a grace
period) — never by name matching.

Run:    python tools/bench_serve.py [--requests 4000] [--concurrency 32]
                                    [--workers 1,4] [--out FILE]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

PUT_BODY = b"74a31e96-1d0f-4fa7-aa14-7212a326986e"


class KeepAliveConn:
    """One HTTP/1.1 keep-alive connection speaking just enough HTTP."""

    def __init__(self, host: str, port: int) -> None:
        self.host = host
        self.port = port
        self.reader = None
        self.writer = None

    async def connect(self) -> None:
        self.reader, self.writer = await asyncio.open_connection(
            self.host, self.port)

    async def close(self) -> None:
        # Swap-then-close so overlapping close() calls cannot both
        # wait on (then re-null) the same writer.
        writer, self.writer = self.writer, None
        if writer is not None:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    def _frame(self, method: str, path: str, body: bytes | None) -> bytes:
        head = (f"{method} {path} HTTP/1.1\r\n"
                f"Host: {self.host}:{self.port}\r\n")
        if body is not None:
            head += f"Content-Length: {len(body)}\r\n"
        head += "\r\n"
        return head.encode("ascii") + (body or b"")

    async def request(self, method: str, path: str,
                      body: bytes | None = None) -> int:
        """Issue one request, drain the response, return the status."""
        if self.writer is None:
            await self.connect()
        frame = self._frame(method, path, body)
        try:
            self.writer.write(frame)
            await self.writer.drain()
            return await self._read_response()
        except (ConnectionError, asyncio.IncompleteReadError, OSError):
            # Server rotated the keep-alive connection: one reconnect.
            await self.close()
            await self.connect()
            self.writer.write(frame)
            await self.writer.drain()
            return await self._read_response()

    async def _read_response(self) -> int:
        head = await self.reader.readuntil(b"\r\n\r\n")
        lines = head.decode("latin-1").split("\r\n")
        status = int(lines[0].split(" ", 2)[1])
        length = 0
        chunked = False
        for ln in lines[1:]:
            low = ln.lower()
            if low.startswith("content-length:"):
                length = int(ln.split(":", 1)[1])
            elif low.startswith("transfer-encoding:") and "chunked" in low:
                chunked = True
        if chunked:
            while True:
                size_ln = await self.reader.readuntil(b"\r\n")
                size = int(size_ln.strip(), 16)
                await self.reader.readexactly(size + 2)
                if size == 0:
                    break
        elif length:
            await self.reader.readexactly(length)
        return status


async def drive(host: str, port: int, method: str, path: str,
                body: bytes | None, total: int, concurrency: int) -> dict:
    latencies: list = []
    errors = [0]
    sample_err = [None]
    queue: asyncio.Queue = asyncio.Queue()
    for _ in range(total):
        queue.put_nowait(None)

    async def worker() -> None:
        conn = KeepAliveConn(host, port)
        try:
            await conn.connect()
            while True:
                try:
                    queue.get_nowait()
                except asyncio.QueueEmpty:
                    return
                t0 = time.perf_counter()
                try:
                    status = await conn.request(method, path, body)
                    if status >= 400:
                        errors[0] += 1
                        if sample_err[0] is None:
                            sample_err[0] = f"status {status}"
                except Exception as e:
                    errors[0] += 1
                    if sample_err[0] is None:
                        sample_err[0] = f"{type(e).__name__}: {e}"
                    await conn.close()
                latencies.append((time.perf_counter() - t0) * 1000)
        finally:
            await conn.close()

    t0 = time.perf_counter()
    await asyncio.gather(*[worker() for _ in range(concurrency)])
    wall = time.perf_counter() - t0
    lat = sorted(latencies) or [0.0]

    def pct(q: float) -> float:
        return lat[min(len(lat) - 1, int(q / 100 * len(lat)))]

    out = {
        "requests": total, "errors": errors[0],
        "req_per_sec": round(total / wall, 1),
        "p50_ms": round(pct(50), 2), "p99_ms": round(pct(99), 2),
    }
    if sample_err[0] is not None:
        out["sample_error"] = sample_err[0]
    return out


async def bench_one(nworkers: int, requests: int, concurrency: int) -> dict:
    from blackbox_util import TestServer

    extra = {"http_workers": nworkers} if nworkers > 1 else {}
    srv = TestServer(f"bs{nworkers}", config_extra=extra).start()
    try:
        srv.wait_for_api()
        srv.wait_for_leader()
        host, port = "127.0.0.1", srv.ports["http"]
        warm = KeepAliveConn(host, port)
        await warm.connect()
        await warm.request("PUT", "/v1/kv/bench", PUT_BODY)
        for _ in range(20):
            await warm.request("GET", "/v1/kv/bench")
        await warm.close()

        results = {}
        legs = [
            ("kv_put", "PUT", "/v1/kv/bench", PUT_BODY),
            ("kv_get", "GET", "/v1/kv/bench", None),
            ("kv_get_stale", "GET", "/v1/kv/bench?stale", None),
            ("kv_get_consistent", "GET", "/v1/kv/bench?consistent", None),
        ]
        for name, method, path, body in legs:
            print(f"[bench-serve] workers={nworkers} {name} x{requests}"
                  f" @{concurrency}", file=sys.stderr)
            results[name] = await drive(host, port, method, path, body,
                                        requests, concurrency)
        return results
    finally:
        srv.stop()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=4000)
    ap.add_argument("--concurrency", type=int, default=32)
    ap.add_argument("--workers", default="1",
                    help="comma list of http_workers settings, e.g. 1,4")
    ap.add_argument("--out", default=os.path.join(REPO, "BENCH_SERVE.json"),
                    help="also write JSON here (the serving-plane "
                         "trajectory file, like BENCH_r*.json; '' skips)")
    args = ap.parse_args()

    out = {"requests": args.requests, "concurrency": args.concurrency,
           "runs": {}}
    for n in [int(w) for w in args.workers.split(",") if w.strip()]:
        out["runs"][f"workers={n}"] = asyncio.run(
            bench_one(n, args.requests, args.concurrency))
    text = json.dumps(out, indent=1)
    print(text)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(text + "\n")


if __name__ == "__main__":
    main()
