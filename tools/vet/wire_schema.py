"""Wire-schema drift pass: the encoder and decoder of a hand-rolled
msgpack schema are two separate piles of string literals; nothing but
convention keeps them aligned.  A key written but never read (or read
but never written) is silent cross-node corruption — the field
vanishes or arrives as the decoder's default.

The pass collects field-name string literals per **schema unit** and
reports the set difference in both directions:

- **W01 written-never-read**: a key the encode side emits that no
  decoder of the unit ever looks at.
- **W02 read-never-written**: a key the decode side expects that no
  encoder of the unit ever produces.

Units are discovered generically:

- a function (or method) named ``X_to_wire`` / ``encode_X`` is the
  encoder of unit ``X``; ``X_from_wire`` / ``decode_X`` the decoder
  (for methods the unit is the enclosing class, pairing each class's
  ``to_wire`` with its ``from_wire``);
- lambda tables — ``_TO_WIRE = {SomeClass: lambda m: {...}}`` keyed by
  class pair with ``*_FROM_WIRE = {"method": lambda d: ...}`` entries
  through the :data:`PAIRS` alias map (this repo's raft RPC tables);
- **envelope** units: within each :data:`ENVELOPE_GROUPS` module set,
  every Capitalized dict key is an encode and every Capitalized
  ``d["K"]`` / ``d.get("K")`` a decode — the RPC and IPC envelope key
  namespaces (``Method``/``Body``/``Trace``/…, ``Seq``/``Command``/…)
  are capitalized precisely so this pass can see each whole, writer
  side and reader side together.

Encode keys are dict-literal string keys plus string-subscript stores;
decode keys are string-subscript loads plus ``.get("k")`` calls.  A
unit with contexts on only one side is skipped (its peer lives outside
the scanned set — e.g. ``_meta_wire`` whose reader is the HTTP layer).
Only findings, not pairings, consult the source, so the pass stays a
single AST walk per file.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from tools.vet.core import FileCtx, Finding

WRITTEN_NEVER_READ = "W01"
READ_NEVER_WRITTEN = "W02"

# Modules forming the wire surface (matched by path suffix).
WIRE_MODULES = (
    "consul_tpu/structs/codec.py",
    "consul_tpu/rpc/wire.py",
    "consul_tpu/rpc/server.py",
    "consul_tpu/rpc/pool.py",
    "consul_tpu/server/client.py",
    "consul_tpu/ipc/server.py",
    "consul_tpu/ipc/client.py",
    "consul_tpu/agent/workers.py",
)

# (unit name, module suffixes) whose Capitalized keys form one shared
# envelope namespace: writer and reader live in different files, so
# each group is compared as a whole.
ENVELOPE_GROUPS = (
    ("rpc-envelope", ("consul_tpu/rpc/server.py",
                      "consul_tpu/rpc/pool.py")),
    ("ipc-envelope", ("consul_tpu/ipc/server.py",
                      "consul_tpu/ipc/client.py",
                      "consul_tpu/agent/workers.py")),
)

# decode-table entries -> the encode unit they must mirror
# (table variable name, entry key) : unit
PAIRS: Dict[Tuple[str, str], str] = {
    ("_REQ_FROM_WIRE", "request_vote"): "VoteReq",
    ("_REQ_FROM_WIRE", "append_entries"): "AppendReq",
    ("_REQ_FROM_WIRE", "install_snapshot"): "SnapReq",
    ("_RESP_FROM_WIRE", "request_vote"): "VoteResp",
    ("_RESP_FROM_WIRE", "append_entries"): "AppendResp",
    ("_RESP_FROM_WIRE", "install_snapshot"): "SnapResp",
}

_ENC_NAME = re.compile(r"^(?:_?(?P<stem>\w+?)_to_wire|encode_(?P<stem2>\w+)"
                       r"|to_wire)$")
_DEC_NAME = re.compile(r"^(?:_?(?P<stem>\w+?)_from_wire"
                       r"|decode_(?P<stem2>\w+)|from_wire)$")
_CAP_KEY = re.compile(r"^[A-Z][A-Za-z]*$")


@dataclass
class _Unit:
    enc_keys: Dict[str, int] = field(default_factory=dict)  # key -> line
    dec_keys: Dict[str, int] = field(default_factory=dict)
    enc_paths: Set[str] = field(default_factory=set)
    dec_paths: Set[str] = field(default_factory=set)
    has_encoder: bool = False
    has_decoder: bool = False


def _collect_keys(node: ast.AST) -> Tuple[Dict[str, int], Dict[str, int]]:
    """(encode keys, decode keys) within one context body."""
    enc: Dict[str, int] = {}
    dec: Dict[str, int] = {}
    for n in ast.walk(node):
        if isinstance(n, ast.Dict):
            for k in n.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    enc.setdefault(k.value, k.lineno)
        elif isinstance(n, ast.Subscript):
            sl = n.slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                if isinstance(n.ctx, ast.Store):
                    enc.setdefault(sl.value, n.lineno)
                elif isinstance(n.ctx, ast.Load):
                    dec.setdefault(sl.value, n.lineno)
        elif isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr == "get" and n.args \
                and isinstance(n.args[0], ast.Constant) \
                and isinstance(n.args[0].value, str):
            dec.setdefault(n.args[0].value, n.lineno)
    return enc, dec


def _unit_of_name(name: str, cls: Optional[str],
                  pattern: re.Pattern) -> Optional[str]:
    m = pattern.match(name)
    if not m:
        return None
    if name in ("to_wire", "from_wire") or name.startswith(("encode",
                                                            "decode")):
        if cls is not None:
            return cls
    stem = m.groupdict().get("stem") or m.groupdict().get("stem2")
    return stem or cls


def _scan_module(ctx: FileCtx, units: Dict[str, _Unit]) -> None:
    class_stack: List[str] = []

    def visit(node: ast.AST) -> None:
        if isinstance(node, ast.ClassDef):
            class_stack.append(node.name)
            for c in node.body:
                visit(c)
            class_stack.pop()
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            cls = class_stack[-1] if class_stack else None
            enc_unit = _unit_of_name(node.name, cls, _ENC_NAME)
            dec_unit = _unit_of_name(node.name, cls, _DEC_NAME)
            if enc_unit:
                _absorb(units, enc_unit, ctx, node, encode=True)
            elif dec_unit:
                _absorb(units, dec_unit, ctx, node, encode=False)
            return  # no nested schema contexts
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Dict):
            for target in node.targets:
                if isinstance(target, ast.Name):
                    _scan_table(ctx, target.id, node.value, units)
        for c in ast.iter_child_nodes(node):
            visit(c)

    visit(ctx.tree)


def _scan_table(ctx: FileCtx, var: str, table: ast.Dict,
                units: Dict[str, _Unit]) -> None:
    is_enc = var.endswith("_TO_WIRE")
    is_dec = var.endswith("_FROM_WIRE")
    if not (is_enc or is_dec):
        return
    for k, v in zip(table.keys, table.values):
        if v is None or k is None:
            continue
        if is_enc and isinstance(k, ast.Name):
            unit = k.id
        elif is_dec and isinstance(k, ast.Constant) \
                and isinstance(k.value, str):
            unit = PAIRS.get((var, k.value), k.value)
        else:
            continue
        _absorb(units, unit, ctx, v, encode=is_enc)


def _absorb(units: Dict[str, _Unit], unit_name: str, ctx: FileCtx,
            body: ast.AST, encode: bool) -> None:
    unit = units.setdefault(unit_name, _Unit())
    enc, dec = _collect_keys(body)
    if encode:
        unit.has_encoder = True
        unit.enc_paths.add(ctx.path)
        for k, line in enc.items():
            unit.enc_keys.setdefault(k, line)
    else:
        unit.has_decoder = True
        unit.dec_paths.add(ctx.path)
        for k, line in dec.items():
            unit.dec_keys.setdefault(k, line)


def _scan_envelopes(ctxs: List[FileCtx], units: Dict[str, _Unit],
                    groups) -> None:
    for name, suffixes in groups:
        for ctx in ctxs:
            if not ctx.path.endswith(tuple(suffixes)):
                continue
            unit = units.setdefault(name, _Unit())
            enc, dec = _collect_keys(ctx.tree)
            for k, line in enc.items():
                if _CAP_KEY.match(k):
                    unit.has_encoder = True
                    unit.enc_paths.add(ctx.path)
                    unit.enc_keys.setdefault(k, line)
            for k, line in dec.items():
                if _CAP_KEY.match(k):
                    unit.has_decoder = True
                    unit.dec_paths.add(ctx.path)
                    unit.dec_keys.setdefault(k, line)


def check_project(ctxs: List[FileCtx],
                  modules: Tuple[str, ...] = WIRE_MODULES,
                  envelope_groups=ENVELOPE_GROUPS) -> List[Finding]:
    wire_ctxs = [c for c in ctxs if c.path.endswith(tuple(modules))]
    if not wire_ctxs:
        return []
    units: Dict[str, _Unit] = {}
    for ctx in wire_ctxs:
        _scan_module(ctx, units)
    _scan_envelopes(wire_ctxs, units, envelope_groups)
    findings: List[Finding] = []
    for name, unit in sorted(units.items()):
        if not (unit.has_encoder and unit.has_decoder):
            continue  # peer lives outside the scanned surface
        enc_path = min(unit.enc_paths) if unit.enc_paths else "?"
        dec_path = min(unit.dec_paths) if unit.dec_paths else "?"
        for key in sorted(set(unit.enc_keys) - set(unit.dec_keys)):
            findings.append(Finding(
                enc_path, unit.enc_keys[key], WRITTEN_NEVER_READ,
                f"wire key '{key}' of unit '{name}' is written but never "
                f"read by its decoder ({dec_path}) — dead field or "
                "decoder drift"))
        for key in sorted(set(unit.dec_keys) - set(unit.enc_keys)):
            findings.append(Finding(
                dec_path, unit.dec_keys[key], READ_NEVER_WRITTEN,
                f"wire key '{key}' of unit '{name}' is read but never "
                f"written by its encoder ({enc_path}) — arrives as the "
                "decoder default on every message"))
    return sorted(findings, key=lambda f: (f.path, f.line, f.message))
