"""Shard-exactness pass: check the conventions that make the sharded
SWIM kernel bit-identical to the single-device kernel.

The shard_map port (gossip/kernel.py §"ICI sharding") runs with
``check_rep=False``, so XLA verifies *nothing* about replication: the
merge discipline is a human convention — every cross-device combine is
an integer ``psum`` of **disjoint** shard-local contributions, and
every write to a replicated register is owner-gated (``jnp.where(
owned, loc, OOB)`` + ``mode="drop"``).  Break the convention and the
sharded kernel diverges from the reference kernel silently; the parity
suite only catches it for the shapes it happens to run.

Scope: functions passed callable-first to ``shard_map(...)`` plus
everything transitively called from them (simple-name call graph) in
the same module.

- **S01 inexact collective**: ``psum``/``pmax``/``pmin`` whose operand
  shows float evidence — a float dtype cast/constructor, a float
  literal, true division, or ``mean``/``pmean`` — and no integer cast
  downstream of it.  Float addition is not associative, so a float
  psum is ordering-dependent across device layouts and can never be
  bit-exact.  ``pmean`` flags unconditionally (it divides).  Kill
  rule: an ``astype(<int dtype>)`` / int-constructor wrapping the
  operand restores exactness.
- **S02 ungated replicated write**: an ``x.at[idx].set/add/...(...)``
  scatter whose index derives from ``axis_index`` arithmetic with
  neither a ``jnp.where`` owner-mask in the index nor ``mode="drop"``
  on the write.  Each replica writes a *different* slot, so the
  "replicated" register diverges across devices — exactly what
  ``check_rep=False`` stops catching.  Kill rules: ``jnp.where``
  anywhere in the index expression (the owner-predicate idiom routes
  non-owners out of bounds) or a ``mode=`` keyword on the op (dropped
  lanes are the gate).
- **S03 non-permutation ppermute table**: a ``ppermute`` whose literal
  ``perm`` table repeats a source or destination (lost or duplicated
  payloads — ppermute delivers nothing to an uncovered destination,
  which is only sound when that is the intent).  Comprehension tables
  ``[(i, (i + k) % n) for i in range(n)]`` are accepted when both pair
  elements reference the comprehension variable; a constant element
  (``(i, 0)``: everyone sends to device 0) flags.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from tools.vet.core import FileCtx, Finding
from tools.vet.tracer_purity import _collect_defs, _tail

INEXACT_COLLECTIVE = "S01"
UNGATED_WRITE = "S02"
BAD_PERM = "S03"

_REDUCERS = {"psum", "pmax", "pmin", "psum_scatter"}
_FLOAT_DTYPES = {"float16", "float32", "float64", "bfloat16", "half",
                 "single", "double"}
_INT_DTYPES = {"int8", "int16", "int32", "int64", "uint8", "uint16",
               "uint32", "uint64", "bool_"}
_FLOAT_CALLS = {"mean", "average", "pmean", "std", "var", "norm"}
_SCATTER_OPS = {"set", "add", "max", "min", "mul", "apply"}


def _shard_rooted(tree: ast.Module) -> Set[int]:
    """id() of every def reachable from a shard_map callable-first
    call site, by simple-name edges."""
    defs = _collect_defs(tree)
    roots: List[str] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and node.args \
                and _tail(node.func) == "shard_map":
            fn = _tail(node.args[0])
            if fn in defs:
                roots.append(fn)
    seen: Set[int] = set()
    out: Set[int] = set()
    todo = [i for name in roots for i in defs.get(name, [])]
    while todo:
        info = todo.pop()
        if id(info) in seen:
            continue
        seen.add(id(info))
        out.add(id(info.node))
        for callee in info.calls:
            todo.extend(defs.get(callee, []))
    return out


def _float_evidence(expr: ast.expr) -> Optional[str]:
    """Why ``expr`` may be float-valued, or None.  An int cast at the
    top level launders everything under it."""
    if isinstance(expr, ast.Call):
        ct = _tail(expr.func)
        if ct == "astype" and expr.args:
            adt = _tail(expr.args[0])
            if adt in _INT_DTYPES:
                return None           # exact by construction
            if adt in _FLOAT_DTYPES:
                return f"astype({adt})"
        if ct in _INT_DTYPES:
            return None
        if ct in _FLOAT_DTYPES:
            return f"{ct}() cast"
        if ct in _FLOAT_CALLS:
            return f"{ct}()"
    for node in ast.walk(expr):
        if isinstance(node, ast.Constant) and isinstance(node.value, float):
            return f"float literal {node.value}"
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Div):
            return "true division"
        if isinstance(node, ast.Call):
            ct = _tail(node.func)
            if ct in _FLOAT_CALLS:
                return f"{ct}()"
            if ct == "astype" and node.args \
                    and _tail(node.args[0]) in _FLOAT_DTYPES:
                return f"astype({_tail(node.args[0])})"
            if ct in _FLOAT_DTYPES:
                return f"{ct}() cast"
        if isinstance(node, (ast.Name, ast.Attribute)):
            if _tail(node) in _FLOAT_DTYPES:
                return f"{_tail(node)} dtype"
    return None


def _index_exprs(sub: ast.expr) -> List[ast.expr]:
    if isinstance(sub, ast.Tuple):
        return list(sub.elts)
    return [sub]


def _axis_tainted(fn: ast.AST) -> Set[str]:
    """Names derived (transitively, 2 rounds) from ``axis_index``."""
    tainted: Set[str] = set()

    def mentions(expr: ast.expr) -> bool:
        for n in ast.walk(expr):
            if isinstance(n, (ast.Name, ast.Attribute)) \
                    and _tail(n) == "axis_index":
                return True
            if isinstance(n, ast.Name) and n.id in tainted:
                return True
        return False

    assigns = [n for n in ast.walk(fn) if isinstance(n, ast.Assign)]
    for _ in range(2):
        changed = False
        for node in assigns:
            if not mentions(node.value):
                continue
            for t in node.targets:
                for el in ast.walk(t):
                    if isinstance(el, ast.Name) and el.id not in tainted:
                        tainted.add(el.id)
                        changed = True
        if not changed:
            break
    return tainted


def _check_scatter(ctx: FileCtx, fn_name: str, node: ast.Call,
                   tainted: Set[str], out: List[Finding]) -> None:
    # shape: <base>.at[<idx>].<op>(<val>, [mode=...])
    if not (isinstance(node.func, ast.Attribute)
            and node.func.attr in _SCATTER_OPS
            and isinstance(node.func.value, ast.Subscript)
            and isinstance(node.func.value.value, ast.Attribute)
            and node.func.value.value.attr == "at"):
        return
    if any(kw.arg == "mode" for kw in node.keywords):
        return  # dropped out-of-bounds lanes are the owner gate
    idx = node.func.value.slice
    derived = False
    for part in _index_exprs(idx):
        for n in ast.walk(part):
            if isinstance(n, ast.Call) and _tail(n.func) == "where":
                return  # owner-predicate mask in the index
            if isinstance(n, (ast.Name, ast.Attribute)) \
                    and _tail(n) == "axis_index":
                derived = True
            if isinstance(n, ast.Name) and n.id in tainted:
                derived = True
    if derived:
        out.append(Finding(
            ctx.path, node.lineno, UNGATED_WRITE,
            f"scatter .at[...].{node.func.attr}() in shard_map body "
            f"'{fn_name}' indexes with axis_index-derived values but has "
            "no jnp.where owner mask and no mode=\"drop\" — each replica "
            "writes a different slot, so the replicated register "
            "diverges across devices"))


def _perm_table(call: ast.Call) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == "perm":
            return kw.value
    if len(call.args) >= 3:
        return call.args[2]
    return None


def _check_perm(ctx: FileCtx, fn_name: str, call: ast.Call,
                out: List[Finding]) -> None:
    perm = _perm_table(call)
    if perm is None:
        return

    def emit(msg: str) -> None:
        out.append(Finding(
            ctx.path, call.lineno, BAD_PERM,
            f"ppermute table in shard_map body '{fn_name}' {msg}"))

    if isinstance(perm, (ast.List, ast.Tuple, ast.Set)):
        srcs: List[object] = []
        dsts: List[object] = []
        for el in perm.elts:
            if not (isinstance(el, (ast.Tuple, ast.List))
                    and len(el.elts) == 2):
                return  # non-pair element: not statically checkable
            pair = []
            for part in el.elts:
                if isinstance(part, ast.Constant) \
                        and isinstance(part.value, int):
                    pair.append(part.value)
                else:
                    return  # symbolic entry: give up on this table
            srcs.append(pair[0])
            dsts.append(pair[1])
        if len(set(srcs)) != len(srcs):
            emit("repeats a source device — duplicated sends are not a "
                 "permutation; the payload ordering is undefined")
        elif len(set(dsts)) != len(dsts):
            emit("repeats a destination device — colliding sends lose "
                 "payloads; not a permutation")
    elif isinstance(perm, (ast.ListComp, ast.GeneratorExp, ast.SetComp)):
        elt = perm.elt
        if not (isinstance(elt, (ast.Tuple, ast.List))
                and len(elt.elts) == 2):
            return
        tvars = {n.id for gen in perm.generators
                 for n in ast.walk(gen.target) if isinstance(n, ast.Name)}
        for part in elt.elts:
            refs = {n.id for n in ast.walk(part)
                    if isinstance(n, ast.Name)}
            if not (refs & tvars):
                emit("maps every source to the same destination "
                     "(comprehension element does not use the loop "
                     "variable) — collapsed sends lose payloads")
                return


def check(ctx: FileCtx) -> List[Finding]:
    if "shard_map" not in ctx.src:
        return []
    from tools.vet.async_safety import _module_imports
    imports = _module_imports(ctx.tree)
    if imports.get("jax") != "jax" and not any(
            v == "jax" or v.startswith("jax.") for v in imports.values()):
        return []
    rooted = _shard_rooted(ctx.tree)
    if not rooted:
        return []
    findings: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                or id(node) not in rooted:
            continue
        tainted = _axis_tainted(node)
        for c in ast.walk(node):
            if not isinstance(c, ast.Call):
                continue
            t = _tail(c.func)
            if t in _REDUCERS and c.args:
                why = _float_evidence(c.args[0])
                if why is not None:
                    findings.append(Finding(
                        ctx.path, c.lineno, INEXACT_COLLECTIVE,
                        f"{t}() over a possibly-float value in shard_map "
                        f"body '{node.name}' ({why}) — float reduction "
                        "is ordering-dependent and cannot be bit-exact; "
                        "reduce integers (astype an int dtype) or move "
                        "the float math after the merge"))
            elif t == "pmean" and c.args:
                findings.append(Finding(
                    ctx.path, c.lineno, INEXACT_COLLECTIVE,
                    f"pmean() in shard_map body '{node.name}' divides by "
                    "the axis size — inherently inexact; psum integers "
                    "and divide after the merge"))
            elif t == "ppermute":
                _check_perm(ctx, node.name, c, findings)
            _check_scatter(ctx, node.name, c, tainted, findings)
    return sorted(set(findings), key=lambda f: (f.line, f.code, f.message))
