"""Cancellation-safety tier: future-resolution flow analysis over the
host plane (four passes, codes Q01-Q04).

An ``await`` on a future is a two-way coupling: cancellation of the
awaiting task propagates INTO the future (``Task.cancel`` cancels the
future the task is blocked on), and an exception escaping the awaiting
frame can skip the continuation that would have resolved some OTHER
future.  Both directions killed real code here: the ADVICE r5 high
finding (pre-fix ``server/server.py``) let one cancelled aiohttp reader
cancel a ReadIndex batch future shared by every batchmate, and let a
cancelled predecessor batch unwind the next batch's runner before it
fired — stranding joiners forever, the exact silent-unsafety class
"Scaling Strongly Consistent Replication" (PAPERS.md) warns read-
scaling schemes about.  These passes turn that bug class into checked
invariants over the whole tree; ``tools/vet/dyn.py``'s cancel-injection
harness (``CONSUL_TPU_DYN_CANCEL=1``) is the dynamic twin.

- **Q01 bare await of a shared future**: ``await f`` where ``f`` has
  *shared-future provenance* — stored on ``self.*`` / a module-level
  dict (directly, as dict values, or as values of dicts whose entries
  hold a ``"fut"``-style key), with the slot touched from two or more
  functions — and the await is not wrapped in ``asyncio.shield``.
  Cancelling the waiter cancels the shared future and poisons every
  other waiter (the ``_confirm_batched`` vs ``_leader_confirm``
  asymmetry).  Killed by shielding the await; a deliberate
  propagate-cancellation-to-peers design earns a ``# noqa: Q01`` with
  the ownership argument in a comment.
- **Q02 future-resolution completeness**: a function that *owns
  resolution* of a created future (calls ``set_result``/
  ``set_exception``/``cancel`` on a slot some function created via
  ``create_future()``/``Future()``) must resolve it on ALL paths —
  including a ``CancelledError``/``BaseException`` escaping one of its
  awaits.  An await with no enclosing ``finally``-resolution and no
  ``BaseException``-catching handler that resolves lets an escape
  strand the future: every waiter hangs forever.  Also flags futures
  created and stored to shared state that NO function ever resolves,
  and locally-created futures that never escape and are never
  resolved.  Killed by resolving in a ``finally``, by an
  ``except BaseException`` handler that resolves before re-raising, or
  by handing the slot to a resolver function.
- **Q03 Exception-guard across a must-happen hand-off**: a ``try``
  whose broadest handler is ``except Exception`` (no ``BaseException``
  / ``CancelledError`` split, no ``finally`` hand-off), whose body
  awaits, and whose continuation — later statements in the body, the
  handler itself, or the statements after the ``try`` — performs a
  hand-off another task is waiting on (resolves a future, flips a
  ``fired``-style flag, sets an ``asyncio.Event``).  ``CancelledError``
  derives from ``BaseException`` precisely so broad handlers don't eat
  it — which means it sails PAST this handler and the hand-off never
  happens.  Demands the ``BaseException`` split or a ``finally``.
- **Q04 unsupervised hand-off task**: ``create_task``/``ensure_future``
  of a coroutine whose body performs a hand-off, where the task handle
  gets no ``add_done_callback`` and is never awaited/gathered, and the
  coroutine body does not self-supervise (no ``finally`` / broad-
  ``BaseException`` hand-off).  If the task dies — cancellation at
  teardown, a bug — its death is invisible and the hand-off's waiters
  hang.

Suppression conventions mirror the interleave tier: a ``# noqa: Q0x``
must carry the cancellation-containment argument in an adjacent
comment (sole-waiter ownership, teardown-only path, etc.).

The passes ride the PR-17 per-class caches: ``interleave.class_scans``
memoizes the module prescan + per-class scans on the FileCtx, and this
module memoizes its own future-provenance scan the same way, so the
four Q passes cost ONE provenance walk per file between them.
"""

from __future__ import annotations

import ast
import weakref
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from tools.vet.core import FileCtx, Finding
from tools.vet.interleave import (_attr_use_counts, _self_attr, _walk_local,
                                  class_scans)
from tools.vet.tracer_purity import _tail

UNSHIELDED_SHARED = "Q01"
UNRESOLVED_FUTURE = "Q02"
EXCEPTION_GUARD_HANDOFF = "Q03"
UNSUPERVISED_HANDOFF_TASK = "Q04"

# Factories minting a bare Future the creator must see resolved.
_FUTURE_FACTORIES = {"create_future", "Future"}
# Task-flavored futures: self-resolving (the coroutine's return/raise
# resolves them), so Q02's completeness obligation does not apply —
# but awaiting a SHARED one bare still propagates cancellation (Q01).
_TASK_FACTORIES = {"ensure_future", "create_task", "wrap_future",
                   "run_coroutine_threadsafe"}
_RESOLVERS = {"set_result", "set_exception", "cancel"}
_SPAWNERS = {"create_task", "ensure_future"}
# Event factories: `.set()` on one of these attrs is a waiter hand-off.
_EVENT_FACTORIES = {"Event"}


def _call_name(func: ast.AST) -> Optional[str]:
    """Trailing name of a call target, surviving chained calls
    (``asyncio.get_event_loop().create_future`` -> ``create_future``)
    where ``dotted_name``/``_tail`` give up."""
    if isinstance(func, ast.Attribute):
        return func.attr
    return _tail(func)


def _is_future_factory(node: ast.AST, include_tasks: bool = True) -> bool:
    if not isinstance(node, ast.Call):
        return False
    tail = _call_name(node.func)
    if tail in _FUTURE_FACTORIES:
        return True
    return include_tasks and tail in _TASK_FACTORIES


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _functions_of(tree: ast.AST) -> List[ast.AST]:
    """Direct function children (module level or class body)."""
    return [n for n in ast.iter_child_nodes(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]


# Four passes each visit every function several times; re-walking the
# AST dominated the tier's cost (the _walk_local recursion, not the
# analysis).  One flat node list per function, weakly keyed so entries
# die with the FileCtx's tree.
_NODES_MEMO: "weakref.WeakKeyDictionary[ast.AST, List[ast.AST]]" = \
    weakref.WeakKeyDictionary()


def _nodes(fn: ast.AST) -> List[ast.AST]:
    """``list(_walk_local(fn))``, memoized per function node."""
    nodes = _NODES_MEMO.get(fn)
    if nodes is None:
        nodes = _NODES_MEMO[fn] = list(_walk_local(fn))
    return nodes


@dataclass
class _Slots:
    """Future-provenance facts for one scope (a class, or the module).

    ``future_attrs``    self.A (or module NAME) holding a future
    ``future_dicts``    self.D (or NAME) mapping keys -> futures
    ``batch_dicts``     self.D mapping keys -> dicts that carry futures
                        under ``future_keys`` (the confirm-batch shape)
    ``future_keys``     dict-literal / subscript-store keys observed
                        holding a future ("fut")
    ``event_attrs``     self.E assigned from asyncio.Event()
    ``resolved_slots``  attr/key names some function resolves
                        (set_result/set_exception/cancel receiver
                        provenance)
    ``creations``       [(fn, assign node, slot or None, escapes)]
    """

    future_attrs: Set[str] = field(default_factory=set)
    future_dicts: Set[str] = field(default_factory=set)
    batch_dicts: Set[str] = field(default_factory=set)
    future_keys: Set[str] = field(default_factory=set)
    event_attrs: Set[str] = field(default_factory=set)
    resolved_slots: Set[str] = field(default_factory=set)
    use_counts: Dict[str, Set[str]] = field(default_factory=dict)
    # names of functions (this scope ∪ module level) whose body
    # directly resolves a future — calls to them discharge hand-offs
    resolver_fns: Set[str] = field(default_factory=set)


def _dict_future_keys(d: ast.Dict) -> Set[str]:
    out: Set[str] = set()
    for k, v in zip(d.keys, d.values):
        key = _const_str(k) if k is not None else None
        if key and _is_future_factory(v):
            out.add(key)
    return out


def _scope_root_attr(node: ast.AST, module_dicts: Set[str]
                     ) -> Optional[str]:
    """The slot name for an expression rooted at ``self.A`` or at a
    module-level dict NAME; None otherwise."""
    attr = _self_attr(node)
    if attr is not None:
        return attr
    if isinstance(node, ast.Name) and node.id in module_dicts:
        return node.id
    return None


def _module_dict_names(tree: ast.Module) -> Set[str]:
    out: Set[str] = set()
    for n in tree.body:
        targets = []
        if isinstance(n, ast.Assign):
            targets = n.targets
        elif isinstance(n, ast.AnnAssign) and n.value is not None:
            targets = [n.target]
        else:
            continue
        if isinstance(n.value, (ast.Dict, ast.DictComp)) or (
                isinstance(n.value, ast.Call)
                and _tail(n.value.func) == "dict"):
            for t in targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _local_slot_aliases(fn: ast.AST, module_dicts: Set[str]
                        ) -> Dict[str, Set[str]]:
    """Local names that alias a self/module slot in this function:
    ``fut = getattr(self, "_stats_future", None)``, ``fut = self.x``,
    and the chained-assign ``fut = self._stats_future = factory()``.
    A resolution through the alias resolves the slot(s) — multi-valued
    because dispatch functions rebind one local from several getattrs
    (tpu_backend._handle), and crediting only the last binding would
    leave the others looking unresolved."""
    out: Dict[str, Set[str]] = {}
    for n in _nodes(fn):
        if not isinstance(n, ast.Assign):
            continue
        v = n.value
        slot: Optional[str] = _scope_root_attr(v, module_dicts)
        if slot is None and isinstance(v, ast.Call) \
                and isinstance(v.func, ast.Name) \
                and v.func.id == "getattr" and len(v.args) >= 2 \
                and isinstance(v.args[0], ast.Name) \
                and v.args[0].id == "self":
            slot = _const_str(v.args[1])
        if slot is None:
            # chained assign: a sibling attr target names the slot
            for t in n.targets:
                s = _scope_root_attr(t, module_dicts)
                if s is not None:
                    slot = s
        if slot is None:
            continue
        for t in n.targets:
            if isinstance(t, ast.Name):
                out.setdefault(t.id, set()).add(slot)
    return out


def _scan_scope(scope: ast.AST, fns: Sequence[ast.AST],
                module_dicts: Set[str],
                use_counts: Dict[str, Set[str]]) -> _Slots:
    """One walk over a class (or module) collecting future provenance."""
    slots = _Slots(use_counts=use_counts)
    # Pass 1: direct evidence — factory assigns, dict-literal keys,
    # event attrs, resolver receivers.
    for fn in fns:
        aliases = _local_slot_aliases(fn, module_dicts)
        for n in _nodes(fn):
            if isinstance(n, ast.Assign):
                v = n.value
                for t in n.targets:
                    slot = _scope_root_attr(t, module_dicts)
                    sub_slot = _scope_root_attr(t.value, module_dicts) \
                        if isinstance(t, ast.Subscript) else None
                    if _is_future_factory(v):
                        if slot is not None:
                            slots.future_attrs.add(slot)
                        if sub_slot is not None:
                            slots.future_dicts.add(sub_slot)
                    elif isinstance(v, ast.Call) \
                            and _call_name(v.func) in _EVENT_FACTORIES:
                        if slot is not None:
                            slots.event_attrs.add(slot)
                    if isinstance(v, ast.Dict):
                        fkeys = _dict_future_keys(v)
                        if fkeys:
                            slots.future_keys |= fkeys
                            if sub_slot is not None:
                                slots.batch_dicts.add(sub_slot)
            elif isinstance(n, ast.Call) and isinstance(
                    n.func, ast.Attribute) and n.func.attr in _RESOLVERS:
                recv = n.func.value
                slot = _scope_root_attr(recv, module_dicts)
                if slot is not None:
                    slots.resolved_slots.add(slot)
                elif isinstance(recv, ast.Subscript):
                    key = _const_str(recv.slice)
                    if key is not None:
                        slots.resolved_slots.add(key)
                    root = _scope_root_attr(recv.value, module_dicts)
                    if root is not None:
                        slots.resolved_slots.add(root)
                elif isinstance(recv, ast.Name):
                    slots.resolved_slots |= aliases.get(
                        recv.id, {recv.id})
            elif isinstance(n, ast.Dict):
                slots.future_keys |= _dict_future_keys(n)
    # Pass 2: provenance chains — a store of an already-future value
    # into a self/module dict makes that dict a future dict
    # (self._confirm_prev[key] = b["fut"]).
    for fn in fns:
        for n in _nodes(fn):
            if not isinstance(n, ast.Assign):
                continue
            v = n.value
            value_is_future = (
                _is_future_factory(v)
                or (isinstance(v, ast.Subscript)
                    and _const_str(v.slice) in slots.future_keys)
                or (_scope_root_attr(v, module_dicts)
                    in slots.future_attrs))
            if not value_is_future:
                continue
            for t in n.targets:
                if isinstance(t, ast.Subscript):
                    root = _scope_root_attr(t.value, module_dicts)
                    if root is not None:
                        slots.future_dicts.add(root)
                    key = _const_str(t.slice)
                    if key is not None:
                        slots.future_keys.add(key)
    return slots


def _slots_for(ctx: FileCtx) -> Tuple[_Slots, Dict[int, _Slots]]:
    """(module-scope slots, per-class slots by class node id) — one
    provenance walk per file, memoized on the FileCtx (the Q passes and
    the driver share FileCtx instances)."""
    cached = getattr(ctx, "_cancel_slots", None)
    if cached is None:
        module_dicts = _module_dict_names(ctx.tree)
        mod_fns = _functions_of(ctx.tree)
        mod_slots = _scan_scope(ctx.tree, mod_fns, module_dicts, {})
        mod_slots.resolver_fns = _resolver_fn_names(mod_fns)
        per_class: Dict[int, _Slots] = {}
        _imports, _targets, scans = class_scans(ctx)
        for scan in scans:
            s = _scan_scope(scan.cls, scan.fns, module_dicts,
                            _attr_use_counts(scan.cls))
            s.resolver_fns = _resolver_fn_names(scan.fns) \
                | mod_slots.resolver_fns
            per_class[id(scan.cls)] = s
        cached = (mod_slots, per_class, module_dicts)
        ctx._cancel_slots = cached  # type: ignore[attr-defined]
    return cached[0], cached[1]


def _module_dicts_of(ctx: FileCtx) -> Set[str]:
    _slots_for(ctx)
    return ctx._cancel_slots[2]  # type: ignore[attr-defined]


def _scopes(ctx: FileCtx) -> Iterator[Tuple[ast.AST, List[ast.AST], _Slots]]:
    """Yield (scope node, functions, slots) for the module scope and
    every class."""
    mod_slots, per_class = _slots_for(ctx)
    yield ctx.tree, _functions_of(ctx.tree), mod_slots
    _imports, _targets, scans = class_scans(ctx)
    for scan in scans:
        yield scan.cls, list(scan.fns), per_class[id(scan.cls)]


def _is_shared(slot: str, slots: _Slots, scope: ast.AST) -> bool:
    """Module-level slots are shared by construction; class attrs are
    shared when two or more methods touch them."""
    if isinstance(scope, ast.Module):
        return True
    return len(slots.use_counts.get(slot, set())) >= 2


def _contains_shield(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call) and _call_name(n.func) == "shield":
            return True
    return False


# -- Q01 ---------------------------------------------------------------------


def _local_handles(fn: ast.AST, slots: _Slots, module_dicts: Set[str]
                   ) -> Tuple[Set[str], Set[str]]:
    """(future-handle locals, batch-dict-handle locals) for one
    function: names bound from a shared future slot / batch dict —
    directly (``f = self.fut``), by subscript (``p = self.d[k]``), by
    ``.get`` (``p = self.d.get(k)``), or via a chained store whose
    sibling target is a slot subscript
    (``b = self._batches[key] = {...}``)."""
    fut_handles: Set[str] = set()
    dict_handles: Set[str] = set()

    def source_kind(v: ast.AST) -> Optional[str]:
        root = _scope_root_attr(v, module_dicts)
        if root in slots.future_attrs:
            return "future"
        if isinstance(v, ast.Subscript):
            root = _scope_root_attr(v.value, module_dicts)
            if root in slots.batch_dicts:
                return "dict"
            if root in slots.future_dicts:
                return "future"
            if _const_str(v.slice) in slots.future_keys:
                return "future"
        if isinstance(v, ast.Call) and isinstance(v.func, ast.Attribute) \
                and v.func.attr == "get":
            root = _scope_root_attr(v.func.value, module_dicts)
            if root in slots.batch_dicts:
                return "dict"
            if root in slots.future_dicts:
                return "future"
        return None

    for n in _nodes(fn):
        if not isinstance(n, ast.Assign):
            continue
        kind = source_kind(n.value)
        # chained store: `b = self._batches[key] = {...}` — the dict
        # literal IS the batch record; classify via the sibling target
        if kind is None and isinstance(n.value, ast.Dict) \
                and _dict_future_keys(n.value):
            for t in n.targets:
                if isinstance(t, ast.Subscript) and _scope_root_attr(
                        t.value, module_dicts) in slots.batch_dicts:
                    kind = "dict"
        # tuple-unpack from a shared slot swap: x, self.a = self.a, None
        if kind is None and isinstance(n.value, ast.Tuple):
            for t in n.targets:
                if isinstance(t, ast.Tuple) \
                        and len(t.elts) == len(n.value.elts):
                    for te, ve in zip(t.elts, n.value.elts):
                        k = source_kind(ve)
                        if k == "future" and isinstance(te, ast.Name):
                            fut_handles.add(te.id)
                        elif k == "dict" and isinstance(te, ast.Name):
                            dict_handles.add(te.id)
            continue
        if kind is None:
            continue
        for t in n.targets:
            if isinstance(t, ast.Name):
                (fut_handles if kind == "future" else dict_handles).add(
                    t.id)
    return fut_handles, dict_handles


def check_q01(ctx: FileCtx) -> List[Finding]:
    out: List[Finding] = []
    module_dicts = _module_dicts_of(ctx)
    for scope, fns, slots in _scopes(ctx):
        if not (slots.future_attrs or slots.future_dicts
                or slots.batch_dicts):
            continue
        for fn in fns:
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            fut_handles, dict_handles = _local_handles(
                fn, slots, module_dicts)
            # teardown joins: a handle this function itself .cancel()s
            # is being reaped, not waited on for a result — awaiting it
            # bare is the swap-then-cancel stop() idiom, not a leak of
            # cancellation into live waiters
            cancelled_here: Set[str] = set()
            for n in _nodes(fn):
                if isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Attribute) \
                        and n.func.attr == "cancel":
                    recv = n.func.value
                    if isinstance(recv, ast.Name):
                        cancelled_here.add(recv.id)
                    r = _scope_root_attr(recv, module_dicts)
                    if r is not None:
                        cancelled_here.add(r)
            for n in _nodes(fn):
                if not isinstance(n, ast.Await):
                    continue
                op = n.value
                if _contains_shield(op):
                    continue
                op_root = _scope_root_attr(op, module_dicts) or (
                    op.id if isinstance(op, ast.Name) else None)
                if op_root in cancelled_here:
                    continue
                slot: Optional[str] = None
                desc = ""
                root = _scope_root_attr(op, module_dicts)
                if root in slots.future_attrs \
                        and _is_shared(root, slots, scope):
                    slot, desc = root, f"'{root}'"
                elif isinstance(op, ast.Name):
                    if op.id in fut_handles:
                        slot, desc = op.id, \
                            f"'{op.id}' (bound from a shared slot)"
                elif isinstance(op, ast.Subscript):
                    sroot = _scope_root_attr(op.value, module_dicts)
                    key = _const_str(op.slice)
                    if sroot in slots.future_dicts \
                            and _is_shared(sroot, slots, scope):
                        slot, desc = sroot, f"'{sroot}[...]'"
                    elif isinstance(op.value, ast.Name) \
                            and op.value.id in dict_handles \
                            and (key is None or key in slots.future_keys):
                        slot = key or op.value.id
                        desc = f"'{op.value.id}[{key!r}]' " \
                            "(a shared batch record)"
                if slot is None:
                    continue
                out.append(Finding(
                    ctx.path, n.lineno, UNSHIELDED_SHARED,
                    f"bare await of shared future {desc} — cancelling "
                    "this waiter cancels the future itself and poisons "
                    "every other waiter (client disconnect cancels the "
                    "whole batch); wrap in asyncio.shield(...), or "
                    "noqa with the sole-waiter ownership argument"))
    return out


# -- shared escape-protection machinery (Q02/Q03) ----------------------------


def _stmt_contains(stmts: Sequence[ast.stmt], pred) -> bool:
    for s in stmts:
        for n in ast.walk(s):
            if pred(n):
                return True
    return False


def _handler_catches_base(h: ast.ExceptHandler) -> bool:
    """Bare except, BaseException, or CancelledError in the caught
    set — i.e. the handler sees a cancellation escape."""
    if h.type is None:
        return True
    nodes = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
    names = {_tail(n) for n in nodes}
    return bool(names & {"BaseException", "CancelledError"})


def _enclosing_trys(fn: ast.AST, target: ast.AST) -> List[ast.Try]:
    """Try statements (inside fn, innermost last) whose body lexically
    contains target."""
    chain: List[ast.Try] = []

    def descend(node: ast.AST) -> bool:
        if node is target:
            return True
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, ast.Try):
                if any(descend(s) for s in child.body):
                    chain.append(child)
                    return True
                # target in a handler/else/finally: the try no longer
                # guards it, keep descending without recording
                rest = (list(child.handlers) + list(child.orelse)
                        + list(child.finalbody))
                if any(descend(s) for s in rest):
                    return True
            elif descend(child):
                return True
        return False

    descend(fn)
    chain.reverse()   # outermost first
    return chain


def _escape_protected(fn: ast.AST, await_node: ast.AST, pred) -> bool:
    """True when an exception escaping ``await_node`` is guaranteed to
    pass a ``pred``-satisfying statement inside ``fn``: a finally block
    containing one, or a BaseException/CancelledError/bare handler
    containing one."""
    for t in _enclosing_trys(fn, await_node):
        if _stmt_contains(t.finalbody, pred):
            return True
        for h in t.handlers:
            if _handler_catches_base(h) and _stmt_contains(h.body, pred):
                return True
    return False


# -- Q02 ---------------------------------------------------------------------


def _resolver_fn_names(fns: Sequence[ast.AST]) -> Set[str]:
    """Functions whose body directly resolves a future: a call to one
    of these (``self._fail_pending()``) is itself a resolution — the
    canonical drain-helper shape."""
    out: Set[str] = set()
    for fn in fns:
        for n in _nodes(fn):
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr in _RESOLVERS:
                out.add(fn.name)
                break
    return out


def _resolution_pred(slots: _Slots, module_dicts: Set[str],
                     locals_ok: Optional[Set[str]] = None,
                     resolver_fns: Set[str] = frozenset()):
    def pred(n: ast.AST) -> bool:
        if not isinstance(n, ast.Call):
            return False
        callee = _self_attr(n.func) or (
            n.func.id if isinstance(n.func, ast.Name) else None)
        if callee in resolver_fns:
            return True
        if not (isinstance(n.func, ast.Attribute)
                and n.func.attr in _RESOLVERS):
            return False
        recv = n.func.value
        if locals_ok is not None and isinstance(recv, ast.Name) \
                and recv.id in locals_ok:
            return True
        if _scope_root_attr(recv, module_dicts) is not None:
            return True
        if isinstance(recv, ast.Subscript):
            return True
        return locals_ok is None and isinstance(recv, ast.Name)
    return pred


def _escapes_function(fn: ast.AST, name: str) -> bool:
    """A local future escapes when returned, yielded, stored to
    self/module state, put in a container literal, or passed to a
    call — resolution responsibility moved elsewhere."""
    for n in _nodes(fn):
        if isinstance(n, (ast.Return, ast.Yield, ast.YieldFrom)) \
                and n.value is not None:
            for c in ast.walk(n.value):
                if isinstance(c, ast.Name) and c.id == name:
                    return True
        elif isinstance(n, ast.Assign):
            for t in n.targets:
                if not isinstance(t, ast.Name):   # attr/subscript store
                    for c in ast.walk(t):
                        if isinstance(c, ast.Name) and c.id == name \
                                and isinstance(c.ctx, ast.Load):
                            return True
            if isinstance(n.value, (ast.Dict, ast.List, ast.Tuple,
                                    ast.Set)):
                for c in ast.walk(n.value):
                    if isinstance(c, ast.Name) and c.id == name:
                        return True
        elif isinstance(n, ast.Call):
            for a in list(n.args) + [kw.value for kw in n.keywords]:
                for c in ast.walk(a):
                    if isinstance(c, ast.Name) and c.id == name:
                        return True
    return False


def check_q02(ctx: FileCtx) -> List[Finding]:
    out: List[Finding] = []
    module_dicts = _module_dicts_of(ctx)
    for scope, fns, slots in _scopes(ctx):
        resolver_fns = slots.resolver_fns
        for fn in fns:
            # (a) locally-created, never-escaping, never-resolved
            created_locals: Dict[str, int] = {}
            for n in _nodes(fn):
                if isinstance(n, ast.Assign) \
                        and _is_future_factory(n.value,
                                               include_tasks=False):
                    for t in n.targets:
                        if isinstance(t, ast.Name):
                            created_locals[t.id] = n.lineno
            for name, line in sorted(created_locals.items()):
                resolved = any(
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in _RESOLVERS
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id == name
                    for n in _nodes(fn))
                if not resolved and not _escapes_function(fn, name):
                    out.append(Finding(
                        ctx.path, line, UNRESOLVED_FUTURE,
                        f"future '{name}' is created here but no path "
                        "resolves it (set_result/set_exception/cancel) "
                        "and it never escapes this function — every "
                        "awaiter would hang forever"))

            # (b) resolver functions: an await whose escape skips every
            # resolution strands the future
            res_pred = _resolution_pred(slots, module_dicts,
                                        resolver_fns=resolver_fns)
            res_calls = [n for n in _nodes(fn) if res_pred(n)]
            # the obligation must be established by set_result /
            # set_exception: a function whose only "resolutions" are
            # .cancel() calls is tearing tasks down (swap-then-cancel,
            # stop paths), not completing a future others await
            if not any(isinstance(n, ast.Call)
                       and isinstance(n.func, ast.Attribute)
                       and n.func.attr in ("set_result", "set_exception")
                       for n in res_calls):
                continue
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            first_res = min(n.lineno for n in res_calls)
            for n in _nodes(fn):
                if not isinstance(n, ast.Await):
                    continue
                if n.lineno > max(x.lineno for x in res_calls):
                    continue    # awaits after the last resolution
                if _escape_protected(fn, n, res_pred):
                    continue
                # the await itself may BE the resolved-value producer
                # inside a protected region only; anything else flags
                out.append(Finding(
                    ctx.path, n.lineno, UNRESOLVED_FUTURE,
                    "a CancelledError/BaseException escaping this "
                    "await skips the future resolution at line "
                    f"{first_res} — the future is stranded and its "
                    "waiters hang; resolve in a finally, or catch "
                    "BaseException, resolve, and re-raise"))
                break   # one finding per function is enough signal

        # (c) stored-to-shared futures nothing ever resolves
        for fn in fns:
            for n in _nodes(fn):
                if not (isinstance(n, ast.Assign)
                        and _is_future_factory(n.value,
                                               include_tasks=False)):
                    continue
                for t in n.targets:
                    slot = _scope_root_attr(t, module_dicts)
                    if isinstance(t, ast.Subscript):
                        slot = _scope_root_attr(t.value, module_dicts) \
                            or _const_str(t.slice)
                    if slot is None:
                        continue
                    if slot in slots.resolved_slots:
                        continue
                    # a batch-record store under a future key counts as
                    # resolved when the KEY is a resolved slot
                    out.append(Finding(
                        ctx.path, n.lineno, UNRESOLVED_FUTURE,
                        f"future stored to shared slot '{slot}' but no "
                        "function in this scope ever resolves that "
                        "slot — waiters that join it hang forever"))
    return out


# -- Q03 ---------------------------------------------------------------------


def _self_waited_events(fn: ast.AST, module_dicts: Set[str]) -> Set[str]:
    """Event attrs this function awaits via ``.wait()``: a ``.set()``
    on one of these inside the same function is a self-rearm trigger
    (sync-loop retry patterns), not a hand-off to another task."""
    out: Set[str] = set()
    for n in _nodes(fn):
        if isinstance(n, ast.Await) and isinstance(n.value, ast.Call):
            f = n.value.func
            # allow wait_for(self.E.wait(), t) wrapping
            for c in ast.walk(n.value):
                if isinstance(c, ast.Call) \
                        and isinstance(c.func, ast.Attribute) \
                        and c.func.attr == "wait":
                    root = _scope_root_attr(c.func.value, module_dicts)
                    if root is not None:
                        out.add(root)
            del f
    return out


def _handoff_pred(slots: _Slots, module_dicts: Set[str],
                  self_waited: Set[str] = frozenset()):
    """A statement-level predicate for 'another task observes this':
    future resolution, a fired-style flag flip, or an Event.set()."""
    def pred(n: ast.AST) -> bool:
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute):
            if n.func.attr in ("set_result", "set_exception"):
                return True
            if n.func.attr == "set" and not n.args:
                root = _scope_root_attr(n.func.value, module_dicts)
                if root in slots.event_attrs and root not in self_waited:
                    return True
        if isinstance(n, ast.Assign):
            for t in n.targets:
                key = _const_str(t.slice) if isinstance(t, ast.Subscript) \
                    else None
                name = t.attr if isinstance(t, ast.Attribute) else key
                if name and ("fired" in name or name.endswith("_done")):
                    return True
        return False
    return pred


def _protect_pred(pred, resolver_fns: Set[str]):
    """Protection contexts (finally blocks, BaseException handlers)
    also discharge the hand-off through a drain helper — a call to a
    sibling function that itself resolves futures
    (``self._fail_pending()``).  Detection contexts keep the narrow
    pred: a helper CALL is not itself evidence a hand-off is owed."""
    def protected(n: ast.AST) -> bool:
        if pred(n):
            return True
        if isinstance(n, ast.Call):
            callee = _self_attr(n.func) or (
                n.func.id if isinstance(n.func, ast.Name) else None)
            return callee in resolver_fns
        return False
    return protected


def _describe_handoff(n: ast.AST) -> str:
    if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute):
        return f"{n.func.attr}() at line {n.lineno}"
    return f"hand-off at line {n.lineno}"


def check_q03(ctx: FileCtx) -> List[Finding]:
    out: List[Finding] = []
    module_dicts = _module_dicts_of(ctx)
    for _scope, fns, slots in _scopes(ctx):
        resolver_fns = slots.resolver_fns
        for fn in fns:
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            pred = _handoff_pred(slots, module_dicts,
                                 _self_waited_events(fn, module_dicts))
            protect = _protect_pred(pred, resolver_fns)
            body_stmts = list(fn.body)
            for t in _nodes(fn):
                if not isinstance(t, ast.Try):
                    continue
                # guard shape: broadest handler is Exception; no
                # BaseException/CancelledError split; no finally
                # hand-off
                catches_exc = any(
                    h.type is not None and "Exception" in {
                        _tail(x) for x in (
                            h.type.elts if isinstance(h.type, ast.Tuple)
                            else [h.type])}
                    for h in t.handlers)
                if not catches_exc:
                    continue
                if any(_handler_catches_base(h) for h in t.handlers):
                    continue
                if _stmt_contains(t.finalbody, protect):
                    continue
                awaits = [n for s in t.body for n in ast.walk(s)
                          if isinstance(n, ast.Await)]
                awaits = [a for a in awaits
                          if not any(a in set(ast.walk(s))
                                     for s in t.finalbody)]
                if not awaits:
                    continue
                first_await = min(a.lineno for a in awaits)
                # continuation hand-offs: later in the try body, in a
                # handler, or after the try inside the function
                handoff: Optional[ast.AST] = None
                for s in t.body:
                    for n in ast.walk(s):
                        if pred(n) and n.lineno > first_await:
                            handoff = handoff or n
                for h in t.handlers:
                    for s in h.body:
                        for n in ast.walk(s):
                            if pred(n):
                                handoff = handoff or n
                t_end = getattr(t, "end_lineno", t.lineno) or t.lineno
                for s in body_stmts:
                    if s.lineno <= t_end:
                        continue
                    for n in ast.walk(s):
                        if pred(n):
                            handoff = handoff or n
                if handoff is None:
                    continue
                # an outer protector (finally / BaseException handler
                # performing the hand-off) absolves this try
                probe = awaits[0]
                if _escape_protected(fn, probe, protect):
                    continue
                out.append(Finding(
                    ctx.path, t.lineno, EXCEPTION_GUARD_HANDOFF,
                    "'except Exception' guards the await at line "
                    f"{first_await} but the continuation performs a "
                    f"must-happen hand-off ({_describe_handoff(handoff)})"
                    " — a CancelledError escapes this handler and the "
                    "hand-off never runs, stranding its waiters; catch "
                    "BaseException (resolve, re-raise) or move the "
                    "hand-off to a finally"))
    return out


# -- Q04 ---------------------------------------------------------------------


def _self_supervising(fn: ast.AST, pred) -> bool:
    """The coroutine's own body guarantees the hand-off on death: a
    finally containing one, or a BaseException/bare handler containing
    one, at the top level of some try enclosing its awaits."""
    for t in _nodes(fn):
        if not isinstance(t, ast.Try):
            continue
        if _stmt_contains(t.finalbody, pred):
            return True
        for h in t.handlers:
            if _handler_catches_base(h) and _stmt_contains(h.body, pred):
                return True
    return False


def check_q04(ctx: FileCtx) -> List[Finding]:
    out: List[Finding] = []
    module_dicts = _module_dicts_of(ctx)
    for _scope, fns, slots in _scopes(ctx):
        by_name = {f.name: f for f in fns}
        resolver_fns = slots.resolver_fns
        # names with a done-callback or an await anywhere in the scope
        supervised_names: Set[str] = set()
        awaited_names: Set[str] = set()
        for fn in fns:
            for n in _nodes(fn):
                if isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Attribute) \
                        and n.func.attr == "add_done_callback":
                    recv = n.func.value
                    if isinstance(recv, ast.Name):
                        supervised_names.add(recv.id)
                    attr = _self_attr(recv)
                    if attr:
                        supervised_names.add(attr)
                elif isinstance(n, ast.Await):
                    for c in ast.walk(n.value):
                        if isinstance(c, ast.Name):
                            awaited_names.add(c.id)
                        attr = _self_attr(c)
                        if attr:
                            awaited_names.add(attr)
        for fn in fns:
            for n in _nodes(fn):
                if not (isinstance(n, ast.Call)
                        and _call_name(n.func) in _SPAWNERS and n.args):
                    continue
                coro = n.args[0]
                if not isinstance(coro, ast.Call):
                    continue
                callee = _self_attr(coro.func) or (
                    coro.func.id if isinstance(coro.func, ast.Name)
                    else None)
                target = by_name.get(callee or "")
                if target is None:
                    continue
                tpred = _handoff_pred(
                    slots, module_dicts,
                    _self_waited_events(target, module_dicts))
                if not any(tpred(x) for x in _nodes(target)):
                    continue
                if _self_supervising(target,
                                     _protect_pred(tpred, resolver_fns)):
                    continue
                # handle bound where?
                handle: Optional[str] = None
                parent_assign = getattr(n, "_q04_parent", None)
                # find the assignment statement containing this call
                for fn2 in (fn,):
                    for s in _nodes(fn2):
                        if isinstance(s, ast.Assign) and any(
                                c is n for c in ast.walk(s.value)):
                            for t in s.targets:
                                if isinstance(t, ast.Name):
                                    handle = t.id
                                attr = _self_attr(t)
                                if attr:
                                    handle = attr
                del parent_assign
                if handle is not None and (
                        handle in supervised_names
                        or handle in awaited_names):
                    continue
                out.append(Finding(
                    ctx.path, n.lineno, UNSUPERVISED_HANDOFF_TASK,
                    f"task spawned to run '{callee}' — whose body "
                    "performs a hand-off other tasks wait on — but the "
                    "handle gets no add_done_callback and is never "
                    "awaited, and the body does not self-supervise "
                    "(finally / BaseException hand-off): if the task "
                    "dies its waiters hang silently; supervise the "
                    "handle or make the body resolve on all paths"))
    return out


def check(ctx: FileCtx) -> List[Finding]:
    """All four Q passes at once (unit-test convenience; the driver
    registers them individually so per-pass timings stay honest)."""
    out = (check_q01(ctx) + check_q02(ctx) + check_q03(ctx)
           + check_q04(ctx))
    return sorted(set(out), key=lambda f: (f.line, f.code, f.message))
