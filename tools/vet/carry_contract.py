"""Carry-contract pass: the carry a ``lax.scan`` / ``while_loop`` /
``fori_loop`` body returns must mirror the carry it receives — same
legs, same order, same dtypes.

JAX enforces pytree *structure* equality at trace time, but two
classes of bug survive tracing:

- legs of the same structure/dtype swapped (``return (hist, flight)``
  for a ``(flight, hist)`` carry) trace fine and corrupt both streams
  — exactly the risk of the state/flight/hist carry threading in the
  SWIM scan (gossip/kernel.py ``_run_rounds_impl``);
- a dtype cast on one leg (``x.astype(jnp.float32)``) fails only at
  trace time *if* the shapes disagree too; a silent widening on a
  weakly-typed leg changes numerics without any error.

The pass is deliberately syntactic: it only judges bodies whose carry
handling is statically visible — the first carry parameter unpacked by
a single ``a, b, c = carry`` assignment (or a tuple parameter), and a
``return`` whose carry-out is a tuple *literal*.  Conditional carries
(``return (out if flag else st), y``), bare-name carries
(``return st``) and constructed carries (``_replace(...)``) are
skipped: those shapes are checked by the tracer itself, and guessing
would only produce noise.

- **C01 carry shape drift**: carry-out literal drops, adds, or
  reorders legs relative to the carry-in unpacking.
- **C02 carry dtype drift**: a carry-out leg is an explicit dtype cast
  (``astype`` / ``jnp.int64(...)``-style constructor) of its own
  carry-in leg, or its cast dtype disagrees with the dtype the
  matching leg of a literal ``init`` tuple pins at the call site
  (``jnp.zeros(n, jnp.int32)``, ``jnp.int32(0)``, ...).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from tools.vet.core import FileCtx, Finding
from tools.vet.tracer_purity import _SCAN_NAMES, _collect_defs, _tail

CARRY_SHAPE = "C01"
CARRY_DTYPE = "C02"

_DTYPES = {"int8", "int16", "int32", "int64", "uint8", "uint16",
           "uint32", "uint64", "float16", "float32", "float64",
           "bfloat16", "bool_"}

# loop combinator -> (body arg index, init arg index, carry is first
# element of a (carry, ys) return pair)
_LOOP_SHAPES = {
    "scan": (0, 1, True),
    "while_loop": (1, 2, False),
    "fori_loop": (2, 3, False),
}


@dataclass
class _BodySite:
    fn: ast.AST                   # the body FunctionDef
    loop: str                     # "scan" | "while_loop" | "fori_loop"
    pairs_return: bool            # scan returns (carry, y)
    init: Optional[ast.expr]      # init expr at the call site, if any
    carry_param_index: int        # 0 for scan/while, 1 for fori (i, c)


def _body_sites(tree: ast.Module) -> List[_BodySite]:
    defs = _collect_defs(tree)
    sites: List[_BodySite] = []
    seen: set = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        t = _tail(node.func)
        if t not in _SCAN_NAMES or t not in _LOOP_SHAPES:
            continue
        body_i, init_i, pairs = _LOOP_SHAPES[t]
        if len(node.args) <= body_i:
            continue
        fn_name = _tail(node.args[body_i])
        if fn_name is None or fn_name not in defs:
            continue
        init = node.args[init_i] if len(node.args) > init_i else None
        for info in defs[fn_name]:
            if id(info.node) in seen:
                continue
            seen.add(id(info.node))
            sites.append(_BodySite(
                info.node, t, pairs, init,
                carry_param_index=1 if t == "fori_loop" else 0))
    return sites


def _carry_legs(fn: ast.AST, param_index: int) -> Optional[List[str]]:
    """Names of the carry legs, from ``a, b = carry`` unpacking of the
    carry parameter in the body's first statements.  None when the
    carry is used whole (bare name) — not judgeable."""
    args = fn.args.posonlyargs + fn.args.args
    if len(args) <= param_index:
        return None
    cname = args[param_index].arg
    for st in fn.body:
        if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                and isinstance(st.value, ast.Name) \
                and st.value.id == cname \
                and isinstance(st.targets[0], (ast.Tuple, ast.List)):
            legs = []
            for el in st.targets[0].elts:
                if not isinstance(el, ast.Name):
                    return None
                legs.append(el.id)
            return legs
    return None


def _carry_out(fn: ast.AST, pairs_return: bool) -> List[Tuple[ast.stmt,
                                                              List[ast.expr]]]:
    """(return stmt, carry-out literal elements) for every judgeable
    return.  Non-literal carries are skipped."""
    out = []
    todo: List[ast.AST] = list(fn.body)
    nodes: List[ast.AST] = []
    while todo:  # returns of NESTED defs are not this body's carry
        n = todo.pop()
        nodes.append(n)
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            todo.extend(ast.iter_child_nodes(n))
    for node in nodes:
        if not isinstance(node, ast.Return) or node.value is None:
            continue
        val = node.value
        if pairs_return:
            if not (isinstance(val, ast.Tuple) and len(val.elts) == 2):
                continue
            val = val.elts[0]
        if isinstance(val, (ast.Tuple, ast.List)):
            out.append((node, list(val.elts)))
    return out


def _leg_name(expr: ast.expr) -> Optional[str]:
    """The carry-in name an out-leg passes through, seeing through a
    single dtype cast."""
    if isinstance(expr, ast.Name):
        return expr.id
    cast = _cast_of(expr)
    if cast is not None:
        return cast[0]
    return None


def _cast_of(expr: ast.expr) -> Optional[Tuple[str, str]]:
    """(name, dtype) when ``expr`` is ``name.astype(dt)`` or
    ``jnp.dt(name)``."""
    if not isinstance(expr, ast.Call):
        return None
    t = _tail(expr.func)
    if t == "astype" and isinstance(expr.func, ast.Attribute) \
            and isinstance(expr.func.value, ast.Name) and expr.args:
        dt = _tail(expr.args[0])
        if dt in _DTYPES:
            return expr.func.value.id, dt
    elif t in _DTYPES and len(expr.args) == 1 \
            and isinstance(expr.args[0], ast.Name):
        return expr.args[0].id, t
    return None


def _init_dtypes(init: Optional[ast.expr]) -> Dict[int, str]:
    """leg index -> dtype for the statically readable legs of a
    literal init tuple: ``jnp.int32(0)``, ``jnp.zeros(n, jnp.int32)``,
    ``jnp.full(n, v, jnp.uint8)``, ``dtype=`` keywords."""
    out: Dict[int, str] = {}
    if not isinstance(init, (ast.Tuple, ast.List)):
        return out
    for i, el in enumerate(init.elts):
        if not isinstance(el, ast.Call):
            continue
        t = _tail(el.func)
        if t in _DTYPES:
            out[i] = t
            continue
        for kw in el.keywords:
            if kw.arg == "dtype" and _tail(kw.value) in _DTYPES:
                out[i] = _tail(kw.value)  # type: ignore[assignment]
        if i not in out and t in ("zeros", "ones", "full", "empty"):
            # dtype as trailing positional: zeros(n, jnp.int32)
            for a in el.args[1:]:
                if _tail(a) in _DTYPES:
                    out[i] = _tail(a)  # type: ignore[assignment]
    return out


def _judge(ctx: FileCtx, site: _BodySite, out: List[Finding]) -> None:
    fn = site.fn
    name = getattr(fn, "name", "<body>")
    legs_in = _carry_legs(fn, site.carry_param_index)
    if legs_in is None:
        return
    init_dts = _init_dtypes(site.init)
    for ret, legs_out in _carry_out(fn, site.pairs_return):
        names_out = [_leg_name(e) for e in legs_out]
        if any(n is None for n in names_out):
            continue  # constructed leg — tracer's problem, not ours
        if len(legs_out) != len(legs_in):
            missing = [n for n in legs_in if n not in names_out]
            extra = [n for n in names_out if n not in legs_in]
            detail = []
            if missing:
                detail.append(f"drops {', '.join(repr(m) for m in missing)}")
            if extra:
                detail.append(f"adds {', '.join(repr(e) for e in extra)}")
            out.append(Finding(
                ctx.path, ret.lineno, CARRY_SHAPE,
                f"{site.loop} body '{name}' returns {len(legs_out)} carry "
                f"leg(s) for a {len(legs_in)}-leg carry"
                + (f" ({'; '.join(detail)})" if detail else "")
                + " — the loop re-feeds a misshapen carry"))
            continue
        if set(names_out) == set(legs_in) and names_out != legs_in:
            out.append(Finding(
                ctx.path, ret.lineno, CARRY_SHAPE,
                f"{site.loop} body '{name}' reorders its carry legs "
                f"({', '.join(legs_in)}) -> ({', '.join(names_out)}) — "
                "same-structure legs swap silently and corrupt both "
                "streams"))
            continue
        for i, (el, nm) in enumerate(zip(legs_out, names_out)):
            cast = _cast_of(el)
            if cast is None:
                continue
            src, dt = cast
            if nm != legs_in[i] or src != legs_in[i]:
                continue  # reorder already reported above
            pinned = init_dts.get(i)
            if pinned is not None and pinned == dt:
                continue  # cast back to the pinned dtype: a no-op
            pin = f" (init pins {pinned})" if pinned else ""
            out.append(Finding(
                ctx.path, el.lineno, CARRY_DTYPE,
                f"{site.loop} body '{name}' returns carry leg "
                f"'{legs_in[i]}' cast to {dt}{pin} — carry-out dtype "
                "must match carry-in, or every round re-casts and the "
                "trace either fails late or silently changes numerics"))


def check(ctx: FileCtx) -> List[Finding]:
    if not any(k in ctx.src for k in _SCAN_NAMES):
        return []
    from tools.vet.async_safety import _module_imports
    imports = _module_imports(ctx.tree)
    if imports.get("jax") != "jax" and not any(
            v == "jax" or v.startswith("jax.") for v in imports.values()):
        return []
    findings: List[Finding] = []
    for site in _body_sites(ctx.tree):
        _judge(ctx, site, findings)
    return sorted(set(findings), key=lambda f: (f.line, f.code, f.message))
