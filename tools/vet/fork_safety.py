"""Fork/concurrency safety pass for the serving plane.

PR 7's multi-worker HTTP front spawns workers from the agent process;
the classic way that goes wrong is state that exists *before* the
child processes split off:

- **R01 concurrency before fork**: a thread, event loop, or executor
  started on a code path reachable before an ``os.fork()`` /
  ``os.forkpty()``.  Only the forking thread survives in the child —
  any other thread's locks are frozen mid-state (CPython's
  ``os.fork`` warning made this a DeprecationWarning in 3.12).  The
  pass flags (a) starts earlier in the same function as a fork call
  and (b) module-level starts in any module that forks (import-time
  threads precede every fork).  ``subprocess.Popen`` is exempt by
  construction — it execs, it does not fork-without-exec — which is
  why ``agent/workers.py`` is clean.
- **R02 unlocked cross-context write**: mutable module-level state
  (dict/list/set and friends) mutated from BOTH a coroutine context
  (``async def``) and a thread context (a function handed to
  ``threading.Thread(target=...)``, ``asyncio.to_thread``, or
  ``run_in_executor``) where at least one of the writes holds no
  module-level ``threading.Lock``/``RLock``.  The event loop and the
  thread interleave arbitrarily; dict/list ops are atomic only by
  CPython accident, and compound updates (check-then-set,
  read-modify-write) are not atomic at all.

Scope: R01 gates on ``fork`` appearing in the source; R02 on files
that define module-level mutable containers AND start threads or
define coroutines.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from tools.vet.core import FileCtx, Finding, dotted_name
from tools.vet.tracer_purity import _tail

FORK_AFTER_START = "R01"
UNLOCKED_SHARED_WRITE = "R02"

_FORKS = {"os.fork", "os.forkpty"}
_LOOP_STARTS = {"asyncio.run", "asyncio.new_event_loop",
                "asyncio.get_event_loop"}
_MUTABLE_CTORS = {"dict", "list", "set", "defaultdict", "deque",
                  "Counter", "OrderedDict"}
_MUTATORS = {"append", "add", "update", "pop", "popitem", "setdefault",
             "extend", "remove", "discard", "clear", "insert"}
_THREAD_HANDOFFS = {"to_thread", "run_in_executor"}


def _enclosing_functions(tree: ast.Module) -> List[ast.AST]:
    return [n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]


def _is_thread_ctor(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and _tail(node.func) == "Thread"


def _start_calls(scope: ast.AST) -> List[Tuple[int, str]]:
    """(line, what) for every thread/loop/executor start in scope."""
    thread_names: Set[str] = set()
    for node in ast.walk(scope):
        if isinstance(node, ast.Assign) and _is_thread_ctor(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    thread_names.add(t.id)
    out: List[Tuple[int, str]] = []
    for node in ast.walk(scope):
        if not isinstance(node, ast.Call):
            continue
        dn = dotted_name(node.func)
        if dn in _LOOP_STARTS:
            out.append((node.lineno, f"{dn}()"))
        elif _tail(node.func) == "ThreadPoolExecutor":
            out.append((node.lineno, "ThreadPoolExecutor(...)"))
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr == "start":
            # .attr, not _tail(): the holder may be a Call expression
            # (Thread(...).start()), which dotted-name helpers reject
            holder = node.func.value
            if _is_thread_ctor(holder):
                out.append((node.lineno, "Thread(...).start()"))
            elif isinstance(holder, ast.Name) \
                    and holder.id in thread_names:
                out.append((node.lineno, f"{holder.id}.start()"))
    return out


def _fork_calls(scope: ast.AST) -> List[int]:
    return [n.lineno for n in ast.walk(scope)
            if isinstance(n, ast.Call)
            and dotted_name(n.func) in _FORKS]


def _check_r01(ctx: FileCtx, out: List[Finding]) -> None:
    if "fork" not in ctx.src:
        return
    all_forks = _fork_calls(ctx.tree)
    if not all_forks:
        return
    # (a) starts earlier in the same function as a fork
    for fn in _enclosing_functions(ctx.tree):
        forks = _fork_calls(fn)
        if not forks:
            continue
        first_fork = min(forks)
        for line, what in _start_calls(fn):
            if line < first_fork:
                out.append(Finding(
                    ctx.path, line, FORK_AFTER_START,
                    f"{what} started before the os.fork() at line "
                    f"{first_fork} — only the forking thread survives "
                    "in the child; any lock another thread holds is "
                    "frozen forever (start workers first, or exec)"))
    # (b) module-level starts in a forking module (run at import time,
    # before any fork can happen)
    in_function: Set[int] = set()
    for fn in _enclosing_functions(ctx.tree):
        for sub in ast.walk(fn):
            in_function.add(id(sub))
    module_starts = [
        (line, what) for line, what in _start_calls(ctx.tree)
        if not any(id(node) in in_function
                   for node in ast.walk(ctx.tree)
                   if isinstance(node, ast.Call)
                   and node.lineno == line)]
    for line, what in module_starts:
        out.append(Finding(
            ctx.path, line, FORK_AFTER_START,
            f"module-level {what} in a module that calls os.fork() — "
            "import-time threads precede every fork; start them "
            "lazily after the workers split"))


def _module_mutables(tree: ast.Module) -> Dict[str, int]:
    """Module-level ``NAME = <mutable container>`` -> line."""
    out: Dict[str, int] = {}
    for node in tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        v = node.value
        if isinstance(v, (ast.Dict, ast.List, ast.Set)) \
                or (isinstance(v, ast.Call)
                    and _tail(v.func) in _MUTABLE_CTORS):
            out[node.targets[0].id] = node.lineno
    return out


def _module_locks(tree: ast.Module) -> Set[str]:
    out: Set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call) \
                and _tail(node.value.func) in ("Lock", "RLock"):
            out.add(node.targets[0].id)
    return out


def _thread_entry_names(tree: ast.Module) -> Set[str]:
    """Function names handed to a thread: Thread(target=f),
    to_thread(f, ...), run_in_executor(None, f, ...)."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if _is_thread_ctor(node):
            for kw in node.keywords:
                if kw.arg == "target":
                    name = _tail(kw.value)
                    if name:
                        out.add(name)
        tail = _tail(node.func)
        if tail == "to_thread" and node.args:
            name = _tail(node.args[0])
            if name:
                out.add(name)
        elif tail == "run_in_executor" and len(node.args) >= 2:
            name = _tail(node.args[1])
            if name:
                out.add(name)
    return out


def _mutations(fn: ast.AST, globals_: Set[str],
               locks: Set[str]) -> List[Tuple[str, int, bool]]:
    """(name, line, locked) for every mutation of a module global
    inside fn.  ``locked`` = the mutation sits under ``with <lock>:``
    for a module-level Lock/RLock."""
    lock_spans: List[Tuple[int, int]] = []
    for node in ast.walk(fn):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                name = _tail(item.context_expr)
                if name in locks:
                    end = getattr(node, "end_lineno", node.lineno)
                    lock_spans.append((node.lineno, end))

    def locked(line: int) -> bool:
        return any(a <= line <= b for a, b in lock_spans)

    out: List[Tuple[str, int, bool]] = []
    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id in globals_:
                    out.append((t.value.id, node.lineno,
                                locked(node.lineno)))
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATORS \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id in globals_:
            out.append((node.func.value.id, node.lineno,
                        locked(node.lineno)))
    return out


def _check_r02(ctx: FileCtx, out: List[Finding]) -> None:
    mutables = _module_mutables(ctx.tree)
    if not mutables:
        return
    locks = _module_locks(ctx.tree)
    thread_entries = _thread_entry_names(ctx.tree)
    names = set(mutables)
    # name -> context -> list of (line, locked)
    writes: Dict[str, Dict[str, List[Tuple[int, bool]]]] = {}
    for fn in _enclosing_functions(ctx.tree):
        if isinstance(fn, ast.AsyncFunctionDef):
            context = "async"
        elif fn.name in thread_entries:
            context = "thread"
        else:
            continue
        for name, line, is_locked in _mutations(fn, names, locks):
            writes.setdefault(name, {}).setdefault(
                context, []).append((line, is_locked))
    for name, by_ctx in sorted(writes.items()):
        if "async" not in by_ctx or "thread" not in by_ctx:
            continue
        unlocked = [(line, c) for c in ("async", "thread")
                    for line, is_locked in by_ctx[c] if not is_locked]
        for line, context in sorted(unlocked):
            out.append(Finding(
                ctx.path, line, UNLOCKED_SHARED_WRITE,
                f"module-level '{name}' (line {mutables[name]}) is "
                f"written from both coroutine and thread contexts; "
                f"this {context}-context write holds no module-level "
                "threading.Lock — compound updates interleave with "
                "the other context (guard every writer with one "
                "lock)"))


def check(ctx: FileCtx) -> List[Finding]:
    out: List[Finding] = []
    _check_r01(ctx, out)
    _check_r02(ctx, out)
    return sorted(set(out), key=lambda f: (f.line, f.code, f.message))
