"""JAX tracer-purity pass: flag host-Python escapes inside code that
runs under a tracer.

An impure ``lax.scan`` body breaks the kernel's replayability (the
Lifeguard cross-validation gates compare kernel runs bit-for-bit), and
host round-trips inside jit silently insert device syncs — both
invisible to pytest because tracing "works" and merely produces wrong
or slow programs.

Roots are functions reachable from a tracing entry point:

- decorated with ``@jax.jit`` / ``@jit`` /
  ``@functools.partial(jax.jit, static_argnames=(...))`` (static args
  are exempt from traced-value checks);
- passed callable-first to ``jax.jit`` / ``lax.scan`` / ``shard_map``
  / ``jax.vmap`` / ``jax.pmap`` call sites (scan marks the function as
  a *scan body* for J04).

A module-level call graph (simple-name edges) extends the root set to
helpers the kernel calls.  Within traced code:

- **J01 host round-trip**: ``.item()`` / ``.tolist()`` anywhere, and
  ``float()`` / ``int()`` / ``bool()`` applied to a value derived from
  a traced (non-static) parameter.  Each forces a device sync and
  fails under abstract tracers.
- **J02 numpy-in-trace**: ``np.*`` compute calls on the traced path —
  they escape the tracer and freeze the value at trace time (dtype
  constructors like ``np.int32``/``np.iinfo`` are fine and exempt).
- **J03 impure read**: stdlib ``random.*`` / ``time.*`` /
  ``datetime.*`` reads — trace-time constants that make compiled runs
  non-replayable (``jax.random`` is of course exempt; its chain roots
  at ``jax``).
- **J04 scan-body mutation**: assignment through ``nonlocal`` /
  ``global``, stores to attributes/subscripts of names free in the
  scan body (e.g. ``self.x = …``), or mutating method calls
  (``.append`` …) on free names.  The scan body runs ONCE at trace
  time — the mutation happens once, not per step, and the
  cross-validation guarantees are void.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from tools.vet.core import FileCtx, Finding, dotted_name

HOST_ROUNDTRIP = "J01"
NUMPY_IN_TRACE = "J02"
IMPURE_READ = "J03"
SCAN_MUTATION = "J04"

_TRACING_WRAPPERS = {"jit", "vmap", "pmap", "shard_map", "checkpoint",
                     "remat"}
_SCAN_NAMES = {"scan", "fori_loop", "while_loop", "associative_scan"}

_NP_DTYPE_OK = {
    "int8", "int16", "int32", "int64", "uint8", "uint16", "uint32",
    "uint64", "float16", "float32", "float64", "bool_", "dtype",
    "iinfo", "finfo",
}

_TIME_READS = {"time", "monotonic", "perf_counter", "time_ns",
               "monotonic_ns", "perf_counter_ns"}

_MUTATORS = {"append", "extend", "add", "update", "pop", "remove",
             "clear", "setdefault", "insert", "discard"}


@dataclass
class _DefInfo:
    node: ast.AST                       # FunctionDef | AsyncFunctionDef
    name: str
    static: Set[str] = field(default_factory=set)
    is_root: bool = False
    is_scan_body: bool = False
    calls: Set[str] = field(default_factory=set)


def _tail(node: ast.AST) -> Optional[str]:
    dn = dotted_name(node)
    return dn.rsplit(".", 1)[-1] if dn else None


def _static_argnames(call: ast.Call) -> Set[str]:
    for kw in call.keywords:
        if kw.arg in ("static_argnames", "static_argnums"):
            out: Set[str] = set()
            for c in ast.walk(kw.value):
                if isinstance(c, ast.Constant) and isinstance(c.value, str):
                    out.add(c.value)
            return out
    return set()


def _params(fn) -> Set[str]:
    a = fn.args
    names = {x.arg for x in a.posonlyargs + a.args + a.kwonlyargs}
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)
    return names


def _collect_defs(tree: ast.Module) -> Dict[str, List[_DefInfo]]:
    defs: Dict[str, List[_DefInfo]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info = _DefInfo(node, node.name)
            for inner in ast.walk(node):
                if isinstance(inner, ast.Call):
                    t = _tail(inner.func)
                    if t:
                        info.calls.add(t)
            defs.setdefault(node.name, []).append(info)
    return defs


def _mark_roots(tree: ast.Module, defs: Dict[str, List[_DefInfo]]) -> None:
    # decorator form
    for infos in defs.values():
        for info in infos:
            for dec in info.node.decorator_list:
                t = _tail(dec if not isinstance(dec, ast.Call) else dec.func)
                if t in _TRACING_WRAPPERS:
                    info.is_root = True
                elif t == "partial" and isinstance(dec, ast.Call) \
                        and dec.args \
                        and _tail(dec.args[0]) in _TRACING_WRAPPERS:
                    info.is_root = True
                    info.static |= _static_argnames(dec)
    # call-site form: jit(f), lax.scan(f, ...), shard_map(f, ...)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        t = _tail(node.func)
        if t not in _TRACING_WRAPPERS and t not in _SCAN_NAMES:
            continue
        fn_name = _tail(node.args[0])
        if fn_name is None or fn_name not in defs:
            continue
        for info in defs[fn_name]:
            info.is_root = True
            if t in _SCAN_NAMES:
                info.is_scan_body = True
            if t in _TRACING_WRAPPERS:
                info.static |= _static_argnames(node)


def _reachable(defs: Dict[str, List[_DefInfo]]) -> List[_DefInfo]:
    """Roots plus everything transitively called from them, by simple
    name.  Statics do not propagate: a helper may be called with traced
    values from one site and static from another, so only the
    decorated root's own params are exempted."""
    out: List[_DefInfo] = []
    seen: Set[int] = set()
    todo = [i for infos in defs.values() for i in infos if i.is_root]
    while todo:
        info = todo.pop()
        if id(info) in seen:
            continue
        seen.add(id(info))
        out.append(info)
        for callee in info.calls:
            todo.extend(defs.get(callee, []))
    return out


class _TracedWalker(ast.NodeVisitor):
    """Flag walk over ONE traced def, tracking the set of names known
    to derive from traced params (params minus statics, plus a small
    assignment fixpoint computed by the caller)."""

    def __init__(self, ctx: FileCtx, imports: Dict[str, str],
                 traced_names: Set[str], fn_name: str) -> None:
        self.ctx = ctx
        self.imports = imports
        self.traced = traced_names
        self.fn_name = fn_name
        self.findings: List[Finding] = []

    def _emit(self, node: ast.AST, code: str, msg: str) -> None:
        self.findings.append(Finding(self.ctx.path, node.lineno, code, msg))

    def visit_Call(self, node: ast.Call) -> None:
        t = _tail(node.func)
        dn = dotted_name(node.func) or ""
        root = dn.split(".")[0] if dn else ""
        origin = self.imports.get(root, root)
        # J01: device -> host escapes (.attr directly: the chain may
        # root at a call, e.g. x.sum().item(), where dotted_name is None)
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("item", "tolist"):
            self._emit(node, HOST_ROUNDTRIP,
                       f".{node.func.attr}() inside traced function "
                       f"'{self.fn_name}' forces a device sync and fails "
                       "under jit")
        elif isinstance(node.func, ast.Name) \
                and node.func.id in ("float", "int", "bool") and node.args:
            refs = {n.id for n in ast.walk(node.args[0])
                    if isinstance(n, ast.Name)}
            if refs & self.traced:
                self._emit(
                    node, HOST_ROUNDTRIP,
                    f"{node.func.id}() on traced value in "
                    f"'{self.fn_name}' — concretizes a tracer (use jnp "
                    "ops, or mark the argument static)")
        # J02: numpy compute on the traced path
        if origin == "numpy" and dn.count(".") == 1 \
                and t not in _NP_DTYPE_OK:
            self._emit(node, NUMPY_IN_TRACE,
                       f"{dn}() inside traced function '{self.fn_name}' "
                       "escapes the tracer (freezes at trace time); "
                       "use the jnp equivalent")
        # J03: impure host reads baked in at trace time
        if origin == "random" and dn.startswith("random."):
            self._emit(node, IMPURE_READ,
                       f"stdlib {dn}() inside traced function "
                       f"'{self.fn_name}' is a trace-time constant; "
                       "use jax.random with a threaded key")
        elif origin == "time" and t in _TIME_READS:
            self._emit(node, IMPURE_READ,
                       f"{dn}() inside traced function '{self.fn_name}' "
                       "is read once at trace time, not per call")
        elif origin == "datetime":
            self._emit(node, IMPURE_READ,
                       f"{dn}() inside traced function '{self.fn_name}' "
                       "is read once at trace time, not per call")
        self.generic_visit(node)


def _scan_locals(fn) -> Set[str]:
    """Params + every Name the body stores + nested def names, stopping
    at nested function boundaries (their locals are their own)."""
    names = _params(fn)
    todo = list(fn.body)
    while todo:
        node = todo.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            names.add(node.name)
            continue
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            names.add(node.id)
        todo.extend(ast.iter_child_nodes(node))
    return names


def _check_scan_mutations(ctx: FileCtx, info: _DefInfo,
                          out: List[Finding]) -> None:
    fn = info.node
    local = _scan_locals(fn)

    def root_name(node: ast.AST) -> Optional[str]:
        while isinstance(node, (ast.Attribute, ast.Subscript)):
            node = node.value
        return node.id if isinstance(node, ast.Name) else None

    for node in ast.walk(fn):
        if isinstance(node, (ast.Nonlocal, ast.Global)):
            out.append(Finding(
                ctx.path, node.lineno, SCAN_MUTATION,
                f"'{type(node).__name__.lower()}' mutation inside scan "
                f"body '{info.name}' runs once at trace time, not per "
                "step — thread the value through the carry"))
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, (ast.Attribute, ast.Subscript)):
                    rn = root_name(t)
                    if rn is not None and rn not in local:
                        out.append(Finding(
                            ctx.path, t.lineno, SCAN_MUTATION,
                            f"store to nonlocal '{rn}' inside scan body "
                            f"'{info.name}' happens at trace time only — "
                            "thread it through the carry"))
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATORS:
            rn = root_name(node.func.value)
            if rn is not None and rn not in local:
                out.append(Finding(
                    ctx.path, node.lineno, SCAN_MUTATION,
                    f"mutating call .{node.func.attr}() on nonlocal "
                    f"'{rn}' inside scan body '{info.name}' happens at "
                    "trace time only — thread it through the carry"))


def _traced_name_fixpoint(fn, traced: Set[str]) -> Set[str]:
    """Seed with non-static params; absorb simple ``y = f(x)`` chains
    whose right side references a traced name (two rounds suffice for
    the straight-line kernel style)."""
    assigns = [n for n in ast.walk(fn) if isinstance(n, ast.Assign)]
    for _ in range(2):
        changed = False
        for node in assigns:
            refs = {n.id for n in ast.walk(node.value)
                    if isinstance(n, ast.Name)}
            if not (refs & traced):
                continue
            for t in node.targets:
                for el in ast.walk(t):
                    if isinstance(el, ast.Name) and el.id not in traced:
                        traced.add(el.id)
                        changed = True
        if not changed:
            break
    return traced


def check(ctx: FileCtx) -> List[Finding]:
    src_has_jax = "jax" in ctx.src
    if not src_has_jax:
        return []
    from tools.vet.async_safety import _module_imports
    imports = _module_imports(ctx.tree)
    if imports.get("jax") != "jax" and not any(
            v == "jax" or v.startswith("jax.") for v in imports.values()):
        return []
    defs = _collect_defs(ctx.tree)
    _mark_roots(ctx.tree, defs)
    findings: List[Finding] = []
    for info in _reachable(defs):
        traced = _traced_name_fixpoint(
            info.node, _params(info.node) - info.static)
        walker = _TracedWalker(ctx, imports, traced, info.name)
        for stmt in info.node.body:
            walker.visit(stmt)
        findings.extend(walker.findings)
        if info.is_scan_body:
            _check_scan_mutations(ctx, info, findings)
    # a helper reachable from two roots would double-report
    return sorted(set(findings), key=lambda f: (f.line, f.code, f.message))
