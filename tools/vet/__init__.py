"""`make vet`'s analyzer: ten passes over one shared parse.

The ``go vet`` role for a tree with no third-party linter.  Passes
(each module documents its codes and heuristics):

- ``names``            N01 undefined name, N02 unused import
- ``async-safety``     A01 unawaited coroutine, A02 dropped task,
                       A03 blocking call in coroutine, A04 threading
                       lock in coroutine
- ``tracer-purity``    J01 host round-trip, J02 numpy-in-trace,
                       J03 impure read, J04 scan-body mutation
- ``wire-schema``      W01 written-never-read, W02 read-never-written
- ``exception-hygiene``  E01 bare except, E02 silent broad handler,
                       E03 swallowed CancelledError
- ``donation``         D01 use-after-donate, D02 donated
                       global/attribute (cross-file; kill rules:
                       rebind, del, ``jax.block_until_ready``)
- ``shard-exact``      S01 inexact collective, S02 ungated replicated
                       write, S03 non-permutation ppermute table
- ``carry-contract``   C01 carry shape drift, C02 carry dtype drift
                       for scan/while/fori bodies
- ``overflow``         O01 unbounded int32 accumulator at paper scale,
                       O02 mixed-width integer arithmetic

The last four are the flow-sensitive JAX-semantics passes (this PR's
kernel-safety analyzer); ``--fast`` skips them for inner-loop runs.

Suppression: ``# noqa: CODE[,CODE…]`` per line (blanket ``# noqa``
still works), or an entry in ``tools/vet/baseline.txt`` for accepted
legacy findings.  Run: ``python -m tools.vet <paths>``; add
``--format json`` / ``--report vet_report.json`` for the CI artifact.
"""

from tools.vet.core import FileCtx, Finding, Pass  # noqa: F401 (re-export)
