"""`make vet`'s analyzer: six passes over one shared parse.

The ``go vet`` role for a tree with no third-party linter.  Passes
(each module documents its codes and heuristics):

- ``names``            N01 undefined name, N02 unused import
- ``async-safety``     A01 unawaited coroutine, A02 dropped task,
                       A03 blocking call in coroutine, A04 threading
                       lock in coroutine
- ``tracer-purity``    J01 host round-trip, J02 numpy-in-trace,
                       J03 impure read, J04 scan-body mutation
- ``wire-schema``      W01 written-never-read, W02 read-never-written
- ``exception-hygiene``  E01 bare except, E02 silent broad handler,
                       E03 swallowed CancelledError

Suppression: ``# noqa: CODE[,CODE…]`` per line (blanket ``# noqa``
still works), or an entry in ``tools/vet/baseline.txt`` for accepted
legacy findings.  Run: ``python -m tools.vet <paths>``.
"""

from tools.vet.core import FileCtx, Finding, Pass  # noqa: F401 (re-export)
