"""Driver: collect files, parse once, run the passes, apply noqa +
baseline suppression, print findings and the per-pass summary.

Exit codes (the ``make vet`` contract): 0 clean, 1 findings, 2 a file
failed to parse (syntax errors are compileall's job, but we must not
crash past them silently).
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.vet import (async_safety, cancel_safety, carry_contract,
                       donation, exceptions, fork_safety, interleave,
                       names, overflow, pallas_safety, role_transition,
                       shard_exact, table_drift, tracer_purity,
                       wire_schema)
from tools.vet.core import (FileCtx, Finding, Pass, collect_files,
                            load_baseline, write_baseline)

DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.txt"

PASSES: List[Pass] = [
    Pass("names", codes=("N01", "N02"), check=names.check),
    Pass("async-safety", codes=("A01", "A02", "A03", "A04"),
         check=async_safety.check),
    Pass("tracer-purity", codes=("J01", "J02", "J03", "J04"),
         check=tracer_purity.check),
    Pass("wire-schema", codes=("W01", "W02"),
         check_project=wire_schema.check_project),
    Pass("exception-hygiene", codes=("E01", "E02", "E03"),
         check=exceptions.check),
    Pass("donation", codes=("D01", "D02"),
         check_project=donation.check_project),
    Pass("shard-exact", codes=("S01", "S02", "S03"),
         check=shard_exact.check),
    Pass("carry-contract", codes=("C01", "C02"),
         check=carry_contract.check),
    Pass("overflow", codes=("O01", "O02"), check=overflow.check),
    Pass("pallas-safety", codes=("P01", "P02", "P03", "P04"),
         check=pallas_safety.check),
    Pass("table-drift", codes=("K01", "K02"),
         check_project=table_drift.check_project),
    Pass("fork-safety", codes=("R01", "R02"), check=fork_safety.check),
    Pass("interleave", codes=("X01", "X02", "X03", "X04"),
         check=interleave.check),
    Pass("role-transition", codes=("T01", "T02"),
         check=role_transition.check),
    Pass("cancel-shield", codes=("Q01",), check=cancel_safety.check_q01),
    Pass("future-resolution", codes=("Q02",),
         check=cancel_safety.check_q02),
    Pass("cancel-handoff", codes=("Q03",),
         check=cancel_safety.check_q03),
    Pass("handoff-supervision", codes=("Q04",),
         check=cancel_safety.check_q04),
]

# pyvet backwards-compat: the two legacy passes ride in "names"
LEGACY_PASSES = ("names",)

# the flow-sensitive JAX-semantics passes: `--fast` (make vet-fast)
# skips these for inner-loop runs
FLOW_PASSES = ("donation", "shard-exact", "carry-contract", "overflow")

# role-transition invariant spans the raft core and its lease/read
# consumers: touching a consumer must re-vet the core (and vice versa)
ROLE_TRANSITION_GROUP = (
    "consul_tpu/consensus/raft.py",
    "consul_tpu/server/server.py",
    "consul_tpu/agent/hotpath.py",
)

# fused write path (PR 18): the batched reconciler mirrors the
# sequential leader handlers against the plane's event batches and the
# FSM's BATCH envelope — touching any leg must re-vet all three
FUSED_RECONCILE_GROUP = (
    "consul_tpu/agent/reconcile.py",
    "consul_tpu/gossip/plane.py",
    "consul_tpu/consensus/fsm.py",
)

# `make vet` refuses to let the growing pass count rot the inner loop:
# total analyzer time above this multiple of the previous recorded run
# (the vet_report.json artifact) fails the build
TIME_GUARD_FACTOR = 1.5
# absolute slack so a near-zero baseline (tiny --changed run recorded
# by accident) or scheduler jitter cannot flake the guard
TIME_GUARD_SLACK_MS = 500.0


@dataclass
class VetResult:
    findings: List[Finding] = field(default_factory=list)   # reported
    baselined: int = 0
    stale_baseline: List[str] = field(default_factory=list)
    parse_errors: List[Finding] = field(default_factory=list)
    per_pass: Dict[str, int] = field(default_factory=dict)
    per_pass_ms: Dict[str, float] = field(default_factory=dict)
    files: int = 0

    @property
    def rc(self) -> int:
        if self.parse_errors:
            return 2
        return 1 if self.findings else 0


def partner_groups() -> List[Tuple[str, ...]]:
    """Path-suffix groups a cross-file pass compares as a unit: when
    ``--changed`` touches one member, the whole group must be vetted
    or the comparison is against thin air."""
    groups: List[Tuple[str, ...]] = [tuple(wire_schema.WIRE_MODULES)]
    for g in table_drift.GROUPS:
        groups.append(tuple([g.governing.suffix]
                            + [s.suffix for s in g.satellites]))
    groups.append(table_drift.ENV_GATE_PARTNERS)
    groups.append(ROLE_TRANSITION_GROUP)
    groups.append(FUSED_RECONCILE_GROUP)
    return groups


def _suffix_match(path: str, suffix: str) -> bool:
    return path == suffix or path.endswith("/" + suffix)


def expand_partners(changed: Set[str],
                    all_paths: Sequence[str]) -> Set[str]:
    """The changed set plus every cross-file partner group any changed
    file belongs to.  Donation tracking is deliberately NOT expanded
    (donors can live anywhere jax is imported) — the full ``make vet``
    stays the authority; ``--changed`` is the cheap pre-commit gate."""
    only = {p for p in all_paths if p in changed}
    for group in partner_groups():
        members = [p for p in all_paths
                   if any(_suffix_match(p, s) for s in group)]
        if any(p in only for p in members):
            only.update(members)
    return only


def changed_paths() -> Set[str]:
    """Repo-relative .py files touched per git (worktree vs HEAD, plus
    untracked).  Run from the repo root so the paths line up with the
    vet display paths."""
    out: Set[str] = set()
    for cmd in (["git", "diff", "--name-only", "HEAD"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            r = subprocess.run(cmd, capture_output=True, text=True)
        except OSError:
            continue
        if r.returncode == 0:
            out.update(line.strip() for line in r.stdout.splitlines()
                       if line.strip())
    return {p for p in out if p.endswith(".py")}


def run_vet(roots: Sequence[str],
            passes: Optional[Sequence[str]] = None,
            baseline_path: Optional[Path] = DEFAULT_BASELINE,
            update_baseline: bool = False,
            only: Optional[Set[str]] = None) -> VetResult:
    result = VetResult()
    selected = [p for p in PASSES if passes is None or p.name in passes]
    ctxs: List[FileCtx] = []
    for path in collect_files(roots):
        display = path.as_posix()
        try:
            ctxs.append(FileCtx.load(path, display))
        except SyntaxError as e:
            result.parse_errors.append(Finding(
                display, e.lineno or 0, "P00", f"syntax error: {e.msg}"))
    if only is not None:
        only = expand_partners(only, [c.path for c in ctxs])
        result.parse_errors = [f for f in result.parse_errors
                               if f.path in only]
    result.files = len(ctxs) if only is None else \
        sum(1 for c in ctxs if c.path in only)
    by_path = {c.path: c for c in ctxs}

    raw: List[Finding] = []
    for p in selected:
        t0 = time.perf_counter()
        if only is not None and p.check is not None:
            # per-file passes only need the changed files; project
            # passes see everything and get their findings filtered
            found = p.run([c for c in ctxs if c.path in only])
        else:
            found = p.run(ctxs)
        if only is not None:
            found = [f for f in found if f.path in only]
        # Findings may land on non-Python artifacts (README.md from the
        # env-gate group) — no FileCtx, so no noqa channel; keep as-is.
        kept = [f for f in found
                if f.path not in by_path
                or not by_path[f.path].suppressed(f.line, f.code)]
        result.per_pass[p.name] = len(kept)
        result.per_pass_ms[p.name] = round(
            (time.perf_counter() - t0) * 1000.0, 2)
        raw.extend(kept)

    baseline = load_baseline(baseline_path) if baseline_path else []
    if update_baseline and baseline_path is not None:
        write_baseline(baseline_path, raw)
        baseline = load_baseline(baseline_path)
    matched: set = set()
    for f in raw:
        key = f.baseline_key()
        if key in baseline:
            matched.add(key)
            result.baselined += 1
            # summary counts report what the pass FOUND; subtract the
            # baselined share so `per_pass` mirrors the printed list
            for p in selected:
                if f.code in p.codes:
                    result.per_pass[p.name] -= 1
                    break
        else:
            result.findings.append(f)
    # A partial run (--changed / explicit subset) cannot judge
    # staleness: entries for un-vetted files would all look stale.
    result.stale_baseline = [] if only is not None or passes is not None \
        else [k for k in baseline if k not in matched]
    result.findings.sort(key=lambda f: (f.path, f.line, f.code))
    return result


def result_to_json(result: VetResult) -> Dict[str, object]:
    """The machine-readable CI artifact (``--format json`` and
    ``--report``): everything the text output says, keyed for tooling."""
    def enc(f: Finding) -> Dict[str, object]:
        return {"path": f.path, "line": f.line, "code": f.code,
                "message": f.message}
    return {
        "files": result.files,
        "rc": result.rc,
        "findings": [enc(f) for f in result.findings],
        "parse_errors": [enc(f) for f in result.parse_errors],
        "per_pass": dict(result.per_pass),
        "per_pass_ms": dict(result.per_pass_ms),
        "baselined": result.baselined,
        "stale_baseline": list(result.stale_baseline),
    }


def prior_total_ms(report_path: Path) -> float:
    """Total analyzer time recorded by the previous run's report
    artifact, or 0.0 when there is none (first run: guard disarmed)."""
    try:
        data = json.loads(report_path.read_text(encoding="utf-8"))
    except (OSError, ValueError):
        return 0.0
    per_pass_ms = data.get("per_pass_ms")
    if not isinstance(per_pass_ms, dict):
        return 0.0
    try:
        return float(sum(per_pass_ms.values()))
    except TypeError:
        return 0.0


def time_guard_exceeded(prior_ms: float, total_ms: float) -> bool:
    """True when this run blew the wall-time budget: more than
    TIME_GUARD_FACTOR × the previous recorded total (plus absolute
    slack).  A zero/absent baseline disarms the guard."""
    if prior_ms <= 0.0:
        return False
    return total_ms > prior_ms * TIME_GUARD_FACTOR + TIME_GUARD_SLACK_MS


def slowest_passes(per_pass_ms: Dict[str, float], n: int = 2
                   ) -> List[Tuple[str, float]]:
    return sorted(per_pass_ms.items(), key=lambda kv: -kv[1])[:n]


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tools.vet",
        description="multi-pass static analyzer (see tools/vet/*.py)")
    ap.add_argument("paths", nargs="*",
                    default=["consul_tpu", "tests"],
                    help="files or directories to analyze")
    ap.add_argument("--passes", default=None,
                    help="comma-separated subset of: "
                         + ",".join(p.name for p in PASSES))
    ap.add_argument("--fast", action="store_true",
                    help="skip the flow-sensitive JAX passes ("
                         + ", ".join(FLOW_PASSES) + ") for inner-loop use")
    ap.add_argument("--changed", action="store_true",
                    help="vet only files touched per git (worktree vs "
                         "HEAD + untracked) plus their cross-file pass "
                         "partners (wire surface, dispatch-table "
                         "groups); exit-code contract unchanged "
                         "(0 clean / 1 findings / 2 parse error); run "
                         "from the repo root")
    ap.add_argument("--baseline", default=str(DEFAULT_BASELINE),
                    help="baseline file (default tools/vet/baseline.txt)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline file")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept all current findings into the baseline")
    ap.add_argument("--format", choices=("text", "json"), default="text",
                    help="findings output format (default text)")
    ap.add_argument("--report", default=None, metavar="PATH",
                    help="also write the JSON report to PATH "
                         "(the vet_report.json CI artifact)")
    ap.add_argument("--time-guard", action="store_true",
                    help="fail (exit 2) when total analyzer time "
                         f"exceeds {TIME_GUARD_FACTOR}x the previous "
                         "run recorded at --report, so the pass count "
                         "can grow without rotting the inner loop")
    args = ap.parse_args(argv)

    passes = None
    if args.passes:
        passes = [s.strip() for s in args.passes.split(",") if s.strip()]
        known = {p.name for p in PASSES}
        unknown = [s for s in passes if s not in known]
        if unknown:
            print(f"vet: unknown pass(es): {', '.join(unknown)}",
                  file=sys.stderr)
            return 2
    if args.fast:
        passes = [p.name for p in PASSES
                  if (passes is None or p.name in passes)
                  and p.name not in FLOW_PASSES]

    only: Optional[Set[str]] = None
    if args.changed:
        only = changed_paths()

    prior_ms = prior_total_ms(Path(args.report)) \
        if args.time_guard and args.report else 0.0

    result = run_vet(
        args.paths, passes=passes,
        baseline_path=None if args.no_baseline else Path(args.baseline),
        update_baseline=args.write_baseline, only=only)

    if args.report:
        Path(args.report).write_text(
            json.dumps(result_to_json(result), indent=2) + "\n",
            encoding="utf-8")
    total_ms = sum(result.per_pass_ms.values())
    guard_tripped = args.time_guard and time_guard_exceeded(prior_ms,
                                                            total_ms)
    if guard_tripped:
        top = ", ".join(f"{name} ({ms:.0f} ms)" for name, ms
                        in slowest_passes(result.per_pass_ms))
        print(f"vet: time guard: {total_ms:.0f} ms total exceeds "
              f"{TIME_GUARD_FACTOR}x the recorded {prior_ms:.0f} ms "
              f"baseline; slowest passes: {top}", file=sys.stderr)
    if args.format == "json":
        print(json.dumps(result_to_json(result), indent=2))
        return 2 if guard_tripped else result.rc

    for f in result.parse_errors + result.findings:
        print(f.render())
    for name, count in result.per_pass.items():
        print(f"vet: {name}: {count} finding(s)", file=sys.stderr)
    extras = []
    if result.baselined:
        extras.append(f"{result.baselined} baselined")
    if result.stale_baseline:
        extras.append(f"{len(result.stale_baseline)} stale baseline "
                      "entr(y/ies) — prune tools/vet/baseline.txt")
        # the exact lines to delete, one per line, greppable verbatim
        for key in result.stale_baseline:
            print(f"vet: stale baseline entry: {key}", file=sys.stderr)
    tail = f" ({'; '.join(extras)})" if extras else ""
    status = "clean" if result.rc == 0 else \
        f"{len(result.findings) + len(result.parse_errors)} finding(s)"
    if result.per_pass_ms:
        top = slowest_passes(result.per_pass_ms)
        shown = ", ".join(f"{name} ({ms:.0f} ms)" for name, ms in top)
        print(f"vet: slowest pass{'es' if len(top) > 1 else ''}: "
              f"{shown} of {total_ms:.0f} ms total", file=sys.stderr)
    print(f"vet: {result.files} files, {status}{tail}", file=sys.stderr)
    return 2 if guard_tripped else result.rc


__all__ = ["run_vet", "main", "VetResult", "PASSES", "LEGACY_PASSES",
           "FLOW_PASSES", "ROLE_TRANSITION_GROUP",
           "FUSED_RECONCILE_GROUP", "result_to_json",
           "changed_paths", "expand_partners", "partner_groups",
           "prior_total_ms", "time_guard_exceeded", "slowest_passes",
           "TIME_GUARD_FACTOR", "TIME_GUARD_SLACK_MS"]

if __name__ == "__main__":
    sys.exit(main())
