"""Shared infrastructure for the vet passes: one parse per file, one
finding type, one suppression model.

Every pass consumes a :class:`FileCtx` (path + source + AST + noqa
map) and emits :class:`Finding` objects.  The driver owns suppression:

- ``# noqa`` on a line suppresses every finding on that line (legacy
  blanket form, kept for compatibility);
- ``# noqa: A02`` / ``# noqa: A02, E03`` suppresses only the listed
  codes — the preferred form, because it keeps the other passes honest
  on that line;
- ``tools/vet/baseline.txt`` holds accepted legacy findings keyed by
  ``path|CODE|message`` (no line numbers, so the baseline survives
  unrelated edits).  ``--write-baseline`` regenerates it.

Exit status contract (same as the old pyvet): 0 clean, 1 findings,
2 parse failure.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set

_NOQA_RE = re.compile(r"#\s*noqa(?:\s*:\s*(?P<codes>[A-Za-z0-9_, ]+))?",
                      re.IGNORECASE)


@dataclass(frozen=True)
class Finding:
    path: str
    line: int
    code: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"

    def baseline_key(self) -> str:
        return f"{self.path}|{self.code}|{self.message}"


@dataclass
class FileCtx:
    """One parsed source file, shared by every pass (single parse)."""

    path: str
    src: str
    tree: ast.Module
    # line -> None (blanket noqa) or the set of suppressed codes
    noqa: Dict[int, Optional[Set[str]]] = field(default_factory=dict)

    @classmethod
    def load(cls, path: Path, display: str) -> "FileCtx":
        src = path.read_text(encoding="utf-8", errors="replace")
        tree = ast.parse(src, filename=display)  # may raise SyntaxError
        return cls(display, src, tree, parse_noqa(src))

    def suppressed(self, line: int, code: str) -> bool:
        if line not in self.noqa:
            return False
        codes = self.noqa[line]
        return codes is None or code in codes


def parse_noqa(src: str) -> Dict[int, Optional[Set[str]]]:
    out: Dict[int, Optional[Set[str]]] = {}
    for i, text in enumerate(src.splitlines(), 1):
        m = _NOQA_RE.search(text)
        if not m:
            continue
        raw = m.group("codes")
        if raw is None:
            out[i] = None  # blanket
        else:
            codes = {c.strip().upper() for c in raw.split(",") if c.strip()}
            out[i] = codes or None
    return out


@dataclass
class Pass:
    """A named analysis: either per-file (``check``) or whole-project
    (``check_project`` — for cross-file passes like wire-schema)."""

    name: str
    codes: Sequence[str]
    check: Optional[Callable[[FileCtx], List[Finding]]] = None
    check_project: Optional[
        Callable[[List[FileCtx]], List[Finding]]] = None

    def run(self, ctxs: List[FileCtx]) -> List[Finding]:
        if self.check_project is not None:
            return list(self.check_project(ctxs))
        assert self.check is not None
        out: List[Finding] = []
        for ctx in ctxs:
            out.extend(self.check(ctx))
        return out


# -- file collection ---------------------------------------------------------


def collect_files(roots: Iterable[str]) -> List[Path]:
    files: List[Path] = []
    for root in roots:
        p = Path(root)
        if p.is_file():
            files.append(p)
        elif p.is_dir():
            files.extend(f for f in sorted(p.rglob("*.py"))
                         if "__pycache__" not in f.parts)
    return files


# -- baseline ----------------------------------------------------------------


def load_baseline(path: Path) -> List[str]:
    """Baseline entries, one ``path|CODE|message`` key per line;
    ``#``-prefixed lines are justification comments."""
    if not path.is_file():
        return []
    out: List[str] = []
    for line in path.read_text(encoding="utf-8").splitlines():
        line = line.strip()
        if line and not line.startswith("#"):
            out.append(line)
    return out


def write_baseline(path: Path, findings: Sequence[Finding]) -> None:
    lines = [
        "# vet baseline — accepted legacy findings, one per line as",
        "# path|CODE|message (line numbers omitted so entries survive",
        "# unrelated edits).  Regenerate with:  python -m tools.vet",
        "#   <paths> --write-baseline.  New code must come in clean;",
        "# prefer a targeted `# noqa: CODE` with a justification",
        "# comment over growing this file.",
        "",
    ]
    seen: Set[str] = set()
    for f in sorted(findings, key=lambda f: (f.path, f.code, f.message)):
        key = f.baseline_key()
        if key not in seen:
            seen.add(key)
            lines.append(key)
    path.write_text("\n".join(lines) + "\n", encoding="utf-8")


# -- AST helpers shared by several passes ------------------------------------


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain rooted at a Name, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def func_scopes(tree: ast.Module):
    """Yield ``(node, async_stack)`` for every statement-bearing node,
    where ``async_stack`` is True when the nearest enclosing function
    is an ``async def`` (lambdas are transparent)."""
    def walk(node: ast.AST, in_async: bool):
        for child in ast.iter_child_nodes(node):
            child_async = in_async
            if isinstance(child, ast.AsyncFunctionDef):
                child_async = True
            elif isinstance(child, ast.FunctionDef):
                child_async = False
            yield child, child_async
            yield from walk(child, child_async)
    yield from walk(tree, False)
