"""Legacy pyvet passes on the shared walker: undefined names (N01)
and unused imports (N02).

Scope is deliberately narrow and low-false-positive:

- **N01 undefined name**: a Name load with no binding in any enclosing
  scope, module global, builtin, or wildcard-import escape hatch.  A
  module containing ``from x import *`` skips undefined-name analysis
  (the star can bind anything); class bodies and comprehension scopes
  follow Python's actual scoping (class-body names are invisible to
  nested functions).
- **N02 unused import**: a module-level or function-level import whose
  bound name is never read anywhere in the module.  Names re-exported
  via ``__all__`` strings count as used; ``__init__.py`` files are
  exempt entirely (re-export surface); ``from __future__`` and
  ``import x as _`` (underscore convention) are exempt.

Suppression (``# noqa`` / ``# noqa: N01``) and baselines live in the
driver, not here — passes only report.
"""

from __future__ import annotations

import ast
import builtins
from typing import Dict, List, Set, Tuple

from tools.vet.core import FileCtx, Finding

UNDEFINED = "N01"
UNUSED_IMPORT = "N02"

BUILTINS = set(dir(builtins)) | {
    "__file__", "__name__", "__doc__", "__package__", "__spec__",
    "__loader__", "__builtins__", "__debug__", "__path__", "__class__",
    # typing/runtime dunders commonly read without a binding
    "__annotations__", "__dict__", "__all__",
    "WindowsError",  # guarded platform reads
}


class _Scope:
    __slots__ = ("node", "bound", "is_class")

    def __init__(self, node: ast.AST, is_class: bool = False) -> None:
        self.node = node
        self.bound: Set[str] = set()
        self.is_class = is_class


def _binds(node: ast.AST, into: Set[str]) -> None:
    """Collect the names a statement binds in its own scope (no
    recursion into nested function/class bodies)."""
    if isinstance(node, (ast.Import, ast.ImportFrom)):
        for a in node.names:
            if a.name == "*":
                continue
            into.add((a.asname or a.name).split(".")[0])
    elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
        into.add(node.name)
    elif isinstance(node, ast.Name) and isinstance(node.ctx,
                                                   (ast.Store, ast.Del)):
        into.add(node.id)
    elif isinstance(node, (ast.Global, ast.Nonlocal)):
        into.update(node.names)
    elif isinstance(node, ast.ExceptHandler) and node.name:
        into.add(node.name)
    elif isinstance(node, (ast.MatchAs, ast.MatchStar)) \
            and getattr(node, "name", None):
        into.add(node.name)
    elif isinstance(node, ast.MatchMapping) and node.rest:
        into.add(node.rest)


def _args_of(fn) -> Set[str]:
    a = fn.args
    names = {x.arg for x in
             a.posonlyargs + a.args + a.kwonlyargs}
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)
    return names


class _Checker(ast.NodeVisitor):
    """Two-pass per scope: pre-bind every name the scope assigns
    anywhere (Python scoping is whole-scope, not top-down), then walk
    loads."""

    def __init__(self, path: str, tree: ast.Module) -> None:
        self.path = path
        self.findings: List[Finding] = []
        self.has_star = any(
            isinstance(n, ast.ImportFrom) and any(a.name == "*"
                                                  for a in n.names)
            for n in ast.walk(tree))
        # import bookkeeping: name -> (lineno, shown-as)
        self.imports: Dict[str, Tuple[int, str]] = {}
        self.used: Set[str] = set()
        self.scopes: List[_Scope] = []
        self.tree = tree

    # -- scope machinery ----------------------------------------------------

    def _prebind(self, scope: _Scope, body: List[ast.stmt]) -> None:
        todo = list(body)
        while todo:
            node = todo.pop()
            _binds(node, scope.bound)
            for child in ast.iter_child_nodes(node):
                # stop at nested scopes — their bindings are their own
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.ClassDef,
                                      ast.Lambda)):
                    _binds(child, scope.bound)
                    continue
                if isinstance(child, (ast.ListComp, ast.SetComp,
                                      ast.DictComp, ast.GeneratorExp)):
                    continue  # comprehensions have their own scope
                todo.append(child)

    def _visible(self, name: str) -> bool:
        if name in BUILTINS:
            return True
        for i, scope in enumerate(reversed(self.scopes)):
            # class-body bindings are invisible to nested scopes
            # (only the innermost scope may BE the class body)
            if scope.is_class and i != 0:
                continue
            if name in scope.bound:
                return True
        return False

    # -- visitors -----------------------------------------------------------

    def check(self) -> None:
        root = _Scope(self.tree)
        self.scopes.append(root)
        self._prebind(root, self.tree.body)
        for node in self.tree.body:
            self.visit(node)
        self.scopes.pop()
        # __all__ strings count as uses of the re-exported names
        for node in self.tree.body:
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "__all__"
                    for t in node.targets):
                for el in ast.walk(node.value):
                    if isinstance(el, ast.Constant) \
                            and isinstance(el.value, str):
                        self.used.add(el.value)
        for name, (line, shown) in sorted(self.imports.items(),
                                          key=lambda kv: kv[1][0]):
            if name not in self.used and not name.startswith("_"):
                self.findings.append(Finding(
                    self.path, line, UNUSED_IMPORT,
                    f"unused import '{shown}'"))

    def _enter(self, node, bound: Set[str], is_class: bool = False):
        scope = _Scope(node, is_class)
        scope.bound |= bound
        self.scopes.append(scope)
        body = node.body if isinstance(node.body, list) else [node.body]
        self._prebind(scope, [b for b in body
                              if isinstance(b, ast.stmt)] or [])
        return scope

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            name = (a.asname or a.name).split(".")[0]
            self.imports.setdefault(name, (node.lineno,
                                           a.asname or a.name))

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "__future__":
            return
        for a in node.names:
            if a.name == "*":
                continue
            name = a.asname or a.name
            self.imports.setdefault(name, (node.lineno, name))

    def _visit_function(self, node) -> None:
        for dec in node.decorator_list:
            self.visit(dec)
        for default in (node.args.defaults + node.args.kw_defaults):
            if default is not None:
                self.visit(default)
        for x in (node.args.posonlyargs + node.args.args
                  + node.args.kwonlyargs):
            if x.annotation:
                self.visit(x.annotation)
        if node.returns:
            self.visit(node.returns)
        scope = self._enter(node, _args_of(node))
        for stmt in node.body:
            self.visit(stmt)
        self.scopes.pop()
        del scope

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function

    def visit_Lambda(self, node: ast.Lambda) -> None:
        for default in (node.args.defaults + node.args.kw_defaults):
            if default is not None:
                self.visit(default)
        scope = _Scope(node)
        scope.bound |= _args_of(node)
        self.scopes.append(scope)
        self.visit(node.body)
        self.scopes.pop()

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        for dec in node.decorator_list:
            self.visit(dec)
        for base in node.bases + [k.value for k in node.keywords]:
            self.visit(base)
        scope = self._enter(node, set(), is_class=True)
        for stmt in node.body:
            self.visit(stmt)
        self.scopes.pop()
        del scope

    def _visit_comp(self, node) -> None:
        scope = _Scope(node)
        self.scopes.append(scope)
        for gen in node.generators:
            # the first iterable evaluates in the ENCLOSING scope, but
            # treating it as inner only risks false-negatives, not
            # false-positives — acceptable for a lite pass
            for n in ast.walk(gen.target):
                _binds(n, scope.bound)
        for gen in node.generators:
            self.visit(gen.iter)
            for cond in gen.ifs:
                self.visit(cond)
        if isinstance(node, ast.DictComp):
            self.visit(node.key)
            self.visit(node.value)
        else:
            self.visit(node.elt)
        self.scopes.pop()

    visit_ListComp = _visit_comp
    visit_SetComp = _visit_comp
    visit_DictComp = _visit_comp
    visit_GeneratorExp = _visit_comp

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load):
            self.used.add(node.id)
            if not self.has_star and not self._visible(node.id):
                self.findings.append(Finding(
                    self.path, node.lineno, UNDEFINED,
                    f"undefined name '{node.id}'"))

    def visit_Attribute(self, node: ast.Attribute) -> None:
        # `import a.b; a.b.c` — the root name is the use
        self.generic_visit(node)

    def visit_Constant(self, node: ast.Constant) -> None:
        # string annotations under `from __future__ import annotations`
        # may reference imported names — count words as uses (cheap,
        # suppresses typing-only "unused import" false positives)
        if isinstance(node.value, str) and len(node.value) < 200:
            for tok in node.value.replace("[", " ").replace("]", " ") \
                    .replace(",", " ").replace(".", " ").split():
                if tok.isidentifier():
                    self.used.add(tok)


def check(ctx: FileCtx) -> List[Finding]:
    checker = _Checker(ctx.path, ctx.tree)
    checker.check()
    findings = checker.findings
    if ctx.path.rsplit("/", 1)[-1] == "__init__.py":
        # re-export surface: unused-import findings don't apply, but
        # undefined names still do
        findings = [f for f in findings if f.code != UNUSED_IMPORT]
    return sorted(findings, key=lambda f: (f.line, f.code))
