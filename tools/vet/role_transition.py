"""Role-transition pass: raft protocol-state exhaustiveness.

The raft role machine is only safe when every transition runs its
full ritual — persist the term, reset the vote, tear down replication,
and (since PR 7's leader leases) drop the lease state.  A bare
``self.role = ...`` somewhere else is a transition that skipped the
ritual; the chaos gate catches the ones its scenarios provoke, this
pass catches them all:

- **T01 out-of-band role/term write**: in a class that defines
  ``_become_*`` transition helpers, an assignment to ``self.role`` or
  ``self.current_term`` anywhere outside those helpers (plus
  ``_stop_leading``, ``__init__``, and ``shutdown``).  Term and role
  must move together with persistence (``_persist_term``) and
  observer notification; an inline write forks the state machine.
- **T02 transition helper leaks the lease**: a transition helper that
  does not reset ``self._lease_ack``.  The leader lease
  (``_lease_ack`` quorum-ack map + ``_lease_guard_index``) is what
  lets a leader serve reads without a barrier; a deposed or
  re-electing node that keeps stale acks can count a dead quorum as
  fresh — the deposed-leader-never-serves invariant, enforced today
  only dynamically by the chaos gate's stale-read checker.

Scope gate: both checks fire only in classes that define at least one
``_become_*`` method (T02 additionally requires the class to touch
``_lease_ack`` at all), so agent/demo code never pays the pass.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from tools.vet.core import FileCtx, Finding

OUT_OF_BAND_WRITE = "T01"
LEASE_LEAK = "T02"

# state that may only move inside a transition helper
ROLE_STATE_ATTRS = ("role", "current_term")
# lease state every transition helper must reset (clear or reassign)
LEASE_ATTRS = ("_lease_ack",)
# methods allowed to write role state directly
_ALLOWED_EXTRA = ("_stop_leading", "__init__", "shutdown")


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _is_transition_helper(name: str) -> bool:
    return name.startswith("_become_") or name == "_stop_leading"


def _methods(cls: ast.ClassDef):
    return [n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]


def _role_state_writes(fn: ast.AST) -> List[ast.AST]:
    out: List[ast.AST] = []
    for n in ast.walk(fn):
        if isinstance(n, ast.Assign):
            targets = n.targets
        elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
            targets = [n.target]
        else:
            continue
        for t in targets:
            if _self_attr(t) in ROLE_STATE_ATTRS:
                out.append(n)
                break
    return out


def _resets_lease(fn: ast.AST) -> bool:
    """True when fn assigns a lease attr or calls ``.clear()`` on it."""
    for n in ast.walk(fn):
        if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = n.targets if isinstance(n, ast.Assign) else [n.target]
            if any(_self_attr(t) in LEASE_ATTRS for t in targets):
                return True
        elif isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr == "clear" \
                and _self_attr(n.func.value) in LEASE_ATTRS:
            return True
    return False


def _touches_lease(cls: ast.ClassDef) -> bool:
    return any(_self_attr(n) in LEASE_ATTRS for n in ast.walk(cls))


def check(ctx: FileCtx) -> List[Finding]:
    if "_become_" not in ctx.src:
        return []
    out: List[Finding] = []
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        methods = _methods(cls)
        helpers = [m for m in methods if m.name.startswith("_become_")]
        if not helpers:
            continue
        allowed: Set[str] = {m.name for m in methods
                             if _is_transition_helper(m.name)}
        allowed.update(_ALLOWED_EXTRA)
        for m in methods:
            if m.name in allowed:
                continue
            for node in _role_state_writes(m):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]  # type: ignore[attr-defined]
                attr = next(a for a in map(_self_attr, targets)
                            if a in ROLE_STATE_ATTRS)
                out.append(Finding(
                    ctx.path, node.lineno, OUT_OF_BAND_WRITE,
                    f"'self.{attr}' assigned in {cls.name}.{m.name}() "
                    "outside the _become_*/_stop_leading transition "
                    "helpers — role and term must move through one "
                    "helper so persistence, replication teardown, and "
                    "lease reset cannot be skipped"))
        if _touches_lease(cls):
            for m in methods:
                if not _is_transition_helper(m.name):
                    continue
                if not _resets_lease(m):
                    out.append(Finding(
                        ctx.path, m.lineno, LEASE_LEAK,
                        f"transition helper {cls.name}.{m.name}() does "
                        "not reset self._lease_ack — stale quorum acks "
                        "survive the transition and a deposed/"
                        "re-electing node can serve lease reads it no "
                        "longer holds (clear _lease_ack, and re-anchor "
                        "_lease_guard_index when taking leadership)"))
    return sorted(set(out), key=lambda f: (f.line, f.code, f.message))
