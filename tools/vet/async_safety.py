"""Async-safety pass: the event-loop bug classes pytest-on-CPU cannot
see because they only bite under load or GC pressure.

- **A01 unawaited coroutine**: an expression-statement call to a
  function the module defines with ``async def``.  The coroutine is
  created and dropped — the body never runs (asyncio warns at GC time,
  long after the damage).  Matching is scope-aware to stay
  near-zero-false-positive: a bare ``name()`` matches module-level /
  nested ``async def name``, and ``self.name()`` matches an ``async
  def name`` on the *enclosing* class only — ``self.local.start()``
  never matches ``Agent.start``.
- **A02 dropped task**: ``asyncio.create_task(...)`` /
  ``loop.create_task(...)`` / ``ensure_future(...)`` whose return
  value is discarded.  The event loop holds only a weak reference to
  tasks; a dropped handle can be garbage-collected mid-run, silently
  cancelling the work (the gossip plane's failure mode).  Keep a
  strong reference — the task-set pattern:
  ``self._tasks.add(t); t.add_done_callback(self._tasks.discard)``.
- **A03 blocking call in coroutine**: ``time.sleep``, sync
  ``subprocess`` helpers, sync socket/DNS ops, ``os.system`` … lexically
  inside an ``async def`` — each one stalls the whole event loop (the
  gossip plane misses heartbeats for every peer, not just the caller).
  Calls inside a nested plain ``def`` are NOT flagged (that function
  may legitimately run in an executor or thread).
- **A04 threading lock in coroutine**: ``with lock:`` /
  ``lock.acquire()`` on a name assigned from ``threading.Lock()`` (or
  RLock/Condition/Semaphore), used inside an ``async def``.  A
  contended threading lock blocks the loop; use ``asyncio.Lock`` or
  keep the critical section out of coroutines.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from tools.vet.core import FileCtx, Finding, dotted_name

UNAWAITED = "A01"
DROPPED_TASK = "A02"
BLOCKING = "A03"
THREAD_LOCK = "A04"

_TASK_SPAWNERS = {"create_task", "ensure_future"}

# dotted stdlib calls that block the loop (module-rooted chains only)
_BLOCKING_CALLS = {
    "time.sleep",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.getoutput",
    "socket.create_connection", "socket.getaddrinfo",
    "socket.gethostbyname", "socket.gethostbyaddr",
    "os.system", "os.wait", "os.waitpid",
    "urllib.request.urlopen",
    "select.select",
}

_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore"}


def _module_imports(tree: ast.Module) -> Dict[str, str]:
    """local name -> dotted origin, for ``import x`` / ``from x import y``."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[(a.asname or a.name).split(".")[0]] = \
                    a.name if a.asname else a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.level == 0:
            for a in node.names:
                if a.name != "*":
                    out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


def _lock_names(tree: ast.Module, imports: Dict[str, str]) -> Set[str]:
    """Simple names (or attribute tails, for ``self._lock``) assigned
    from a threading lock factory anywhere in the module."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        value = node.value
        if not isinstance(value, ast.Call):
            continue
        dn = dotted_name(value.func)
        if dn is None:
            continue
        parts = dn.split(".")
        is_lock = (len(parts) == 2 and imports.get(parts[0]) == "threading"
                   and parts[1] in _LOCK_FACTORIES) or \
                  (len(parts) == 1 and parts[0] in _LOCK_FACTORIES
                   and imports.get(parts[0], "").startswith("threading."))
        if not is_lock:
            continue
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for t in targets:
            if isinstance(t, ast.Name):
                names.add(t.id)
            elif isinstance(t, ast.Attribute):
                names.add(t.attr)
    return names


def _target_name(node: ast.AST) -> Optional[str]:
    """Name id, or attribute tail for ``self.x`` / ``obj.x`` chains."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


class _Walker(ast.NodeVisitor):
    def __init__(self, ctx: FileCtx, bare_async: Set[str],
                 imports: Dict[str, str], locks: Set[str]) -> None:
        self.ctx = ctx
        self.bare_async = bare_async  # async defs NOT on a class
        self.imports = imports
        self.locks = locks
        self.findings: List[Finding] = []
        self._async_depth = 0
        # async method names of each lexically-enclosing class
        self._class_async: List[Set[str]] = []

    def _emit(self, node: ast.AST, code: str, msg: str) -> None:
        self.findings.append(Finding(self.ctx.path, node.lineno, code, msg))

    # -- scope tracking -----------------------------------------------------

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._async_depth += 1
        self.generic_visit(node)
        self._async_depth -= 1

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        saved, self._async_depth = self._async_depth, 0
        self.generic_visit(node)
        self._async_depth = saved

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._class_async.append({
            n.name for n in node.body
            if isinstance(n, ast.AsyncFunctionDef)})
        self.generic_visit(node)
        self._class_async.pop()

    def _is_unawaited_async(self, call: ast.Call) -> Optional[str]:
        fn = call.func
        if isinstance(fn, ast.Name) and fn.id in self.bare_async:
            return fn.id
        if isinstance(fn, ast.Attribute) \
                and isinstance(fn.value, ast.Name) \
                and fn.value.id in ("self", "cls") \
                and self._class_async \
                and fn.attr in self._class_async[-1]:
            return fn.attr
        return None

    # -- checks -------------------------------------------------------------

    def visit_Expr(self, node: ast.Expr) -> None:
        call = node.value
        if isinstance(call, ast.Call):
            name = _target_name(call.func)
            unawaited = self._is_unawaited_async(call)
            if name in _TASK_SPAWNERS:
                self._emit(
                    node, DROPPED_TASK,
                    f"return value of {name}() is discarded — the loop "
                    "keeps only a weak reference, so the task can be "
                    "garbage-collected mid-run; keep a strong reference "
                    "(task-set pattern)")
            elif unawaited is not None:
                self._emit(
                    node, UNAWAITED,
                    f"call to async function '{unawaited}' is never "
                    "awaited (the coroutine object is created and "
                    "dropped)")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if self._async_depth:
            dn = dotted_name(node.func)
            if dn is not None:
                resolved = self._resolve(dn)
                if resolved in _BLOCKING_CALLS:
                    self._emit(
                        node, BLOCKING,
                        f"blocking call {resolved}() inside 'async def' "
                        "stalls the event loop; use the asyncio "
                        "equivalent or an executor")
            name = _target_name(node.func)
            if name == "acquire" and isinstance(node.func, ast.Attribute):
                tail = _target_name(node.func.value)
                if tail in self.locks:
                    self._emit(
                        node, THREAD_LOCK,
                        f"threading lock '{tail}' acquired inside "
                        "'async def' — a contended acquire blocks the "
                        "event loop; use asyncio.Lock")
        self.generic_visit(node)

    def visit_With(self, node: ast.With) -> None:
        if self._async_depth:
            for item in node.items:
                tail = _target_name(item.context_expr)
                if tail in self.locks:
                    self._emit(
                        node, THREAD_LOCK,
                        f"threading lock '{tail}' held inside 'async def' "
                        "— a contended acquire blocks the event loop; "
                        "use asyncio.Lock")
        self.generic_visit(node)

    def _resolve(self, dn: str) -> str:
        """Rewrite the chain root through the module's imports so
        ``from time import sleep; sleep()`` still resolves to
        ``time.sleep``."""
        root, _, rest = dn.partition(".")
        origin = self.imports.get(root)
        if origin is None:
            return dn
        return f"{origin}.{rest}" if rest else origin


def _bare_async_defs(tree: ast.Module) -> Set[str]:
    """Async defs whose immediate parent is NOT a class body (callable
    by bare name: module level, or nested closures)."""
    method_ids: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            for child in node.body:
                if isinstance(child, ast.AsyncFunctionDef):
                    method_ids.add(id(child))
    return {n.name for n in ast.walk(tree)
            if isinstance(n, ast.AsyncFunctionDef)
            and id(n) not in method_ids}


def check(ctx: FileCtx) -> List[Finding]:
    imports = _module_imports(ctx.tree)
    locks = _lock_names(ctx.tree, imports)
    w = _Walker(ctx, _bare_async_defs(ctx.tree), imports, locks)
    w.visit(ctx.tree)
    return sorted(w.findings, key=lambda f: (f.line, f.code))
