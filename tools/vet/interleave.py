"""Interleaving-race pass: asyncio happens-before bugs on shared
instance state in the agent/raft/serving planes.

An ``await`` is a scheduling point: every other runnable coroutine —
and, through executors, every worker thread — may run between the
statement before it and the statement after it.  State that was
checked before the ``await`` is unverified folklore after it.  The
chaos campaign (PR 15) only provokes the interleavings its scenario
catalog seeds; these passes catch the rest statically:

- **X01 await-separated read-modify-write**: a write to ``self.attr``
  whose *deciding read* of the same attribute (an ``if``/``while``
  test, a ``for`` iterator, or the RHS of the assignment itself) is
  separated from the write by an ``await`` — the canonical
  check-then-act TOCTOU.  Scope-limited to *shared* attributes
  (accessed from two or more methods of the class) and exempting the
  two idioms that make the pattern safe: a re-read/re-test of the
  attribute after the last ``await``, and a write under an
  ``async with <lock>:`` span (the double-checked-lock shape in
  ``rpc/pool.py::_session``).
- **X02 lock-discipline drift**: when the accesses to a field are
  majority-dominated by ``(async) with self.<lock>:`` spans, an
  unguarded *write* to that field is almost always a site someone
  forgot, not a site someone exempted.  Inference, not annotation:
  the clustering is recomputed from the code on every run.
- **X03 lock re-entrance via await**: an ``async with self.<lock>:``
  span that transitively calls (through ``self.*`` methods) back into
  an acquisition of the same lock.  ``asyncio.Lock`` is not
  reentrant — the task deadlocks against itself, and every other
  user of the lock convoys behind it.
- **X04 thread/coroutine attribute race**: ``self.attr`` mutated from
  both a thread context (``Thread(target=...)``, ``to_thread``,
  ``run_in_executor`` — including nested closures handed off from a
  method) and a coroutine, with at least one side holding no
  ``threading.Lock``.  The instance-attribute generalization of
  fork_safety's module-global R02.

Suppression conventions: a ``# noqa: X01``-style pragma must carry a
justification comment explaining the happens-before argument (single
writer task, idempotent re-apply, etc.) — see README §static analysis.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from tools.vet.core import FileCtx, Finding
from tools.vet.tracer_purity import _tail

AWAIT_RMW = "X01"
LOCK_DISCIPLINE = "X02"
LOCK_REENTRANT = "X03"
THREAD_COROUTINE = "X04"

_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore"}
_MUTATORS = {"append", "add", "update", "pop", "popitem", "setdefault",
             "extend", "remove", "discard", "clear", "insert"}
_THREAD_HANDOFFS = {"to_thread", "run_in_executor"}

Pos = Tuple[int, int]


def _pos(node: ast.AST) -> Pos:
    return (node.lineno, node.col_offset)


def _end(node: ast.AST) -> int:
    return getattr(node, "end_lineno", node.lineno) or node.lineno


def _walk_local(root: ast.AST) -> Iterator[ast.AST]:
    """Descendants of root in source order, NOT crossing into nested
    function/lambda bodies (their awaits and writes belong to another
    task's timeline)."""
    for child in ast.iter_child_nodes(root):
        yield child
        if not isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
            yield from _walk_local(child)


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _functions(cls: ast.ClassDef) -> List[ast.AST]:
    return [n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]


def _walk_local_inc(root: ast.AST) -> Iterator[ast.AST]:
    """_walk_local plus the root itself (a caller may hand us a single
    statement — its own targets must still be classified)."""
    yield root
    yield from _walk_local(root)


def _mutated_attr_nodes(root: ast.AST) -> Set[int]:
    """ids of the ``self.attr`` Attribute nodes that are *mutation
    roots*: subscript-store targets (``self.d[k] = v``), mutator-method
    receivers (``self.s.add(x)``), and ``del self.d[k]`` holders.
    Direct stores carry ast.Store ctx and need no override."""
    out: Set[int] = set()

    def mark_target(t: ast.AST) -> None:
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                mark_target(e)
        elif isinstance(t, ast.Subscript) and _self_attr(t.value):
            out.add(id(t.value))
        elif isinstance(t, ast.Starred):
            mark_target(t.value)

    for n in _walk_local_inc(root):
        if isinstance(n, ast.Assign):
            for t in n.targets:
                mark_target(t)
        elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
            mark_target(n.target)
        elif isinstance(n, ast.Delete):
            for t in n.targets:
                mark_target(t)
        elif isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr in _MUTATORS \
                and _self_attr(n.func.value):
            out.add(id(n.func.value))
    return out


def _end_pos(node: ast.AST) -> Pos:
    return (getattr(node, "end_lineno", node.lineno) or node.lineno,
            getattr(node, "end_col_offset", node.col_offset) or 0)


def _attr_events(root: ast.AST) -> List[Tuple[Pos, Pos, str, Optional[str]]]:
    """(pos, end_pos, kind, attr) in source order; kind in
    read/write/await.  Writes = direct stores + subscript stores +
    mutator calls.  An await's end_pos covers its whole operand —
    reads lexically inside the awaited expression happen *before* the
    suspension, not after it."""
    mutated = _mutated_attr_nodes(root)
    events: List[Tuple[Pos, Pos, str, Optional[str]]] = []
    for n in _walk_local_inc(root):
        if isinstance(n, ast.Await):
            events.append((_pos(n), _end_pos(n), "await", None))
            continue
        attr = _self_attr(n)
        if attr is None:
            continue
        if isinstance(n.ctx, (ast.Store, ast.Del)) or id(n) in mutated:  # type: ignore[attr-defined]
            events.append((_pos(n), _end_pos(n), "write", attr))
        else:
            events.append((_pos(n), _end_pos(n), "read", attr))
    events.sort(key=lambda e: e[0])
    return events


def _attrs_read(expr: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for n in ast.walk(expr):
        attr = _self_attr(n)
        if attr is not None:
            out.add(attr)
    return out


def _lock_attrs_by_kind(cls: ast.ClassDef,
                        imports: Dict[str, str]
                        ) -> Tuple[Set[str], Set[str]]:
    """(all lock attrs, threading-only lock attrs) assigned from a lock
    factory anywhere in the class, resolved through the module's
    imports (one subtree walk)."""
    all_locks: Set[str] = set()
    threading_locks: Set[str] = set()
    for n in ast.walk(cls):
        if not isinstance(n, (ast.Assign, ast.AnnAssign)):
            continue
        v = n.value
        if not (isinstance(v, ast.Call) and _tail(v.func) in _LOCK_FACTORIES):
            continue
        if isinstance(v.func, ast.Attribute) and isinstance(v.func.value,
                                                            ast.Name):
            origin = imports.get(v.func.value.id, v.func.value.id)
        elif isinstance(v.func, ast.Name):
            origin = imports.get(v.func.id, "")
        else:
            origin = ""
        root = origin.split(".")[0]
        targets = n.targets if isinstance(n, ast.Assign) else [n.target]
        for t in targets:
            attr = _self_attr(t)
            if attr is not None:
                all_locks.add(attr)
                if root == "threading":
                    threading_locks.add(attr)
    return all_locks, threading_locks


def _lock_spans(fn: ast.AST, locks: Set[str]) -> List[Tuple[str, int, int]]:
    """(lock_attr, first_line, last_line) of every (async) with
    ``self.<lock>:`` inside fn."""
    spans: List[Tuple[str, int, int]] = []
    for n in _walk_local(fn):
        if isinstance(n, (ast.With, ast.AsyncWith)):
            for item in n.items:
                attr = _self_attr(item.context_expr)
                if attr in locks:
                    spans.append((attr, n.lineno, _end(n)))
    return spans


def _in_any_span(line: int, spans: Sequence[Tuple[str, int, int]]) -> bool:
    return any(a <= line <= b for _, a, b in spans)


def _attr_use_counts(cls: ast.ClassDef) -> Dict[str, Set[str]]:
    """attr -> method names that touch it (read or write)."""
    out: Dict[str, Set[str]] = {}
    for fn in _functions(cls):
        for n in ast.walk(fn):
            attr = _self_attr(n)
            if attr is not None:
                out.setdefault(attr, set()).add(fn.name)
    return out


# -- X01 ---------------------------------------------------------------------


def _check_x01(ctx: FileCtx, scan: _ClassScan,
               out: List[Finding]) -> None:
    shared = {a for a, fns in _attr_use_counts(scan.cls).items()
              if len(fns) >= 2}
    if not shared:
        return
    for fn in scan.fns:
        if not isinstance(fn, ast.AsyncFunctionDef):
            continue
        spans = scan.spans(fn)
        flagged: Set[Tuple[str, int]] = set()

        def emit(attr: str, write_pos: Pos, read_line: int) -> None:
            key = (attr, write_pos[0])
            if key in flagged:
                return
            flagged.add(key)
            out.append(Finding(
                ctx.path, write_pos[0], AWAIT_RMW,
                f"write to shared 'self.{attr}' decided by the read at "
                f"line {read_line}, with an await between them — every "
                "other coroutine may run at the await and invalidate the "
                "check (re-validate after the await, or serialize under "
                "one asyncio.Lock)"))

        # case A: single-statement RMW with an await inside the RHS
        for n in _walk_local(fn):
            if isinstance(n, ast.Assign):
                reads = _attrs_read(n.value)
                has_await = any(isinstance(s, ast.Await)
                                for s in ast.walk(n.value))
                if not has_await:
                    continue
                for t in n.targets:
                    attr = _self_attr(t)
                    if attr in shared and attr in reads \
                            and not _in_any_span(n.lineno, spans):
                        emit(attr, _pos(n), n.lineno)
            elif isinstance(n, ast.AugAssign):
                attr = _self_attr(n.target)
                if attr in shared \
                        and any(isinstance(s, ast.Await)
                                for s in ast.walk(n.value)) \
                        and not _in_any_span(n.lineno, spans):
                    emit(attr, _pos(n), n.lineno)

        # case B: guard (if/while test, for iterator) → await → write
        fn_events = scan.events(fn)
        for n in _walk_local(fn):
            if isinstance(n, (ast.If, ast.While)):
                guard_expr: ast.AST = n.test
                region: List[ast.stmt] = list(n.body) + list(n.orelse)
            elif isinstance(n, ast.For):
                guard_expr = n.iter
                region = list(n.body)
            else:
                continue
            guard_attrs = _attrs_read(guard_expr) & shared
            if not guard_attrs:
                continue
            ranges = [(stmt.lineno, _end(stmt)) for stmt in region]

            def in_region(line: int) -> bool:
                return any(a <= line <= b for a, b in ranges)

            events = [e for e in fn_events if in_region(e[0][0])]
            awaits = [(p, pe) for p, pe, kind, _ in events
                      if kind == "await"]
            if not awaits:
                continue
            # a guard re-established after the suspension: an if/while
            # inside the region whose test re-reads instance state —
            # writes under it were re-decided post-await
            recheck_spans = [
                (_pos(g), _end(g)) for g in _walk_local(n)
                if isinstance(g, (ast.If, ast.While))
                and in_region(g.lineno) and _attrs_read(g.test)]
            for attr in guard_attrs:
                for p, _pe, kind, a in events:
                    if kind != "write" or a != attr:
                        continue
                    before = [(w, we) for w, we in awaits if w < p]
                    if not before:
                        continue
                    await_end = max(we for _, we in before)
                    reread = any(
                        kind2 == "read" and a2 == attr
                        and await_end < p2 < p
                        for p2, _pe2, kind2, a2 in events)
                    rechecked = any(
                        gp > await_end and gp < p and p[0] <= ge
                        for gp, ge in recheck_spans)
                    revalidated = reread or rechecked
                    if revalidated or _in_any_span(p[0], spans):
                        continue
                    emit(attr, p, n.lineno)


# -- X02 ---------------------------------------------------------------------


def _check_x02(ctx: FileCtx, scan: _ClassScan,
               out: List[Finding]) -> None:
    locks = scan.locks
    if not locks:
        return
    # attr -> lock -> guarded access count; attr -> unguarded (pos, is_write)
    guarded: Dict[str, Dict[str, int]] = {}
    unguarded: Dict[str, List[Tuple[Pos, bool]]] = {}
    for fn in scan.fns:
        if fn.name == "__init__":
            continue
        spans = scan.spans(fn)
        for p, _pe, kind, attr in scan.events(fn):
            if attr is None or attr in locks:
                continue
            holder = next((L for L, a, b in spans if a <= p[0] <= b), None)
            if holder is not None:
                guarded.setdefault(attr, {})[holder] = \
                    guarded.setdefault(attr, {}).get(holder, 0) + 1
            else:
                unguarded.setdefault(attr, []).append((p, kind == "write"))
    for attr, by_lock in sorted(guarded.items()):
        lock, count = max(by_lock.items(), key=lambda kv: kv[1])
        outside = unguarded.get(attr, [])
        if count < 3 or count <= len(outside):
            continue  # not majority-dominated: no inferred discipline
        for p, is_write in sorted(outside):
            if not is_write:
                continue
            out.append(Finding(
                ctx.path, p[0], LOCK_DISCIPLINE,
                f"'self.{attr}' is accessed under 'async with "
                f"self.{lock}:' at {count} sites but written here "
                "without it — either take the lock or document why "
                "this writer is exempt from the inferred discipline"))


# -- X03 ---------------------------------------------------------------------


def _check_x03(ctx: FileCtx, scan: _ClassScan,
               out: List[Finding]) -> None:
    locks = scan.locks
    if not locks:
        return
    fns = {fn.name: fn for fn in scan.fns}
    acquires: Dict[str, Set[str]] = {}   # method -> locks it takes
    calls: Dict[str, Set[str]] = {}      # method -> self.* methods called
    for name, fn in fns.items():
        acquires[name] = {L for L, _, _ in scan.spans(fn)}
        calls[name] = set()
        for n in _walk_local(fn):
            if not isinstance(n, ast.Call):
                continue
            if isinstance(n.func, ast.Attribute) \
                    and n.func.attr == "acquire":
                attr = _self_attr(n.func.value)
                if attr in locks:
                    acquires[name].add(attr)  # type: ignore[arg-type]
            callee = _self_attr(n.func)
            if callee in fns:
                calls[name].add(callee)  # type: ignore[arg-type]

    def reacquires(start: str, lock: str) -> Optional[str]:
        seen: Set[str] = set()
        frontier = [start]
        while frontier:
            m = frontier.pop()
            if m in seen:
                continue
            seen.add(m)
            if lock in acquires.get(m, set()):
                return m
            frontier.extend(calls.get(m, set()))
        return None

    for name, fn in fns.items():
        spans = scan.spans(fn)
        if not spans:
            continue
        self_calls: List[Tuple[int, str]] = []
        with_locks: List[Tuple[int, str]] = []
        for n in _walk_local(fn):
            if isinstance(n, ast.Call):
                callee = _self_attr(n.func)
                if callee in fns:
                    self_calls.append((n.lineno, callee))  # type: ignore[arg-type]
            elif isinstance(n, (ast.With, ast.AsyncWith)):
                for item in n.items:
                    attr = _self_attr(item.context_expr)
                    if attr in locks:
                        with_locks.append((n.lineno, attr))  # type: ignore[arg-type]
        for lock, a, b in spans:
            for line, callee in self_calls:
                if not a < line <= b:
                    continue
                culprit = reacquires(callee, lock)
                if culprit is not None:
                    out.append(Finding(
                        ctx.path, line, LOCK_REENTRANT,
                        f"call to self.{callee}() inside 'async with "
                        f"self.{lock}:' reaches self.{culprit}(), which "
                        f"acquires self.{lock} again — asyncio.Lock is "
                        "not reentrant; the task deadlocks against "
                        "itself (hoist the call out of the critical "
                        "section or split the lock)"))
            # lexically nested re-acquisition of the same lock
            for line, attr in with_locks:
                if attr == lock and a < line <= b:
                    out.append(Finding(
                        ctx.path, line, LOCK_REENTRANT,
                        f"'async with self.{lock}:' nested inside the "
                        f"same lock's span (line {a}) — asyncio.Lock "
                        "is not reentrant; this deadlocks "
                        "unconditionally"))


# -- X04 ---------------------------------------------------------------------


def _module_prescan(tree: ast.Module
                    ) -> Tuple[Dict[str, str], Set[str], List[ast.ClassDef]]:
    """One whole-tree walk: (imports local→origin-root, names handed to
    a thread via Thread(target=X)/to_thread(X)/run_in_executor(E, X),
    class definitions)."""
    imports: Dict[str, str] = {}
    thread_targets: Set[str] = set()
    classes: List[ast.ClassDef] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            classes.append(node)
        elif isinstance(node, ast.Import):
            for a in node.names:
                imports[(a.asname or a.name).split(".")[0]] = \
                    a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom):
            if node.module and node.level == 0:
                for a in node.names:
                    if a.name != "*":
                        imports[a.asname or a.name] = \
                            f"{node.module.split('.')[0]}.{a.name}"
        elif isinstance(node, ast.Call):
            tail = _tail(node.func)
            if tail == "Thread":
                for kw in node.keywords:
                    if kw.arg == "target":
                        name = _tail(kw.value)
                        if name:
                            thread_targets.add(name)
            elif tail == "to_thread" and node.args:
                name = _tail(node.args[0])
                if name:
                    thread_targets.add(name)
            elif tail == "run_in_executor" and len(node.args) >= 2:
                name = _tail(node.args[1])
                if name:
                    thread_targets.add(name)
    return imports, thread_targets, classes


class _ClassScan:
    """Per-class shared computations, each done once: lock attrs by
    kind, per-method attr events, per-method lock spans."""

    def __init__(self, cls: ast.ClassDef, imports: Dict[str, str]) -> None:
        self.cls = cls
        self.locks, self.tlocks = _lock_attrs_by_kind(cls, imports)
        self.fns = _functions(cls)
        self._events: Dict[int, List[Tuple[Pos, Pos, str,
                                           Optional[str]]]] = {}
        self._spans: Dict[Tuple[int, bool],
                          List[Tuple[str, int, int]]] = {}

    def events(self, fn: ast.AST) -> List[Tuple[Pos, str, Optional[str]]]:
        key = id(fn)
        if key not in self._events:
            self._events[key] = _attr_events(fn)
        return self._events[key]

    def spans(self, fn: ast.AST,
              threading_only: bool = False) -> List[Tuple[str, int, int]]:
        key = (id(fn), threading_only)
        if key not in self._spans:
            self._spans[key] = _lock_spans(
                fn, self.tlocks if threading_only else self.locks)
        return self._spans[key]


def _check_x04(ctx: FileCtx, scan: _ClassScan, thread_targets: Set[str],
               out: List[Finding]) -> None:
    tlocks = scan.tlocks
    # attr -> context -> [(pos, locked)]
    writes: Dict[str, Dict[str, List[Tuple[Pos, bool]]]] = {}

    def visit_fn(fn: ast.AST, context: Optional[str],
                 cached: bool) -> None:
        if context is not None:
            spans = scan.spans(fn, threading_only=True) if cached \
                else _lock_spans(fn, tlocks)
            events = scan.events(fn) if cached else _attr_events(fn)
            for p, _pe, kind, attr in events:
                if kind == "write" and attr is not None \
                        and attr not in tlocks:
                    writes.setdefault(attr, {}).setdefault(
                        context, []).append((p, _in_any_span(p[0], spans)))
        # nested defs: a closure handed to a thread runs on that thread
        for n in ast.iter_child_nodes(fn):
            _visit_nested(n, context)

    def _visit_nested(node: ast.AST, context: Optional[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            inner = "thread" if node.name in thread_targets else context
            visit_fn(node, inner, cached=False)
            return
        if isinstance(node, ast.Lambda):
            return
        for child in ast.iter_child_nodes(node):
            _visit_nested(child, context)

    for fn in scan.fns:
        if fn.name in thread_targets:
            context: Optional[str] = "thread"
        elif isinstance(fn, ast.AsyncFunctionDef):
            context = "async"
        else:
            context = None
        visit_fn(fn, context, cached=True)

    for attr, by_ctx in sorted(writes.items()):
        if "thread" not in by_ctx or "async" not in by_ctx:
            continue
        unlocked = [(p, c) for c in ("async", "thread")
                    for p, locked in by_ctx[c] if not locked]
        for p, context in sorted(unlocked):
            out.append(Finding(
                ctx.path, p[0], THREAD_COROUTINE,
                f"'self.{attr}' is mutated from both a thread context "
                f"and a coroutine; this {context}-side write holds no "
                "threading.Lock — the loop and the thread interleave "
                "arbitrarily (guard both sides with one lock, or "
                "marshal through call_soon_threadsafe)"))


def class_scans(ctx: FileCtx
                ) -> Tuple[Dict[str, str], Set[str], List["_ClassScan"]]:
    """(imports, thread targets, one _ClassScan per class) for a file,
    memoized on the FileCtx instance: the driver shares one FileCtx per
    file across every pass, so the cancel-safety tier (cancel_safety.py)
    rides the same per-class event/lock-span caches this pass builds
    instead of re-walking each class."""
    cached = getattr(ctx, "_class_scans", None)
    if cached is None:
        imports, thread_targets, classes = _module_prescan(ctx.tree)
        cached = (imports, thread_targets,
                  [_ClassScan(c, imports) for c in classes])
        ctx._class_scans = cached  # type: ignore[attr-defined]
    return cached


def check(ctx: FileCtx) -> List[Finding]:
    out: List[Finding] = []
    _imports, thread_targets, scans = class_scans(ctx)
    for scan in scans:
        _check_x01(ctx, scan, out)
        _check_x02(ctx, scan, out)
        _check_x03(ctx, scan, out)
        _check_x04(ctx, scan, thread_targets, out)
    return sorted(set(out), key=lambda f: (f.line, f.code, f.message))
