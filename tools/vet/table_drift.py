"""Dispatch-table drift pass: string-keyed strategy tables that
duplicate one governing literal set across files, with nothing but
review discipline pinning the key sets together.

The repo dispatches two planes on string keys today:

- ``SwimParams.dissem`` — governed by the membership check in
  ``consul_tpu/gossip/params.py`` (``__post_init__``); duplicated by
  ``DENSE_PASSES_BY_DISSEM`` in ``consul_tpu/obs/devstats.py`` (the
  roofline's analytic pass counts), the ``--dissem`` argparse choices
  in ``bench.py``, and the same flag in ``tools/profile_kernel.py``.
  A strategy added to params but not devstats silently prices rounds
  with the wrong pass count; missing argparse choices make it
  unbenchable.
- ``match_backend`` — governed by the membership check in
  ``consul_tpu/state/device_store.py``; mirrored by the
  ``consul_watch_match_backend`` gauge help in
  ``consul_tpu/obs/storestats.py`` (which documents the legs an
  operator can see on a scrape).

One UNION group guards the autotune registry (``union=True``): the
``KNOBS`` dict in ``consul_tpu/obs/tuner.py`` governs, and every
consumer claims the knobs it applies in a module-level
``TUNED_FIELDS`` tuple (gossip/plane.py, agent/agent.py,
state/device_store.py).  Each claim must be a subset of the registry
(a claimed-but-unregistered knob resolves to nothing), and with every
consumer present the union must cover the registry exactly — a knob
added anywhere without tuner coverage, or registered without a
consumer, fails ``make vet``.

A second UNION group pins the journey-ledger stage enumeration: the
``STAGES`` tuple in ``consul_tpu/obs/journey.py`` governs, and the
``JOURNEY_STAGES`` mirrors in ``tools/obs_smoke.py`` and
``tests/test_journey.py`` (which enumerate the stage-labeled scrape
ladder) must each cover it exactly.  Union semantics because "stage"
is a label value, not a dispatched keyword — K02's stray scan would
false-positive on unrelated ``stage=`` keywords.

A third UNION group pins the ``CONSUL_TPU_*`` environment gates
(``check_env_gates`` below): the ``ENV_GATES`` registry in
``consul_tpu/obs/envgates.py`` governs; every full-string gate literal
in the tree must be registered, each gate's canonical reader module
must still reference it, and the README's environment-gate table must
document the registry exactly.

Codes:

- **K01 key-set divergence**: a satellite table's keys differ from the
  governing set (or a registered table cannot be located at all —
  a silently-renamed table is drift, not absence).
- **K02 stray dispatch literal**: a string literal dispatched against
  a governing keyword at a call site (``dissem="..."`` keyword arg,
  ``obj.dissem = "..."`` assignment, ``dissem == "..."`` comparison or
  ``in``-tuple membership) that is absent from the governing set —
  a typo'd strategy name that no runtime check sees until that exact
  line executes.

The registry below is declarative so the meta-test in
``tests/test_vet.py`` can run the pass over a *copy* of the real
sources with a deliberately desynced table and assert K01 fires.
Files are matched by path suffix; a group whose governing file is not
among the vetted files is skipped (subset runs, unit fixtures).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.vet.core import FileCtx, Finding
from tools.vet.tracer_purity import _tail

KEYSET_DIVERGE = "K01"
STRAY_LITERAL = "K02"


# -- extractors: (keys, line) from a FileCtx, or None when absent -----------


def _str_tuple(node: ast.AST) -> Optional[Set[str]]:
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)) and node.elts:
        vals = set()
        for el in node.elts:
            if not (isinstance(el, ast.Constant)
                    and isinstance(el.value, str)):
                return None
            vals.add(el.value)
        return vals
    return None


def extract_membership(ctx: FileCtx, keyword: str
                       ) -> Optional[Tuple[Set[str], int]]:
    """``<x>.keyword not in ("a", "b", ...)`` (or ``in``) — the
    governing validation idiom (params.__post_init__, device_store)."""
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Compare) and len(node.ops) == 1
                and isinstance(node.ops[0], (ast.In, ast.NotIn))):
            continue
        if _tail(node.left) != keyword:
            continue
        keys = _str_tuple(node.comparators[0])
        if keys:
            return keys, node.lineno
    return None


def extract_dict_keys(ctx: FileCtx, varname: str
                      ) -> Optional[Tuple[Set[str], int]]:
    """Module-level ``VARNAME = {"key": ..., ...}`` (annotated or not)."""
    for node in ctx.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
        elif isinstance(node, ast.AnnAssign):
            target = node.target
        else:
            continue
        if isinstance(target, ast.Name) and target.id == varname \
                and isinstance(node.value, ast.Dict):
            keys = set()
            for k in node.value.keys:
                if not (isinstance(k, ast.Constant)
                        and isinstance(k.value, str)):
                    return None
                keys.add(k.value)
            return keys, node.lineno
    return None


def extract_argparse_choices(ctx: FileCtx, flag: str
                             ) -> Optional[Tuple[Set[str], int]]:
    """``ap.add_argument("--flag", choices=(...))``."""
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and _tail(node.func) == "add_argument"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and node.args[0].value == flag):
            continue
        for kw in node.keywords:
            if kw.arg == "choices":
                keys = _str_tuple(kw.value)
                if keys:
                    return keys, node.lineno
    return None


def extract_help_mentions(ctx: FileCtx, gauge: str
                          ) -> Optional[Tuple[str, int]]:
    """The ``help`` string of the gauge dict literal whose ``name``
    is ``gauge`` — compared by *mention* (substring per key), since
    gauge help is prose, not a key list."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Dict):
            continue
        fields: Dict[str, ast.expr] = {}
        for k, v in zip(node.keys, node.values):
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                fields[k.value] = v
        name = fields.get("name")
        if not (isinstance(name, ast.Constant) and name.value == gauge):
            continue
        h = fields.get("help")
        if isinstance(h, ast.Constant) and isinstance(h.value, str):
            return h.value, h.lineno
    return None


def extract_str_tuple_var(ctx: FileCtx, varname: str
                          ) -> Optional[Tuple[Set[str], int]]:
    """Module-level ``VARNAME = ("a", "b", ...)`` string tuple/list —
    the TUNED_FIELDS consumer-claim idiom.  Annotated assignments
    (``VARNAME: Tuple[str, ...] = (...)``) count too."""
    for node in ctx.tree.body:
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            target = node.targets[0].id
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name) \
                and node.value is not None:
            target = node.target.id
        if target == varname:
            keys = _str_tuple(node.value)
            if keys is not None:
                return keys, node.lineno
    return None


_EXTRACTORS = {
    "membership": extract_membership,
    "dict_keys": extract_dict_keys,
    "argparse_choices": extract_argparse_choices,
    "str_tuple_var": extract_str_tuple_var,
}


@dataclass(frozen=True)
class TableRef:
    """One table location: a path suffix + how to read its key set."""

    suffix: str          # matched via ctx.path.endswith(suffix)
    kind: str            # extractor name, or "help_mentions"
    arg: str             # field / var / flag / gauge name


@dataclass(frozen=True)
class TableGroup:
    """A governing literal set and the satellite tables that must
    stay in key-set agreement with it."""

    name: str
    keyword: str                       # the dispatched field name
    governing: TableRef = None         # type: ignore[assignment]
    satellites: Sequence[TableRef] = field(default_factory=tuple)
    # keys legitimately absent from prose-mention satellites (e.g.
    # "auto" resolves to device/host before the gauge reports)
    mention_exempt: Sequence[str] = field(default_factory=tuple)
    # Union semantics: each satellite claims a SUBSET of the governing
    # set, and — when every registered satellite is present — their
    # union must cover it exactly (the autotune-knob group).  K02 does
    # not apply (the keys are knob names, not a dispatched keyword).
    union: bool = False


GROUPS: Sequence[TableGroup] = (
    TableGroup(
        name="dissem",
        keyword="dissem",
        governing=TableRef("consul_tpu/gossip/params.py",
                           "membership", "dissem"),
        satellites=(
            TableRef("consul_tpu/obs/devstats.py",
                     "dict_keys", "DENSE_PASSES_BY_DISSEM"),
            TableRef("bench.py", "argparse_choices", "--dissem"),
            TableRef("tools/profile_kernel.py",
                     "argparse_choices", "--dissem"),
            TableRef("consul_tpu/cli/main.py",
                     "argparse_choices", "-dissem"),
            TableRef("consul_tpu/obs/tuner.py",
                     "str_tuple_var", "DISSEM_CHOICES"),
        ),
    ),
    TableGroup(
        name="chaos-fault",
        keyword="fault",
        governing=TableRef("consul_tpu/chaos/scenarios.py",
                           "membership", "fault"),
        satellites=(
            TableRef("consul_tpu/chaos/scenarios.py",
                     "dict_keys", "CATALOG"),
            TableRef("tools/chaos_campaign.py",
                     "argparse_choices", "--scenario"),
        ),
    ),
    TableGroup(
        name="autotune-knob",
        keyword="knob",
        union=True,
        governing=TableRef("consul_tpu/obs/tuner.py",
                           "dict_keys", "KNOBS"),
        satellites=(
            TableRef("consul_tpu/gossip/plane.py",
                     "str_tuple_var", "TUNED_FIELDS"),
            TableRef("consul_tpu/agent/agent.py",
                     "str_tuple_var", "TUNED_FIELDS"),
            TableRef("consul_tpu/state/device_store.py",
                     "str_tuple_var", "TUNED_FIELDS"),
        ),
    ),
    TableGroup(
        name="journey-stage",
        keyword="stage",
        union=True,
        governing=TableRef("consul_tpu/obs/journey.py",
                           "str_tuple_var", "STAGES"),
        satellites=(
            TableRef("tools/obs_smoke.py",
                     "str_tuple_var", "JOURNEY_STAGES"),
            TableRef("tests/test_journey.py",
                     "str_tuple_var", "JOURNEY_STAGES"),
        ),
    ),
    TableGroup(
        name="match-backend",
        keyword="match_backend",
        governing=TableRef("consul_tpu/state/device_store.py",
                           "membership", "match_backend"),
        satellites=(
            TableRef("consul_tpu/obs/storestats.py",
                     "help_mentions", "consul_watch_match_backend"),
        ),
        mention_exempt=("auto",),
    ),
)


def _find_ctx(ctxs: Sequence[FileCtx], suffix: str) -> Optional[FileCtx]:
    # component-boundary suffix match: "bench.py" must not claim
    # "tools/http_bench.py"
    for ctx in ctxs:
        if ctx.path == suffix or ctx.path.endswith("/" + suffix):
            return ctx
    return None


def _check_group(ctxs: Sequence[FileCtx], group: TableGroup,
                 out: List[Finding]) -> Optional[Tuple[Set[str], str, int]]:
    """K01 for one group; returns (governing keys, path, line) when the
    governing set resolved (K02 needs it)."""
    gctx = _find_ctx(ctxs, group.governing.suffix)
    if gctx is None:
        return None     # subset run: nothing to compare against
    extractor = _EXTRACTORS[group.governing.kind]
    got = extractor(gctx, group.governing.arg)
    if got is None:
        out.append(Finding(
            gctx.path, 1, KEYSET_DIVERGE,
            f"governing {group.keyword!r} set "
            f"({group.governing.kind}: {group.governing.arg}) not "
            "found — the validation idiom moved or was removed; "
            "update tools/vet/table_drift.py GROUPS alongside it"))
        return None
    gov_keys, _gov_line = got

    if group.union:
        _check_union(ctxs, group, gov_keys, gctx, out)
        return gov_keys, gctx.path, _gov_line

    for sat in group.satellites:
        sctx = _find_ctx(ctxs, sat.suffix)
        if sctx is None:
            continue    # subset run
        if sat.kind == "help_mentions":
            hit = extract_help_mentions(sctx, sat.arg)
            if hit is None:
                out.append(Finding(
                    sctx.path, 1, KEYSET_DIVERGE,
                    f"gauge {sat.arg!r} not found but registered as a "
                    f"{group.keyword!r} satellite — update "
                    "tools/vet/table_drift.py GROUPS alongside it"))
                continue
            text, line = hit
            missing = sorted(k for k in gov_keys
                             if k not in group.mention_exempt
                             and k not in text)
            if missing:
                out.append(Finding(
                    sctx.path, line, KEYSET_DIVERGE,
                    f"gauge {sat.arg!r} help does not mention "
                    f"{group.keyword!r} key(s) {missing} from the "
                    f"governing set in {group.governing.suffix} — an "
                    "operator reading the scrape cannot see those "
                    "legs exist"))
            continue
        extractor = _EXTRACTORS[sat.kind]
        got = extractor(sctx, sat.arg)
        if got is None:
            out.append(Finding(
                sctx.path, 1, KEYSET_DIVERGE,
                f"satellite table ({sat.kind}: {sat.arg}) not found "
                f"but registered against the {group.keyword!r} "
                "governing set — update tools/vet/table_drift.py "
                "GROUPS alongside it"))
            continue
        sat_keys, line = got
        missing = sorted(gov_keys - sat_keys)
        extra = sorted(sat_keys - gov_keys)
        if missing or extra:
            detail = []
            if missing:
                detail.append(f"missing {missing}")
            if extra:
                detail.append(f"extra {extra}")
            out.append(Finding(
                sctx.path, line, KEYSET_DIVERGE,
                f"{sat.kind}:{sat.arg} diverges from the governing "
                f"{group.keyword!r} set in {group.governing.suffix}: "
                + ", ".join(detail)))
    return gov_keys, gctx.path, _gov_line


def _check_union(ctxs: Sequence[FileCtx], group: TableGroup,
                 gov_keys: Set[str], gctx: FileCtx,
                 out: List[Finding]) -> None:
    """Union semantics (the autotune-knob group): every satellite's
    claim must be a subset of the governing registry, and — when all
    registered satellites are present — the union must cover the
    registry exactly."""
    claimed: Set[str] = set()
    all_present = True
    for sat in group.satellites:
        sctx = _find_ctx(ctxs, sat.suffix)
        if sctx is None:
            all_present = False   # subset run: skip completeness below
            continue
        extractor = _EXTRACTORS[sat.kind]
        got = extractor(sctx, sat.arg)
        if got is None:
            out.append(Finding(
                sctx.path, 1, KEYSET_DIVERGE,
                f"satellite table ({sat.kind}: {sat.arg}) not found "
                f"but registered against the {group.keyword!r} "
                "governing set — update tools/vet/table_drift.py "
                "GROUPS alongside it"))
            all_present = False
            continue
        sat_keys, line = got
        extra = sorted(sat_keys - gov_keys)
        if extra:
            out.append(Finding(
                sctx.path, line, KEYSET_DIVERGE,
                f"{sat.kind}:{sat.arg} claims {group.keyword}(s) "
                f"{extra} absent from the governing registry in "
                f"{group.governing.suffix} — the claim resolves to "
                "nothing at boot"))
        claimed |= sat_keys
    if all_present:
        unclaimed = sorted(gov_keys - claimed)
        if unclaimed:
            out.append(Finding(
                gctx.path, 1, KEYSET_DIVERGE,
                f"governing {group.keyword!r} registry key(s) "
                f"{unclaimed} are claimed by no consumer TUNED_FIELDS "
                "— a registered knob nothing applies is dead "
                "configuration"))


def _check_strays(ctxs: Sequence[FileCtx], group: TableGroup,
                  gov: Tuple[Set[str], str, int],
                  out: List[Finding]) -> None:
    gov_keys, gov_path, gov_line = gov
    kw = group.keyword
    for ctx in ctxs:
        if kw not in ctx.src:
            continue
        for node in ast.walk(ctx.tree):
            # keyword argument: SwimParams(dissem="florp")
            if isinstance(node, ast.Call):
                for k in node.keywords:
                    if k.arg == kw and isinstance(k.value, ast.Constant) \
                            and isinstance(k.value.value, str) \
                            and k.value.value not in gov_keys:
                        # anchor on the literal's line (where a noqa
                        # naturally sits), not the call head
                        out.append(Finding(
                            ctx.path, k.value.lineno, STRAY_LITERAL,
                            f"{kw}={k.value.value!r} is not in the "
                            f"governing set {sorted(gov_keys)} "
                            f"({gov_path})"))
            # attribute/name assignment: p.dissem = "florp"
            elif isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str) \
                    and node.value.value not in gov_keys:
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and t.attr == kw:
                        out.append(Finding(
                            ctx.path, node.lineno, STRAY_LITERAL,
                            f"{kw} assigned {node.value.value!r}, not "
                            f"in the governing set {sorted(gov_keys)} "
                            f"({gov_path})"))
            # comparison / membership: p.dissem == "florp",
            # dissem in ("swar", "florp")
            elif isinstance(node, ast.Compare) and len(node.ops) == 1 \
                    and _tail(node.left) == kw:
                if ctx.path == gov_path and node.lineno == gov_line:
                    continue    # the governing membership itself
                comp = node.comparators[0]
                bad: List[str] = []
                if isinstance(node.ops[0], (ast.Eq, ast.NotEq)) \
                        and isinstance(comp, ast.Constant) \
                        and isinstance(comp.value, str) \
                        and comp.value not in gov_keys:
                    bad.append(comp.value)
                elif isinstance(node.ops[0], (ast.In, ast.NotIn)):
                    keys = _str_tuple(comp) or set()
                    bad.extend(sorted(keys - gov_keys))
                for val in bad:
                    out.append(Finding(
                        ctx.path, node.lineno, STRAY_LITERAL,
                        f"{kw} compared against {val!r}, not in the "
                        f"governing set {sorted(gov_keys)} "
                        f"({gov_path})"))


# -- environment-gate union group -------------------------------------------
#
# A third table shape: the set of CONSUL_TPU_* environment variables
# the process reads.  The governing registry is ENV_GATES in
# consul_tpu/obs/envgates.py (name -> one-line description); the
# "satellites" are the usage sites themselves — a typo'd gate name at a
# read site resolves to "unset" forever with no runtime check — plus
# the README's environment-gate table.  Union semantics throughout:
# every used name must be registered, every registered name must still
# be read by its canonical reader, and the README must document exactly
# the registry.

ENV_GATE_REGISTRY = TableRef("consul_tpu/obs/envgates.py",
                             "dict_keys", "ENV_GATES")

# Canonical reader per gate: the module whose presence without the
# literal means the gate is dead configuration.  Subset-safe the same
# way satellites are: a gate whose reader isn't among the vetted files
# is skipped.
ENV_GATE_SITES: Dict[str, str] = {
    "CONSUL_TPU_DEV_OBS": "consul_tpu/obs/devstats.py",
    "CONSUL_TPU_RAFT_OBS": "consul_tpu/obs/raftstats.py",
    "CONSUL_TPU_JOURNEY": "consul_tpu/obs/journey.py",
    "CONSUL_TPU_JOURNEY_BUDGET_MS": "consul_tpu/obs/journey.py",
    "CONSUL_TPU_AUTOTUNE": "consul_tpu/obs/tuner.py",
    "CONSUL_TPU_AUTOTUNE_DIR": "consul_tpu/obs/tuner.py",
    "CONSUL_TPU_COMPILE_CACHE": "consul_tpu/gossip/plane.py",
    "CONSUL_TPU_DYN_REPORT": "tools/vet/dyn.py",
    "CONSUL_TPU_DYN_NANS": "tools/vet/dyn.py",
    "CONSUL_TPU_DYN_INTERLEAVE": "tools/vet/dyn.py",
    "CONSUL_TPU_DYN_CANCEL": "tools/vet/dyn.py",
}

# Partner suffixes for --changed expansion (driver.partner_groups).
ENV_GATE_PARTNERS: Tuple[str, ...] = tuple(
    [ENV_GATE_REGISTRY.suffix] + sorted(set(ENV_GATE_SITES.values())))

_ENV_GATE_RE = re.compile(r"CONSUL_TPU_[A-Z0-9_]+")


def _env_literals(ctx: FileCtx) -> List[Tuple[str, int]]:
    """Every full-string CONSUL_TPU_* constant in the file.  Full-match
    only: prose mentions inside docstrings carry surrounding text and
    do not count as usage."""
    out = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and _ENV_GATE_RE.fullmatch(node.value):
            out.append((node.value, node.lineno))
    return out


def check_env_gates(ctxs: Sequence[FileCtx],
                    readme_text: Optional[str] = None) -> List[Finding]:
    """The env-gate union group; ``readme_text`` overrides reading
    README.md from the working directory (unit fixtures).  No README
    present means the README leg is skipped, not failed — subset runs
    and bare checkouts."""
    out: List[Finding] = []
    gctx = _find_ctx(ctxs, ENV_GATE_REGISTRY.suffix)
    if gctx is None:
        return out      # subset run: nothing to compare against
    got = extract_dict_keys(gctx, ENV_GATE_REGISTRY.arg)
    if got is None:
        out.append(Finding(
            gctx.path, 1, KEYSET_DIVERGE,
            "governing ENV_GATES registry not found — the dict moved "
            "or was renamed; update tools/vet/table_drift.py alongside "
            "it"))
        return out
    gates, gov_line = got

    # Registry <-> canonical-site mirror (both live in this repo).
    for name in sorted(gates - set(ENV_GATE_SITES)):
        out.append(Finding(
            gctx.path, gov_line, KEYSET_DIVERGE,
            f"env gate {name} registered in ENV_GATES but has no "
            "canonical reader in tools/vet/table_drift.py "
            "ENV_GATE_SITES — declare where it is read"))
    for name in sorted(set(ENV_GATE_SITES) - gates):
        out.append(Finding(
            gctx.path, gov_line, KEYSET_DIVERGE,
            f"env gate {name} has a canonical reader declared in "
            "ENV_GATE_SITES but is missing from the ENV_GATES "
            "registry"))

    # Usage sweep: every full-string literal must be registered, and
    # each gate's canonical reader must still reference it.
    seen_at_site: Set[str] = set()
    for ctx in ctxs:
        if ctx is gctx:
            continue
        for name, line in _env_literals(ctx):
            if name not in gates:
                out.append(Finding(
                    ctx.path, line, KEYSET_DIVERGE,
                    f"env gate {name} is read here but not registered "
                    "in consul_tpu/obs/envgates.py ENV_GATES — a "
                    "typo'd gate name reads as unset forever"))
            elif _suffix_eq(ctx.path, ENV_GATE_SITES.get(name, "")):
                seen_at_site.add(name)
    for name in sorted(gates & set(ENV_GATE_SITES)):
        site = ENV_GATE_SITES[name]
        sctx = _find_ctx(ctxs, site)
        if sctx is not None and name not in seen_at_site:
            out.append(Finding(
                sctx.path, 1, KEYSET_DIVERGE,
                f"env gate {name} is registered with this module as "
                "its canonical reader, but the literal no longer "
                "appears here — the gate is dead configuration or the "
                "reader moved"))

    # README leg: the environment-gate table must document the
    # registry exactly.
    if readme_text is None:
        p = Path("README.md")
        if not p.is_file():
            return out
        readme_text = p.read_text(encoding="utf-8")
    mentioned: Dict[str, int] = {}
    for i, line in enumerate(readme_text.splitlines(), start=1):
        for m in _ENV_GATE_RE.finditer(line):
            mentioned.setdefault(m.group(0), i)
    for name in sorted(gates - set(mentioned)):
        out.append(Finding(
            "README.md", 1, KEYSET_DIVERGE,
            f"env gate {name} is registered in ENV_GATES but never "
            "mentioned in README.md — operators cannot discover it"))
    for name in sorted(set(mentioned) - gates):
        out.append(Finding(
            "README.md", mentioned[name], KEYSET_DIVERGE,
            f"README.md documents env gate {name}, which is not in "
            "the ENV_GATES registry — stale docs or a typo"))
    return out


def _suffix_eq(path: str, suffix: str) -> bool:
    return bool(suffix) and (path == suffix
                             or path.endswith("/" + suffix))


def check_project(ctxs: List[FileCtx],
                  groups: Sequence[TableGroup] = GROUPS) -> List[Finding]:
    out: List[Finding] = []
    for group in groups:
        gov = _check_group(ctxs, group, out)
        if gov is not None and not group.union:
            _check_strays(ctxs, group, gov, out)
    out.extend(check_env_gates(ctxs))
    return sorted(set(out), key=lambda f: (f.path, f.line, f.code,
                                           f.message))
