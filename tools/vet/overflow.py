"""Overflow pass: width/interval analysis over the integer
accumulators that live inside the traced kernel.

JAX defaults to 32-bit integers (this repo never enables ``x64`` —
doing so would flip every default dtype and break the bit-parity
suite, and ``jnp.int64`` silently *truncates back to int32* under the
default config).  So every monotone counter threaded through the SWIM
scan carry wraps at 2**31 − 1, and nothing crashes when it does: the
crossval gates just start disagreeing with wrong percentiles.

Bounds (documented assumptions, not config):

- ``ROUNDS_BOUND`` = 10_000 rounds/s × 86_400 s ≈ 8.6e8 — one day of
  the paper's 10k-rounds-per-second target (PAPER.md; params.py keeps
  all protocol timing in rounds, so rounds/s is the only wall-clock
  coupling).  A per-round increment of 1 therefore stays under
  2**31 − 1 ≈ 2.1e9: plain ``round + 1`` / cursor bumps are fine.
- ``NODES_BOUND`` = 1_000_000 — the paper's 1M-node scale target.  A
  non-constant per-round increment (``jnp.sum(...)`` over nodes, a
  served-count difference, a vector scatter-add) is bounded only by
  the node count, and 1e6 × 8.6e8 obliterates int32 — and int64 too
  if x64 were ever enabled, hence "fix" usually means wrap-aware
  draining on the host, not widening on the device.

Scope: functions reachable from a tracing entry point (same root set
as the tracer-purity pass) — host-side Python wraps into Python ints
and is exempt.

- **O01 unbounded accumulator**: a self-accumulating statement —
  ``x = x + inc`` / ``x += inc``, the carry idiom
  ``f = state.f + inc`` (or ``_replace(f=state.f + inc)`` /
  ``Type(f=state.f + inc)`` keywords), or a scatter-add
  ``arr.at[i].add(inc)`` — whose increment bound × ``ROUNDS_BOUND``
  exceeds the int32 range.  Kill rules: float evidence in the
  expression (floats saturate precision, they don't wrap); a
  top-level ``jnp.where`` whose other branch re-arms the accumulator
  (a periodic reset bounds the sum); an increment that is a constant
  small enough (|c| × ROUNDS_BOUND < 2**31).
- **O02 mixed-width arithmetic**: a binary op whose two sides carry
  *different* explicit integer dtype markers (``a.astype(jnp.int16) +
  b.astype(jnp.int32)``) — the promotion is silent, and under
  ``check_rep=False`` shard merges a width mismatch between shards'
  contributions is exactly the kind of drift the parity suite cannot
  localize.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.vet.core import FileCtx, Finding, dotted_name
from tools.vet.tracer_purity import (_collect_defs, _mark_roots,
                                     _reachable, _tail)

UNBOUNDED_ACCUMULATOR = "O01"
MIXED_WIDTH = "O02"

ROUNDS_BOUND = 10_000 * 86_400          # one day at 10k rounds/s
NODES_BOUND = 1_000_000                 # paper-scale cluster
INT32_MAX = 2**31 - 1

_INT_WIDTHS = {"int8": 8, "uint8": 8, "int16": 16, "uint16": 16,
               "int32": 32, "uint32": 32, "int64": 64, "uint64": 64}
_FLOAT_DTYPES = {"float16", "float32", "float64", "bfloat16"}


def _has_float(expr: ast.expr) -> bool:
    for n in ast.walk(expr):
        if isinstance(n, ast.Constant) and isinstance(n.value, float):
            return True
        if isinstance(n, (ast.Name, ast.Attribute)) \
                and _tail(n) in _FLOAT_DTYPES:
            return True
        if isinstance(n, ast.BinOp) and isinstance(n.op, ast.Div):
            return True
    return False


def _const_int(expr: ast.expr) -> Optional[int]:
    if isinstance(expr, ast.Constant) \
            and isinstance(expr.value, int) \
            and not isinstance(expr.value, bool):
        return expr.value
    if isinstance(expr, ast.UnaryOp) and isinstance(expr.op, ast.USub):
        inner = _const_int(expr.operand)
        return -inner if inner is not None else None
    return None


_LocalAssigns = Dict[str, List[ast.Assign]]

_FRESH_CTORS = {"zeros", "ones", "full", "empty", "zeros_like",
                "ones_like", "full_like", "arange"}


def _local_assigns(fn: ast.AST) -> _LocalAssigns:
    out: _LocalAssigns = {}
    for n in ast.walk(fn):
        if isinstance(n, ast.Assign):
            for t in n.targets:
                if isinstance(t, ast.Name):
                    out.setdefault(t.id, []).append(n)
    return out


def _bool_evidence(expr: ast.expr, locals_map: _LocalAssigns,
                   depth: int = 0) -> bool:
    """``expr`` is provably 0/1-valued: a comparison, a bool dtype, a
    bool literal — resolving a bare name one level through its local
    assignment (``joining = jnp.zeros((N,), bool).at[...].set(True)``)."""
    for n in ast.walk(expr):
        if isinstance(n, ast.Compare):
            return True
        if isinstance(n, ast.Constant) and isinstance(n.value, bool):
            return True
        if isinstance(n, (ast.Name, ast.Attribute)) \
                and _tail(n) in ("bool", "bool_"):
            return True
    if depth < 1 and isinstance(expr, ast.Name):
        return any(_bool_evidence(a.value, locals_map, depth + 1)
                   for a in locals_map.get(expr.id, []))
    return False


def _inc_bound(inc: ast.expr,
               locals_map: _LocalAssigns) -> Tuple[int, str]:
    """(per-round bound, description) for an increment expression."""
    c = _const_int(inc)
    if c is not None:
        return abs(c), f"constant {c}"
    # a cast of a constant: jnp.int32(1)
    if isinstance(inc, ast.Call) and len(inc.args) == 1:
        c = _const_int(inc.args[0])
        if c is not None and _tail(inc.func) in _INT_WIDTHS:
            return abs(c), f"constant {c}"
    # an element-wise 0/1 mask: mask.astype(jnp.int32), (x == y).astype
    if isinstance(inc, ast.Call) and _tail(inc.func) == "astype" \
            and isinstance(inc.func, ast.Attribute) \
            and _bool_evidence(inc.func.value, locals_map):
        return 1, "a 0/1 mask"
    if _bool_evidence(inc, locals_map) and not any(
            isinstance(n, ast.Call) and _tail(n.func) in ("sum",
                                                          "count_nonzero")
            for n in ast.walk(inc)):
        return 1, "a 0/1 mask"
    for n in ast.walk(inc):
        if isinstance(n, ast.Call) and _tail(n.func) in ("sum",
                                                         "count_nonzero"):
            return NODES_BOUND, "a per-round jnp.sum over nodes"
    return NODES_BOUND, "a non-constant per-round value"


def _reset_anywhere(key: str, locals_map: _LocalAssigns) -> bool:
    """True when SOME assignment to ``key`` in this function re-arms
    it: a ``jnp.where`` with a branch that does not reference the
    accumulator, a scatter ``key.at[...].set(...)``, or a fresh
    constant/constructor rebind.  A periodically reset register is
    bounded by its reset period, not the rounds bound."""
    for a in locals_map.get(key, []):
        v = a.value
        if _reset_evidence(v, key):
            return True
        if isinstance(v, ast.Call) and isinstance(v.func, ast.Attribute) \
                and v.func.attr == "set" \
                and isinstance(v.func.value, ast.Subscript) \
                and isinstance(v.func.value.value, ast.Attribute) \
                and v.func.value.value.attr == "at" \
                and _tail(v.func.value.value.value) == key:
            return True
        if _const_int(v) is not None:
            return True
        if isinstance(v, ast.Call) and _tail(v.func) in _FRESH_CTORS:
            return True
    return False


def _round_local(key: str, lineno: int,
                 locals_map: _LocalAssigns) -> bool:
    """True when ``key`` is freshly constructed earlier in the same
    function (``n_sus = jnp.zeros(...)``): the accumulation is bounded
    by one round's work, not the rounds bound — cross-round state only
    survives through the carry (params / unpacking / attributes)."""
    for a in locals_map.get(key, []):
        if a.lineno >= lineno:
            continue
        v = a.value
        if _const_int(v) is not None:
            return True
        if isinstance(v, ast.Call) and (_tail(v.func) in _FRESH_CTORS
                                        or (_tail(v.func) in _INT_WIDTHS
                                            and v.args
                                            and _const_int(v.args[0])
                                            is not None)):
            return True
    return False


def _split_self_add(target_key: str,
                    value: ast.expr) -> Optional[ast.expr]:
    """When ``value`` is ``<base> + inc`` (either side) with ``base``
    naming the accumulator (bare name or attribute tail, e.g. both
    ``x`` and ``state.x`` match key ``x``), return the increment.
    Sees through a top-level ``jnp.where`` — a conditional accumulate
    (``x = where(c, x + inc, x)``) is still an accumulate."""
    if isinstance(value, ast.Call) and _tail(value.func) == "where" \
            and len(value.args) == 3:
        for branch in value.args[1:]:
            inc = _split_self_add(target_key, branch)
            if inc is not None:
                return inc
        return None
    if not (isinstance(value, ast.BinOp) and isinstance(value.op, ast.Add)):
        return None

    def names_key(side: ast.expr) -> bool:
        return _tail(side) == target_key \
            if isinstance(side, (ast.Name, ast.Attribute)) else False

    if names_key(value.left):
        return value.right
    if names_key(value.right):
        return value.left
    return None


def _reset_evidence(value: ast.expr, key: str) -> bool:
    """``jnp.where(cond, reset, x + inc)`` at top level: one branch
    re-arms the accumulator, bounding the sum between resets."""
    if not (isinstance(value, ast.Call) and _tail(value.func) == "where"
            and len(value.args) == 3):
        return False
    def refs(side: ast.expr) -> bool:
        return any(_tail(n) == key
                   for n in ast.walk(side)
                   if isinstance(n, (ast.Name, ast.Attribute)))
    branches = value.args[1:]
    return any(not refs(b) for b in branches)


def _emit_o01(ctx: FileCtx, fn_name: str, lineno: int, key: str,
              inc: ast.expr, locals_map: _LocalAssigns,
              out: List[Finding]) -> None:
    bound, what = _inc_bound(inc, locals_map)
    if bound * ROUNDS_BOUND <= INT32_MAX:
        return
    total = bound * ROUNDS_BOUND
    out.append(Finding(
        ctx.path, lineno, UNBOUNDED_ACCUMULATOR,
        f"accumulator '{key}' in traced '{fn_name}' grows by {what} "
        f"every round — bound {bound:,} x {ROUNDS_BOUND:,} rounds/day "
        f"~= {total:.1e} overflows int32 ({INT32_MAX:,}); drain it "
        "wrap-aware on the host or document the wrap convention"))


def _check_assign(ctx: FileCtx, fn_name: str, node: ast.stmt,
                  locals_map: _LocalAssigns, out: List[Finding]) -> None:
    if isinstance(node, ast.AugAssign) and isinstance(node.op, ast.Add):
        key = _tail(node.target)
        if key is not None and not _has_float(node.value) \
                and not (isinstance(node.target, ast.Name)
                         and _round_local(key, node.lineno, locals_map)):
            _emit_o01(ctx, fn_name, node.lineno, key, node.value,
                      locals_map, out)
        return
    if not isinstance(node, ast.Assign):
        return
    for tgt in node.targets:
        key = _tail(tgt)
        if key is None:
            continue
        key = key.rsplit(".", 1)[-1]
        val = node.value
        if _reset_evidence(val, key):
            continue
        inc = _split_self_add(key, val)
        if inc is None or _has_float(node.value):
            continue
        if isinstance(tgt, ast.Name) \
                and isinstance(val, ast.BinOp) \
                and isinstance(val.left, ast.Name) \
                and _round_local(key, node.lineno, locals_map):
            continue  # fresh per-call accumulator, not carry state
        if isinstance(tgt, ast.Name) and _reset_anywhere(key, locals_map):
            continue  # periodically re-armed register, bounded
        _emit_o01(ctx, fn_name, node.lineno, key, inc, locals_map, out)


def _check_kwargs(ctx: FileCtx, fn_name: str, call: ast.Call,
                  locals_map: _LocalAssigns, out: List[Finding]) -> None:
    """carry-constructor idiom: SwimState(..., n=state.n + inc) or
    state._replace(n=state.n + inc)."""
    for kw in call.keywords:
        if kw.arg is None:
            continue
        if _reset_evidence(kw.value, kw.arg):
            continue
        inc = _split_self_add(kw.arg, kw.value)
        if inc is not None and not _has_float(kw.value):
            _emit_o01(ctx, fn_name, kw.value.lineno, kw.arg, inc,
                      locals_map, out)


def _check_scatter_add(ctx: FileCtx, fn_name: str, call: ast.Call,
                       out: List[Finding]) -> None:
    # <arr>.at[idx].add(inc): a vector accumulator.  The per-round
    # increment is the scatter payload; a full-vector scatter lands up
    # to one count per node per round on *some* bucket.
    if not (isinstance(call.func, ast.Attribute) and call.func.attr == "add"
            and isinstance(call.func.value, ast.Subscript)
            and isinstance(call.func.value.value, ast.Attribute)
            and call.func.value.value.attr == "at"
            and call.args):
        return
    base = dotted_name(call.func.value.value.value) or "<array>"
    inc = call.args[0]
    if _has_float(inc):
        return
    c = _const_int(inc)
    # even a constant payload lands once per scatter lane, and the
    # kernel's scatters are per-node — fan-in is the node count
    per_round = (abs(c) if c is not None else 1) * NODES_BOUND
    if per_round * ROUNDS_BOUND <= INT32_MAX:
        return
    out.append(Finding(
        ctx.path, call.lineno, UNBOUNDED_ACCUMULATOR,
        f"scatter-add into '{base}' in traced '{fn_name}' accumulates "
        f"up to ~{per_round:.0e}/round across lanes — overflows int32 "
        f"({INT32_MAX:,}) well inside a day at 10k rounds/s; drain it "
        "wrap-aware on the host or document the wrap convention"))


def _int_marker(expr: ast.expr) -> Optional[str]:
    """The explicit integer dtype a side of a BinOp is cast to, if
    exactly one marker is visible."""
    found: Set[str] = set()
    for n in ast.walk(expr):
        if isinstance(n, ast.Call):
            t = _tail(n.func)
            if t in _INT_WIDTHS:
                found.add(t)
            elif t == "astype" and n.args and _tail(n.args[0]) in _INT_WIDTHS:
                found.add(_tail(n.args[0]))  # type: ignore[arg-type]
    return found.pop() if len(found) == 1 else None


def _check_mixed_width(ctx: FileCtx, fn_name: str, node: ast.BinOp,
                       out: List[Finding]) -> None:
    if not isinstance(node.op, (ast.Add, ast.Sub, ast.Mult, ast.BitOr,
                                ast.BitAnd, ast.BitXor)):
        return
    lm, rm = _int_marker(node.left), _int_marker(node.right)
    if lm is None or rm is None or lm == rm:
        return
    if _INT_WIDTHS[lm] == _INT_WIDTHS[rm]:
        return  # same width, signedness mix — a different (rarer) story
    out.append(Finding(
        ctx.path, node.lineno, MIXED_WIDTH,
        f"mixed-width integer arithmetic in traced '{fn_name}': "
        f"{lm} {type(node.op).__name__.lower()} {rm} promotes "
        "silently — cast both sides to one width so shard merges and "
        "the reference kernel agree"))


def check(ctx: FileCtx) -> List[Finding]:
    if "jax" not in ctx.src:
        return []
    from tools.vet.async_safety import _module_imports
    imports = _module_imports(ctx.tree)
    if imports.get("jax") != "jax" and not any(
            v == "jax" or v.startswith("jax.") for v in imports.values()):
        return []
    defs = _collect_defs(ctx.tree)
    _mark_roots(ctx.tree, defs)
    findings: List[Finding] = []
    seen: Set[int] = set()
    for info in _reachable(defs):
        if id(info.node) in seen:
            continue
        seen.add(id(info.node))
        locals_map = _local_assigns(info.node)
        for node in ast.walk(info.node):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                _check_assign(ctx, info.name, node, locals_map, findings)
            elif isinstance(node, ast.Call):
                _check_kwargs(ctx, info.name, node, locals_map, findings)
                _check_scatter_add(ctx, info.name, node, findings)
            elif isinstance(node, ast.BinOp):
                _check_mixed_width(ctx, info.name, node, findings)
    return sorted(set(findings), key=lambda f: (f.line, f.code, f.message))
