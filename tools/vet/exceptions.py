"""Exception-hygiene pass.

- **E01 bare except**: ``except:`` catches ``SystemExit``,
  ``KeyboardInterrupt`` and ``asyncio.CancelledError`` alongside real
  errors — name what you mean.
- **E02 silent broad handler**: ``except Exception: pass`` (or
  ``BaseException``, or a tuple containing either) — errors vanish
  without a trace.  Either handle, log, or narrow; a deliberate
  swallow earns a ``# noqa: E02`` with a justification comment.
- **E03 swallowed cancellation**: a handler *inside a coroutine* whose
  caught set includes ``asyncio.CancelledError`` — explicitly in a
  tuple with other types, via ``BaseException``, or via a bare
  ``except`` — and whose body never re-raises.  Since Python 3.8
  ``CancelledError`` derives from ``BaseException`` precisely so broad
  ``except Exception`` handlers DON'T eat it; a handler that opts back
  in makes the task uncancellable: ``await task`` after ``cancel()``
  hangs, and shutdown deadlocks.  A handler catching **only**
  ``CancelledError`` is exempt — that is the deliberate
  cancel-then-await idiom, visible and greppable.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from tools.vet.core import FileCtx, Finding, dotted_name, func_scopes

BARE_EXCEPT = "E01"
SILENT_BROAD = "E02"
SWALLOWED_CANCEL = "E03"


def _caught_names(handler: ast.ExceptHandler) -> Optional[Set[str]]:
    """Simple names of the caught exception types (dotted chains keep
    only the tail: ``asyncio.CancelledError`` -> ``CancelledError``).
    None for a bare ``except:``."""
    t = handler.type
    if t is None:
        return None
    nodes = t.elts if isinstance(t, ast.Tuple) else [t]
    out: Set[str] = set()
    for n in nodes:
        dn = dotted_name(n)
        if dn:
            out.add(dn.rsplit(".", 1)[-1])
    return out


def _reraises(handler: ast.ExceptHandler) -> bool:
    """True when any path in the handler body re-raises (bare ``raise``
    or an explicit raise of the caught name), stopping at nested
    function boundaries."""
    todo = list(handler.body)
    while todo:
        node = todo.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Raise):
            return True
        todo.extend(ast.iter_child_nodes(node))
    return False


def _is_pass_only(handler: ast.ExceptHandler) -> bool:
    return len(handler.body) == 1 and isinstance(handler.body[0], ast.Pass)


def check(ctx: FileCtx) -> List[Finding]:
    findings: List[Finding] = []
    for node, in_async in func_scopes(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        caught = _caught_names(node)
        if caught is None:
            findings.append(Finding(
                ctx.path, node.lineno, BARE_EXCEPT,
                "bare 'except:' catches SystemExit/KeyboardInterrupt/"
                "CancelledError — name the exceptions you mean"))
        broad = caught is None or bool(
            caught & {"Exception", "BaseException"})
        if broad and _is_pass_only(node):
            findings.append(Finding(
                ctx.path, node.lineno, SILENT_BROAD,
                "broad handler silently swallows exceptions "
                "('except {}: pass') — handle, log, or narrow".format(
                    "/".join(sorted(caught)) if caught else ":")))
        if in_async and not _reraises(node):
            catches_cancel = caught is None \
                or "BaseException" in caught \
                or "CancelledError" in caught
            only_cancel = caught is not None and caught == {
                "CancelledError"}
            if catches_cancel and not only_cancel:
                findings.append(Finding(
                    ctx.path, node.lineno, SWALLOWED_CANCEL,
                    "handler swallows asyncio.CancelledError inside a "
                    "coroutine — the task becomes uncancellable and "
                    "shutdown can deadlock; re-raise it or split the "
                    "handler"))
    return sorted(findings, key=lambda f: (f.line, f.code))
