"""Dynamic sanitizer harness (``make vet-dyn``): run the fast tier-1
slice under every cheap runtime oracle the box offers, then a checkify
sweep over one adversarial dissemination round.

The static passes prove shape/contract properties; this module covers
what only execution shows:

- **NaN debugging**: ``jax.config.jax_debug_nans`` on the whole slice
  (the gossip plane is integer math end to end — a NaN anywhere is a
  bug, and debug_nans makes the producing primitive raise instead of
  the consumer 40 ops later).
- **asyncio debug mode** (``PYTHONASYNCIODEBUG=1`` + ``-X dev``):
  slow-callback warnings, never-retrieved exceptions, and the
  "Task was destroyed but it is pending!" error the serving plane can
  only produce under a live loop; the plugin below captures the
  asyncio logger so those fail the run instead of scrolling by.
- **Warnings as errors** for the coroutine-hygiene classes
  (``RuntimeWarning``: never-awaited coroutines, unawaited tasks).
- **fd / thread / task leak assertions** at session teardown: the
  plugin snapshots ``/proc/self/fd`` and the live thread set at
  configure time and reports the delta in a JSON artifact the runner
  evaluates (``FD_SLACK`` absorbs interpreter-internal churn; a real
  per-test socket leak in a 100+-test slice blows well past it).
- **checkify smoke**: one ``_disseminate`` round per strategy on the
  adversarial saturated inputs, under ``checkify``'s index + float
  error set — the dynamic twin of the P03 window-bounds pass
  (an in-kernel offset past the block window surfaces here as a
  checkify OOB error instead of silent wraparound).
- **forced-interleave leg** (``CONSUL_TPU_DYN_INTERLEAVE=1``): the
  lease/barrier and anti-entropy slices re-run under a Future shim
  whose ``__await__`` yields once before delivering even an
  already-done result, so EVERY await point is a real task switch.
  Awaits that normal scheduling never suspends at (done futures,
  uncontended locks) become suspension points, and any
  read-await-write sequence whose correctness depends on "nothing ran
  in between" trips its own assertions — the dynamic twin of the
  static X01 pass.

Dual-role module: ``python -m tools.vet.dyn`` is the runner;
``-p tools.vet.dyn`` loads it as the pytest plugin inside the child
run.  The runner subprocesses pytest so the sanitizer env (asyncio
debug, warning filters, debug_nans) cannot contaminate the parent.

Exit codes mirror vet: 0 clean, 1 sanitizer findings (pytest failure,
leak, or checkify error).
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import tempfile
import threading
from pathlib import Path
from typing import Dict, List, Optional, Sequence

# The fast tier-1 slice: host-plane suites (FSM, store, watch, lease,
# config, blocking queries) + one jit-heavy integer kernel suite
# (feistel) — measured ~12 s wall on this box, all asyncio-using.
SLICE: Sequence[str] = (
    "tests/test_feistel.py",
    "tests/test_fsm.py",
    "tests/test_state_store.py",
    "tests/test_blocking_notify.py",
    "tests/test_confirm_batch.py",
    "tests/test_leases.py",
    "tests/test_config.py",
    "tests/test_watch.py",
)

REPORT_ENV = "CONSUL_TPU_DYN_REPORT"
NANS_ENV = "CONSUL_TPU_DYN_NANS"
INTERLEAVE_ENV = "CONSUL_TPU_DYN_INTERLEAVE"

# The interleaving-stress slice (the dynamic twin of the static X01
# pass): the lease/barrier and anti-entropy suites — the paths whose
# correctness arguments are happens-before arguments — re-run under an
# event loop that forces a task switch at every await point.
INTERLEAVE_SLICE: Sequence[str] = (
    "tests/test_leases.py",
    "tests/test_confirm_batch.py",
    "tests/test_agent_checks.py",
)

# /proc/self/fd churn an interpreter produces on its own (lazy imports,
# epoll fds, pipes pytest owns) — a real leak in a 100+-test slice is
# O(tests), far beyond this.
FD_SLACK = 32


# -- plugin role -------------------------------------------------------------

_state: Dict[str, object] = {}


def install_forced_interleave() -> None:
    """Replace ``asyncio.Future`` with a subclass whose ``__await__``
    yields once unconditionally before the normal protocol.

    ``Task.__step`` treats a bare ``yield None`` as "reschedule me via
    call_soon", so every ``await`` — including awaits on already-done
    futures and uncontended locks that vanilla asyncio completes
    without suspending — becomes a genuine task switch.  That is the
    maximally hostile (but still legal) scheduler for TOCTOU hunting:
    any coroutine relying on "no one ran between my read and my write"
    loses that property at every await point, not just the ones the
    wall clock happens to contend.

    Patching ``asyncio.futures.Future`` (not instances — the C
    accelerator class rejects attribute assignment) is sufficient:
    ``loop.create_future()`` resolves the name at call time, so locks,
    events, ``sleep``, ``wrap_future`` and friends all mint shimmed
    futures, and ``Task`` remains untouched (a Task IS a Future; only
    awaits *on* futures need the extra hop).
    """
    import asyncio.futures

    base = asyncio.futures._PyFuture

    class _ForcedSwitchFuture(base):  # type: ignore[valid-type, misc]
        def __await__(self):
            yield self._force_marker  # one mandatory trip through the loop
            return (yield from super().__await__())

        # Task.__step special-cases None: anything else raises. The
        # class attr documents intent; the value must stay None.
        _force_marker = None

        __iter__ = __await__

    asyncio.futures.Future = _ForcedSwitchFuture
    asyncio.Future = _ForcedSwitchFuture


def _fd_count() -> int:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:       # non-Linux: fd accounting unavailable
        return -1


class _AsyncioLogCapture(logging.Handler):
    """Collects ERROR records from the asyncio logger — the channel
    for "Task was destroyed but it is pending!" and exception-in-
    never-retrieved-future reports, which otherwise only reach
    stderr."""

    def __init__(self) -> None:
        super().__init__(logging.ERROR)
        self.messages: List[str] = []

    def emit(self, record: logging.LogRecord) -> None:
        self.messages.append(record.getMessage())


def pytest_configure(config) -> None:
    if os.environ.get(NANS_ENV) == "1":
        import jax
        jax.config.update("jax_debug_nans", True)
    if os.environ.get(INTERLEAVE_ENV) == "1":
        install_forced_interleave()
    _state["fd0"] = _fd_count()
    _state["threads0"] = {t.name for t in threading.enumerate()}
    handler = _AsyncioLogCapture()
    logging.getLogger("asyncio").addHandler(handler)
    _state["asyncio_handler"] = handler


def pytest_sessionfinish(session, exitstatus) -> None:
    report_path = os.environ.get(REPORT_ENV)
    if not report_path:
        return
    handler = _state.get("asyncio_handler")
    threads0 = _state.get("threads0") or set()
    extra_threads = sorted(
        t.name for t in threading.enumerate()
        if t.name not in threads0 and not t.daemon and t.is_alive())
    report = {
        "fd_start": _state.get("fd0", -1),
        "fd_end": _fd_count(),
        "extra_threads": extra_threads,
        "asyncio_errors": list(handler.messages) if handler else [],
        "exitstatus": int(exitstatus),
    }
    Path(report_path).write_text(json.dumps(report, indent=2) + "\n",
                                 encoding="utf-8")


# -- leak evaluation (pure, unit-tested) -------------------------------------


def evaluate_leaks(report: Dict[str, object],
                   fd_slack: int = FD_SLACK) -> List[str]:
    """Human-readable problems from a session report; empty = clean."""
    problems: List[str] = []
    fd0 = int(report.get("fd_start", -1))
    fd1 = int(report.get("fd_end", -1))
    if fd0 >= 0 and fd1 >= 0 and fd1 - fd0 > fd_slack:
        problems.append(
            f"fd leak: {fd0} open fds at session start, {fd1} at "
            f"teardown (> {fd_slack} slack) — an unclosed socket/file "
            "per test compounds exactly like this")
    for name in report.get("extra_threads", []):
        problems.append(
            f"thread leak: non-daemon thread {name!r} still alive at "
            "session teardown — it outlives pytest and will deadlock "
            "interpreter shutdown")
    for msg in report.get("asyncio_errors", []):
        problems.append(f"asyncio error-log: {msg}")
    return problems


# -- checkify smoke ----------------------------------------------------------


def checkify_smoke() -> Optional[str]:
    """One adversarial dissemination round per strategy under
    checkify's index+float oracle; returns an error string or None.
    The dynamic twin of the static P03 pass: an in-kernel offset past
    the block window is an OOB gather here, not a silent wrap."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import checkify

    from consul_tpu.gossip.kernel import _disseminate
    from consul_tpu.gossip.params import SwimParams

    S, N = 4, 24
    rng = np.random.default_rng(0)
    heard = jnp.asarray(((rng.integers(0, 4, (S, N)) << 6)
                         | (rng.integers(0, 4, (S, N)) << 4)
                         | rng.integers(0, 16, (S, N))).astype(np.uint8))
    mf = jnp.asarray(rng.choice(
        np.asarray([-1, 10, 200, 2**31 - 1], np.int32), (N,)))
    rx_ok = jnp.asarray(rng.random(N) < 0.9)
    cap = jnp.asarray(rng.integers(0, 4, (S,)).astype(np.int32))
    key = jax.random.key(3)

    for dissem in ("swar", "planes", "prefused", "fused"):
        p = SwimParams(n=N, slots=S, dissem=dissem)

        def round_fn(heard, mf, rx_ok, cap, p=p):
            return _disseminate(p, 5, key, heard, mf, rx_ok, cap)

        try:
            checked = checkify.checkify(
                round_fn,
                errors=checkify.index_checks | checkify.float_checks)
            err, _out = checked(heard, mf, rx_ok, cap)
            err.throw()
        except Exception as e:    # noqa: E02 - the smoke's verdict IS
            # the exception (checkify error or composition failure);
            # it is reported, not swallowed
            if "pallas_call" in str(e):
                # Known jax limitation on this version: checkify cannot
                # functionalize through pallas_call.  The fused leg's
                # window bounds are covered statically (P03) and by the
                # bit-exact parity suite instead.
                print(f"dyn: note: checkify[{dissem}] skipped — "
                      "checkify does not compose with pallas_call on "
                      "this jax; covered by vet P03 + "
                      "tests/test_fused_parity.py", file=sys.stderr)
                continue
            return f"checkify[{dissem}]: {type(e).__name__}: {e}"
    return None


# -- runner role -------------------------------------------------------------


def main(argv: Optional[Sequence[str]] = None) -> int:
    tests = list(argv) if argv else list(SLICE)
    problems: List[str] = []

    with tempfile.TemporaryDirectory(prefix="vet-dyn-") as td:
        report_path = os.path.join(td, "dyn_report.json")
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env["PYTHONASYNCIODEBUG"] = "1"
        env[REPORT_ENV] = report_path
        env.setdefault(NANS_ENV, "1")
        cmd = [sys.executable, "-X", "dev",
               "-W", "error::RuntimeWarning",
               "-m", "pytest", *tests, "-q",
               "-p", "tools.vet.dyn", "-p", "no:cacheprovider"]
        print("dyn: running sanitized slice:", " ".join(tests),
              file=sys.stderr)
        proc = subprocess.run(cmd, env=env)
        if proc.returncode != 0:
            problems.append(
                f"sanitized pytest run failed (rc={proc.returncode}) — "
                "see output above (debug_nans / asyncio debug / "
                "warnings-as-errors)")
        if os.path.isfile(report_path):
            report = json.loads(Path(report_path).read_text())
            problems.extend(evaluate_leaks(report))
        else:
            problems.append("dyn plugin wrote no session report — the "
                            "run died before teardown")

    # Interleaving-stress leg: only when running the default slice (an
    # explicit test list means the caller is bisecting one suite).
    # Asyncio debug mode stays OFF here — the forced switches multiply
    # callback counts ~10x and debug bookkeeping turns signal to noise;
    # the oracle for this leg is the tests' own assertions.
    if not argv:
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env[INTERLEAVE_ENV] = "1"
        cmd = [sys.executable, "-m", "pytest", *INTERLEAVE_SLICE, "-q",
               "-p", "tools.vet.dyn", "-p", "no:cacheprovider"]
        print("dyn: forced-interleave slice (task switch at every "
              "await):", " ".join(INTERLEAVE_SLICE), file=sys.stderr)
        proc = subprocess.run(cmd, env=env)
        if proc.returncode != 0:
            problems.append(
                f"forced-interleave run failed (rc={proc.returncode}) — "
                "an await-atomicity assumption broke when every await "
                "became a real task switch (dynamic twin of vet X01)")

    print("dyn: checkify smoke (index+float oracle over one round per "
          "strategy)", file=sys.stderr)
    err = checkify_smoke()
    if err:
        problems.append(err)

    for p in problems:
        print(f"dyn: FAIL: {p}", file=sys.stderr)
    if not problems:
        print("dyn: clean (slice + leak audit + interleave + checkify)",
              file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
