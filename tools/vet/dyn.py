"""Dynamic sanitizer harness (``make vet-dyn``): run the fast tier-1
slice under every cheap runtime oracle the box offers, then a checkify
sweep over one adversarial dissemination round.

The static passes prove shape/contract properties; this module covers
what only execution shows:

- **NaN debugging**: ``jax.config.jax_debug_nans`` on the whole slice
  (the gossip plane is integer math end to end — a NaN anywhere is a
  bug, and debug_nans makes the producing primitive raise instead of
  the consumer 40 ops later).
- **asyncio debug mode** (``PYTHONASYNCIODEBUG=1`` + ``-X dev``):
  slow-callback warnings, never-retrieved exceptions, and the
  "Task was destroyed but it is pending!" error the serving plane can
  only produce under a live loop; the plugin below captures the
  asyncio logger so those fail the run instead of scrolling by.
- **Warnings as errors** for the coroutine-hygiene classes
  (``RuntimeWarning``: never-awaited coroutines, unawaited tasks).
- **fd / thread / task leak assertions** at session teardown: the
  plugin snapshots ``/proc/self/fd`` and the live thread set at
  configure time and reports the delta in a JSON artifact the runner
  evaluates (``FD_SLACK`` absorbs interpreter-internal churn; a real
  per-test socket leak in a 100+-test slice blows well past it).
- **checkify smoke**: one ``_disseminate`` round per strategy on the
  adversarial saturated inputs, under ``checkify``'s index + float
  error set — the dynamic twin of the P03 window-bounds pass
  (an in-kernel offset past the block window surfaces here as a
  checkify OOB error instead of silent wraparound).
- **forced-interleave leg** (``CONSUL_TPU_DYN_INTERLEAVE=1``): the
  lease/barrier and anti-entropy slices re-run under a Future shim
  whose ``__await__`` yields once before delivering even an
  already-done result, so EVERY await point is a real task switch.
  Awaits that normal scheduling never suspends at (done futures,
  uncontended locks) become suspension points, and any
  read-await-write sequence whose correctness depends on "nothing ran
  in between" trips its own assertions — the dynamic twin of the
  static X01 pass.
- **cancel-injection leg** (``CONSUL_TPU_DYN_CANCEL=1``): the dynamic
  twin of the static Q01–Q04 tier.  Dedicated scenarios drive the
  REAL production objects behind the lease/barrier (ReadIndex confirm
  batching), reconcile-flush, and blocking-query slices — scenarios
  rather than pytest re-runs, because cancellation must land on a
  chosen VICTIM task at a chosen await point and the oracles
  (no future left pending, no batch left unfired, no waiter leaked)
  live on object internals a test run doesn't expose.  A Future shim
  counts the awaits the victim task enters and cancels it at the
  k-th; k sweeps 1, 2, ... until a run completes before the k-th
  await, so every distinct await point in the victim gets exactly one
  run where cancellation lands there.  After each run the scenario
  asserts the hand-off invariants and that a fresh probe request
  still resolves (the system is not wedged).

Dual-role module: ``python -m tools.vet.dyn`` is the runner;
``-p tools.vet.dyn`` loads it as the pytest plugin inside the child
run.  The runner subprocesses pytest so the sanitizer env (asyncio
debug, warning filters, debug_nans) cannot contaminate the parent.

Exit codes mirror vet: 0 clean, 1 sanitizer findings (pytest failure,
leak, or checkify error).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import subprocess
import sys
import tempfile
import threading
from pathlib import Path
from typing import Dict, List, Optional, Sequence

# The fast tier-1 slice: host-plane suites (FSM, store, watch, lease,
# config, blocking queries) + one jit-heavy integer kernel suite
# (feistel) — measured ~12 s wall on this box, all asyncio-using.
SLICE: Sequence[str] = (
    "tests/test_feistel.py",
    "tests/test_fsm.py",
    "tests/test_state_store.py",
    "tests/test_blocking_notify.py",
    "tests/test_confirm_batch.py",
    "tests/test_leases.py",
    "tests/test_config.py",
    "tests/test_watch.py",
)

REPORT_ENV = "CONSUL_TPU_DYN_REPORT"
NANS_ENV = "CONSUL_TPU_DYN_NANS"
INTERLEAVE_ENV = "CONSUL_TPU_DYN_INTERLEAVE"
CANCEL_ENV = "CONSUL_TPU_DYN_CANCEL"

# The interleaving-stress slice (the dynamic twin of the static X01
# pass): the lease/barrier and anti-entropy suites — the paths whose
# correctness arguments are happens-before arguments — re-run under an
# event loop that forces a task switch at every await point.
INTERLEAVE_SLICE: Sequence[str] = (
    "tests/test_leases.py",
    "tests/test_confirm_batch.py",
    "tests/test_agent_checks.py",
)

# /proc/self/fd churn an interpreter produces on its own (lazy imports,
# epoll fds, pipes pytest owns) — a real leak in a 100+-test slice is
# O(tests), far beyond this.
FD_SLACK = 32


# -- plugin role -------------------------------------------------------------

_state: Dict[str, object] = {}


def install_forced_interleave() -> None:
    """Replace ``asyncio.Future`` with a subclass whose ``__await__``
    yields once unconditionally before the normal protocol.

    ``Task.__step`` treats a bare ``yield None`` as "reschedule me via
    call_soon", so every ``await`` — including awaits on already-done
    futures and uncontended locks that vanilla asyncio completes
    without suspending — becomes a genuine task switch.  That is the
    maximally hostile (but still legal) scheduler for TOCTOU hunting:
    any coroutine relying on "no one ran between my read and my write"
    loses that property at every await point, not just the ones the
    wall clock happens to contend.

    Patching ``asyncio.futures.Future`` (not instances — the C
    accelerator class rejects attribute assignment) is sufficient:
    ``loop.create_future()`` resolves the name at call time, so locks,
    events, ``sleep``, ``wrap_future`` and friends all mint shimmed
    futures, and ``Task`` remains untouched (a Task IS a Future; only
    awaits *on* futures need the extra hop).
    """
    import asyncio.futures

    base = asyncio.futures._PyFuture

    class _ForcedSwitchFuture(base):  # type: ignore[valid-type, misc]
        def __await__(self):
            yield self._force_marker  # one mandatory trip through the loop
            return (yield from super().__await__())

        # Task.__step special-cases None: anything else raises. The
        # class attr documents intent; the value must stay None.
        _force_marker = None

        __iter__ = __await__

    asyncio.futures.Future = _ForcedSwitchFuture
    asyncio.Future = _ForcedSwitchFuture


def _fd_count() -> int:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:       # non-Linux: fd accounting unavailable
        return -1


class _AsyncioLogCapture(logging.Handler):
    """Collects ERROR records from the asyncio logger — the channel
    for "Task was destroyed but it is pending!" and exception-in-
    never-retrieved-future reports, which otherwise only reach
    stderr."""

    def __init__(self) -> None:
        super().__init__(logging.ERROR)
        self.messages: List[str] = []

    def emit(self, record: logging.LogRecord) -> None:
        self.messages.append(record.getMessage())


def pytest_configure(config) -> None:
    if os.environ.get(NANS_ENV) == "1":
        import jax
        jax.config.update("jax_debug_nans", True)
    if os.environ.get(INTERLEAVE_ENV) == "1":
        install_forced_interleave()
    _state["fd0"] = _fd_count()
    _state["threads0"] = {t.name for t in threading.enumerate()}
    handler = _AsyncioLogCapture()
    logging.getLogger("asyncio").addHandler(handler)
    _state["asyncio_handler"] = handler


def pytest_sessionfinish(session, exitstatus) -> None:
    report_path = os.environ.get(REPORT_ENV)
    if not report_path:
        return
    handler = _state.get("asyncio_handler")
    threads0 = _state.get("threads0") or set()
    extra_threads = sorted(
        t.name for t in threading.enumerate()
        if t.name not in threads0 and not t.daemon and t.is_alive())
    report = {
        "fd_start": _state.get("fd0", -1),
        "fd_end": _fd_count(),
        "extra_threads": extra_threads,
        "asyncio_errors": list(handler.messages) if handler else [],
        "exitstatus": int(exitstatus),
    }
    Path(report_path).write_text(json.dumps(report, indent=2) + "\n",
                                 encoding="utf-8")


# -- leak evaluation (pure, unit-tested) -------------------------------------


def evaluate_leaks(report: Dict[str, object],
                   fd_slack: int = FD_SLACK) -> List[str]:
    """Human-readable problems from a session report; empty = clean."""
    problems: List[str] = []
    fd0 = int(report.get("fd_start", -1))
    fd1 = int(report.get("fd_end", -1))
    if fd0 >= 0 and fd1 >= 0 and fd1 - fd0 > fd_slack:
        problems.append(
            f"fd leak: {fd0} open fds at session start, {fd1} at "
            f"teardown (> {fd_slack} slack) — an unclosed socket/file "
            "per test compounds exactly like this")
    for name in report.get("extra_threads", []):
        problems.append(
            f"thread leak: non-daemon thread {name!r} still alive at "
            "session teardown — it outlives pytest and will deadlock "
            "interpreter shutdown")
    for msg in report.get("asyncio_errors", []):
        problems.append(f"asyncio error-log: {msg}")
    return problems


# -- checkify smoke ----------------------------------------------------------


def checkify_smoke() -> Optional[str]:
    """One adversarial dissemination round per strategy under
    checkify's index+float oracle; returns an error string or None.
    The dynamic twin of the static P03 pass: an in-kernel offset past
    the block window is an OOB gather here, not a silent wrap."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import checkify

    from consul_tpu.gossip.kernel import _disseminate
    from consul_tpu.gossip.params import SwimParams

    S, N = 4, 24
    rng = np.random.default_rng(0)
    heard = jnp.asarray(((rng.integers(0, 4, (S, N)) << 6)
                         | (rng.integers(0, 4, (S, N)) << 4)
                         | rng.integers(0, 16, (S, N))).astype(np.uint8))
    mf = jnp.asarray(rng.choice(
        np.asarray([-1, 10, 200, 2**31 - 1], np.int32), (N,)))
    rx_ok = jnp.asarray(rng.random(N) < 0.9)
    cap = jnp.asarray(rng.integers(0, 4, (S,)).astype(np.int32))
    key = jax.random.key(3)

    for dissem in ("swar", "planes", "prefused", "fused"):
        p = SwimParams(n=N, slots=S, dissem=dissem)

        def round_fn(heard, mf, rx_ok, cap, p=p):
            return _disseminate(p, 5, key, heard, mf, rx_ok, cap)

        try:
            checked = checkify.checkify(
                round_fn,
                errors=checkify.index_checks | checkify.float_checks)
            err, _out = checked(heard, mf, rx_ok, cap)
            err.throw()
        except Exception as e:    # noqa: E02 - the smoke's verdict IS
            # the exception (checkify error or composition failure);
            # it is reported, not swallowed
            if "pallas_call" in str(e):
                # Known jax limitation on this version: checkify cannot
                # functionalize through pallas_call.  The fused leg's
                # window bounds are covered statically (P03) and by the
                # bit-exact parity suite instead.
                print(f"dyn: note: checkify[{dissem}] skipped — "
                      "checkify does not compose with pallas_call on "
                      "this jax; covered by vet P03 + "
                      "tests/test_fused_parity.py", file=sys.stderr)
                continue
            return f"checkify[{dissem}]: {type(e).__name__}: {e}"
    return None


# -- cancel-injection leg ----------------------------------------------------
#
# Scenario harness, not a pytest re-run: cancellation has to land on a
# specific task at a specific await point, and the invariants live on
# production-object internals (_confirm_batches records, NotifyGroup
# waiter sets) that a test run doesn't expose.  The scenarios build the
# real objects the tier-1 slices exercise — Server's confirm-batch
# state, a Reconciler over a real StateStore, blocking_query over a
# real StateStore — pick one task as the victim, and sweep k over its
# await points.


class _CancelInjector:
    """Cancels the registered victim task at its ``k``-th await.

    ``note_await`` is called by the patched Future at every
    ``__await__`` entry; awaits by any other task are ignored, so the
    count is exactly "await expressions the victim entered".  A sweep
    ends when a run finishes with ``fired`` still False: the victim
    completed with fewer than k awaits, so every point has been hit."""

    def __init__(self, k: int) -> None:
        self.k = k
        self.seen = 0
        self.fired = False
        self.victim: Optional[asyncio.Task] = None

    def note_await(self) -> None:
        if self.victim is None or self.fired:
            return
        try:
            cur = asyncio.current_task()
        except RuntimeError:
            return
        if cur is not self.victim or self.victim.done():
            return
        self.seen += 1
        if self.seen >= self.k:
            self.fired = True
            # We are inside the victim's own frame, pre-yield: cancel()
            # sets _must_cancel, and the forced yield below hands
            # control back to Task.__step, which delivers the
            # CancelledError AT this await point.
            self.victim.cancel()


_cancel_injector: Optional[_CancelInjector] = None


def install_cancel_injection() -> None:
    """Patch ``asyncio.Future`` with a shim that reports every await to
    the active injector, then yields once (the forced-interleave trick
    — without the unconditional yield, an await on a done future never
    suspends and the cancel would slide to a LATER point, collapsing
    distinct k values onto one schedule)."""
    import asyncio.futures

    base = asyncio.futures._PyFuture

    class _InjectFuture(base):  # type: ignore[valid-type, misc]
        def __await__(self):
            inj = _cancel_injector
            if inj is not None:
                inj.note_await()
            yield None  # deliver a pending cancel exactly here
            return (yield from super().__await__())

        __iter__ = __await__

    asyncio.futures.Future = _InjectFuture
    asyncio.Future = _InjectFuture


async def _settle(cycles: int = 20) -> None:
    """Let every ready task run to its next suspension point.
    ``sleep(0)`` never mints a future, so settling adds no counted
    awaits even when the caller is the victim's parent."""
    for _ in range(cycles):
        await asyncio.sleep(0)


def _retrieve(fut: "asyncio.Future") -> Optional[BaseException]:
    """Mark a done future's exception retrieved (keeps the leg's output
    free of never-retrieved noise) and return it."""
    if not fut.done() or fut.cancelled():
        return None
    return fut.exception()


async def _scenario_confirm_batch(victim: str,
                                  inj: _CancelInjector) -> List[str]:
    """Two serialized ReadIndex confirmation batches; the victim is a
    batch-B joiner or batch B's runner (whose first await is the
    shield on batch A's future — the exact point of the r5 finding)."""
    from consul_tpu.server.server import Server

    srv = object.__new__(Server)
    srv._confirm_batches = {}
    srv._confirm_prev = {}
    srv._confirm_tasks = set()

    gate_a = asyncio.Event()
    gate_b = asyncio.Event()

    async def runner_a():
        await gate_a.wait()
        return "a"

    async def runner_b():
        await gate_b.wait()
        return "b"

    problems: List[str] = []

    # Batch A forms and fires; its runner parks on gate_a.
    a_joiners = [asyncio.ensure_future(srv._confirm_batched("ri", runner_a))
                 for _ in range(2)]
    await _settle()
    before = set(srv._confirm_tasks)

    # Batch B forms behind it; its runner serializes on batch A's
    # future.  ONE bare cycle: _confirm_batched has created the runner
    # task but the runner has not yet entered its first await, so
    # marking it now makes k=1 land on ``await asyncio.shield(prev)``.
    b_joiners = [asyncio.ensure_future(srv._confirm_batched("ri", runner_b))
                 for _ in range(2)]
    if victim == "joiner":
        inj.victim = b_joiners[0]
    await asyncio.sleep(0)
    if victim == "runner":
        fresh = [t for t in srv._confirm_tasks if t not in before]
        if fresh:
            inj.victim = fresh[0]

    await _settle()
    gate_a.set()
    await _settle()
    gate_b.set()

    everyone = a_joiners + b_joiners
    done, pending = await asyncio.wait(everyone, timeout=5.0)
    for t in pending:
        problems.append("joiner left pending after both batches "
                        "released — a hand-off was dropped")
        t.cancel()
    for t in done:
        try:
            t.result()
        except BaseException:  # noqa: E02,E03 — the harness's oracle
            # is "resolved, not hung"; the victim's CancelledError and
            # a poisoned batch's error are both expected outcomes
            pass

    for key, b in srv._confirm_batches.items():
        _retrieve(b["fut"])
        if not b["fut"].done():
            problems.append(
                f"batch {key!r} future left pending "
                f"(fired={b['fired']}) — joiners would hang forever")

    # The system must not be wedged: a fresh request forms a new batch,
    # serializes on whatever _confirm_prev holds, and resolves.
    async def probe():
        return "probe"

    try:
        got = await asyncio.wait_for(
            srv._confirm_batched("ri", probe), timeout=2.0)
        if got != "probe":
            problems.append(f"probe returned {got!r}, expected 'probe'")
    except asyncio.TimeoutError:
        problems.append("probe request hung — the _confirm_prev chain "
                        "is wedged on an unresolved batch")
    except BaseException as e:  # noqa: E02,E03 — any escape IS the
        # probe's verdict; it is reported as a finding, not swallowed
        problems.append(f"probe request failed: {type(e).__name__}: {e}")

    leftovers = list(srv._confirm_tasks)
    if leftovers:
        gathered = asyncio.gather(*leftovers, return_exceptions=True)
        try:
            await asyncio.wait_for(gathered, timeout=2.0)
        except asyncio.TimeoutError:
            problems.append("confirm-batch runner task never finished")
    return problems


async def _scenario_reconcile_flush(victim: str,
                                    inj: _CancelInjector) -> List[str]:
    """A reconcile flush cancelled mid-submit.  A cancelled flush may
    drop its drained pending set (the periodic full reconcile
    re-derives it — that is the documented contract), but it must not
    wedge the reconciler: a follow-up note+flush must ship."""
    from consul_tpu.agent.reconcile import Reconciler
    from consul_tpu.membership.swim import STATE_ALIVE, Node
    from consul_tpu.state.store import StateStore

    class _Raft:
        def __init__(self):
            self.peers = set()

        async def add_peer(self, name):
            self.peers.add(name)

        async def remove_peer(self, name):
            self.peers.discard(name)

    class _Config:
        node_name = "leader0"
        datacenter = "dc1"

    class _Srv:
        def __init__(self):
            self.store = StateStore()
            self.raft = _Raft()
            self.config = _Config()
            self.gate = asyncio.Event()
            self.batches: List[list] = []

        async def raft_apply_batch(self, ops):
            await self.gate.wait()
            self.batches.append(list(ops))

    problems: List[str] = []
    srv = _Srv()
    rec = Reconciler(srv)

    def member(i: int) -> Node:
        return Node(name=f"n{i}", addr=f"10.0.0.{i + 1}", port=8301,
                    state=STATE_ALIVE)

    rec.note(member(0))
    rec.note(member(1))
    flusher = asyncio.ensure_future(rec.flush())
    inj.victim = flusher
    await _settle()
    srv.gate.set()
    done, pending = await asyncio.wait({flusher}, timeout=5.0)
    if pending:
        problems.append("flush never returned after the submit gate "
                        "opened — cancellation wedged it mid-envelope")
        flusher.cancel()
    else:
        try:
            flusher.result()
        except BaseException:  # noqa: E02,E03 — the victim's own
            # CancelledError is the expected outcome; the oracle is
            # only that the task RESOLVED
            pass

    # Not-wedged oracle: the next cadence works end to end.
    rec.note(member(2))
    try:
        shipped = await asyncio.wait_for(rec.flush(), timeout=5.0)
        if shipped < 1:
            problems.append(
                f"follow-up flush shipped {shipped} ops for a brand-new "
                "alive member — the reconciler lost its write path")
        if rec.pending:
            problems.append("follow-up flush left members pending")
    except BaseException as e:  # noqa: E02,E03 — any escape IS the
        # verdict; it is reported as a finding, not swallowed
        problems.append(
            f"follow-up flush failed: {type(e).__name__}: {e}")
    return problems


async def _scenario_blocking_query(victim: str,
                                   inj: _CancelInjector) -> List[str]:
    """A long-poller cancelled at each await inside blocking_query.
    The oracle is the try/finally deregistration contract: however the
    poller exits, no AsyncWaiter may stay registered on the store's
    table NotifyGroups or the KV watch tree (a leaked waiter is woken
    forever and pins its event loop objects)."""
    from consul_tpu.server.blocking import blocking_query
    from consul_tpu.state.store import StateStore
    from consul_tpu.structs.structs import QueryMeta, QueryOptions

    problems: List[str] = []
    store = StateStore()
    meta = QueryMeta()

    async def run():
        meta.index = 1  # never passes min_query_index: keep polling

    opts = QueryOptions(min_query_index=5, max_query_time=0.5)
    poller = asyncio.ensure_future(blocking_query(
        store, opts, meta, run, tables=("nodes",), kv_prefix="kv/"))
    inj.victim = poller
    done, pending = await asyncio.wait({poller}, timeout=5.0)
    if pending:
        problems.append("long-poller still running well past its "
                        "max_query_time")
        poller.cancel()
        await asyncio.wait({poller}, timeout=1.0)
    else:
        try:
            poller.result()
        except BaseException:  # noqa: E02,E03 — the victim's own
            # CancelledError is the expected outcome; the oracle is
            # only that the task RESOLVED
            pass

    leaked = sum(len(g) for g in store._watch.values())
    if leaked:
        problems.append(
            f"{leaked} waiter(s) left on table NotifyGroups after the "
            "poller exited — stop_watch was skipped on this path")
    kv_left = [p for p, g in store._kv_watch.registered() if len(g)]
    if kv_left:
        problems.append(
            f"KV watch groups still registered for {kv_left} after the "
            "poller exited — stop_watch_kv was skipped on this path")
    return problems


# (scenario name, victim labels, coroutine fn)
_CANCEL_SCENARIOS = (
    ("confirm-batch", ("joiner", "runner"), _scenario_confirm_batch),
    ("reconcile-flush", ("flusher",), _scenario_reconcile_flush),
    ("blocking-query", ("poller",), _scenario_blocking_query),
)

_CANCEL_SWEEP_CAP = 64  # no victim here has remotely this many awaits


def cancel_injection_main() -> int:
    """Child entry for the cancel leg (``--cancel``): sweep every
    (scenario, victim, k) and report.  Runs in its own process because
    the Future patch is global and must not leak into the parent."""
    global _cancel_injector
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    install_cancel_injection()
    problems: List[str] = []
    for name, victims, fn in _CANCEL_SCENARIOS:
        for victim in victims:
            k = 1
            while True:
                inj = _CancelInjector(k)
                _cancel_injector = inj
                try:
                    found = asyncio.run(fn(victim, inj))
                except BaseException as e:
                    problems.append(
                        f"{name}/{victim} k={k}: scenario crashed: "
                        f"{type(e).__name__}: {e}")
                    break
                finally:
                    _cancel_injector = None
                for p in found:
                    problems.append(f"{name}/{victim} k={k}: {p}")
                if inj.victim is None:
                    problems.append(
                        f"{name}/{victim}: victim task never marked — "
                        "the scenario is vacuous")
                    break
                if not inj.fired:
                    # Victim finished with < k awaits: sweep complete,
                    # and this last run doubles as the uninjected
                    # baseline for the oracles.
                    print(f"dyn: cancel[{name}/{victim}]: swept "
                          f"{k - 1} await point(s)", file=sys.stderr)
                    break
                k += 1
                if k > _CANCEL_SWEEP_CAP:
                    problems.append(
                        f"{name}/{victim}: sweep passed k={k} — the "
                        "victim's await count should be tiny; the "
                        "scenario is runaway")
                    break
    for p in problems:
        print(f"dyn: FAIL: {p}", file=sys.stderr)
    if not problems:
        print("dyn: cancel-injection leg clean", file=sys.stderr)
    return 1 if problems else 0


# -- runner role -------------------------------------------------------------


def main(argv: Optional[Sequence[str]] = None) -> int:
    if argv and list(argv) == ["--cancel"]:
        return cancel_injection_main()
    tests = list(argv) if argv else list(SLICE)
    problems: List[str] = []

    with tempfile.TemporaryDirectory(prefix="vet-dyn-") as td:
        report_path = os.path.join(td, "dyn_report.json")
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env["PYTHONASYNCIODEBUG"] = "1"
        env[REPORT_ENV] = report_path
        env.setdefault(NANS_ENV, "1")
        cmd = [sys.executable, "-X", "dev",
               "-W", "error::RuntimeWarning",
               "-m", "pytest", *tests, "-q",
               "-p", "tools.vet.dyn", "-p", "no:cacheprovider"]
        print("dyn: running sanitized slice:", " ".join(tests),
              file=sys.stderr)
        proc = subprocess.run(cmd, env=env)
        if proc.returncode != 0:
            problems.append(
                f"sanitized pytest run failed (rc={proc.returncode}) — "
                "see output above (debug_nans / asyncio debug / "
                "warnings-as-errors)")
        if os.path.isfile(report_path):
            report = json.loads(Path(report_path).read_text())
            problems.extend(evaluate_leaks(report))
        else:
            problems.append("dyn plugin wrote no session report — the "
                            "run died before teardown")

    # Interleaving-stress leg: only when running the default slice (an
    # explicit test list means the caller is bisecting one suite).
    # Asyncio debug mode stays OFF here — the forced switches multiply
    # callback counts ~10x and debug bookkeeping turns signal to noise;
    # the oracle for this leg is the tests' own assertions.
    if not argv:
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env[INTERLEAVE_ENV] = "1"
        cmd = [sys.executable, "-m", "pytest", *INTERLEAVE_SLICE, "-q",
               "-p", "tools.vet.dyn", "-p", "no:cacheprovider"]
        print("dyn: forced-interleave slice (task switch at every "
              "await):", " ".join(INTERLEAVE_SLICE), file=sys.stderr)
        proc = subprocess.run(cmd, env=env)
        if proc.returncode != 0:
            problems.append(
                f"forced-interleave run failed (rc={proc.returncode}) — "
                "an await-atomicity assumption broke when every await "
                "became a real task switch (dynamic twin of vet X01)")

    # Cancel-injection leg: subprocessed because the injection patch
    # replaces asyncio.Future process-wide.  Same bisect rule as the
    # interleave leg: an explicit test list skips it.
    if not argv:
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env[CANCEL_ENV] = "1"
        cmd = [sys.executable, "-m", "tools.vet.dyn", "--cancel"]
        print("dyn: cancel-injection sweep (cancel the victim at every "
              "await point; confirm-batch / reconcile-flush / "
              "blocking-query)", file=sys.stderr)
        proc = subprocess.run(cmd, env=env)
        if proc.returncode != 0:
            problems.append(
                f"cancel-injection sweep failed (rc={proc.returncode}) "
                "— a cancellation schedule left a future pending, a "
                "batch unfired, or a waiter registered (dynamic twin "
                "of vet Q01-Q04)")

    print("dyn: checkify smoke (index+float oracle over one round per "
          "strategy)", file=sys.stderr)
    err = checkify_smoke()
    if err:
        problems.append(err)

    for p in problems:
        print(f"dyn: FAIL: {p}", file=sys.stderr)
    if not problems:
        print("dyn: clean (slice + leak audit + interleave + "
              "cancel-injection + checkify)", file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
