import sys

from tools.vet.driver import main

sys.exit(main())
