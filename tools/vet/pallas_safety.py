"""Pallas kernel-safety pass: the lowering contracts around every
``pallas_call`` site that Mosaic enforces late (or never) and the
interpreter not at all.

The fused dissemination kernel (``gossip/fused.py``) established the
conventions this pass audits.  Its failure modes are silent on the CPU
mesh — interpret mode pads or wraps where the TPU lowering would
corrupt or reject — so they belong to vet, not pytest:

- **P01 unguarded block divisibility**: a BlockSpec block width (or
  grid extent) derived by floor division ``B = X // Y`` whose
  divisibility contract ``X % Y == 0`` has no guard in the enclosing
  function.  A remainder column silently falls outside the grid.
  Guard evidence, in agreement with the runtime (the shared helper
  ``consul_tpu/ops/divisibility.py``): a ``require_divisible(X, Y)``
  call, or an explicit ``X % Y`` test (``if``/``assert``/comparison).
  When both operands are integer literals the pass constant-folds with
  the SAME ``divides`` predicate the runtime guard uses: a statically
  violated contract flags even if guarded (the guard would always
  raise), a statically satisfied one is clean.
- **P02 no interpret fallback**: a ``pallas_call`` without an
  ``interpret=`` keyword.  Off-TPU (CPU CI, the 8-device virtual
  mesh) such a call aborts in the Mosaic lowering — every kernel here
  must stay runnable on this box (``fused._interpret()`` idiom).
- **P03 unbounded window offset**: index arithmetic that can step
  outside the block window. Two shapes: (a) a BlockSpec index-map
  lambda that subscripts its scalar-prefetch parameter with no
  modulo reduction around the use (block indices must wrap mod the
  block count: ``(j - qr[f] - 1) % nb``); (b) an in-kernel
  ``dynamic_slice`` whose start uses a value read out of a Ref with
  no modulo evidence either at the use site or in the construction
  of the scalar operand passed to the ``pallas_call`` (the residue
  certificate: ``offs % Bn`` feeding the prefetch vector bounds the
  in-kernel splice).
- **P04 non-static scalar-prefetch consumption**: under a
  ``PrefetchScalarGridSpec``, the first ``num_scalar_prefetch``
  kernel parameters are scalar refs meant to be indexed statically
  (Python ints, ``range()`` loop variables).  Indexing one with
  ``program_id(...)`` or with a value read from another ref is a
  data-dependent gather the Mosaic lowering handles differently from
  the interpreter — exactly the class of divergence the parity suite
  cannot sweep.

Scope: files that import ``jax.experimental.pallas`` (source mention
of ``pallas`` + a resolvable ``pallas_call`` call site).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from consul_tpu.ops.divisibility import divides
from tools.vet.core import FileCtx, Finding
from tools.vet.tracer_purity import _tail

UNGUARDED_DIV = "P01"
NO_INTERPRET = "P02"
UNBOUNDED_OFFSET = "P03"
NONSTATIC_PREFETCH = "P04"

_GUARD_FUNCS = {"require_divisible"}


def _enclosing_function(tree: ast.Module, node: ast.AST
                        ) -> Optional[ast.AST]:
    """Innermost FunctionDef/AsyncFunctionDef containing ``node``
    (module itself when at top level)."""
    best: Optional[ast.AST] = None
    for fn in ast.walk(tree):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(fn):
                if sub is node:
                    if best is None or (fn.lineno >= best.lineno):
                        best = fn
                    break
    return best


def _defs_by_name(tree: ast.Module) -> Dict[str, ast.FunctionDef]:
    out: Dict[str, ast.FunctionDef] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, node)
    return out


def _int_const(node: ast.AST) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    return None


def _kw(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _expr_token(node: ast.expr) -> Optional[str]:
    """A comparable token for a divisibility operand: dotted name or
    int literal rendered as text."""
    dn = _tail(node)
    if dn is not None:
        return dn
    c = _int_const(node)
    return str(c) if c is not None else None


def _mod_pairs(scope: ast.AST) -> Set[Tuple[str, str]]:
    """Every ``X % Y`` pair (by token) appearing anywhere in scope —
    guard evidence for the (X, Y) divisibility contract."""
    out: Set[Tuple[str, str]] = set()
    for node in ast.walk(scope):
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod):
            a, b = _expr_token(node.left), _expr_token(node.right)
            if a and b:
                out.add((a, b))
    return out


def _guard_calls(scope: ast.AST) -> Set[Tuple[str, str]]:
    """(X, Y) token pairs passed to the shared require_divisible
    helper inside scope."""
    out: Set[Tuple[str, str]] = set()
    for node in ast.walk(scope):
        if isinstance(node, ast.Call) and _tail(node.func) in _GUARD_FUNCS \
                and len(node.args) >= 2:
            a = _expr_token(node.args[0])
            b = _expr_token(node.args[1])
            if a and b:
                out.add((a, b))
    return out


class _Site:
    """One resolved ``pallas_call`` site."""

    def __init__(self, call: ast.Call, scope: ast.AST,
                 kernel: Optional[ast.FunctionDef],
                 prefetch: int, grid_spec: Optional[ast.Call]) -> None:
        self.call = call
        self.scope = scope          # enclosing function (or module)
        self.kernel = kernel        # the kernel def, when resolvable
        self.prefetch = prefetch    # num_scalar_prefetch (0 = none)
        self.grid_spec = grid_spec


def _collect_sites(ctx: FileCtx) -> List[_Site]:
    module_defs = _defs_by_name(ctx.tree)
    sites: List[_Site] = []
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and _tail(node.func) == "pallas_call"):
            continue
        scope0 = _enclosing_function(ctx.tree, node) or ctx.tree
        kernel = None
        if node.args:
            kname = _tail(node.args[0])
            # Resolve within the enclosing scope first: every kernel
            # body here is a closure named ``kern`` nested in its own
            # wrapper, so the module-level map would alias them.
            local_defs = _defs_by_name(scope0) \
                if scope0 is not ctx.tree else module_defs
            if kname in local_defs:
                kernel = local_defs[kname]
            elif kname in module_defs:
                kernel = module_defs[kname]
        prefetch = 0
        grid_spec = None
        gs = _kw(node, "grid_spec")
        if isinstance(gs, ast.Call) \
                and _tail(gs.func) == "PrefetchScalarGridSpec":
            grid_spec = gs
            nsp = _kw(gs, "num_scalar_prefetch")
            c = _int_const(nsp) if nsp is not None else None
            prefetch = c if c is not None else 1
        sites.append(_Site(node, scope0, kernel, prefetch, grid_spec))
    return sites


# -- P01: block divisibility ------------------------------------------------


def _blockish_names(site: _Site) -> Set[str]:
    """Names used as BlockSpec shape elements or grid extents in the
    site's enclosing scope — the values whose floor-division origin
    must be guarded.  Walks the whole scope, not just the call
    expression: the idiom builds ``in_specs = [...]`` as a separate
    statement and passes the name (gossip/fused.py)."""
    out: Set[str] = set()
    for node in ast.walk(site.scope):
        if isinstance(node, ast.Call) and _tail(node.func) == "BlockSpec" \
                and node.args:
            shape = node.args[0]
            for el in ast.walk(shape):
                if isinstance(el, ast.Name):
                    out.add(el.id)
        elif isinstance(node, ast.keyword) and node.arg == "grid":
            for el in ast.walk(node.value):
                if isinstance(el, ast.Name):
                    out.add(el.id)
    return out


def _check_p01(ctx: FileCtx, site: _Site, out: List[Finding]) -> None:
    wanted = _blockish_names(site)
    if not wanted:
        return
    # floor-division assignments in the enclosing scope: B = X // Y
    pairs: List[Tuple[str, ast.BinOp, int]] = []
    for node in ast.walk(site.scope):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.BinOp) \
                and isinstance(node.value.op, ast.FloorDiv):
            name = node.targets[0].id
            if name in wanted:
                pairs.append((name, node.value, node.lineno))
    if not pairs:
        return
    mods = _mod_pairs(site.scope)
    guards = _guard_calls(site.scope)
    for name, binop, lineno in pairs:
        a, b = _expr_token(binop.left), _expr_token(binop.right)
        if a is None or b is None:
            continue
        ca = _int_const(binop.left)
        cb = _int_const(binop.right)
        if ca is not None and cb is not None:
            # constant-fold with the runtime's own predicate
            if divides(ca, cb):
                continue
            out.append(Finding(
                ctx.path, lineno, UNGUARDED_DIV,
                f"block width '{name}' = {ca} // {cb} does not tile: "
                f"{ca} % {cb} != 0 — the pallas_call grid drops the "
                "remainder columns (divides() in "
                "consul_tpu/ops/divisibility.py)"))
            continue
        if (a, b) in mods or (a, b) in guards:
            continue
        out.append(Finding(
            ctx.path, lineno, UNGUARDED_DIV,
            f"block width '{name}' = {a} // {b} feeds a pallas_call "
            f"BlockSpec/grid but the divisibility contract "
            f"{a} % {b} == 0 is unguarded in the enclosing function — "
            f"call require_divisible({a}, {b}, ...) "
            "(consul_tpu/ops/divisibility.py) so the remainder columns "
            "cannot silently fall off the grid"))


# -- P02: interpret fallback ------------------------------------------------


def _check_p02(ctx: FileCtx, site: _Site, out: List[Finding]) -> None:
    if _kw(site.call, "interpret") is None:
        out.append(Finding(
            ctx.path, site.call.lineno, NO_INTERPRET,
            "pallas_call without an interpret= fallback — off-TPU "
            "(CPU CI, the virtual mesh) this aborts in the Mosaic "
            "lowering; gate it like gossip/fused.py's _interpret() "
            "(interpret=True whenever the backend is not a TPU)"))


# -- P03: window offsets ----------------------------------------------------


def _under_mod(root: ast.expr, target: ast.AST) -> bool:
    """True when ``target`` sits under a ``%`` BinOp within root."""
    for node in ast.walk(root):
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod):
            for sub in ast.walk(node):
                if sub is target:
                    return True
    return False


def _check_index_maps(ctx: FileCtx, site: _Site,
                      out: List[Finding]) -> None:
    # Walk the whole enclosing scope: index maps are usually built in
    # a separate ``in_specs = [...]`` statement (gossip/fused.py), not
    # inline in the pallas_call expression.
    for node in ast.walk(site.scope):
        if not (isinstance(node, ast.Call)
                and _tail(node.func) == "BlockSpec"
                and len(node.args) >= 2
                and isinstance(node.args[1], ast.Lambda)):
            continue
        lam = node.args[1]
        # scalar-prefetch param of the index map: every arg past
        # the grid axes; with num_scalar_prefetch the convention
        # is (j, ..., qr) — subscripting ANY lambda param is the
        # prefetch-read shape we bound-check.
        params = {a.arg for a in lam.args.args}
        for sub in ast.walk(lam.body):
            if isinstance(sub, ast.Subscript) \
                    and isinstance(sub.value, ast.Name) \
                    and sub.value.id in params \
                    and not _under_mod(lam.body, sub):
                out.append(Finding(
                    ctx.path, getattr(sub, "lineno", node.lineno),
                    UNBOUNDED_OFFSET,
                    f"BlockSpec index map reads prefetch scalar "
                    f"'{ast.unparse(sub)}' without a modulo "
                    "reduction — a shift >= the block count "
                    "indexes a block outside the grid; wrap the "
                    "expression mod the block count "
                    "((j - qr[f] - 1) % nb)"))


def _ref_read_names(kernel: ast.FunctionDef,
                    ref_params: Set[str]) -> Set[str]:
    """Names assigned from a subscript of a ref parameter inside the
    kernel body (``r = qr_ref[...]``)."""
    out: Set[str] = set()
    for node in ast.walk(kernel):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Subscript) \
                        and isinstance(sub.value, ast.Name) \
                        and sub.value.id in ref_params:
                    out.add(node.targets[0].id)
    return out


def _prefetch_operand_has_mod(ctx: FileCtx, site: _Site) -> bool:
    """The residue certificate: the scalar operand handed to the
    pallas_call invocation was built with a ``%`` (e.g. ``offs % Bn``
    concatenated into the prefetch vector)."""
    # the invocation wrapping the pallas_call result: find Call whose
    # func IS site.call
    operand: Optional[ast.expr] = None
    for node in ast.walk(site.scope):
        if isinstance(node, ast.Call) and node.func is site.call \
                and node.args:
            operand = node.args[0]
            break
    if operand is None:
        return False
    for sub in ast.walk(operand):
        if isinstance(sub, ast.BinOp) and isinstance(sub.op, ast.Mod):
            return True
    name = _tail(operand)
    if name is None:
        return False
    for node in ast.walk(site.scope):
        if isinstance(node, ast.Assign) \
                and any(isinstance(t, ast.Name) and t.id == name
                        for t in node.targets):
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.BinOp) \
                        and isinstance(sub.op, ast.Mod):
                    return True
    return False


def _check_p03(ctx: FileCtx, site: _Site, out: List[Finding]) -> None:
    _check_index_maps(ctx, site, out)
    if site.kernel is None:
        return
    ref_params = {a.arg for a in site.kernel.args.args}
    if site.kernel.args.vararg is not None:
        ref_params.add(site.kernel.args.vararg.arg)
    reads = _ref_read_names(site.kernel, ref_params)
    if not reads:
        return
    certified = _prefetch_operand_has_mod(ctx, site)
    for node in ast.walk(site.kernel):
        if not (isinstance(node, ast.Call)
                and _tail(node.func) == "dynamic_slice"
                and len(node.args) >= 2):
            continue
        start = node.args[1]
        for sub in ast.walk(start):
            if isinstance(sub, ast.Name) and sub.id in reads:
                if _under_mod(start, sub) or certified:
                    break
                out.append(Finding(
                    ctx.path, node.lineno, UNBOUNDED_OFFSET,
                    f"in-kernel dynamic_slice start uses '{sub.id}' "
                    "read from a Ref with no modulo evidence — "
                    "neither at the slice nor in the construction of "
                    "the scalar-prefetch operand (the 'offs % Bn' "
                    "residue certificate); an oversized offset reads "
                    "past the block window"))
                break


# -- P04: static prefetch consumption ---------------------------------------


def _check_p04(ctx: FileCtx, site: _Site, out: List[Finding]) -> None:
    if site.kernel is None or site.prefetch <= 0:
        return
    posargs = [a.arg for a in site.kernel.args.args]
    scalar_refs = set(posargs[:site.prefetch])
    if not scalar_refs:
        return
    other_refs = set(posargs[site.prefetch:])
    if site.kernel.args.vararg is not None:
        other_refs.add(site.kernel.args.vararg.arg)
    for node in ast.walk(site.kernel):
        if not (isinstance(node, ast.Subscript)
                and isinstance(node.value, ast.Name)
                and node.value.id in scalar_refs):
            continue
        why = None
        for sub in ast.walk(node.slice):
            if isinstance(sub, ast.Call) \
                    and _tail(sub.func) == "program_id":
                why = "program_id(...)"
                break
            if isinstance(sub, ast.Subscript) \
                    and isinstance(sub.value, ast.Name) \
                    and sub.value.id in (scalar_refs | other_refs):
                why = f"a read of ref '{sub.value.id}'"
                break
        if why is not None:
            out.append(Finding(
                ctx.path, node.lineno, NONSTATIC_PREFETCH,
                f"scalar-prefetch ref '{node.value.id}' indexed with "
                f"{why} — prefetch operands must be consumed with "
                "static (Python-int) indices; a data-dependent gather "
                "lowers differently under Mosaic than under the "
                "interpreter"))


def check(ctx: FileCtx) -> List[Finding]:
    if "pallas" not in ctx.src:
        return []
    sites = _collect_sites(ctx)
    if not sites:
        return []
    findings: List[Finding] = []
    for site in sites:
        _check_p01(ctx, site, findings)
        _check_p02(ctx, site, findings)
        _check_p03(ctx, site, findings)
        _check_p04(ctx, site, findings)
    return sorted(set(findings), key=lambda f: (f.line, f.code, f.message))
