"""Donation-safety pass: flag reads of buffers already handed to a
donating jit (``donate_argnums`` / ``donate_argnames``).

A donated buffer is DELETED by the dispatch — the kernel writes its
output into the input's memory (64 MB per round at 1M nodes is why the
SWIM jits donate, gossip/kernel.py).  A later read of the old binding
raises ``RuntimeError: Array has been deleted`` on backends that honor
donation, but *silently works* on backends that don't — exactly the
class of bug no CPU test tier catches until a TPU run.

Donating callables are discovered per module and shared across the
project by simple name (the donating jits live in gossip/kernel.py;
their call sites live in plane.py, the benches and the tests):

- defs decorated ``@functools.partial(jax.jit, donate_arg*=...)``;
- ``g = jax.jit(f, donate_arg*=...)`` assignments;
- factory defs whose ``return`` is such a ``jax.jit(...)`` call
  (``fn = factory(...)`` then makes ``fn`` donating);
- wrapper propagation: a def that passes its OWN parameter (as a bare
  name, no copy in between) at a donated slot of a known donating
  callable donates that parameter too — including through
  ``fn(*args)`` when ``args`` is a local tuple/list literal, and
  through ``functools.partial(f, kw=...)`` aliases.

Only module-level defs/assignments export their donation info to other
files; function-local aliases stay file-local.

Checks, within every non-traced scope (functions, lambdas, the module
body):

- **D01 use-after-donate**: a bare local name passed at a donated slot
  is tainted from the call onward; any later read flags.  Kill rules:
  the name is a target of the assignment *containing* the donating
  call (``state = swim_round(state, ...)``), any later rebinding or
  ``del``, or a ``jax.block_until_ready(name)`` sync (the deliberate
  observe-deletion idiom — reads inside it are exempt and it ends the
  taint).  A donating call inside a loop whose donated name is never
  rebound in that loop flags too (iteration 2 reuses the deleted
  buffer even though no textual read follows the call).
- **D02 donated global/attribute**: the donated argument is an
  attribute chain (``self._state``) or a name not bound in the current
  scope — the stale binding outlives the call for every other reader.
  Killed by a later store to the same dotted target (including targets
  of the containing assignment).

Calls inside functions that are themselves traced (jit-decorated or
jit/scan/shard_map-rooted, transitively) are exempt: donation is a
dispatch-boundary property, and an inner donating jit is inlined by
the outer trace without consuming anything (tools/profile_kernel.py
relies on this).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from tools.vet.core import FileCtx, Finding, dotted_name
from tools.vet.tracer_purity import (_collect_defs, _mark_roots, _reachable,
                                     _tail)

USE_AFTER_DONATE = "D01"
DONATED_NONLOCAL = "D02"

_DONATE_KWS = ("donate_argnums", "donate_argnames")


@dataclass
class _Donor:
    """Donated positions/param names of one donating callable."""

    positions: Set[int] = field(default_factory=set)
    names: Set[str] = field(default_factory=set)

    def merged(self, params: Sequence[str]) -> "_Donor":
        """Positions with names resolved through the param list (and
        vice versa) so positional and keyword call sites both match."""
        d = _Donor(set(self.positions), set(self.names))
        for i in self.positions:
            if i < len(params):
                d.names.add(params[i])
        for n in self.names:
            if n in params:
                d.positions.add(params.index(n))
        return d


def _const_strs_ints(node: ast.AST) -> Tuple[Set[str], Set[int]]:
    strs: Set[str] = set()
    ints: Set[int] = set()
    for c in ast.walk(node):
        if isinstance(c, ast.Constant):
            if isinstance(c.value, str):
                strs.add(c.value)
            elif isinstance(c.value, int) and not isinstance(c.value, bool):
                ints.add(c.value)
    return strs, ints


def _donate_kw(call: ast.Call) -> Optional[_Donor]:
    """The _Donor described by a ``jax.jit(...)``-style call's
    donate_argnums/donate_argnames keywords, or None."""
    d = _Donor()
    found = False
    for kw in call.keywords:
        if kw.arg in _DONATE_KWS:
            found = True
            strs, ints = _const_strs_ints(kw.value)
            d.names |= strs
            d.positions |= ints
    return d if found else None


def _positional_params(fn) -> List[str]:
    a = fn.args
    return [x.arg for x in a.posonlyargs + a.args]


def _own_nodes(scope: ast.AST) -> Iterator[ast.AST]:
    """Every AST node of ``scope`` excluding nested function/lambda
    bodies (each nested def or lambda is its own donation scope)."""
    body = getattr(scope, "body", None)
    todo: List[ast.AST] = list(body) if isinstance(body, list) \
        else ([body] if body is not None else [])
    while todo:
        node = todo.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        todo.extend(ast.iter_child_nodes(node))


def _stored_names(node: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for c in ast.walk(node):
        if isinstance(c, ast.Name) and isinstance(c.ctx, (ast.Store,
                                                          ast.Del)):
            out.add(c.id)
        elif isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.add(c.name)
    return out


def _scope_params(scope: ast.AST) -> Set[str]:
    if not isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
        return set()
    a = scope.args
    out = {x.arg for x in a.posonlyargs + a.args + a.kwonlyargs}
    if a.vararg:
        out.add(a.vararg.arg)
    if a.kwarg:
        out.add(a.kwarg.arg)
    return out


def _literal_seqs(scope: ast.AST) -> Dict[str, List[ast.expr]]:
    """Local ``args = (a, b, c)`` / list-literal bindings, including
    the ``(a, b) + ((c,) if cond else ())`` concatenation idiom (only
    the leading literal elements matter)."""
    out: Dict[str, List[ast.expr]] = {}
    for node in _own_nodes(scope):
        if not isinstance(node, ast.Assign):
            continue
        val = node.value
        elems: Optional[List[ast.expr]] = None
        if isinstance(val, (ast.Tuple, ast.List)):
            elems = []
            for el in val.elts:
                if isinstance(el, ast.Starred):
                    break
                elems.append(el)
        elif isinstance(val, ast.BinOp) and isinstance(val.op, ast.Add) \
                and isinstance(val.left, (ast.Tuple, ast.List)):
            elems = list(val.left.elts)
        if elems is None:
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                out[tgt.id] = elems
    return out


class _DonorTable:
    """name -> _Donor for every donating callable visible in a module.
    ``seed`` carries project-wide donors from other files."""

    def __init__(self, tree: ast.Module,
                 seed: Optional[Dict[str, _Donor]] = None) -> None:
        self.defs: Dict[str, ast.AST] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # first def wins; same-name redefinitions are rare and
                # the analysis is best-effort
                self.defs.setdefault(node.name, node)
        self.donors: Dict[str, _Donor] = {}
        for name, d in (seed or {}).items():
            self._add(name, d)
        self.factories: Dict[str, _Donor] = {}
        self._direct()
        self._assigned(tree)
        self._propagate(tree)

    def _add(self, name: str, donor: _Donor) -> bool:
        cur = self.donors.setdefault(name, _Donor())
        before = (len(cur.positions), len(cur.names))
        cur.positions |= donor.positions
        cur.names |= donor.names
        return (len(cur.positions), len(cur.names)) != before

    def _direct(self) -> None:
        for name, fn in self.defs.items():
            params = _positional_params(fn)
            for dec in fn.decorator_list:
                if not isinstance(dec, ast.Call):
                    continue
                t = _tail(dec.func)
                d = None
                if t == "jit":
                    d = _donate_kw(dec)
                elif t == "partial" and dec.args \
                        and _tail(dec.args[0]) == "jit":
                    d = _donate_kw(dec)
                if d is not None:
                    self._add(name, d.merged(params))
            # factory form: `return jax.jit(..., donate_arg*=...)`
            for node in ast.walk(fn):
                if isinstance(node, ast.Return) \
                        and isinstance(node.value, ast.Call) \
                        and _tail(node.value.func) == "jit":
                    d = _donate_kw(node.value)
                    if d is not None:
                        self.factories[name] = d

    def _assigned(self, tree: ast.Module) -> None:
        # g = jax.jit(f, donate_arg*=...)   and   fn = factory(...)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign) \
                    or not isinstance(node.value, ast.Call):
                continue
            call = node.value
            t = _tail(call.func)
            d = None
            if t == "jit":
                d = _donate_kw(call)
                if d is not None and call.args:
                    inner = _tail(call.args[0])
                    if inner in self.defs:
                        d = d.merged(_positional_params(self.defs[inner]))
            elif t in self.factories:
                d = self.factories[t]
            if d is None:
                continue
            for tgt in node.targets:
                tn = _tail(tgt)
                if tn:
                    self._add(tn, d)

    def _partial_aliases(self, tree: ast.Module) -> bool:
        """g = functools.partial(f, kw=...) — keyword-only partials
        keep positional donation; positional prefix args shift it."""
        changed = False
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign) \
                    or not isinstance(node.value, ast.Call) \
                    or _tail(node.value.func) != "partial" \
                    or not node.value.args:
                continue
            src = _tail(node.value.args[0])
            donor = self.donors.get(src) if src else None
            if not donor or not (donor.positions or donor.names):
                continue
            shift = len(node.value.args) - 1
            bound = {kw.arg for kw in node.value.keywords}
            d = _Donor({p - shift for p in donor.positions if p >= shift},
                       {n for n in donor.names if n not in bound})
            for tgt in node.targets:
                tn = _tail(tgt)
                if tn:
                    changed |= self._add(tn, d)
        return changed

    def donated_args(self, call: ast.Call,
                     literals: Dict[str, List[ast.expr]]) -> List[ast.expr]:
        """Argument expressions of ``call`` landing on donated slots."""
        t = _tail(call.func)
        donor = self.donors.get(t) if t else None
        if not donor or not (donor.positions or donor.names):
            return []
        out: List[ast.expr] = []
        args = call.args
        if len(args) == 1 and isinstance(args[0], ast.Starred):
            # fn(*args) with a local literal-tuple `args`
            star = args[0].value
            elems = literals.get(star.id) \
                if isinstance(star, ast.Name) else None
            return [a for i, a in enumerate(elems or [])
                    if i in donor.positions]
        for i, a in enumerate(args):
            if isinstance(a, ast.Starred):
                break  # positions after a star are unknowable
            if i in donor.positions:
                out.append(a)
        for kw in call.keywords:
            if kw.arg in donor.names:
                out.append(kw.value)
        return out

    def _propagate(self, tree: ast.Module) -> None:
        # wrapper defs: param passed (bare) at a donated slot of a
        # donating callable makes the wrapper donate it too
        for _ in range(3):
            changed = self._partial_aliases(tree)
            for name, fn in self.defs.items():
                params = _positional_params(fn)
                pset = set(params)
                literals = _literal_seqs(fn)
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    for arg in self.donated_args(node, literals):
                        if isinstance(arg, ast.Name) and arg.id in pset:
                            changed |= self._add(
                                name,
                                _Donor(names={arg.id}).merged(params))
            if not changed:
                break

    def exported(self, tree: ast.Module) -> Dict[str, _Donor]:
        """Donors bound at module level — the names other files can
        import.  Function-local aliases (``fn = factory(...)`` inside a
        wrapper) stay file-local."""
        top: Set[str] = set()
        for st in tree.body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                top.add(st.name)
            elif isinstance(st, ast.Assign):
                for tgt in st.targets:
                    if isinstance(tgt, ast.Name):
                        top.add(tgt.id)
        return {n: d for n, d in self.donors.items()
                if n in top and (d.positions or d.names)}


# -- per-scope flow ----------------------------------------------------------


class _Scope:
    """One function/lambda (or the module body) under donation
    analysis.  Flow is line-ordered — the straight-line dispatch style
    of the kernel callers — with structural kills for the assignment
    containing the donating call."""

    def __init__(self, ctx: FileCtx, table: _DonorTable,
                 scope: ast.AST) -> None:
        self.ctx = ctx
        self.table = table
        self.scope = scope
        self.nodes = list(_own_nodes(scope))
        self.local = _scope_params(scope)
        for n in self.nodes:
            if isinstance(n, ast.Name) and isinstance(n.ctx, (ast.Store,
                                                              ast.Del)):
                self.local.add(n.id)
            elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.local.add(n.name)
        self.end = max([getattr(n, "end_lineno", 0) or 0
                        for n in self.nodes] or [0])
        self.findings: List[Finding] = []

    def _emit(self, line: int, code: str, msg: str) -> None:
        self.findings.append(Finding(self.ctx.path, line, code, msg))

    def _containing_assign(self, call: ast.Call) -> Optional[ast.stmt]:
        for n in self.nodes:
            if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)) \
                    and any(c is call for c in ast.walk(n)):
                return n
        return None

    def _assign_targets(self, stmt: ast.stmt) -> List[ast.expr]:
        return stmt.targets if isinstance(stmt, ast.Assign) \
            else [stmt.target]

    def _sync_lines(self, name: str) -> Set[int]:
        """Lines where ``jax.block_until_ready`` receives ``name`` —
        the sanctioned sync/observe-deletion idiom."""
        out: Set[int] = set()
        for n in self.nodes:
            if isinstance(n, ast.Call) \
                    and _tail(n.func) == "block_until_ready":
                for a in n.args:
                    if any(isinstance(c, ast.Name) and c.id == name
                           for c in ast.walk(a)):
                        out.add(n.lineno)
        return out

    def _kill_lines(self, call: ast.Call, name: str) -> Set[int]:
        kills: Set[int] = set()
        holder = self._containing_assign(call)
        if holder is not None:
            for t in self._assign_targets(holder):
                if any(isinstance(c, ast.Name) and c.id == name
                       for c in ast.walk(t)):
                    kills.add(call.lineno)
        for n in self.nodes:
            if isinstance(n, ast.Name) and n.id == name \
                    and isinstance(n.ctx, (ast.Store, ast.Del)) \
                    and n.lineno > call.lineno:
                kills.add(n.lineno)
        return kills

    def check_call(self, call: ast.Call,
                   literals: Dict[str, List[ast.expr]]) -> None:
        for arg in self.table.donated_args(call, literals):
            if isinstance(arg, ast.Name):
                if arg.id in self.local:
                    self._check_local(call, arg.id)
                else:
                    self._check_nonlocal(call, arg.id, "global")
            else:
                dn = dotted_name(arg)
                if dn is not None:
                    self._check_nonlocal(call, dn, "attribute")
                # anything else (a call, a copy, a subscript) builds a
                # fresh value at the call site — nothing outlives it

    def _check_local(self, call: ast.Call, name: str) -> None:
        fn = _tail(call.func) or "?"
        kills = self._kill_lines(call, name)
        sync = self._sync_lines(name)
        kill_at = min(kills | sync) if (kills | sync) else None
        # the call's own (possibly multi-line) argument list is the
        # donation itself, not a read after it
        in_call = {id(c) for c in ast.walk(call)}
        for n in self.nodes:
            if not (isinstance(n, ast.Name) and n.id == name
                    and isinstance(n.ctx, ast.Load)):
                continue
            if id(n) in in_call:
                continue
            if n.lineno <= call.lineno or n.lineno > self.end:
                continue
            if kill_at is not None and n.lineno >= kill_at:
                continue
            if n.lineno in sync:
                continue  # inside the sanctioned sync itself
            self._emit(
                n.lineno, USE_AFTER_DONATE,
                f"'{name}' read after being donated to {fn}() on line "
                f"{call.lineno} — the buffer is deleted by the dispatch; "
                "rebind the name or pass a copy")
        # loop-carried reuse: donated every iteration, never rebound
        for loop in self.nodes:
            if not isinstance(loop, (ast.For, ast.While, ast.AsyncFor)):
                continue
            if not (loop.lineno <= call.lineno
                    <= (loop.end_lineno or loop.lineno)):
                continue
            if name not in _stored_names(loop) and not any(
                    loop.lineno <= s <= (loop.end_lineno or loop.lineno)
                    for s in sync):
                self._emit(
                    call.lineno, USE_AFTER_DONATE,
                    f"'{name}' donated to {fn}() inside a loop without "
                    "being rebound in the loop body — iteration 2 passes "
                    "an already-deleted buffer")
                break

    def _check_nonlocal(self, call: ast.Call, dotted: str,
                        kind: str) -> None:
        fn = _tail(call.func) or "?"
        holder = self._containing_assign(call)
        if holder is not None:
            for t in self._assign_targets(holder):
                for c in ast.walk(t):
                    if isinstance(getattr(c, "ctx", None), ast.Store) \
                            and dotted_name(c) == dotted:
                        return  # rebound by the very same statement
        if "." in dotted:
            for n in self.nodes:
                if isinstance(n, ast.Attribute) \
                        and isinstance(n.ctx, ast.Store) \
                        and dotted_name(n) == dotted \
                        and n.lineno >= call.lineno:
                    return  # rebound later in this scope
        else:
            if any(ln >= call.lineno
                   for ln in self._kill_lines(call, dotted)) \
                    or self._sync_lines(dotted):
                return
        self._emit(
            call.lineno, DONATED_NONLOCAL,
            f"{kind} '{dotted}' donated to {fn}() is never rebound in "
            "this scope — every later reader sees a deleted buffer; "
            "rebind it after the call or pass a copy")


def _imports_jax(ctx: FileCtx) -> bool:
    if "jax" not in ctx.src:
        return False
    from tools.vet.async_safety import _module_imports
    imports = _module_imports(ctx.tree)
    return imports.get("jax") == "jax" or any(
        v == "jax" or v.startswith("jax.") for v in imports.values())


def check_project(ctxs: List[FileCtx]) -> List[Finding]:
    jax_ctxs = [c for c in ctxs if _imports_jax(c)]
    if not jax_ctxs:
        return []
    # two rounds so donors defined in a file processed later (kernel)
    # still reach wrappers in files processed earlier (plane, tests)
    shared: Dict[str, _Donor] = {}
    tables: Dict[str, _DonorTable] = {}
    for _ in range(2):
        for ctx in jax_ctxs:
            t = _DonorTable(ctx.tree, seed=shared)
            tables[ctx.path] = t
            for name, d in t.exported(ctx.tree).items():
                cur = shared.setdefault(name, _Donor())
                cur.positions |= d.positions
                cur.names |= d.names

    findings: List[Finding] = []
    for ctx in jax_ctxs:
        table = tables[ctx.path]
        if not any(d.positions or d.names for d in table.donors.values()):
            continue
        defs = _collect_defs(ctx.tree)
        _mark_roots(ctx.tree, defs)
        traced_ids = {id(info.node) for info in _reachable(defs)}
        scopes: List[ast.AST] = [ctx.tree]
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and id(node) not in traced_ids:
                scopes.append(node)
            elif isinstance(node, ast.Lambda):
                scopes.append(node)
        file_findings: List[Finding] = []
        for scope_node in scopes:
            sc = _Scope(ctx, table, scope_node)
            literals = _literal_seqs(scope_node)
            for n in sc.nodes:
                if isinstance(n, ast.Call):
                    sc.check_call(n, literals)
            file_findings.extend(sc.findings)
        findings.extend(sorted(set(file_findings),
                               key=lambda f: (f.line, f.code, f.message)))
    return findings
