"""Dissemination-strategy parity gate: every lowering bit-identical or bust.

Runs the SWIM kernel's full round loop under every dissemination
strategy (params.SwimParams.dissem — "swar" reference, the round-3
"planes" loop, the roll-commuted "prefused" tail, and the Pallas
one-pass "fused" kernel, interpret-mode on CPU) across a small regime
matrix — healthy, churn+loss, push/pull, hot tier — and asserts the
ENTIRE end state is bit-identical to the SWAR reference, field by
field.  The sharded config runs every strategy through the
8-CPU-device shard_map lowering (fused's halo-hop hybrid) against the
sharded SWAR reference — which tests/test_shard_map_parity.py pins to
the unsharded kernel — so a divergence anywhere in the halo/collective
composition fails the gate too.

Fast mode (the `make vet` hook) trims the matrix to a few seconds;
full mode adds seeds, longer horizons, and a fused block-size sweep.

Run: python -m tools.fused_crossval [--fast] [--seeds N]
Exit 0 clean, 1 on any divergence.
"""

from __future__ import annotations

import argparse
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault(
    "XLA_FLAGS",
    (os.environ.get("XLA_FLAGS", "") +
     " --xla_force_host_platform_device_count=8").strip())

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

STRATEGIES = ("planes", "prefused", "fused")


def _end_state(p, fail, steps, seed, ndev=0):
    import jax
    import jax.numpy as jnp

    from consul_tpu.gossip.kernel import (init_state, run_rounds,
                                          run_rounds_sharded, shard_state)
    st = init_state(p)
    if ndev > 1:
        st, _ = run_rounds_sharded(shard_state(st, ndev),
                                   jax.random.key(seed),
                                   jnp.asarray(fail), p, steps, ndev=ndev)
    else:
        st, _ = run_rounds(st, jax.random.key(seed), jnp.asarray(fail),
                           p, steps)
    return st


def _diff_fields(ref, other) -> list[str]:
    import numpy as np
    return [name for name in ref._fields
            if not np.array_equal(np.asarray(getattr(ref, name)),
                                  np.asarray(getattr(other, name)))]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--fast", action="store_true",
                    help="vet-gate sizing (a few seconds)")
    ap.add_argument("--seeds", type=int, default=None,
                    help="seed count override")
    args = ap.parse_args(argv)

    import numpy as np

    import jax

    from consul_tpu.gossip.kernel import NEVER
    from consul_tpu.gossip.params import SwimParams

    seeds = args.seeds or (1 if args.fast else 3)
    steps = 120 if args.fast else 300
    n = 240  # divisible by 8 devices and probe_every=5

    def fails(spec):
        f = np.full(n, NEVER, np.int32)
        for idx, rnd in spec:
            f[idx] = rnd
        return f

    churn = fails([(40, 20), (90, 35), (170, 50), (230, 65)])
    configs = [
        ("healthy", dict(), fails([]), 0),
        ("churn_loss", dict(loss_rate=0.1), churn, 0),
        ("pushpull", dict(pushpull_every=20, loss_rate=0.05), churn, 0),
        ("hot_tier", dict(hot_slots=4), churn, 0),
        ("sharded8", dict(loss_rate=0.1), churn, 8),
    ]
    base = dict(n=n, slots=16, probe_every=5)

    print(f"[fused-crossval] backend={jax.default_backend()} "
          f"devices={jax.device_count()} seeds={seeds} steps={steps}",
          flush=True)
    failures = 0
    for name, kw, fail, ndev in configs:
        for seed in range(seeds):
            ref = _end_state(SwimParams(**base, **kw), fail, steps, seed,
                             ndev=ndev)
            for dissem in STRATEGIES:
                nbs = ((1,) if args.fast or dissem != "fused"
                       else (1, 2, 8))
                for nb in nbs:
                    p = SwimParams(**base, **kw, dissem=dissem,
                                   fused_nb=nb)
                    st = _end_state(p, fail, steps, seed, ndev=ndev)
                    bad = _diff_fields(ref, st)
                    tag = (f"{name} seed={seed} dissem={dissem}"
                           + (f" nb={nb}" if dissem == "fused" else ""))
                    if bad:
                        failures += 1
                        print(f"[fused-crossval] FAIL {tag}: diverged "
                              f"fields {bad}", file=sys.stderr)
                    else:
                        print(f"[fused-crossval]   ok {tag}", flush=True)
    if failures:
        print(f"[fused-crossval] {failures} divergence(s)",
              file=sys.stderr)
        return 1
    print(f"[fused-crossval] ok: all strategies bit-identical "
          f"({len(configs)} configs x {seeds} seed(s), divergence 0)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
