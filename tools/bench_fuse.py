"""Fused-planes reconcile bench: batched vs per-agent catalog writes.

Boots a 3-node in-process cluster (the chaos-campaign cluster shape:
MemoryTransport, compressed raft timings) per leg and drives synthetic
gossip member transitions into the leader's reconcile queue — N
simulated agents flipping alive/failed per round against held blocking
watches on each agent's serfHealth check.  Per leg it reports:

    entries_per_transition  raft log entries appended / transitions
    p50_ms / p99_ms         detection -> watcher-visible latency (the
                            membership_notify stamp to the blocking
                            query waking with the new verdict)
    first_visible_*_ms      per-burst minimum of the same stamps — the
                            first watcher served fresh data, which is
                            what the journey ledger's wake stage
                            measures (cross-checked in the gate below)

Legs: ``sequential`` (extra["reconcile_batched"]=False — the per-agent
loop, one append+quorum per transition) and ``batch=N`` for each
``--batch-sizes`` tier (the PR-18 fused path: one BATCH envelope per
drain cadence).  The PR-18 acceptance bar is checked in-process: the
batch>=64 tier must cut raft entries per transition >=10x below
sequential without regressing p99 (the p99 gate is skipped under
``--fast`` — smoke boxes are too noisy to pin a latency bar).

Output is one JSON object shaped for obs/tuner.py's ``adapt_fuse``
evidence adapter; ``--out`` (default BENCH_FUSE.json, '' skips —
``--fast`` skips unless --out is explicit) feeds the
``reconcile_batch_max`` autotune rule.

Run:    python tools/bench_fuse.py [--agents 64] [--rounds 8]
                                   [--batch-sizes 8,64] [--fast]
                                   [--out FILE]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from consul_tpu.consensus.raft import MemoryTransport, RaftConfig  # noqa: E402
from consul_tpu.membership.swim import (                           # noqa: E402
    STATE_ALIVE, STATE_DEAD, Node)
from consul_tpu.obs import journey as _journey                     # noqa: E402
from consul_tpu.server.server import Server, ServerConfig          # noqa: E402
from consul_tpu.structs.structs import (                           # noqa: E402
    HEALTH_CRITICAL, HEALTH_PASSING, QueryOptions, SERF_CHECK_ID)

NODE_NAMES = ("b0", "b1", "b2")


def _bench_raft() -> RaftConfig:
    # The chaos-campaign compressed envelope: election settles in
    # ~0.2s, appends commit in single-digit milliseconds, so the
    # coalescing win dominates the measurement rather than timeouts.
    return RaftConfig(heartbeat_interval=0.02, election_timeout_min=0.1,
                      election_timeout_max=0.2, rpc_timeout=0.05)


def _leader(servers):
    for s in servers:
        if s.is_leader():
            return s
    return None


async def _boot(extra: dict):
    tr = MemoryTransport()
    names = list(NODE_NAMES)
    servers = [Server(ServerConfig(node_name=nm, peers=names,
                                   raft=_bench_raft(), extra=dict(extra)),
                      transport=tr)
               for nm in names]
    for s in servers:
        await s.start()
    deadline = time.monotonic() + 10.0
    while _leader(servers) is None:
        if time.monotonic() > deadline:
            raise TimeoutError("no leader elected")
        await asyncio.sleep(0.01)
    # Let the leader's establish barrier land so the reconcile loop is
    # armed before the first injection.
    await asyncio.sleep(0.3)
    return servers


async def _watch(srv, name: str, want_status: str, t0s: dict,
                 lats: list) -> None:
    """Hold a blocking query on ``name``'s checks until serfHealth
    reads ``want_status``; stamp detection->visible on wake.  The index
    floor is 1, never 0: min_query_index=0 is the non-blocking fast
    path, and an empty store reports index 0 — looping on it would spin
    without ever yielding."""
    idx = 1
    while True:
        meta, checks = await srv.health.node_checks(name, QueryOptions(
            min_query_index=idx, max_query_time=2.0))
        serf = next((c for c in checks if c.check_id == SERF_CHECK_ID),
                    None)
        if serf is not None and serf.status == want_status:
            lats.append((time.monotonic() - t0s[name]) * 1000.0)
            return
        idx = max(idx, meta.index, 1)


def _journey_leg() -> dict:
    """Stage breakdown of the leg just run (obs/journey.py): exact
    percentiles over the record ring's raw e2e values plus per-stage
    banks and each stage's share of the total ledger time.  None when
    the ledger is compiled out or never closed a batch (the sequential
    loop never arms one)."""
    jy = _journey.journey
    if jy is None or jy.transitions_total == 0:
        return None
    vals = sorted(r["e2e_ms"] for r in jy.records())

    def pct(q: float) -> float:
        return vals[min(len(vals) - 1, int(q / 100 * len(vals)))]

    sums = jy.stage_sums()
    total = sum(sums.values()) or 1.0
    return {
        "transitions": jy.transitions_total,
        "e2e_p50_ms": round(pct(50), 2),
        "e2e_p99_ms": round(pct(99), 2),
        "stages": {s: jy.stage[s].wire() for s in _journey.STAGES},
        "stage_share": {s: round(sums[s] / total, 4)
                        for s in _journey.STAGES},
    }


async def _run_leg(extra: dict, agents: int, rounds: int) -> dict:
    servers = await _boot(extra)
    try:
        # Isolate the leg's ledger AFTER boot so the servers' own
        # join reconciles don't ride the measurement.
        if _journey.journey is not None:
            _journey.journey.reset()
        names = [f"sim{i:03d}" for i in range(agents)]
        addrs = {nm: f"10.77.{i // 250}.{i % 250 + 1}"
                 for i, nm in enumerate(names)}
        lats: list = []
        firsts: list = []
        transitions = 0
        entries = 0
        for r in range(rounds):
            ld = _leader(servers)
            if ld is None:
                raise RuntimeError("lost leader mid-bench")
            alive = r % 2 == 0
            want = HEALTH_PASSING if alive else HEALTH_CRITICAL
            state = STATE_ALIVE if alive else STATE_DEAD
            kind = "member-join" if alive else "member-failed"
            t0s: dict = {}
            watchers = [asyncio.create_task(
                _watch(ld, nm, want, t0s, lats)) for nm in names]
            await asyncio.sleep(0.05)  # watchers parked on min_index
            before = ld.raft.last_log_index()
            # One synchronous burst, the gossip evbatch shape: every
            # put_nowait lands before the reconcile loop wakes.
            for nm in names:
                t0s[nm] = time.monotonic()
                ld.membership_notify(kind, Node(
                    name=nm, addr=addrs[nm], port=8301, state=state))
            n0 = len(lats)
            await asyncio.wait_for(asyncio.gather(*watchers),
                                   timeout=30.0)
            # First watcher served fresh data this burst — the harness
            # twin of the journey ledger's wake stamp (per-watcher p99
            # additionally carries the N-coroutine resume fan-out the
            # pipeline ledger deliberately does not measure).
            if len(lats) > n0:
                firsts.append(min(lats[n0:]))
            entries += ld.raft.last_log_index() - before
            transitions += agents
        lat = sorted(lats) or [0.0]
        fv = sorted(firsts) or [0.0]

        def pct(q: float, vals=None) -> float:
            vals = lat if vals is None else vals
            return vals[min(len(vals) - 1, int(q / 100 * len(vals)))]

        out = {
            "transitions": transitions,
            "raft_entries": entries,
            "entries_per_transition": round(entries / max(1, transitions),
                                            4),
            "p50_ms": round(pct(50), 2),
            "p99_ms": round(pct(99), 2),
            "first_visible_p50_ms": round(pct(50, fv), 2),
            "first_visible_p99_ms": round(pct(99, fv), 2),
        }
        jleg = _journey_leg()
        if jleg is not None:
            out["journey"] = jleg
        return out
    finally:
        for s in servers:
            await s.stop()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--agents", type=int, default=64,
                    help="simulated agents flipping state per round")
    ap.add_argument("--rounds", type=int, default=8)
    ap.add_argument("--batch-sizes", default="8,64",
                    help="comma list of reconcile_batch_max tiers")
    ap.add_argument("--fast", action="store_true",
                    help="smoke shape: 2 rounds, sequential + batch=64 "
                         "only, p99 gate skipped, no artifact unless "
                         "--out is explicit")
    ap.add_argument("--out", default=None,
                    help="JSON artifact path (default BENCH_FUSE.json; "
                         "'' skips; --fast defaults to '')")
    args = ap.parse_args()

    rounds = 2 if args.fast else args.rounds
    tiers = [64] if args.fast else sorted(
        {int(b) for b in args.batch_sizes.split(",") if b.strip()})
    out_path = args.out
    if out_path is None:
        out_path = "" if args.fast else os.path.join(REPO,
                                                     "BENCH_FUSE.json")

    runs = {}
    print(f"[bench-fuse] sequential: {args.agents} agents x{rounds}",
          file=sys.stderr)
    runs["sequential"] = asyncio.run(_run_leg(
        {"reconcile_batched": False}, args.agents, rounds))
    for n in tiers:
        print(f"[bench-fuse] batch={n}: {args.agents} agents x{rounds}",
              file=sys.stderr)
        runs[f"batch={n}"] = asyncio.run(_run_leg(
            {"reconcile_batch_max": n}, args.agents, rounds))

    out = {"agents": args.agents, "rounds": rounds, "runs": runs}
    text = json.dumps(out, indent=1)
    print(text)
    if out_path:
        with open(out_path, "w") as fh:
            fh.write(text + "\n")

    # The PR-18 acceptance gate, checked where the numbers are made.
    seq = runs["sequential"]
    big = max((n for n in tiers if n >= 64), default=None)
    if big is None:
        return 0
    b = runs[f"batch={big}"]
    ratio = (seq["entries_per_transition"]
             / max(b["entries_per_transition"], 1e-9))
    ok = ratio >= 10.0
    if not args.fast:
        ok = ok and b["p99_ms"] <= seq["p99_ms"] * 1.05
    print(f"[bench-fuse] batch={big}: {ratio:.1f}x fewer raft entries "
          f"per transition ({seq['entries_per_transition']} -> "
          f"{b['entries_per_transition']}), p99 "
          f"{seq['p99_ms']}ms -> {b['p99_ms']}ms: "
          f"{'PASS' if ok else 'FAIL'}", file=sys.stderr)
    # Journey ledger gate: the always-on ledger must have seen every
    # harness transition, and (full runs — smoke boxes are too noisy
    # for a latency bar) its end-to-end p99 must agree within 20% with
    # the harness's independent first-visible measurement (both stamp
    # detect -> first watcher served fresh data; the per-watcher p99
    # additionally carries the N-coroutine resume fan-out).
    jb = b.get("journey")
    if jb is not None:
        hv = b["first_visible_p99_ms"]
        jok = jb["transitions"] >= b["transitions"]
        if not args.fast:
            jok = jok and (abs(jb["e2e_p99_ms"] - hv)
                           <= 0.2 * max(hv, 1e-9))
        ok = ok and jok
        print(f"[bench-fuse] journey: {jb['transitions']} transitions, "
              f"e2e p99 {jb['e2e_p99_ms']}ms vs harness first-visible "
              f"p99 {hv}ms: {'PASS' if jok else 'FAIL'}", file=sys.stderr)
    elif _journey.journey is not None:
        ok = False
        print("[bench-fuse] journey: ledger enabled but recorded no "
              "transitions: FAIL", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
