"""Observability smoke: boot a small CPU gossip plane + one
kernel-backed agent, scrape ``/v1/agent/metrics?format=prometheus``,
and validate every line with tools/check_prom's strict checker —
including the detection-latency observatory's histogram families —
then sanity-check the ``/v1/agent/slo`` JSON shell.

The first boot also runs the consensus-plane deep phase: KV traffic
through raft, a ``?consistent`` lease-path read, the
``/v1/operator/raft/telemetry`` route, and a ``/v1/agent/debug/bundle``
capture which is untarred in memory and held to the manifest contract
(check_prom runs on the bundled metrics snapshot too).  The same boot
checks the device/kernel observatory (obs/devstats.py): the
``consul_device_*``/``consul_kernel_*`` families plus
``consul_build_info``/``consul_up`` in the scrape, the
``/v1/agent/device`` JSON twin, and the bundle's ``device/`` member.
It also drives a synthetic member burst through the leader's batched
reconcile (agent/reconcile.py) and holds the scrape to the
``consul_reconcile_*`` families plus the bundle's ``reconcile/``
member.

The deep boot also exercises the autotune control plane (obs/tuner.py)
end to end: a verdict is pre-settled into a throwaway
``CONSUL_TPU_AUTOTUNE_DIR`` before the plane boots, so the boot must
resolve its knobs from it (source ``verdict`` for every
evidence-backed row), report the whole registry at
``/v1/operator/autotune``, carry the strict ``consul_autotune_*``
families in the scrape, and ship ``autotune/verdict.json`` in the
debug bundle.

A second boot runs the plane under a live nemesis scenario
(``PlaneConfig(nemesis="block_kill")``, gossip/nemesis.py) and holds
the scrape to the scenario-labeled contract: labeled histogram series
in the Prometheus text, and the ``scenario``/``scenarios`` breakdown
at ``/v1/agent/slo``.

This is the `make obs-smoke` gate: it catches exposition drift
(obs/prom.py), bridge-frame drift (plane ``slo`` frame ->
tpu_backend.plane_slo -> agent route), and plane wiring regressions
in one boot.  Runs entirely on CPU (JAX_PLATFORMS=cpu) in one process.

Run: python -m tools.obs_smoke
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# Families the scrape MUST carry for the observatory to count as wired.
REQUIRED = [
    "consul_swim_detection_latency_rounds_bucket",
    "consul_swim_suspicion_dwell_rounds_bucket",
    "consul_swim_refutation_latency_rounds_bucket",
    "consul_swim_spread_members_bucket",
    "consul_flight_round",
]

# Device/kernel observatory families (obs/devstats.py) + scrape hygiene.
# The HBM gauges are deliberately NOT here: CPU reports no
# memory_stats, so on this smoke they are absent by design.
REQUIRED_DEVICE = [
    "consul_kernel_dispatch_ms_bucket",
    "consul_kernel_rounds_per_sec",
    "consul_kernel_dispatches_total",
    "consul_kernel_compile_cache_hits_total",
    "consul_kernel_compile_cache_misses_total",
    "consul_device_live_buffers",
    "consul_build_info",
    "consul_up",
]

NEMESIS = "block_kill"  # scenario the second boot runs live

# Consensus-plane families the deep phase must surface on a
# lease-holding (single-node) leader after a little KV traffic.
REQUIRED_RAFT = [
    "consul_raft_append_quorum_ms_bucket",
    "consul_raft_commit_apply_ms_bucket",
    "consul_raft_lease_margin_ms_bucket",
    "consul_raft_snapshot_install_ms_bucket",
    "consul_antientropy_sync_ms_bucket",
    "consul_antientropy_failures_total",
    "consul_consistent_reads_total",
]

# Autotune observatory families (obs/tuner.py prom_families) — the
# knob resolution must be scrapeable on every agent.
REQUIRED_AUTOTUNE = [
    "consul_autotune_knob_info",
    "consul_autotune_knob_value",
    "consul_autotune_evidence_age_seconds",
    "consul_autotune_resettles_total",
]

# Batched-reconcile observatory families (agent/reconcile.py
# reconstats) — the deep boot drives synthetic member transitions
# through the leader's fused reconcile loop so these carry content.
REQUIRED_RECONCILE = [
    "consul_reconcile_batch_size_bucket",
    "consul_reconcile_visible_latency_ms",
    "consul_reconcile_batches_total",
    "consul_reconcile_entries_coalesced_total",
    "consul_reconcile_events_merged_total",
    "consul_reconcile_submit_failures_total",
]

# Journey-ledger stage labels (obs/journey.py STAGES) — mirrored here
# so the vet table-drift pass pins this enumeration to the governing
# tuple; every stage's labeled ladder must render (zeros included).
JOURNEY_STAGES = ("detect", "drain", "decode", "enqueue", "submit",
                  "append_quorum", "fsm_apply", "render", "wake")

# Transition-journey observatory families (obs/journey.py) — the deep
# boot's member burst closes at least one journey batch behind them.
REQUIRED_JOURNEY = [
    "consul_journey_stage_ms_bucket",
    "consul_journey_e2e_ms_bucket",
    "consul_journey_transitions_total",
    "consul_journey_wakeless_total",
]

# Bundle manifest sections the acceptance contract names.
REQUIRED_SECTIONS = {"metrics", "slo", "traces", "flight", "raft",
                     "reconcile", "journey", "device", "autotune",
                     "tasks"}

# Device state-store observatory families (obs/storestats.py), present
# on the third boot (device_store=True) after a little KV traffic with
# a standing watch.
REQUIRED_STORE = [
    "consul_store_dispatch_ms_bucket",
    "consul_store_apply_batch_entries_bucket",
    "consul_store_applied_entries_total",
    "consul_watch_fired_total",
    "consul_watch_match_events_total",
    "consul_store_divergence_total",
    "consul_store_capacity",
    "consul_store_occupancy",
    "consul_watch_registered",
]


def _get(url: str) -> bytes:
    with urllib.request.urlopen(url, timeout=15) as r:
        return r.read()


def _put(url: str, data: bytes) -> bytes:
    req = urllib.request.Request(url, data=data, method="PUT")
    with urllib.request.urlopen(req, timeout=15) as r:
        return r.read()


async def _boot_and_scrape(nemesis: str = "", deep: bool = False):
    """One plane + one kernel-backed agent; returns the Prometheus
    text and the /v1/agent/slo JSON after a few dispatches land.
    ``deep`` additionally drives KV traffic through raft (so the
    consensus-plane histograms have content), then captures the raft
    telemetry JSON and a debug bundle — returned as two extra items."""
    from consul_tpu.agent.agent import Agent, AgentConfig
    from consul_tpu.consensus.raft import RaftConfig
    from consul_tpu.gossip.plane import GossipPlane, PlaneConfig

    plane = GossipPlane(PlaneConfig(
        bind_port=0, capacity=16, slots=16, gossip_interval_s=0.02,
        suspicion_mult=1.0, hb_lapse_s=0.3, nemesis=nemesis))
    await plane.start()
    agent = None
    try:
        agent = Agent(AgentConfig(
            node_name="obs-smoke", datacenter="dc1", server=True,
            bootstrap=True, rpc_mesh_port=0, http_port=0, dns_port=0,
            serf_wan_port=0, enable_debug=True,
            raft_config=RaftConfig(
                heartbeat_interval=0.03, election_timeout_min=0.06,
                election_timeout_max=0.12, rpc_timeout=0.5),
            gossip_backend="tpu",
            gossip_plane="127.0.0.1:%d" % plane.local_addr[1]))
        await agent.start()
        # Let a few kernel dispatches land so the flight ring and the
        # observatory banks have content behind the scrape.
        await asyncio.sleep(1.0)
        host, port = agent.http.addr
        base = f"http://{host}:{port}"
        telemetry = bundle = journey = None
        rc_landed = 0
        if deep:
            # KV writes through raft group-commit populate the
            # append→quorum and commit→apply ladders; a ?consistent
            # read on the (always lease-holding) single-node leader
            # exercises the lease fast path.
            for i in range(5):
                await asyncio.to_thread(
                    _put, f"{base}/v1/kv/obs-smoke/k{i}", b"v")
            await asyncio.to_thread(
                _get, f"{base}/v1/kv/obs-smoke/k0?consistent")
            # Fused-planes reconcile: a synchronous burst of synthetic
            # member transitions into the leader's reconcile queue must
            # coalesce into BATCH envelopes and land every node in the
            # catalog (consul_reconcile_* families carry the evidence).
            from consul_tpu.membership.swim import STATE_ALIVE
            from consul_tpu.membership.swim import Node as GossipNode
            ghosts = [f"obs-ghost{i}" for i in range(4)]
            for i, g in enumerate(ghosts):
                agent.server.membership_notify("member-join", GossipNode(
                    name=g, addr=f"10.88.0.{i + 1}", port=8301,
                    state=STATE_ALIVE))
            deadline = asyncio.get_running_loop().time() + 5.0
            while asyncio.get_running_loop().time() < deadline:
                rc_landed = sum(
                    1 for g in ghosts
                    if agent.server.store.get_node(g)[1] is not None)
                if rc_landed == len(ghosts):
                    break
                await asyncio.sleep(0.05)
            # One trailing transition arms a second journey batch,
            # which finalizes the ghost burst's parked batch (no
            # long-poller runs in this smoke, so nothing else would
            # wake it) — making transitions_total deterministic below.
            agent.server.membership_notify("member-join", GossipNode(
                name="obs-ghost-flush", addr="10.88.0.250", port=8301,
                state=STATE_ALIVE))
            await asyncio.sleep(0.3)
            telemetry = json.loads(await asyncio.to_thread(
                _get, f"{base}/v1/operator/raft/telemetry"))
            journey = json.loads(await asyncio.to_thread(
                _get, f"{base}/v1/operator/journey"))
            bundle = await asyncio.to_thread(
                _get, f"{base}/v1/agent/debug/bundle?seconds=1")
        text = (await asyncio.to_thread(
            _get, f"{base}/v1/agent/metrics?format=prometheus")).decode()
        slo = json.loads(await asyncio.to_thread(
            _get, f"{base}/v1/agent/slo"))
        device = json.loads(await asyncio.to_thread(
            _get, f"{base}/v1/agent/device"))
        autotune = json.loads(await asyncio.to_thread(
            _get, f"{base}/v1/operator/autotune"))
        return (text, slo, telemetry, bundle, device, autotune, journey,
                rc_landed)
    finally:
        if agent is not None:
            await agent.stop()
        await plane.stop()


async def _boot_device_store():
    """Third boot: a swim-backed server agent with the device-resident
    state store on (state/device_store.py).  KV writes travel raft's
    commit→apply batching into the device table; a standing KV watch
    exercises the device matcher; two GETs of the same key exercise the
    index-validated byte cache.  Returns the Prometheus text plus the
    bridge/cache ground truth for the caller's assertions."""
    from consul_tpu.agent.agent import Agent, AgentConfig
    from consul_tpu.consensus.raft import RaftConfig
    from consul_tpu.server.blocking import AsyncWaiter

    agent = Agent(AgentConfig(
        node_name="obs-smoke-store", datacenter="dc1", server=True,
        bootstrap=True, rpc_mesh_port=0, http_port=0, dns_port=0,
        serf_wan_port=0, device_store=True,
        device_store_capacity=1 << 10,
        raft_config=RaftConfig(
            heartbeat_interval=0.03, election_timeout_min=0.06,
            election_timeout_max=0.12, rpc_timeout=0.5)))
    await agent.start()
    try:
        srv = agent.server
        waiter = AsyncWaiter(asyncio.get_running_loop())
        srv.store.watch_kv("obs-smoke/", waiter)
        host, port = agent.http.addr
        base = f"http://{host}:{port}"
        for i in range(6):
            await asyncio.to_thread(
                _put, f"{base}/v1/kv/obs-smoke/k{i}", b"v")
        await waiter.wait(2.0)  # the device∪host matcher must wake us
        for _ in range(2):      # second GET lands in the byte cache
            await asyncio.to_thread(_get, f"{base}/v1/kv/obs-smoke/k0")
        text = (await asyncio.to_thread(
            _get, f"{base}/v1/agent/metrics?format=prometheus")).decode()
        bridge = srv.fsm.device
        cache = getattr(srv, "kv_byte_cache", None)
        info = {
            "attached": bridge is not None,
            "divergence": None if bridge is None else bridge.divergence,
            "occupancy": None if bridge is None else bridge.occupancy(),
            "woke": waiter._event.is_set(),
            "cache_hits": None if cache is None else cache.hits,
        }
        return text, info
    finally:
        await agent.stop()


def _check_bundle(bundle: bytes, errors: list) -> None:
    """Untar the capture in memory and hold it to the manifest +
    exposition contract (check_prom on the bundled scrape)."""
    import io
    import tarfile

    from tools.check_prom import check_text

    try:
        tar = tarfile.open(fileobj=io.BytesIO(bundle), mode="r:gz")
    except tarfile.TarError as e:
        errors.append(f"bundle is not a tar.gz: {e}")
        return
    with tar:
        names = set(tar.getnames())
        m = tar.extractfile("manifest.json") if "manifest.json" in names \
            else None
        if m is None:
            errors.append("bundle has no manifest.json")
            return
        manifest = json.load(m)
        missing = REQUIRED_SECTIONS - set(manifest.get("sections", []))
        if missing:
            errors.append(f"bundle manifest missing sections {sorted(missing)}")
        for want in ("metrics/prometheus.txt", "metrics/snapshot_start.json",
                     "metrics/snapshot_end.json", "raft/telemetry.json",
                     "reconcile/telemetry.json", "journey/telemetry.json",
                     "device/telemetry.json", "autotune/verdict.json",
                     "tasks.txt", "config.json", "slo.json", "traces.json",
                     "flight.json"):
            if want not in names:
                errors.append(f"bundle missing file {want}")
        if "metrics/prometheus.txt" in names:
            ptxt = tar.extractfile("metrics/prometheus.txt").read().decode()
            errors += [f"bundled scrape: {e}" for e in check_text(ptxt)]
        if "raft/telemetry.json" in names:
            rt = json.load(tar.extractfile("raft/telemetry.json"))
            if "timeline" not in rt:
                errors.append("bundled raft telemetry has no timeline")
        if "reconcile/telemetry.json" in names:
            rt = json.load(tar.extractfile("reconcile/telemetry.json"))
            for key in ("batches_total", "entries_coalesced",
                        "reconciler_armed"):
                if key not in rt:
                    errors.append(f"bundled reconcile telemetry has no "
                                  f"{key!r}")
        if "journey/telemetry.json" in names:
            jt = json.load(tar.extractfile("journey/telemetry.json"))
            for key in ("enabled", "stages", "transitions_total"):
                if key not in jt:
                    errors.append(f"bundled journey telemetry has no "
                                  f"{key!r}")
        if "device/telemetry.json" in names:
            dt = json.load(tar.extractfile("device/telemetry.json"))
            if "enabled" not in dt:
                errors.append("bundled device telemetry has no 'enabled'")
        if "autotune/verdict.json" in names:
            at = json.load(tar.extractfile("autotune/verdict.json"))
            for key in ("knobs", "fingerprint"):
                if key not in at:
                    errors.append(f"bundled autotune verdict has no {key!r}")
        if "config.json" in names:
            cfg = json.load(tar.extractfile("config.json"))
            for k in ("encrypt", "acl_master_token", "acl_token"):
                if cfg.get(k) not in ("", "<redacted>"):
                    errors.append(f"bundle config leaks secret field {k}")


async def main() -> int:
    import tempfile

    from consul_tpu.obs import tuner
    from tools.check_prom import _iter_series, _require_ok, check_text

    errors = []

    # Pre-settle an autotune verdict into a throwaway dir (hermetic:
    # never the developer's real cache) so the boots below exercise the
    # persisted-verdict resolution path, not just registry defaults.
    os.environ["CONSUL_TPU_AUTOTUNE_DIR"] = tempfile.mkdtemp(
        prefix="obs_smoke_autotune_")
    verdict = tuner.settle(tuner.gather_evidence(REPO), tuner.fingerprint())
    vpath = tuner.save_verdict(verdict)
    print(f"[obs-smoke] pre-settled autotune verdict "
          f"({verdict['evidence_rows']} evidence rows) at {vpath}",
          flush=True)

    print("[obs-smoke] starting plane (first boot compiles the kernel)...",
          flush=True)
    text, slo, telemetry, bundle, device, autotune, journey, rc_landed = \
        await _boot_and_scrape(deep=True)
    errors += check_text(text)
    series = list(_iter_series(text))
    names = {n for n, _ in series}
    for want in (REQUIRED + REQUIRED_RAFT + REQUIRED_DEVICE +
                 REQUIRED_AUTOTUNE + REQUIRED_RECONCILE +
                 REQUIRED_JOURNEY):
        if want not in names:
            errors.append(f"required metric {want} not in scrape")
    # Batched-reconcile ground truth behind the scraped families: every
    # synthetic member must have landed in the catalog, through at
    # least one BATCH envelope (reconstats is process-global, so the
    # deep boot's counters are readable here).
    from consul_tpu.agent.reconcile import reconstats
    if rc_landed != 4:
        errors.append(f"reconcile phase landed {rc_landed}/4 synthetic "
                      "members in the catalog")
    if reconstats.batches_total < 1:
        errors.append("reconcile phase submitted no batch envelopes "
                      f"(batches_total={reconstats.batches_total})")
    if reconstats.submit_failures:
        errors.append(f"reconcile phase had {reconstats.submit_failures} "
                      "submit failures")
    # Transition-journey observatory: every stage's labeled ladder must
    # render (zero-count stages included — the ladder is always
    # complete), and the /v1/operator/journey shell must carry the
    # contract keys with at least one transition closed (the boot's own
    # member reconcile; the ghost batch may still be parked awaiting a
    # wake, which is fine — read surfaces lag by at most one batch).
    for s in JOURNEY_STAGES:
        want = f'consul_journey_stage_ms_bucket{{stage="{s}"}}'
        if not _require_ok(want, series, errors):
            errors.append(f"scrape missing journey stage ladder {want}")
    if not (journey or {}).get("enabled"):
        errors.append(f"/v1/operator/journey enabled = "
                      f"{(journey or {}).get('enabled')!r}")
    else:
        for key in ("budget_ms", "stages", "e2e", "slo",
                    "transitions_total", "wakeless_total", "records"):
            if key not in journey:
                errors.append(f"/v1/operator/journey missing key {key!r}")
        jmissing = set(JOURNEY_STAGES) - set(journey.get("stages") or {})
        if jmissing:
            errors.append(f"/v1/operator/journey stages missing "
                          f"{sorted(jmissing)}")
        if journey.get("transitions_total", 0) < 4:
            errors.append("journey ledger closed fewer transitions than "
                          "the ghost burst (transitions_total="
                          f"{journey.get('transitions_total')!r} < 4)")
    # Autotune observatory: the route must cover the whole registry
    # with well-formed rows, the boot must have found the pre-settled
    # verdict, and every evidence-backed verdict row must have resolved
    # with source "verdict" (flag > verdict > default, nothing set).
    aknobs = autotune.get("knobs") or {}
    missing_knobs = set(tuner.KNOBS) - set(aknobs)
    if missing_knobs:
        errors.append(f"/v1/operator/autotune missing knobs "
                      f"{sorted(missing_knobs)}")
    for kname in sorted(aknobs):
        row = aknobs[kname]
        for key in ("value", "source", "evidence", "reason"):
            if key not in row:
                errors.append(f"autotune knob {kname} row missing {key!r}")
        if row.get("source") not in ("flag", "verdict", "default"):
            errors.append(f"autotune knob {kname} has source "
                          f"{row.get('source')!r}")
    if not isinstance(autotune.get("fingerprint"), dict):
        errors.append("/v1/operator/autotune missing fingerprint")
    if not autotune.get("verdict_found"):
        errors.append("boot did not pick up the pre-settled verdict")
    for kname, vrow in sorted(verdict["knobs"].items()):
        if vrow["source"] != "evidence":
            continue
        got = (aknobs.get(kname) or {}).get("source")
        if got != "verdict":
            errors.append(f"knob {kname} is evidence-backed in the "
                          f"verdict but booted with source {got!r}")
    if not _require_ok('consul_autotune_knob_info{knob="dissem"}',
                       series, errors):
        errors.append('scrape missing consul_autotune_knob_info'
                      '{knob="dissem"}')
    # Device observatory JSON twin: the bridge `device` frame rendered
    # at /v1/agent/device, plus the agent's build row.
    if not device.get("enabled"):
        errors.append(f"/v1/agent/device enabled = {device.get('enabled')!r}")
    for key in ("dispatch", "roofline", "devices", "compile", "build"):
        if key not in device:
            errors.append(f"/v1/agent/device missing key {key!r}")
    build = device.get("build") or {}
    for key in ("version", "jax_version", "backend"):
        if not build.get(key):
            errors.append(f"/v1/agent/device build missing {key!r}")
    # Lease efficacy split: the deep phase's ?consistent read on a
    # lease-holding single-node leader must land on the lease row.
    if not _require_ok('consul_consistent_reads_total{path="lease"}',
                       series, errors):
        errors.append('scrape missing consul_consistent_reads_total'
                      '{path="lease"}')
    # Raft telemetry route: stats + observatory payload shape.
    if telemetry is None or "raft" not in telemetry:
        errors.append("/v1/operator/raft/telemetry missing 'raft'")
    else:
        for key in ("histograms", "timeline", "antientropy"):
            if key not in telemetry:
                errors.append(f"/v1/operator/raft/telemetry missing {key!r}")
    if bundle is None:
        errors.append("no debug bundle captured")
    else:
        _check_bundle(bundle, errors)
    for key in ("slo", "latency", "hists"):
        if key not in slo:
            errors.append(f"/v1/agent/slo missing key {key!r}")
    snap = slo.get("slo") or {}
    for key in ("objective_rounds", "attainment_target", "burn_rate"):
        if key not in snap:
            errors.append(f"/v1/agent/slo slo snapshot missing {key!r}")

    # -- nemesis phase: the same contract under a live fault scenario.
    # The scenario banks exist from the first attributed drain (zero
    # deltas still create them), so the labeled series and the
    # ``scenarios`` breakdown must be present even before any
    # detection fires.
    print(f"[obs-smoke] rebooting plane under nemesis={NEMESIS!r} "
          "(new static schedule recompiles)...", flush=True)
    ntext, nslo, _, _, _, _, _, _ = await _boot_and_scrape(nemesis=NEMESIS)
    nerrors = check_text(ntext)
    for fam in REQUIRED[:4]:
        want = fam + f'{{scenario="{NEMESIS}"}}'
        if not _require_ok(want, list(_iter_series(ntext)), nerrors):
            nerrors.append(f"nemesis scrape missing labeled series {want}")
    if nslo.get("scenario") != NEMESIS:
        nerrors.append(f"/v1/agent/slo scenario = {nslo.get('scenario')!r}, "
                       f"want {NEMESIS!r}")
    scns = nslo.get("scenarios")
    if not isinstance(scns, dict) or NEMESIS not in scns:
        nerrors.append(f"/v1/agent/slo scenarios breakdown missing {NEMESIS!r}")
    elif "latency" not in scns[NEMESIS]:
        nerrors.append("scenarios breakdown row missing 'latency'")
    errors += nerrors

    # -- device state-store phase: batched apply + device watch match
    # must surface the consul_store_*/consul_watch_* families, wake the
    # standing watch, keep host/device lockstep (divergence 0), and
    # serve the second GET from the byte cache.
    print("[obs-smoke] rebooting with device_store=True "
          "(device table + watch matcher compile)...", flush=True)
    stext, sinfo = await _boot_device_store()
    serrors = check_text(stext)
    snames = {n for n, _ in _iter_series(stext)}
    for want in REQUIRED_STORE:
        if want not in snames:
            serrors.append(f"device-store scrape missing {want}")
    if not _require_ok('consul_store_dispatch_ms_bucket{class="store_apply"}',
                       list(_iter_series(stext)), serrors):
        serrors.append("device-store scrape missing store_apply class")
    if not _require_ok('consul_store_dispatch_ms_bucket{class="watch_match"}',
                       list(_iter_series(stext)), serrors):
        serrors.append("device-store scrape missing watch_match class")
    if not sinfo["attached"]:
        serrors.append("device_store=True but no bridge on the FSM")
    if sinfo["divergence"] != 0:
        serrors.append(f"device-store divergence {sinfo['divergence']} != 0")
    if not sinfo["woke"]:
        serrors.append("standing KV watch never woke on committed writes")
    if not sinfo["cache_hits"]:
        serrors.append(f"KV byte cache hits = {sinfo['cache_hits']!r}, "
                       "wanted > 0")
    errors += serrors

    for e in errors:
        print(f"[obs-smoke] FAIL: {e}", file=sys.stderr)
    if errors:
        return 1
    print(f"[obs-smoke] ok: {len(names)} series names, "
          f"{len(text.splitlines())} lines, slo objective "
          f"{snap.get('objective_rounds')} rounds, debug bundle "
          f"{len(bundle)} bytes; nemesis scrape "
          f"{len(ntext.splitlines())} lines, scenarios "
          f"{sorted(scns)}; device store occupancy "
          f"{sinfo['occupancy']}, cache hits {sinfo['cache_hits']}")
    return 0


if __name__ == "__main__":
    sys.exit(asyncio.run(main()))
