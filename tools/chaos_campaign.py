"""Chaos campaign CLI: seeded fault scenarios against a live cluster.

Usage:
    python -m tools.chaos_campaign --fast --seed 1234 --out CHAOS.json
    python -m tools.chaos_campaign --scenario leader_flap
    python -m tools.chaos_campaign            # the full catalog

Each scenario boots its own 3-node cluster (or forks the real agent for
the black-box worker-crash leg), injects one fault through the
consul_tpu.chaos broker, and gates on linearizability, lease safety,
and fault *detectability* in the raft observatory.  The report lands in
``--out`` (CHAOS.json) and per-scenario debug bundles under
``--debug-dir``.  Same seed, same verdicts: ``make chaos-fast`` runs
this twice in CI lockstep.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from consul_tpu.chaos.campaign import run_campaign            # noqa: E402
from consul_tpu.chaos.scenarios import CATALOG, FAST_SCENARIOS  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="chaos_campaign",
        description="Deterministic consensus-plane fault campaign.")
    ap.add_argument(
        "--scenario", action="append",
        choices=["clock_skew", "clock_jump", "fsync_stall", "leader_flap",
                 "asym_partition", "slow_follower",
                 "worker_crash_under_load", "reconcile_fsync_stall"],
        help="scenario to run (repeatable); default: the full catalog")
    ap.add_argument("--fast", action="store_true",
                    help="run only the fast subset (the make chaos-fast / "
                         "CI tier)")
    ap.add_argument("--seed", type=int, default=1234,
                    help="campaign seed; fixes the whole fault schedule")
    ap.add_argument("--out", default="CHAOS.json",
                    help="report path (default: CHAOS.json)")
    ap.add_argument("--debug-dir", default="chaos_debug",
                    help="per-scenario debug bundle root")
    args = ap.parse_args(argv)

    if args.scenario:
        scenarios = args.scenario
    elif args.fast:
        scenarios = list(FAST_SCENARIOS)
    else:
        scenarios = list(CATALOG)

    report = run_campaign(scenarios, seed=args.seed, out_dir=args.debug_dir)
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2)

    wide = max(len(s) for s in scenarios)
    for r in report["scenarios"]:
        if "error" in r:
            line = f"ERROR  {r['error']}"
        else:
            g = r["gates"]
            line = ("PASS" if r["pass"] else "FAIL") + \
                (f"  lin={g['linearizable']} lease={g['single_lease_holder']}"
                 f" deposed_ok={g['no_deposed_serve']}"
                 f" detected={r['detection']['detected']}"
                 f" ops={r['ops']['total']}")
        print(f"{r['scenario']:<{wide}}  {line}")
    print(f"campaign: {'PASS' if report['passed'] else 'FAIL'}"
          f" (seed {args.seed}, report {args.out})")
    return 0 if report["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
