"""Strict Prometheus text-format (0.0.4) line checker.

Validates a scrape of ``/v1/agent/metrics?format=prometheus`` the way a
strict ingester would, so exposition drift (obs/prom.py) fails `make
obs-smoke` instead of a dashboard three deploys later:

- every line is a ``# HELP``, a ``# TYPE``, a sample, or blank;
- metric and label names match the spec grammar; label values use only
  the three legal escapes (``\\``, ``\"``, ``\n``);
- every sample belongs to a family with a declared TYPE, declared
  BEFORE the first sample, at most once;
- HELP (when present) is declared at most once, before the samples;
- sample values parse as Go-style floats (incl. ``+Inf``/``-Inf``/
  ``NaN``); optional timestamps are integers;
- no duplicate (name, labelset) sample;
- summary children are limited to ``_sum``/``_count`` (+ quantile'd
  base series), histogram children to ``_bucket``/``_sum``/``_count``;
- histogram buckets carry ``le``, appear in ascending ``le`` order
  with non-decreasing cumulative counts, include the mandatory
  ``+Inf`` bucket, and ``+Inf`` == ``_count`` — checked PER LABELSET
  (minus ``le``), so scenario-labeled nemesis ladders
  (``{scenario="..."}``, obs/hist.py) are each validated as their own
  histogram instead of being pooled with the unlabeled aggregate.

Run: python -m tools.check_prom [file] [--require NAME ...]
(reads stdin without a file; --require asserts at least one sample of
that exact metric name exists — obs_smoke pins the observatory
families with it.  A matcher form ``NAME{label="value",...}`` requires
a sample of that name whose labelset includes every listed pair, e.g.
``consul_swim_detection_latency_rounds_bucket{scenario="block_kill"}``).
Exit 0 clean, 1 findings.
"""

from __future__ import annotations

import re
import sys
from typing import Dict, List, Optional, Tuple

_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_HELP_RE = re.compile(rf"^# HELP ({_NAME}) (.*)$")
_TYPE_RE = re.compile(
    rf"^# TYPE ({_NAME}) (counter|gauge|histogram|summary|untyped)$")
_SAMPLE_RE = re.compile(
    rf"^({_NAME})(\{{(.*)\}})? ([^ ]+)( -?\d+)?$")
_LABEL_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\\n]|\\\\|\\"|\\n)*)"')
_VALUE_RE = re.compile(
    r"^([+-]?(\d+(\.\d*)?|\.\d+)([eE][+-]?\d+)?|[+-]?Inf|NaN)$")

_HIST_SUFFIXES = ("_bucket", "_sum", "_count")
_SUMMARY_SUFFIXES = ("_sum", "_count")


def _family_of(name: str, types: Dict[str, str]) -> Optional[str]:
    """Resolve a sample name to its declared family (histogram/summary
    children strip their suffix; exact match wins)."""
    if name in types:
        return name
    for suf in _HIST_SUFFIXES:
        if name.endswith(suf):
            base = name[: -len(suf)]
            if types.get(base) in ("histogram", "summary"):
                return base
    return None


def _parse_labels(raw: str, lineno: int,
                  errors: List[str]) -> Optional[List[Tuple[str, str]]]:
    """Strict label-body parse: comma-separated name="value" pairs,
    one optional trailing comma (per the format grammar)."""
    out: List[Tuple[str, str]] = []
    pos = 0
    while pos < len(raw):
        m = _LABEL_RE.match(raw, pos)
        if m is None:
            errors.append(f"line {lineno}: bad label syntax at "
                          f"{raw[pos:pos + 20]!r}")
            return None
        out.append((m.group(1), m.group(2)))
        pos = m.end()
        if pos < len(raw):
            if raw[pos] != ",":
                errors.append(f"line {lineno}: expected ',' between labels")
                return None
            pos += 1
    return out


def _float(v: str) -> float:
    return float(v.replace("Inf", "inf").replace("NaN", "nan"))


def check_text(text: str) -> List[str]:
    """Validate a full exposition; returns a list of findings (empty =
    clean)."""
    errors: List[str] = []
    types: Dict[str, str] = {}
    helps: Dict[str, int] = {}
    sampled: set = set()          # families that have emitted a sample
    seen_series: set = set()      # (name, labelset) duplicates
    # histogram bookkeeping per (family, labelset-minus-le): each
    # labeled variant (nemesis scenario ladders) is its own histogram
    hist_buckets: Dict[Tuple[str, tuple], List[Tuple[float, float]]] = {}
    hist_count: Dict[Tuple[str, tuple], float] = {}

    for lineno, line in enumerate(text.split("\n"), 1):
        if line == "":
            continue
        if line != line.strip():
            errors.append(f"line {lineno}: leading/trailing whitespace")
            continue
        if line.startswith("#"):
            m = _HELP_RE.match(line)
            if m is not None:
                fam = m.group(1)
                if fam in helps:
                    errors.append(f"line {lineno}: duplicate HELP for {fam}")
                if fam in sampled:
                    errors.append(
                        f"line {lineno}: HELP for {fam} after its samples")
                helps[fam] = lineno
                continue
            m = _TYPE_RE.match(line)
            if m is not None:
                fam = m.group(1)
                if fam in types:
                    errors.append(f"line {lineno}: duplicate TYPE for {fam}")
                if fam in sampled:
                    errors.append(
                        f"line {lineno}: TYPE for {fam} after its samples")
                types[fam] = m.group(2)
                continue
            if line.startswith("# HELP") or line.startswith("# TYPE"):
                errors.append(f"line {lineno}: malformed HELP/TYPE line")
            continue  # other comments are legal and ignored
        m = _SAMPLE_RE.match(line)
        if m is None:
            errors.append(f"line {lineno}: unparseable sample: {line!r}")
            continue
        name, _, label_raw, value, _ts = m.groups()
        if not _VALUE_RE.match(value):
            errors.append(f"line {lineno}: bad value {value!r}")
            continue
        labels = _parse_labels(label_raw or "", lineno, errors)
        if labels is None:
            continue
        series = (name, tuple(sorted(labels)))
        if series in seen_series:
            errors.append(f"line {lineno}: duplicate series {name}"
                          f"{dict(labels)}")
        seen_series.add(series)
        fam = _family_of(name, types)
        if fam is None:
            errors.append(
                f"line {lineno}: sample {name} has no TYPE declaration")
            continue
        sampled.add(fam)
        kind = types[fam]
        child = name[len(fam):]
        if kind == "histogram":
            if child not in ("",) + _HIST_SUFFIXES or child == "":
                # base-name samples are not part of the histogram ABI
                errors.append(f"line {lineno}: {name} is not a valid "
                              f"histogram child of {fam}")
                continue
            lset = tuple(sorted((k, v) for k, v in labels if k != "le"))
            if child == "_bucket":
                le = dict(labels).get("le")
                if le is None:
                    errors.append(
                        f"line {lineno}: {name} bucket missing le label")
                    continue
                if not _VALUE_RE.match(le):
                    errors.append(f"line {lineno}: bad le value {le!r}")
                    continue
                hist_buckets.setdefault((fam, lset), []).append(
                    (_float(le), _float(value)))
            elif child == "_count":
                hist_count[(fam, lset)] = _float(value)
        elif kind == "summary":
            if child not in ("",) + _SUMMARY_SUFFIXES:
                errors.append(f"line {lineno}: {name} is not a valid "
                              f"summary child of {fam}")
        elif child != "":
            errors.append(f"line {lineno}: {name} sampled under {kind} "
                          f"family {fam}")

    for fam, kind in types.items():
        if fam not in sampled:
            errors.append(f"family {fam}: TYPE declared but no samples")
    for fam in [f for f, k in types.items() if k == "histogram"
                and f in sampled]:
        keys = sorted(set(k for k in hist_buckets if k[0] == fam)
                      | set(k for k in hist_count if k[0] == fam))
        if not keys:
            errors.append(f"histogram {fam}: no _bucket samples")
            continue
        for key in keys:
            _, lset = key
            who = fam + ("{" + ",".join(f'{k}="{v}"' for k, v in lset) + "}"
                         if lset else "")
            buckets = hist_buckets.get(key, [])
            if not buckets:
                errors.append(f"histogram {who}: no _bucket samples")
                continue
            les = [le for le, _ in buckets]
            if les != sorted(les):
                errors.append(f"histogram {who}: le edges not ascending")
            if sorted(set(les)) != sorted(les):
                errors.append(f"histogram {who}: duplicate le edges")
            cums = [c for _, c in buckets]
            if any(b < a for a, b in zip(cums, cums[1:])):
                errors.append(f"histogram {who}: cumulative counts decrease")
            if les[-1] != float("inf"):
                errors.append(f"histogram {who}: missing +Inf bucket")
            elif key not in hist_count:
                errors.append(f"histogram {who}: missing _count")
            elif cums[-1] != hist_count[key]:
                errors.append(f"histogram {who}: +Inf bucket {cums[-1]} != "
                              f"_count {hist_count[key]}")
    return errors


def _iter_series(text: str):
    """(name, labels dict) for every parseable sample line."""
    for ln in text.split("\n"):
        m = _SAMPLE_RE.match(ln)
        if m is None:
            continue
        labels = _parse_labels(m.group(3) or "", 0, [])
        yield m.group(1), dict(labels or [])


def _require_ok(want: str, series: List[Tuple[str, Dict[str, str]]],
                errors: List[str]) -> bool:
    """``NAME`` requires any sample of that name; ``NAME{l="v",...}``
    additionally requires the listed label pairs (subset match, so a
    bucket's ``le`` doesn't have to be spelled out)."""
    name, _, label_raw = want.partition("{")
    need: Dict[str, str] = {}
    if label_raw:
        parsed = _parse_labels(label_raw.rstrip("}"), 0, [])
        if parsed is None:
            errors.append(f"--require {want!r}: bad label matcher syntax")
            return False
        need = dict(parsed)
    return any(n == name and all(labels.get(k) == v
                                 for k, v in need.items())
               for n, labels in series)


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("file", nargs="?", help="exposition file (default stdin)")
    ap.add_argument("--require", action="append", default=[],
                    metavar="NAME",
                    help="fail unless a sample with this exact metric "
                         "name exists (repeatable); NAME{l=\"v\"} also "
                         "requires the label pairs")
    args = ap.parse_args(argv)
    if args.file:
        with open(args.file, "r", encoding="utf-8") as f:
            text = f.read()
    else:
        text = sys.stdin.read()
    errors = check_text(text)
    series = list(_iter_series(text))
    names = {n for n, _ in series}
    for want in args.require:
        if not _require_ok(want, series, errors):
            errors.append(f"required metric {want} not found")
    for e in errors:
        print(f"check_prom: {e}", file=sys.stderr)
    if not errors:
        print(f"check_prom: ok ({len(names)} series names)")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
