"""Cross-validation report: TPU kernel vs discrete-event SWIM oracle.

VERDICT round-1 item 6 / BASELINE.md configs #2-#3: quantify the
kernel's detection-time distribution against the per-node reference
model at 1k and 10k nodes with matched protocol configs, reporting
p50/p99 latency error and false-positive counts into ``CROSSVAL.json``
at the repo root.

Per-event kernel latencies come from the round trace: a victim's
episode slot records ``slot_dead_round`` when its suspicion timer
fires; latency = dead_round - fail_round (the same definition
``RefModel.detection_latencies`` uses: dead_tick - fail_tick).

Run:  python tools/crossval_report.py [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# The oracle is pure Python; the kernel runs fine on the CPU backend.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)


def kernel_event_latencies(p, fail_at: dict, steps: int, seed: int):
    import jax
    import jax.numpy as jnp

    from consul_tpu.gossip.kernel import NEVER, init_state, run_rounds

    fail = np.full(p.n, NEVER, np.int32)
    for v, t in fail_at.items():
        fail[v] = t
    st, trace = run_rounds(init_state(p), jax.random.key(seed),
                           jnp.asarray(fail), p, steps, trace=True)
    slot_node = np.asarray(trace.slot_node)        # [T, S]
    slot_dead = np.asarray(trace.slot_dead_round)  # [T, S]
    lats = []
    for v, t_fail in fail_at.items():
        # Only true detections: a lossy run can falsely declare a victim
        # dead BEFORE its fail round — the refmodel books those under
        # n_false_dead, not detection latency, so we must too.
        mask = (slot_node == v) & (slot_dead >= t_fail)
        if mask.any():
            lats.append(int(slot_dead[mask].min()) - t_fail)
    return lats, int(st.n_false_dead), int(st.n_refuted)


def refmodel_event_latencies(p, fail_at: dict, steps: int, seed: int):
    from consul_tpu.gossip.refmodel import RefModel
    m = RefModel(p, dict(fail_at), seed=seed)
    m.run(steps)
    return m.detection_latencies(), m.n_false_dead, m.n_refuted


def run_config(n: int, n_victims: int, seeds: int, loss: float = 0.0):
    from consul_tpu.gossip.params import SwimParams
    p = SwimParams(n=n, slots=64, probe_every=5, loss_rate=loss)
    first_fail = 30
    spacing = max(5, p.suspicion_min_rounds // 4)
    fail_at = {(n // (n_victims + 1)) * (i + 1): first_fail + i * spacing
               for i in range(n_victims)}
    steps = (first_fail + n_victims * spacing
             + p.slot_ttl_rounds + 8 * p.probe_every)

    k_lats, r_lats = [], []
    k_fp = r_fp = k_ref = r_ref = 0
    t0 = time.time()
    for s in range(seeds):
        kl, kf, kr = kernel_event_latencies(p, fail_at, steps, seed=s)
        k_lats += kl
        k_fp += kf
        k_ref += kr
    t_kernel = time.time() - t0
    t0 = time.time()
    for s in range(seeds):
        rl, rf, rr = refmodel_event_latencies(p, fail_at, steps,
                                              seed=1000 + s)
        r_lats += rl
        r_fp += rf
        r_ref += rr
    t_ref = time.time() - t0

    k = np.asarray(k_lats, float)
    r = np.asarray(r_lats, float)

    def pct(a, q):
        return float(np.percentile(a, q)) if len(a) else None

    def rel(kv, rv):
        if kv is None or rv is None or not rv:
            return None
        return round(abs(kv - rv) / rv, 4)

    out = {
        "n": n,
        "loss_rate": loss,
        "victims_per_run": n_victims,
        "seeds": seeds,
        "samples": {"kernel": len(k), "refmodel": len(r)},
        "expected_events": n_victims * seeds,
        "detection_latency_rounds": {
            "kernel": {"mean": round(float(k.mean()), 2) if len(k) else None,
                       "p50": pct(k, 50), "p99": pct(k, 99)},
            "refmodel": {"mean": round(float(r.mean()), 2) if len(r) else None,
                         "p50": pct(r, 50), "p99": pct(r, 99)},
        },
        "relative_error": {
            "mean": rel(float(k.mean()) if len(k) else None,
                        float(r.mean()) if len(r) else None),
            "p50": rel(pct(k, 50), pct(r, 50)),
            "p99": rel(pct(k, 99), pct(r, 99)),
        },
        "false_dead": {"kernel": k_fp, "refmodel": r_fp},
        "refutes": {"kernel": k_ref, "refmodel": r_ref},
        "lifeguard_envelope_rounds": [p.suspicion_min_rounds,
                                      p.suspicion_max_rounds],
        "wall_s": {"kernel": round(t_kernel, 1), "refmodel": round(t_ref, 1)},
    }
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="fewer seeds (CI-sized)")
    args = ap.parse_args()
    seeds = 3 if args.quick else 8
    victims = 8 if args.quick else 16

    report = {"generated_unix": int(time.time()),
              "definition": "latency = dead_declared_round - fail_round; "
                            "relative_error = |kernel - refmodel| / refmodel",
              "configs": []}
    path = os.path.join(REPO, "CROSSVAL.json")

    def _flush():
        # Write after EVERY config: the lossy oracle tail can run for
        # an hour+ of CPU — it must never hold the artifact hostage.
        with open(path, "w") as f:
            json.dump(report, f, indent=1, allow_nan=False)
        print(f"[crossval] wrote {path} ({len(report['configs'])} configs)",
              file=sys.stderr, flush=True)

    for n in (1000, 10000):
        print(f"[crossval] n={n} ...", file=sys.stderr, flush=True)
        report["configs"].append(run_config(n, victims, seeds))
        _flush()
    # False-positive behavior under heavy loss (BASELINE config #2
    # tail).  Loss makes the per-node oracle pathologically slow (every
    # probe spawns suspicion churn), so this config runs at reduced
    # scale — the point is comparing false-positive/refute RATES, which
    # n=500 resolves fine.
    print("[crossval] n=500 loss=0.25 ...", file=sys.stderr, flush=True)
    report["configs"].append(run_config(500, max(4, victims // 2),
                                        max(2, seeds // 4), loss=0.25))
    _flush()
    print(json.dumps(report, indent=1))


if __name__ == "__main__":
    main()
