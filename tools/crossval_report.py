"""Cross-validation artifact generator: ``CROSSVAL.json``.

BASELINE.md configs #2-#3: quantify the kernel's detection-time
distribution against the per-node reference model at 1k and 10k nodes
with matched protocol configs, plus the heavy-loss false-positive
config.  The statistics core is ``consul_tpu.gossip.crossval`` — the
same code the in-suite regression tier gates on
(``tests/test_gossip_crossval.py``), so this artifact can never drift
from what the suite asserts.  Every config row carries a ``scenario``
column: ``"iid"`` for the historical bernoulli-churn configs, the
catalog name for the nemesis correlated-fault rows
(``gossip/nemesis.py``), so per-scenario oracle-vs-kernel detection
fidelity is one report.

Run:  python tools/crossval_report.py [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# The oracle is pure Python; the kernel runs fine on the CPU backend.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

from consul_tpu.gossip.crossval import (run_config, run_event_config,  # noqa: E402
                                        run_join_config,
                                        run_nemesis_config)
from consul_tpu.gossip.nemesis import names as nemesis_names  # noqa: E402


def _iid(row: dict) -> dict:
    """Tag a bernoulli-churn config row for the scenario column (the
    nemesis rows carry their catalog name; everything historical is
    "iid")."""
    row.setdefault("scenario", "iid")
    return row


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="fewer seeds (CI-sized)")
    args = ap.parse_args()
    seeds = 3 if args.quick else 8
    victims = 8 if args.quick else 16

    report = {"generated_unix": int(time.time()),
              "definition": "latency = dead_declared_round - fail_round; "
                            "relative_error = |kernel - refmodel| / refmodel; "
                            "completeness = detected / injected",
              "configs": []}
    path = os.path.join(REPO, "CROSSVAL.json")

    def _flush():
        # Write after EVERY config: the lossy oracle tail can run for
        # an hour+ of CPU — it must never hold the artifact hostage.
        with open(path, "w") as f:
            json.dump(report, f, indent=1, allow_nan=False)
        print(f"[crossval] wrote {path} ({len(report['configs'])} configs)",
              file=sys.stderr, flush=True)

    for n in (1000, 10000):
        print(f"[crossval] n={n} ...", file=sys.stderr, flush=True)
        report["configs"].append(_iid(run_config(n, victims, seeds)))
        _flush()
    # Nemesis catalog fidelity (gossip/nemesis.py): one row per
    # correlated-fault scenario, oracle modeling the same fault, so the
    # per-scenario detection story lives in the same artifact as the
    # iid rows.  Oracle-tractable scale — the per-node refmodel pays
    # O(n) python per message and the partition scenarios manufacture
    # n/2 concurrent episodes.
    nem_n, nem_seeds = 256, (1 if args.quick else 2)
    for name in nemesis_names():
        print(f"[crossval] nemesis {name} n={nem_n} ...", file=sys.stderr,
              flush=True)
        report["configs"].append(run_nemesis_config(name, nem_n, nem_seeds))
        _flush()
    # False-positive + completeness behavior under heavy loss (BASELINE
    # config #2 tail).  Loss makes the per-node oracle pathologically
    # slow (every probe spawns suspicion churn), so this config runs at
    # reduced scale — the point is comparing false-positive/refute
    # RATES and detection completeness, which n=500 resolves fine.
    # Slot provisioning is loss-sized (crossval.loss_sized_slots).
    print("[crossval] n=500 loss=0.25 ...", file=sys.stderr, flush=True)
    report["configs"].append(_iid(run_config(500, max(4, victims // 2),
                                             max(2, seeds // 4), loss=0.25)))
    _flush()
    # Same loss regime with push/pull armed in BOTH models: anti-entropy
    # is exactly what memberlist relies on at this loss rate (rumors
    # whose retransmit budget expires before reaching everyone are
    # recovered by the periodic full sync).
    print("[crossval] n=500 loss=0.25 +pushpull ...", file=sys.stderr,
          flush=True)
    report["configs"].append(_iid(run_config(500, max(4, victims // 2),
                                             max(2, seeds // 4), loss=0.25,
                                             pushpull=True)))
    _flush()
    # BASELINE table row 4: 100k nodes, Lifeguard + push/pull.  The
    # pure-Python oracle is tractable to a few thousand nodes, so this
    # row gates on the row's OWN published criterion — p99 inside the
    # Lifeguard envelope — with the identical config shape
    # oracle-validated at 1k/10k above (sampling documented here).
    print("[crossval] n=100000 +pushpull (envelope gate) ...",
          file=sys.stderr, flush=True)
    report["configs"].append(_iid(run_config(100_000, victims,
                                             max(2, seeds // 4),
                                             pushpull=True, oracle=False)))
    _flush()
    # Join churn (gossip.html.markdown:10-43): concurrent joins +
    # failures, detection gates unchanged, join-propagation latency
    # compared against the oracle's alive-flood.
    print("[crossval] join churn n=1000 ...", file=sys.stderr, flush=True)
    report["join_churn"] = run_join_config(
        1000, n_joiners=8, n_victims=8, seeds=max(2, seeds // 2))
    _flush()
    # BASELINE config #3's other half: event-convergence statistics
    # (rounds to 50%/99% coverage) vs the iid-target flood oracle.
    report["event_convergence"] = []
    for n in (1000, 10000):
        print(f"[crossval] events n={n} ...", file=sys.stderr, flush=True)
        report["event_convergence"].append(
            run_event_config(n, max(4, seeds // 2)))
        _flush()
    print(json.dumps(report, indent=1))


if __name__ == "__main__":
    main()
