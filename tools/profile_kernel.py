"""Per-phase on-chip timing of the SWIM round kernel.

Times each phase of ``swim_round`` as its own jitted function with forced
device->host materialization (block_until_ready alone returns at enqueue
on the tunneled backend — see bench.py:_sync).  Phase boundaries force
materializations that the fused whole avoids, so the parts can sum to
more than the whole; the point is finding the dominant phase, not exact
accounting.

Run: PYTHONPATH=/root/repo:/root/.axon_site python tools/profile_kernel.py [--n 1000000]
"""

from __future__ import annotations

import argparse
import functools
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax
import jax.numpy as jnp
import numpy as np


def _checksum(out):
    """Tiny on-device reduction over every output leaf, so forcing
    completion costs a 4-byte fetch — NOT a 64MB pull through the
    tunnel (which reads ~120MB/s and swamped the first profile)."""
    tot = jnp.int32(0)
    for leaf in jax.tree.leaves(out):
        tot = tot + jnp.sum(leaf, dtype=jnp.int32)
    return tot


def make_timed(raw_fn):
    return jax.jit(lambda *a: _checksum(raw_fn(*a)))


def timed(fn, *args, iters=8, warmup=2):
    """fn must return a SCALAR (see make_timed)."""
    out = None
    for _ in range(warmup):
        out = fn(*args)
    int(out)  # device->host 4 bytes: cannot return before execution
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    int(out)
    return (time.perf_counter() - t0) / iters


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1_000_000)
    ap.add_argument("--slots", type=int, default=64)
    args = ap.parse_args()

    from consul_tpu.gossip.kernel import (
        NEVER, _AGE_MASK, _MSG_SHIFT, MSG_SUSPECT, _age_tick, _block_size,
        _probe_tick, init_state, run_rounds, swim_round)
    from consul_tpu.gossip.params import lan_profile
    from consul_tpu.ops.feistel import gossip_sources

    n, S = args.n, args.slots
    p = lan_profile(n, slots=S)
    print(f"device: {jax.devices()[0]}", file=sys.stderr)

    # Build a warm, realistically-populated state: run a few hundred
    # rounds with churn so slots are saturated like the bench steady state.
    state = init_state(p)
    key = jax.random.PRNGKey(42)
    n_fail = max(1, n // 1000)
    fail = (jnp.full((n,), NEVER, jnp.int32)
            .at[:n_fail].set((jnp.arange(n_fail, dtype=jnp.int32) * 2048) // n_fail))
    t0 = time.perf_counter()
    state, _ = run_rounds(state, key, fail, p, steps=256)
    jax.block_until_ready(state); int(state.round)
    print(f"state warmed: {time.perf_counter() - t0:.1f}s "
          f"(256 rounds incl. compile)", file=sys.stderr)

    rnd = state.round
    heard = state.heard
    mf = jnp.where(state.member, fail, -1)

    class _Results(dict):
        """Print each timing the moment it lands (remote compiles are
        slow; a late crash must not eat the measurements)."""

        def __setitem__(self, k, v):
            print(f"{k:32s} {v * 1e3:9.2f} ms", flush=True)
            super().__setitem__(k, v)

    results = _Results()

    # -- full round (the reference point) --------------------------------
    f_full = make_timed(functools.partial(swim_round, p=p))
    results["full_round"] = timed(f_full, state, key, fail)

    # -- scan of 64 rounds / 64 (amortized dispatch) ---------------------
    f_scan = make_timed(lambda st: run_rounds(st, key, fail, p, steps=64)[0])
    results["full_round_amortized_64"] = timed(f_scan, state, iters=2, warmup=1) / 64

    # -- phase 1: age tick ------------------------------------------------
    f_age = make_timed(_age_tick)
    results["age_tick"] = timed(f_age, heard)

    # -- phase 2: probe tick ---------------------------------------------
    def f_probe_raw(st, mf_):
        keys = jax.random.split(key, 4)
        carry = (st.heard, st.slot_node, st.slot_phase, st.slot_inc,
                 st.slot_start, st.slot_nsusp, st.slot_dead_round,
                 st.slot_of_node, st.incarnation, st.member, st.drops)
        return _probe_tick(p, st.round, keys, mf_, carry)[0]
    results["probe_tick"] = timed(make_timed(f_probe_raw), state, mf)

    # -- phase 3a: the fanout source permutations ------------------------
    f_src = make_timed(lambda k: gossip_sources(k, n, p.fanout))
    results["gossip_sources"] = timed(f_src, key)

    # -- phase 3b: gather + merge (the dissemination data path) ----------
    def f_gossip(h, mf_, k):
        srcs_all = gossip_sources(k, n, p.fanout)
        ids_n = jnp.arange(n, dtype=jnp.int32)
        cur_msg = (h >> _MSG_SHIFT).astype(jnp.uint8)
        in_msg = jnp.zeros_like(cur_msg)
        n_sus_in = jnp.zeros(h.shape, jnp.uint8)
        for f in range(p.fanout):
            srcs = srcs_all[f]
            src_ok = (mf_[srcs] > rnd) & (srcs != ids_n)
            hin = h[:, srcs]
            active = src_ok[None, :] & ((hin & _AGE_MASK) < p.spread_budget_rounds)
            m = jnp.where(active, (hin >> _MSG_SHIFT).astype(jnp.uint8), jnp.uint8(0))
            in_msg = jnp.maximum(in_msg, m)
            n_sus_in = n_sus_in + (m == MSG_SUSPECT).astype(jnp.uint8)
        return in_msg, n_sus_in
    results["gossip_gather_merge"] = timed(make_timed(f_gossip), heard, mf, key)

    # -- phase 3b': ONE gather only --------------------------------------
    def f_one_gather(h, k):
        srcs = gossip_sources(k, n, 1)[0]
        return h[:, srcs]
    results["one_SxN_gather"] = timed(make_timed(f_one_gather), heard, key)

    # -- transposed gather: rows of [N, S] -------------------------------
    heard_t = jnp.asarray(heard.T)  # [N, S]
    def f_one_gather_t(ht, k):
        srcs = gossip_sources(k, n, 1)[0]
        return ht[srcs, :]
    results["one_NxS_row_gather"] = timed(make_timed(f_one_gather_t), heard_t, key)

    # -- elementwise S×N pass (roofline probe) ---------------------------
    f_elem = make_timed(lambda h: (h ^ jnp.uint8(3)) + jnp.uint8(1))
    results["one_SxN_elementwise"] = timed(f_elem, heard)

    # -- u32-packed elementwise (same bytes, wider lanes) ----------------
    packed = jnp.asarray(np.frombuffer(
        np.asarray(heard).tobytes(), np.uint32).reshape(S, n // 4))
    f_elem32 = make_timed(lambda h: (h ^ jnp.uint32(3)) + jnp.uint32(1))
    results["one_SxN4_u32_elementwise"] = timed(f_elem32, packed)

    # -- timer fire + GC side --------------------------------------------
    def f_fire(st, h):
        tbl = jnp.asarray(p.timeout_table())
        conf_cap = jnp.minimum(p.max_confirmations,
                               jnp.maximum(st.slot_nsusp - 1, 0))[:, None]
        c_eff = jnp.minimum(((h >> 4) & 0x3).astype(jnp.int32), conf_cap)
        elapsed = st.round - st.slot_start
        fire = ((st.slot_phase == 1)[:, None]
                & ((h >> _MSG_SHIFT) == MSG_SUSPECT)
                & (elapsed[:, None] >= tbl[c_eff]))
        return jnp.any(fire, axis=1)
    results["timer_fire"] = timed(make_timed(f_fire), state, heard)

    print("\n-- sorted --", flush=True)
    for k, v in sorted(results.items(), key=lambda kv: -kv[1]):
        print(f"{k:32s} {v * 1e3:9.2f} ms", flush=True)


if __name__ == "__main__":
    main()
