"""Per-phase on-chip timing of the SWIM round kernel.

Times each phase of ``swim_round`` as its own jitted function with a
tiny on-device checksum reduction (a naive fetch pulls 64MB through the
tunnel at ~120MB/s and swamps every number; block_until_ready alone
returns at enqueue — see bench.py:_sync).  The per-dispatch floor on the
tunneled backend is ~8-9 ms/call: subtract it when reading small
entries, or compare the amortized scan numbers.  Phase boundaries force
materializations the fused whole avoids, so parts can sum to more than
the whole; the point is finding the dominant phase.

Run: PYTHONPATH=/root/repo:/root/.axon_site python tools/profile_kernel.py [--n 1000000]
"""

from __future__ import annotations

import argparse
import functools
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax
import jax.numpy as jnp


def _checksum(out):
    tot = jnp.int32(0)
    for leaf in jax.tree.leaves(out):
        tot = tot + jnp.sum(leaf, dtype=jnp.int32)
    return tot


def make_timed(raw_fn):
    return jax.jit(lambda *a: _checksum(raw_fn(*a)))


def timed(fn, *args, iters=8, warmup=2):
    """fn must return a SCALAR (see make_timed)."""
    out = None
    for _ in range(warmup):
        out = fn(*args)
    int(out)  # device->host 4 bytes: cannot return before execution
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    int(out)
    return (time.perf_counter() - t0) / iters


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1_000_000)
    ap.add_argument("--slots", type=int, default=64)
    ap.add_argument("--shard-devices", dest="shard_devices", type=int,
                    default=0,
                    help="also profile the shard_map'd path over this many "
                         "devices (0 = all local devices when n is aligned; "
                         "skipped on a single device)")
    ap.add_argument("--scenario", type=str, default="",
                    help="also profile the 64-round amortized scan under "
                         "this nemesis injection schedule "
                         "(gossip/nemesis.py catalog name; window widened "
                         "so the fault masks stay live) — the delta over "
                         "round_amortized_64 prices the scenario")
    ap.add_argument("--dissem",
                    choices=("swar", "planes", "prefused", "fused"),
                    default="swar",
                    help="dissemination strategy profiled by the main "
                         "entries (params.dissem); non-swar runs suffix "
                         "the strategy-dependent keys so captures from "
                         "different strategies diff cleanly")
    args = ap.parse_args()

    from consul_tpu.gossip.kernel import (
        NEVER, _age_tick, _disseminate, _probe_tick, init_state, run_rounds,
        swim_round)
    from consul_tpu.gossip.params import lan_profile
    from consul_tpu.ops.feistel import gossip_sources

    n, S = args.n, args.slots
    p = lan_profile(n, slots=S, dissem=args.dissem)
    p_nopp = lan_profile(n, slots=S, pushpull_every=0, dissem=args.dissem)
    # Strategy-dependent entries carry the strategy in their key; the
    # default swar keys stay bare for trend continuity with older
    # captures.
    sfx = "" if args.dissem == "swar" else f"_{args.dissem}"
    print(f"device: {jax.devices()[0]}  dissem: {args.dissem}",
          file=sys.stderr)

    # Build a warm, realistically-populated state: run a few hundred
    # rounds with churn so slots are saturated like the bench steady state.
    state = init_state(p)
    key = jax.random.PRNGKey(42)
    n_fail = max(1, n // 1000)
    fail = (jnp.full((n,), NEVER, jnp.int32)
            .at[:n_fail].set((jnp.arange(n_fail, dtype=jnp.int32) * 2048) // n_fail))
    t0 = time.perf_counter()
    state, _ = run_rounds(state, key, fail, p, steps=256)
    jax.block_until_ready(state); int(state.round)
    print(f"state warmed: {time.perf_counter() - t0:.1f}s "
          f"(256 rounds incl. compile)", file=sys.stderr)

    rnd = state.round
    heard = state.heard
    mf = jnp.where(state.member, fail, -1)
    alive = fail > rnd
    rx_ok = alive & state.member
    conf_cap = jnp.minimum(p.max_confirmations,
                           jnp.maximum(state.slot_nsusp - 1, 0))

    class _Results(dict):
        def __setitem__(self, k, v):
            print(f"{k:32s} {v * 1e3:9.2f} ms", flush=True)
            super().__setitem__(k, v)

    results = _Results()

    # -- full round, amortized over a 64-round scan (the honest number) --
    f_scan = make_timed(lambda st: run_rounds(st, key, fail, p, steps=64)[0])
    results[f"round_amortized_64{sfx}"] = timed(
        f_scan, state, iters=2, warmup=1) / 64

    # -- dissemination-strategy A/B: the profiled strategy (--dissem,
    # default SWAR single-pass) vs the round-3 per-byte-plane loop
    # (params.dissem) ----------------------------------------------------
    if args.dissem != "planes":
        p_planes = lan_profile(n, slots=S, dissem="planes")
        f_scan_pl = make_timed(
            lambda st: run_rounds(st, key, fail, p_planes, steps=64)[0])
        results["round_amortized_64_planes"] = timed(
            f_scan_pl, state, iters=2, warmup=1) / 64

    # -- nemesis injection overhead (--scenario): the identical scan
    # with the scenario's schedule compiled in.  The catalog windows
    # are oracle-scale and the warmed state is past them, so the
    # window is widened to keep the fault masks live during timing.
    if args.scenario:
        import dataclasses as _dc

        from consul_tpu.gossip.kernel import init_nem_state
        from consul_tpu.gossip.nemesis import build as build_nemesis
        nem_sc = build_nemesis(args.scenario, n)
        nem = _dc.replace(nem_sc.nem, start=0, stop=int(NEVER))
        nem_fail = jnp.minimum(fail, jnp.asarray(nem_sc.fail_round))
        nem_kw = {"nem": nem}
        if nem.needs_join:
            nem_kw["join_round"] = (
                jnp.asarray(nem_sc.join_round)
                if nem_sc.join_round is not None
                else jnp.full((n,), NEVER, jnp.int32))
        if nem.needs_state:
            nem_kw["nem_state"] = init_nem_state(n)
        f_scan_nem = make_timed(lambda st: run_rounds(
            st, key, nem_fail, p, steps=64, **nem_kw)[0])
        results[f"round_amortized_64_nem_{args.scenario}"] = timed(
            f_scan_nem, state, iters=2, warmup=1) / 64

    # -- join-tick overhead: the same 64-round scan with the join input
    # armed but quiescent (all NEVER — one N-compare + cond per round)
    # and with active join churn (64 joins spread over the scan).  The
    # delta over round_amortized_64 prices gossip_backend=tpu's
    # always-on join path and the sim's join-churn regime.
    join_quiet = jnp.full((n,), NEVER, jnp.int32)
    f_jq = make_timed(lambda st: run_rounds(
        st, key, fail, p, steps=64, join_round=join_quiet)[0])
    results["round_amortized_64_joinquiet"] = timed(
        f_jq, state, iters=2, warmup=1) / 64
    rnd0 = int(state.round)
    join_act = (jnp.full((n,), NEVER, jnp.int32)
                .at[n - 64:].set(rnd0 + jnp.arange(64, dtype=jnp.int32)))
    state_j = state._replace(member=state.member.at[n - 64:].set(False))
    f_ja = make_timed(lambda st: run_rounds(
        st, key, fail, p, steps=64, join_round=join_act)[0])
    results["round_amortized_64_joinchurn"] = timed(
        f_ja, state_j, iters=2, warmup=1) / 64

    # -- realistic-churn regime: 1-2 live episodes (vs the bench's 64
    # saturated slots), full tail vs the hot tier's sliced-row subset
    # pipeline.  This is the measurement VERDICT r3 asked for before
    # enabling hot_slots by default.
    p_hot = lan_profile(n, slots=S, hot_slots=8)
    fail2 = (jnp.full((n,), NEVER, jnp.int32)
             .at[:2].set(jnp.asarray([64, 128], jnp.int32)))
    state2 = init_state(p)
    state2, _ = run_rounds(state2, key, fail2, p, steps=192)
    jax.block_until_ready(state2); int(state2.round)
    f2_full = make_timed(lambda st: run_rounds(st, key, fail2, p, steps=64)[0])
    results["realistic_churn_full_64"] = timed(
        f2_full, state2, iters=2, warmup=1) / 64
    state2h = init_state(p_hot)
    state2h, _ = run_rounds(state2h, key, fail2, p_hot, steps=192)
    jax.block_until_ready(state2h); int(state2h.round)
    f2_hot = make_timed(
        lambda st: run_rounds(st, key, fail2, p_hot, steps=64)[0])
    results["realistic_churn_hot8_64"] = timed(
        f2_hot, state2h, iters=2, warmup=1) / 64

    # -- ablation scans: the same 64-round scan with phases removed.
    # Within-scan attribution — the per-phase standalone timings below
    # carry materialization-boundary + dispatch noise that makes them
    # sum to more than the whole.
    from consul_tpu.gossip.kernel import (
        _disseminate as _dis, _finish_round as _fin,
        _probe_tick as _probe)

    def ablated_scan(do_probe, do_dis, do_fin):
        # Mirrors swim_round's production ordering: probe FIRST on the
        # un-aged matrix (fresh marks carry the _AGE_FRESH sentinel);
        # aging happens inside _disseminate's pack, so there is no
        # standalone age pass to ablate.
        def round_fn(st, _):
            rnd = st.round
            k = jax.random.fold_in(key, rnd)
            k_probe = jax.random.split(jax.random.fold_in(k, 1), 4)
            k_gossip = jax.random.fold_in(k, 2)
            alive_ = fail > rnd
            mf_ = jnp.where(st.member, fail, -1)
            carry = (st.heard, st.slot_node, st.slot_phase, st.slot_inc,
                     st.slot_start, st.slot_nsusp, st.slot_dead_round,
                     st.slot_of_node, st.incarnation, st.member, st.drops)
            if do_probe:
                carry = _probe(p, rnd, k_probe, mf_, carry)[0]
            (heard_, slot_node, slot_phase, slot_inc, slot_start, slot_nsusp,
             slot_dead_round, slot_of_node, incarnation, member_, drops) = carry
            rx = alive_ & member_
            cc = jnp.minimum(p.max_confirmations,
                             jnp.maximum(slot_nsusp - 1, 0))
            if do_dis:
                heard_ = _dis(p, rnd, k_gossip, heard_, mf_, rx, cc)
            if do_fin:
                st2 = _fin(p, st, rnd, fail, alive_, member_, heard_,
                           None, jnp.arange(S, dtype=jnp.int32),
                           slot_node, slot_phase, slot_inc, slot_start,
                           slot_nsusp, slot_dead_round, slot_of_node,
                           incarnation, drops, cc, rx)
            else:
                st2 = st._replace(round=rnd + 1, heard=heard_,
                                  member=member_)
            return st2, None

        def scan(st):
            return jax.lax.scan(round_fn, st, None, length=64)[0]
        return make_timed(scan)

    results["scan64_base"] = timed(
        ablated_scan(False, False, False), state, iters=2, warmup=1) / 64
    results["scan64_probe"] = timed(
        ablated_scan(True, False, False), state, iters=2, warmup=1) / 64
    results["scan64_probe_dis"] = timed(
        ablated_scan(True, True, False), state, iters=2, warmup=1) / 64
    results["scan64_dis_fin"] = timed(
        ablated_scan(False, True, True), state, iters=2, warmup=1) / 64
    results["scan64_all"] = timed(
        ablated_scan(True, True, True), state, iters=2, warmup=1) / 64

    # -- single dispatched round -----------------------------------------
    results["full_round"] = timed(make_timed(functools.partial(swim_round, p=p)),
                                  state, key, fail)

    # -- phases -----------------------------------------------------------
    # Standalone age pass: a real production phase ONLY for the planes
    # strategy.  The swar family merges it into dissemination (swar:
    # inside the pack; prefused: commuted across the rolls into the
    # per-pin chains; fused: inside the Pallas body), so for those this
    # row is the ablation reference for what the merge saves, not a
    # phase the round actually dispatches.
    results["age_tick_standalone"] = timed(make_timed(_age_tick), heard)

    def f_probe_raw(st, mf_):
        keys = jax.random.split(key, 4)
        carry = (st.heard, st.slot_node, st.slot_phase, st.slot_inc,
                 st.slot_start, st.slot_nsusp, st.slot_dead_round,
                 st.slot_of_node, st.incarnation, st.member, st.drops)
        return _probe_tick(p, st.round, keys, mf_, carry)[0]
    results["probe_tick"] = timed(make_timed(f_probe_raw), state, mf)

    # Merged age+gossip phase: every swar-family strategy ages inside
    # this call, so the row prices age+gossip+SWAR-merge as ONE phase
    # (the pre-round-12 table listed it as "disseminate" next to a
    # standalone "age_tick", reading as if the round paid both).
    # planes keeps the old label — there the age pass really is
    # separate.
    dis_key = ("disseminate" if p.dissem == "planes"
               else f"age_gossip_merge{sfx}")
    results[dis_key] = timed(
        make_timed(lambda h, mf_, cc: _disseminate(p, rnd, key, h, mf_, rx_ok, cc)),
        heard, mf, conf_cap)

    from consul_tpu.gossip.kernel import _finish_round

    def f_finish(st, h, cc, rx):
        return _finish_round(p, st, st.round, fail, fail > st.round,
                             st.member, h, None,
                             jnp.arange(S, dtype=jnp.int32), st.slot_node,
                             st.slot_phase, st.slot_inc, st.slot_start,
                             st.slot_nsusp, st.slot_dead_round,
                             st.slot_of_node, st.incarnation, st.drops,
                             cc, rx)
    results["finish_round"] = timed(make_timed(f_finish), state, heard,
                                    conf_cap, rx_ok)

    results["gossip_sources"] = timed(
        make_timed(lambda k: gossip_sources(k, n, p.fanout)), key)

    # -- packing + gathers in isolation ----------------------------------
    S4 = S // 4

    def pack(h):
        planes = h.reshape(S4, 4, n).astype(jnp.uint32)
        return (planes[:, 0] | (planes[:, 1] << 8)
                | (planes[:, 2] << 16) | (planes[:, 3] << 24))

    results["pack_u32"] = timed(make_timed(pack), heard)

    packed = jax.jit(pack)(heard)

    def f_one_gather32(pk, k):
        srcs = gossip_sources(k, n, 1)[0]
        return pk[:, srcs]
    results["one_S4xN_u32_gather"] = timed(make_timed(f_one_gather32), packed, key)

    def f_one_gather8(h, k):
        srcs = gossip_sources(k, n, 1)[0]
        return h[:, srcs]
    results["one_SxN_u8_gather"] = timed(make_timed(f_one_gather8), heard, key)

    # -- timeout-table gather (S×N int gather from a 4-entry table) ------
    def f_tbl(h, cc):
        tbl = jnp.asarray(p.timeout_table())
        c_eff = jnp.minimum(((h >> 4) & 0x3).astype(jnp.int32), cc[:, None])
        return tbl[c_eff]
    results["timeout_table_lookup"] = timed(make_timed(f_tbl), heard, conf_cap)

    # -- sharded path (kernel.py "ICI sharding"): per-phase cost under
    # shard_map.  Every entry below runs the SAME math as its unsharded
    # counterpart above — the deltas price the collective schedule:
    # psum merges in probe/finish, the ppermute halo exchange in the
    # circulant rolls.  make_timed's outer jit inlines the donating
    # jits, so donation never eats the reused profiling state.
    ndev = args.shard_devices or len(jax.devices())
    if ndev > 1 and n % ndev == 0 and n % p.probe_every == 0:
        from jax.experimental.shard_map import shard_map

        from consul_tpu.gossip.kernel import (
            _SHARD_AXIS, _ShardCtx, _disseminate as _dis_sc,
            _finish_round as _fin_sc, _probe_tick as _probe_sc,
            _roll_sharded, _shard_mesh, _state_spec, run_rounds_sharded,
            shard_state)

        mesh = _shard_mesh(ndev)
        sc = _ShardCtx(ndev, n // ndev)
        Ps = jax.sharding.PartitionSpec
        hspec = Ps(None, _SHARD_AXIS)
        st_spec = _state_spec()

        def sh(fn, in_specs, out_specs):
            return shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_rep=False)

        st_sh = shard_state(state, ndev)
        f_scan_sh = make_timed(lambda st: run_rounds_sharded(
            st, key, fail, p, steps=64)[0])
        results[f"shard{ndev}_round_amortized_64"] = timed(
            f_scan_sh, st_sh, iters=2, warmup=1) / 64

        def f_probe_sh(st, mf_):
            keys = jax.random.split(key, 4)
            carry = (st.heard, st.slot_node, st.slot_phase, st.slot_inc,
                     st.slot_start, st.slot_nsusp, st.slot_dead_round,
                     st.slot_of_node, st.incarnation, st.member, st.drops)
            return _probe_sc(p, st.round, keys, mf_, carry, sc)[0][0]
        results[f"shard{ndev}_probe_tick"] = timed(
            make_timed(sh(f_probe_sh, (st_spec, Ps()), hspec)), st_sh, mf)

        h_sh = jax.device_put(heard, jax.sharding.NamedSharding(mesh, hspec))
        results[f"shard{ndev}_disseminate"] = timed(
            make_timed(sh(
                lambda h, mf_, cc: _dis_sc(p, rnd, key, h, mf_, rx_ok, cc, sc),
                (hspec, Ps(), Ps()), hspec)),
            h_sh, mf, conf_cap)

        def f_finish_sh(st, h, cc, rx):
            return _fin_sc(p, st, st.round, fail, fail > st.round,
                           st.member, h, None,
                           jnp.arange(S, dtype=jnp.int32), st.slot_node,
                           st.slot_phase, st.slot_inc, st.slot_start,
                           st.slot_nsusp, st.slot_dead_round,
                           st.slot_of_node, st.incarnation, st.drops,
                           cc, rx, sc)
        results[f"shard{ndev}_finish_tail"] = timed(
            make_timed(sh(f_finish_sh, (st_spec, hspec, Ps(), Ps()),
                          st_spec)),
            st_sh, h_sh, conf_cap, rx_ok)

        # ppermute halo isolation: one full circulant delivery roll vs
        # the shard-local part alone — the delta is the ring exchange
        # (log2(ndev) conditional ppermutes + the boundary neighbor).
        packed_sh = jax.device_put(packed,
                                   jax.sharding.NamedSharding(mesh, hspec))
        o = jnp.int32(n // 3 + 1)  # crosses shard boundaries
        results[f"shard{ndev}_roll_with_halo"] = timed(
            make_timed(sh(lambda x, oo: _roll_sharded(sc, x, oo),
                          (hspec, Ps()), hspec)),
            packed_sh, o)
        results[f"shard{ndev}_roll_local_only"] = timed(
            make_timed(sh(lambda x, oo: jnp.roll(x, oo % sc.L, axis=-1),
                          (hspec, Ps()), hspec)),
            packed_sh, o)
    elif ndev > 1:
        print(f"[shard] skipped: n={n} not aligned to ndev={ndev} "
              f"x probe_every={p.probe_every}", file=sys.stderr)

    print("\n-- sorted --", flush=True)
    for k, v in sorted(results.items(), key=lambda kv: -kv[1]):
        print(f"{k:32s} {v * 1e3:9.2f} ms", flush=True)

    # Roofline utilization from the honest amortized number — the SAME
    # derivation (obs/devstats.py) the live agent exports as
    # consul_kernel_roofline_utilization and bench.py persists, so all
    # three profiling paths agree on one figure instead of §1c prose.
    from consul_tpu.obs.devstats import (
        EFFECTIVE_HBM_GBPS, DENSE_PASSES_BY_DISSEM, dense_bytes_per_round,
        roofline_utilization)
    dense_mb = dense_bytes_per_round(S, n, args.dissem) / 1e6
    util = roofline_utilization(
        dense_bytes_per_round(S, n, args.dissem),
        1.0 / results[f"round_amortized_64{sfx}"])
    if util is not None:
        print(f"\nroofline_utilization{sfx} {util:.4f} "
              f"(dense {dense_mb:.1f} MB/round = "
              f"{DENSE_PASSES_BY_DISSEM[args.dissem]} S*N passes "
              f"@ {EFFECTIVE_HBM_GBPS:.0f} GB/s ceiling)", flush=True)


if __name__ == "__main__":
    main()
