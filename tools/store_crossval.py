"""Device state-store crossval gate: host/device lockstep or bust.

Runs state/device_store.py's randomized crossval oracle — batched
applies + watch matching through BOTH the host StateStore and the
device table, asserting bit-identical modify-index/existed verdicts,
identical fired-watcher sets, identical wakeups, and zero divergence —
on the forced 8-CPU-device mesh (the multi-device sharding shape tests
run under, tests/conftest.py).

Fast mode (the `make vet` hook) trims the workload to a few seconds;
the full mode sweeps more seeds and a deeper batch stream.

Run: python -m tools.store_crossval [--fast] [--seeds N]
Exit 0 clean, 1 on any divergence.
"""

from __future__ import annotations

import argparse
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault(
    "XLA_FLAGS",
    (os.environ.get("XLA_FLAGS", "") +
     " --xla_force_host_platform_device_count=8").strip())

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--fast", action="store_true",
                    help="vet-gate sizing (a few seconds)")
    ap.add_argument("--seeds", type=int, default=None,
                    help="seed count override")
    args = ap.parse_args(argv)

    import jax

    from consul_tpu.state.device_store import crossval

    if args.fast:
        seeds = args.seeds or 2
        kw = dict(n_batches=8, batch=16, n_watches=64, capacity=1 << 10)
    else:
        seeds = args.seeds or 4
        kw = dict(n_batches=20, batch=32, n_watches=200, capacity=1 << 12)

    print(f"[store-crossval] backend={jax.default_backend()} "
          f"devices={jax.device_count()} seeds={seeds} {kw}", flush=True)
    for seed in range(seeds):
        try:
            summary = crossval(seed=seed, **kw)
        except AssertionError as e:
            print(f"[store-crossval] FAIL seed={seed}: {e}", file=sys.stderr)
            return 1
        print(f"[store-crossval]   seed={seed}: {summary}", flush=True)
    print("[store-crossval] ok: host/device lockstep held "
          f"({seeds} seeds, {jax.device_count()} devices)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
