"""Asyncio SWIM over real UDP/TCP sockets — the memberlist role.

Protocol semantics follow the reference's external memberlist dep (the
behavior contract Consul documents at
``website/source/docs/internals/gossip.html.markdown:10-43`` and tunes
at ``consul/config.go:266-272`` / ``consul/server_test.go:50-62``):

- **Failure detection**: every ``probe_interval`` one member (from a
  shuffled round-robin sweep) gets a direct UDP ping; on timeout,
  ``indirect_checks`` random peers are asked to probe on our behalf;
  no ack at all ⇒ broadcast a suspect message.
- **Suspicion**: a suspected node is declared dead after
  ``suspicion_mult * log10(n+1) * probe_interval`` unless it refutes by
  re-asserting itself at a higher incarnation (the alive message wins
  iff its incarnation is strictly newer — the SWIM ordering rule).
- **Dissemination**: membership messages ride piggybacked on every
  outbound UDP packet, each retransmitted ``retransmit_mult *
  log10(n+1)`` times; newer information about a node invalidates queued
  older messages about it.
- **Anti-entropy**: periodic TCP push/pull exchanges the full node
  table with one random peer (join uses the same exchange).
- **Encryption**: AES-128/256-GCM per packet when a keyring is armed —
  encrypt with the primary key, decrypt trying every installed key
  (matches memberlist's multi-key rollover model).

This is intentionally an event-loop state machine, not a thread per
timer: compressed-timer multi-node tests run in one process the same
way the reference's do (SURVEY §4).
"""

from __future__ import annotations

import asyncio
import math
import os
import random
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import msgpack

# node states (memberlist stateAlive/stateSuspect/stateDead + serf "left")
STATE_ALIVE = "alive"
STATE_SUSPECT = "suspect"
STATE_DEAD = "failed"
STATE_LEFT = "left"

# event kinds surfaced to the layer above (serf EventMember*)
EV_JOIN = "member-join"
EV_LEAVE = "member-leave"
EV_FAILED = "member-failed"
EV_UPDATE = "member-update"

_UDP_BUDGET = 1350  # payload budget per packet (memberlist udpSendBuf)
_AAD = b"consul-tpu-gossip-v0"


@dataclass
class MemberConfig:
    node_name: str = "node1"
    bind_addr: str = "127.0.0.1"
    bind_port: int = 0            # 0 = ephemeral (tests)
    advertise_addr: str = ""      # defaults to bind_addr
    tags: Dict[str, str] = field(default_factory=dict)
    # LAN-profile timings (memberlist DefaultLANConfig; WAN profile and
    # the compressed test profile just override these).
    probe_interval: float = 1.0
    probe_timeout: float = 0.5
    indirect_checks: int = 3
    gossip_interval: float = 0.2
    gossip_nodes: int = 3
    suspicion_mult: float = 4.0
    retransmit_mult: float = 4.0
    push_pull_interval: float = 30.0
    # reaping: forget failed nodes after reconnect_timeout and left nodes
    # after tombstone_timeout (serf's reaper; tests compress these)
    reap_interval: float = 10.0
    reconnect_timeout: float = 72 * 3600.0
    tombstone_timeout: float = 24 * 3600.0
    # Protocol negotiation (consul/config.go:31-37; memberlist's
    # alive-message version check): this node speaks protocol_version
    # and can interoperate with [protocol_min, protocol_max].  Peers
    # advertise theirs in vsn/vsn_min/vsn_max tags; incompatible nodes
    # are refused at admission.
    protocol_version: int = 2
    protocol_min: int = 1
    protocol_max: int = 2


@dataclass
class Node:
    name: str
    addr: str
    port: int
    incarnation: int = 0
    state: str = STATE_ALIVE
    tags: Dict[str, str] = field(default_factory=dict)
    state_change: float = field(default_factory=time.monotonic)

    def wire(self) -> Dict[str, Any]:
        return {"name": self.name, "addr": self.addr, "port": self.port,
                "inc": self.incarnation, "state": self.state,
                "tags": self.tags}


class Memberlist:
    """One gossip pool member.  ``start()`` binds UDP+TCP on the same
    port number (the memberlist convention); ``join()`` push/pulls with
    a seed; events stream to the registered handler."""

    def __init__(self, config: MemberConfig,
                 keyring: Optional[Any] = None,
                 on_event: Optional[Callable[[str, Node], None]] = None,
                 on_user_msg: Optional[Callable[[Dict], None]] = None,
                 member_filter: Optional[Callable[[Node], bool]] = None) -> None:
        self.config = config
        if not config.advertise_addr:
            config.advertise_addr = config.bind_addr
        self.keyring = keyring  # agent keyring: list_keys()[0] is primary
        self.on_event = on_event or (lambda kind, node: None)
        # Hook for the serf layer: unknown message types are handed up
        # (user events ride the same piggyback queue).
        self.on_user_msg = on_user_msg or (lambda msg: None)
        # Merge-delegate role (consul/merge.go): a pool can refuse
        # members that don't belong (the WAN pool only admits consul
        # servers; the LAN pool only admits its own datacenter).
        self.member_filter = member_filter
        self.incarnation = 0
        self.nodes: Dict[str, Node] = {}
        self._seq = 0
        self._ack_waiters: Dict[int, asyncio.Future] = {}
        # broadcast queue: name -> (msg, transmits_left); newer info
        # about a node replaces queued older info (memberlist invalidation)
        self._bcast: Dict[str, Tuple[Dict, int]] = {}
        self._extra_bcast: List[Tuple[Dict, int]] = []  # serf-layer msgs
        self._suspicion_timers: Dict[str, asyncio.TimerHandle] = {}
        self._probe_ring: List[str] = []
        self._probe_idx = 0
        self._udp: Optional[asyncio.DatagramTransport] = None
        self._tcp: Optional[asyncio.AbstractServer] = None
        self._tasks: List[asyncio.Task] = []
        self._stopped = False
        self.local_addr: Tuple[str, int] = ("", 0)

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        loop = asyncio.get_event_loop()
        self._tcp = await asyncio.start_server(
            self._serve_tcp, self.config.bind_addr, self.config.bind_port)
        port = self._tcp.sockets[0].getsockname()[1]
        self._udp, _ = await loop.create_datagram_endpoint(
            lambda: _UDPProtocol(self),
            local_addr=(self.config.bind_addr, port))
        self.local_addr = (self.config.advertise_addr, port)
        me = Node(self.config.node_name, self.config.advertise_addr, port,
                  incarnation=self.incarnation, tags=dict(self.config.tags))
        self.nodes[me.name] = me
        self.on_event(EV_JOIN, me)
        self._tasks = [
            loop.create_task(self._probe_loop()),
            loop.create_task(self._gossip_loop()),
            loop.create_task(self._pushpull_loop()),
            loop.create_task(self._reap_loop()),
        ]

    async def stop(self) -> None:
        self._stopped = True
        for t in self._tasks:
            t.cancel()
        for h in self._suspicion_timers.values():
            h.cancel()
        self._suspicion_timers.clear()
        if self._udp is not None:
            self._udp.close()
        if self._tcp is not None:
            self._tcp.close()
            await self._tcp.wait_closed()

    async def join(self, addrs: List[str]) -> int:
        """TCP push/pull with each seed (memberlist Join).  Returns the
        number of seeds successfully contacted."""
        ok = 0
        for a in addrs:
            host, _, port = a.rpartition(":")
            try:
                await self._pushpull(host or a,
                                     int(port) if port else self.local_addr[1])
                ok += 1
            except (OSError, asyncio.TimeoutError, ValueError,
                    ConnectionError, asyncio.IncompleteReadError):
                continue
        return ok

    async def leave(self) -> None:
        """Graceful leave: broadcast our own death flagged as intent
        (serf Leave → memberlist dead with node==from), linger a few
        gossip intervals so it disseminates."""
        me = self.nodes[self.config.node_name]
        self.incarnation += 1
        me.incarnation = self.incarnation
        me.state = STATE_LEFT
        me.state_change = time.monotonic()
        self._queue_bcast({"t": "dead", "node": me.name,
                           "inc": me.incarnation, "from": me.name})
        for _ in range(3):
            await asyncio.sleep(self.config.gossip_interval)

    def force_leave(self, name: str) -> bool:
        """Operator override for a failed node (RemoveFailedNode,
        consul/server.go:624-632): transition failed → left so the
        reaper can claim it without waiting."""
        node = self.nodes.get(name)
        if node is None or node.state not in (STATE_DEAD, STATE_SUSPECT):
            return False
        node.state = STATE_LEFT
        node.state_change = time.monotonic()
        self._queue_bcast({"t": "dead", "node": name,
                           "inc": node.incarnation, "from": name})
        return True

    def members(self) -> List[Node]:
        return sorted(self.nodes.values(), key=lambda n: n.name)

    def alive_members(self) -> List[Node]:
        return [n for n in self.members() if n.state == STATE_ALIVE]

    def num_alive(self) -> int:
        return sum(1 for n in self.nodes.values() if n.state == STATE_ALIVE)

    def set_tags(self, tags: Dict[str, str]) -> None:
        """Re-advertise self with new tags (serf SetTags)."""
        me = self.nodes[self.config.node_name]
        self.incarnation += 1
        me.incarnation = self.incarnation
        me.tags = dict(tags)
        self.config.tags = dict(tags)
        self._queue_bcast({"t": "alive", **me.wire()})

    def queue_user_msg(self, msg: Dict, transmits: Optional[int] = None) -> None:
        """Serf-layer broadcast (user events) on the piggyback queue."""
        self._extra_bcast.append((msg, transmits or self._retransmits()))

    # -- wire helpers ------------------------------------------------------

    def _encrypt(self, buf: bytes) -> bytes:
        if self.keyring is None:
            return b"\x00" + buf
        import base64

        from cryptography.hazmat.primitives.ciphers.aead import AESGCM
        key = base64.b64decode(self.keyring.list_keys()[0])
        nonce = os.urandom(12)
        return b"\x01" + nonce + AESGCM(key).encrypt(nonce, buf, _AAD)

    def _decrypt(self, buf: bytes) -> Optional[bytes]:
        if not buf:
            return None
        if buf[0] == 0:
            # Reject plaintext when encryption is armed (memberlist
            # GossipVerifyIncoming default).
            return None if self.keyring is not None else buf[1:]
        if self.keyring is None:
            return None
        import base64

        from cryptography.hazmat.primitives.ciphers.aead import AESGCM
        nonce, ct = buf[1:13], buf[13:]
        for k in self.keyring.list_keys():
            try:
                return AESGCM(base64.b64decode(k)).decrypt(nonce, ct, _AAD)
            except Exception:
                continue
        return None

    def _send_udp(self, addr: Tuple[str, int], msgs: List[Dict]) -> None:
        if self._udp is None or self._udp.is_closing():
            return
        buf = self._encrypt(msgpack.packb(msgs, use_bin_type=True))
        try:
            self._udp.sendto(buf, addr)
        except OSError:
            pass

    def _next_seq(self) -> int:
        self._seq += 1
        return self._seq

    def _retransmits(self) -> int:
        n = max(len(self.nodes), 1)
        return max(1, int(math.ceil(
            self.config.retransmit_mult * math.log10(n + 1))))

    def _queue_bcast(self, msg: Dict) -> None:
        # alive messages carry the subject under "name", the rest under
        # "node"; either way newer info replaces queued older info
        subject = msg.get("node") or msg["name"]
        self._bcast[subject] = (msg, self._retransmits())

    def _take_piggyback(self, budget: int = _UDP_BUDGET) -> List[Dict]:
        """Drain up to ``budget`` encoded bytes of queued broadcasts,
        decrementing retransmit counters (memberlist getBroadcasts)."""
        out: List[Dict] = []
        used = 0
        for name in list(self._bcast):
            msg, left = self._bcast[name]
            size = len(msgpack.packb(msg, use_bin_type=True))
            if used + size > budget:
                continue
            out.append(msg)
            used += size
            left -= 1
            if left <= 0:
                del self._bcast[name]
            else:
                self._bcast[name] = (msg, left)
        kept: List[Tuple[Dict, int]] = []
        for msg, left in self._extra_bcast:
            size = len(msgpack.packb(msg, use_bin_type=True))
            if used + size > budget:
                kept.append((msg, left))
                continue
            out.append(msg)
            used += size
            if left - 1 > 0:
                kept.append((msg, left - 1))
        self._extra_bcast = kept
        return out

    # -- protocol loops ----------------------------------------------------

    async def _probe_loop(self) -> None:
        try:
            while not self._stopped:
                await asyncio.sleep(self.config.probe_interval)
                await self._probe_once()
        except asyncio.CancelledError:
            pass

    def _next_probe_target(self) -> Optional[Node]:
        """Shuffled round-robin sweep (memberlist's nextIncarnation of
        the node ring) — every member probed once per cycle."""
        candidates = [n.name for n in self.nodes.values()
                      if n.name != self.config.node_name
                      and n.state in (STATE_ALIVE, STATE_SUSPECT)]
        if not candidates:
            return None
        if self._probe_idx >= len(self._probe_ring):
            self._probe_ring = candidates
            random.shuffle(self._probe_ring)
            self._probe_idx = 0
        while self._probe_idx < len(self._probe_ring):
            node = self.nodes.get(self._probe_ring[self._probe_idx])
            self._probe_idx += 1
            if node is not None and node.state in (STATE_ALIVE, STATE_SUSPECT):
                return node
        return self._next_probe_target()

    async def _probe_once(self) -> None:
        target = self._next_probe_target()
        if target is None:
            return
        seq = self._next_seq()
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._ack_waiters[seq] = fut
        self._send_udp((target.addr, target.port),
                       [{"t": "ping", "seq": seq,
                         "from": self.config.node_name},
                        *self._take_piggyback()])
        try:
            await asyncio.wait_for(fut, self.config.probe_timeout)
            return
        except asyncio.TimeoutError:
            pass
        finally:
            self._ack_waiters.pop(seq, None)
        # indirect probes through k random helpers (SWIM §4.1)
        helpers = [n for n in self.nodes.values()
                   if n.state == STATE_ALIVE
                   and n.name not in (self.config.node_name, target.name)]
        random.shuffle(helpers)
        seq2 = self._next_seq()
        fut2: asyncio.Future = asyncio.get_event_loop().create_future()
        self._ack_waiters[seq2] = fut2
        for h in helpers[:self.config.indirect_checks]:
            self._send_udp((h.addr, h.port),
                           [{"t": "ind", "seq": seq2, "node": target.name,
                             "addr": target.addr, "port": target.port,
                             "from": self.config.node_name}])
        try:
            await asyncio.wait_for(fut2, self.config.probe_interval)
            return
        except asyncio.TimeoutError:
            pass
        finally:
            self._ack_waiters.pop(seq2, None)
        self._suspect(target.name, target.incarnation,
                      self.config.node_name)
        self._queue_bcast({"t": "suspect", "node": target.name,
                           "inc": target.incarnation,
                           "from": self.config.node_name})

    async def _gossip_loop(self) -> None:
        try:
            while not self._stopped:
                await asyncio.sleep(self.config.gossip_interval)
                if not self._bcast and not self._extra_bcast:
                    continue
                peers = [n for n in self.nodes.values()
                         if n.name != self.config.node_name
                         and n.state in (STATE_ALIVE, STATE_SUSPECT)]
                random.shuffle(peers)
                for p in peers[:self.config.gossip_nodes]:
                    msgs = self._take_piggyback()
                    if msgs:
                        self._send_udp((p.addr, p.port), msgs)
        except asyncio.CancelledError:
            pass

    async def _pushpull_loop(self) -> None:
        try:
            while not self._stopped:
                await asyncio.sleep(self.config.push_pull_interval)
                peers = [n for n in self.alive_members()
                         if n.name != self.config.node_name]
                if not peers:
                    continue
                p = random.choice(peers)
                try:
                    await self._pushpull(p.addr, p.port)
                except (OSError, asyncio.TimeoutError, ConnectionError,
                        asyncio.IncompleteReadError):
                    continue
        except asyncio.CancelledError:
            pass

    async def _reap_loop(self) -> None:
        """Forget long-departed nodes (serf's reap): failed past
        reconnect_timeout, left past tombstone_timeout.  Reaped names
        vanish from members(), which is what lets the leader's full
        reconcile deregister them from the catalog."""
        try:
            while not self._stopped:
                await asyncio.sleep(self.config.reap_interval)
                now = time.monotonic()
                for name, n in list(self.nodes.items()):
                    if name == self.config.node_name:
                        continue
                    age = now - n.state_change
                    if (n.state == STATE_DEAD
                            and age > self.config.reconnect_timeout) or \
                       (n.state == STATE_LEFT
                            and age > self.config.tombstone_timeout):
                        del self.nodes[name]
        except asyncio.CancelledError:
            pass

    # -- TCP push/pull (memberlist pushPullNode) ---------------------------

    def _state_wire(self) -> Dict:
        return {"nodes": [n.wire() for n in self.nodes.values()]}

    async def _pushpull(self, host: str, port: int) -> None:
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, port), 5.0)
        try:
            buf = self._encrypt(msgpack.packb(self._state_wire(),
                                              use_bin_type=True))
            writer.write(len(buf).to_bytes(4, "big") + buf)
            await writer.drain()
            n = int.from_bytes(await asyncio.wait_for(
                reader.readexactly(4), 5.0), "big")
            raw = self._decrypt(await asyncio.wait_for(
                reader.readexactly(n), 5.0))
            if raw is None:
                raise ConnectionError("undecryptable push/pull reply")
            self._merge_state(msgpack.unpackb(raw, raw=False,
                                              strict_map_key=False))
        finally:
            writer.close()

    async def _serve_tcp(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter) -> None:
        try:
            n = int.from_bytes(await asyncio.wait_for(
                reader.readexactly(4), 5.0), "big")
            raw = self._decrypt(await asyncio.wait_for(
                reader.readexactly(n), 5.0))
            if raw is None:
                return
            remote = msgpack.unpackb(raw, raw=False, strict_map_key=False)
            buf = self._encrypt(msgpack.packb(self._state_wire(),
                                              use_bin_type=True))
            writer.write(len(buf).to_bytes(4, "big") + buf)
            await writer.drain()
            self._merge_state(remote)
        except (asyncio.IncompleteReadError, asyncio.TimeoutError,
                ConnectionError, OSError, msgpack.UnpackException):
            pass
        finally:
            writer.close()

    def _merge_state(self, remote: Dict) -> None:
        for w in remote.get("nodes", []):
            state = w.get("state", STATE_ALIVE)
            if state == STATE_ALIVE:
                self._alive(w)
            elif state == STATE_SUSPECT:
                self._suspect(w["name"], w["inc"], w.get("from", ""))
            else:
                self._dead(w["name"], w["inc"], w.get("from", ""),
                           left=(state == STATE_LEFT))

    # -- message handling --------------------------------------------------

    def _on_datagram(self, data: bytes, addr: Tuple[str, int]) -> None:
        raw = self._decrypt(data)
        if raw is None:
            return
        try:
            msgs = msgpack.unpackb(raw, raw=False, strict_map_key=False)
        except Exception:
            return
        if not isinstance(msgs, list):
            msgs = [msgs]
        for m in msgs:
            try:
                self._handle_msg(m, addr)
            except (KeyError, TypeError, ValueError):
                continue

    def _handle_msg(self, m: Dict, addr: Tuple[str, int]) -> None:
        t = m.get("t")
        if t == "ping":
            self._send_udp(addr, [{"t": "ack", "seq": m["seq"],
                                   "from": self.config.node_name},
                                  *self._take_piggyback()])
        elif t == "ack":
            fut = self._ack_waiters.get(m["seq"])
            if fut is not None and not fut.done():
                fut.set_result(True)
            # relay leg of an indirect probe we serviced
            relay = m.get("relay")
            if relay:
                self._send_udp((relay["addr"], relay["port"]),
                               [{"t": "ack", "seq": relay["seq"],
                                 "from": self.config.node_name}])
        elif t == "ind":
            # probe the target on the requester's behalf; ask the target
            # to have the eventual ack relayed back to the requester
            requester = self.nodes.get(m["from"])
            seq = self._next_seq()
            fut: asyncio.Future = asyncio.get_event_loop().create_future()
            self._ack_waiters[seq] = fut

            def _relay(_f, m=m, requester=requester, addr=addr):
                dest = ((requester.addr, requester.port)
                        if requester is not None else addr)
                self._send_udp(dest, [{"t": "ack", "seq": m["seq"],
                                       "from": self.config.node_name}])

            fut.add_done_callback(
                lambda f: (_relay(f) if not f.cancelled()
                           and f.exception() is None else None))
            asyncio.get_event_loop().call_later(
                self.config.probe_timeout * 2, self._ack_waiters.pop,
                seq, None)
            self._send_udp((m["addr"], m["port"]),
                           [{"t": "ping", "seq": seq,
                             "from": self.config.node_name}])
        elif t == "alive":
            self._alive(m)
        elif t == "suspect":
            self._suspect(m["node"], m["inc"], m["from"])
        elif t == "dead":
            self._dead(m["node"], m["inc"], m["from"],
                       left=(m["node"] == m["from"]))
        else:
            self.on_user_msg(m)

    # -- SWIM state transitions (memberlist aliveNode/suspectNode/deadNode) -

    def _version_ok(self, node: Node) -> bool:
        """Protocol compatibility gate (memberlist's alive-message
        version check; tags per consul/server.go:292-304).

        Admit a peer iff its operating version lies in OUR supported
        range and our operating version lies in ITS advertised range —
        the symmetric condition that lets mixed-version clusters roll
        through an upgrade.  A peer with no version tags (pre-versioning
        build) defaults to operating version 2 with a point range."""
        t = node.tags
        try:
            vsn = int(t.get("vsn", "2"))
            vmin = int(t.get("vsn_min", str(vsn)))
            vmax = int(t.get("vsn_max", str(vsn)))
        except ValueError:
            return False
        c = self.config
        return (c.protocol_min <= vsn <= c.protocol_max
                and vmin <= c.protocol_version <= vmax)

    def _alive(self, w: Dict) -> None:
        name, inc = w["name"], w["inc"]
        node = self.nodes.get(name)
        if name == self.config.node_name:
            # Someone is spreading stale/competing info about us; refute
            # by outliving its incarnation (memberlist refute()).
            if node is not None and inc >= self.incarnation and \
                    w.get("addr") != node.addr:
                self._refute(inc)
            return
        if node is None:
            node = Node(name, w["addr"], w["port"], incarnation=inc,
                        tags=w.get("tags") or {})
            if not self._version_ok(node):
                return  # incompatible protocol version (rolling upgrade)
            if self.member_filter is not None and not self.member_filter(node):
                return  # merge delegate refused (consul/merge.go)
            self.nodes[name] = node
            self._queue_bcast({"t": "alive", **node.wire()})
            self.on_event(EV_JOIN, node)
            return
        if inc <= node.incarnation and node.state == STATE_ALIVE:
            return
        if inc < node.incarnation:
            return
        # Re-run the merge delegate on identity updates too — an admitted
        # member must not be able to mutate into a filtered-out identity
        # (e.g. a WAN member dropping its server role) and stay.
        probe = Node(name, w["addr"], w["port"], incarnation=inc,
                     tags=w.get("tags") or {})
        if not self._version_ok(probe):
            return
        if self.member_filter is not None and not self.member_filter(probe):
            return
        was = node.state
        tags_changed = (w.get("tags") or {}) != node.tags
        node.incarnation = inc
        node.addr, node.port = w["addr"], w["port"]
        node.tags = w.get("tags") or {}
        node.state = STATE_ALIVE
        node.state_change = time.monotonic()
        self._cancel_suspicion(name)
        self._queue_bcast({"t": "alive", **node.wire()})
        if was in (STATE_DEAD, STATE_LEFT):
            self.on_event(EV_JOIN, node)
        elif tags_changed:
            self.on_event(EV_UPDATE, node)

    def _suspect(self, name: str, inc: int, from_: str) -> None:
        node = self.nodes.get(name)
        if node is None or inc < node.incarnation:
            return
        if name == self.config.node_name:
            self._refute(inc)
            return
        if node.state != STATE_ALIVE:
            return
        node.state = STATE_SUSPECT
        node.state_change = time.monotonic()
        self._queue_bcast({"t": "suspect", "node": name, "inc": inc,
                           "from": from_})
        n = max(self.num_alive(), 1)
        timeout = (self.config.suspicion_mult * max(math.log10(n + 1), 1.0)
                   * self.config.probe_interval)
        loop = asyncio.get_event_loop()
        self._cancel_suspicion(name)
        self._suspicion_timers[name] = loop.call_later(
            timeout, self._suspicion_expired, name, inc)

    def _suspicion_expired(self, name: str, inc: int) -> None:
        self._suspicion_timers.pop(name, None)
        node = self.nodes.get(name)
        if node is None or node.state != STATE_SUSPECT:
            return
        self._dead(name, inc, self.config.node_name)
        self._queue_bcast({"t": "dead", "node": name, "inc": inc,
                           "from": self.config.node_name})

    def _dead(self, name: str, inc: int, from_: str, left: bool = False) -> None:
        node = self.nodes.get(name)
        if node is None or inc < node.incarnation:
            return
        if name == self.config.node_name:
            if not left:
                self._refute(inc)
            return
        if node.state in (STATE_DEAD, STATE_LEFT):
            if left and node.state == STATE_DEAD:
                node.state = STATE_LEFT  # force-leave upgrade
            return
        self._cancel_suspicion(name)
        node.incarnation = inc
        node.state = STATE_LEFT if left else STATE_DEAD
        node.state_change = time.monotonic()
        self._queue_bcast({"t": "dead", "node": name, "inc": inc,
                           "from": from_})
        self.on_event(EV_LEAVE if left else EV_FAILED, node)

    def _refute(self, seen_inc: int) -> None:
        self.incarnation = max(self.incarnation, seen_inc) + 1
        me = self.nodes[self.config.node_name]
        me.incarnation = self.incarnation
        self._queue_bcast({"t": "alive", **me.wire()})

    def _cancel_suspicion(self, name: str) -> None:
        h = self._suspicion_timers.pop(name, None)
        if h is not None:
            h.cancel()


class _UDPProtocol(asyncio.DatagramProtocol):
    def __init__(self, ml: Memberlist) -> None:
        self.ml = ml

    def datagram_received(self, data: bytes, addr: Tuple[str, int]) -> None:
        self.ml._on_datagram(data, addr)

    def error_received(self, exc: Exception) -> None:  # ICMP unreachable etc.
        pass
