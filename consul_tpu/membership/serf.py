"""The Serf role: tags, user events, snapshots, join/leave choreography.

Parity target: the reference's external ``hashicorp/serf`` dep as
consumed by Consul (``consul/serf.go``, ``consul/server.go:284-325``
for the tag scheme, ``consul/server.go:34-35`` for snapshots/rejoin).

Adds on top of :class:`~consul_tpu.membership.swim.Memberlist`:

- **Role tags** — Consul encodes {role, dc, port, vsn, bootstrap,
  expect} into serf tags; helpers here parse them back into the
  ``serverParts`` shape (``consul/util.go`` IsConsulServer).
- **User events** — Lamport-clocked named broadcasts with a dedup
  window, flooded on the gossip piggyback queue (serf UserEvent).
- **Membership snapshots** — alive peers + clocks appended to
  ``<dir>/local.snapshot``; ``previous_peers()`` feeds rejoin-after-
  restart (RejoinAfterLeave, consul/config.go:131-135).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from consul_tpu.membership.swim import (
    EV_FAILED, EV_JOIN, EV_LEAVE, MemberConfig, Memberlist, Node)

EV_USER = "user"

_SNAPSHOT_MAX_LINES = 4096


@dataclass
class SerfConfig:
    node_name: str = "node1"
    bind_addr: str = "127.0.0.1"
    bind_port: int = 0
    advertise_addr: str = ""
    tags: Dict[str, str] = field(default_factory=dict)
    snapshot_path: str = ""          # "" = no snapshots (dev mode)
    event_buffer: int = 256
    # timing profile handed straight to the memberlist config
    probe_interval: float = 1.0
    probe_timeout: float = 0.5
    gossip_interval: float = 0.2
    suspicion_mult: float = 4.0
    push_pull_interval: float = 30.0
    reap_interval: float = 10.0
    reconnect_timeout: float = 72 * 3600.0
    tombstone_timeout: float = 24 * 3600.0
    # protocol negotiation passthrough (consul -protocol flag)
    protocol_version: int = 2
    protocol_min: int = 1
    protocol_max: int = 2


class SerfPool:
    """One gossip pool (LAN or WAN) with serf semantics.  Events are
    delivered as ``(kind, payload)`` to the handler: membership kinds
    carry a :class:`Node`, ``"user"`` carries the event dict."""

    def __init__(self, config: SerfConfig, keyring: Optional[Any] = None,
                 on_event: Optional[Callable[[str, Any], None]] = None,
                 member_filter: Optional[Callable[[Node], bool]] = None) -> None:
        self.config = config
        self.on_event = on_event or (lambda kind, payload: None)
        self.event_ltime = 0          # lamport clock for user events
        self._seen_events: Dict[Tuple[int, str], bool] = {}
        self.ml = Memberlist(
            MemberConfig(
                node_name=config.node_name, bind_addr=config.bind_addr,
                bind_port=config.bind_port,
                advertise_addr=config.advertise_addr,
                tags=dict(config.tags),
                probe_interval=config.probe_interval,
                probe_timeout=config.probe_timeout,
                gossip_interval=config.gossip_interval,
                suspicion_mult=config.suspicion_mult,
                push_pull_interval=config.push_pull_interval,
                reap_interval=config.reap_interval,
                reconnect_timeout=config.reconnect_timeout,
                tombstone_timeout=config.tombstone_timeout,
                protocol_version=config.protocol_version,
                protocol_min=config.protocol_min,
                protocol_max=config.protocol_max),
            keyring=keyring,
            on_event=self._member_event,
            on_user_msg=self._user_msg,
            member_filter=member_filter)
        self._snapshot_lines = 0

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        await self.ml.start()

    async def stop(self) -> None:
        await self.ml.stop()

    async def join(self, addrs: List[str]) -> int:
        n = await self.ml.join(addrs)
        self._snapshot()
        return n

    async def leave(self) -> None:
        await self.ml.leave()

    def force_leave(self, name: str) -> bool:
        return self.ml.force_leave(name)

    @property
    def local_addr(self) -> Tuple[str, int]:
        return self.ml.local_addr

    def members(self) -> List[Node]:
        return self.ml.members()

    def alive_members(self) -> List[Node]:
        return self.ml.alive_members()

    def set_tags(self, tags: Dict[str, str]) -> None:
        self.ml.set_tags(tags)

    # -- user events (serf UserEvent) --------------------------------------

    def user_event(self, name: str, payload: bytes,
                   coalesce: bool = True) -> None:
        self.event_ltime += 1
        msg = {"t": "uev", "ltime": self.event_ltime, "name": name,
               "payload": payload, "cc": coalesce}
        self._seen_events[(msg["ltime"], name)] = True
        self.ml.queue_user_msg(msg)
        self.on_event(EV_USER, msg)

    def _user_msg(self, msg: Dict) -> None:
        if msg.get("t") != "uev":
            return
        ltime = int(msg.get("ltime", 0))
        key = (ltime, msg.get("name", ""))
        if key in self._seen_events:
            return
        self._seen_events[key] = True
        if len(self._seen_events) > self.config.event_buffer:
            for k in sorted(self._seen_events)[:len(self._seen_events)
                                               - self.config.event_buffer]:
                del self._seen_events[k]
        self.event_ltime = max(self.event_ltime, ltime)
        self.ml.queue_user_msg(msg)  # keep flooding
        self.on_event(EV_USER, msg)

    # -- membership events + snapshotting ----------------------------------

    def _member_event(self, kind: str, node: Node) -> None:
        if kind in (EV_JOIN, EV_LEAVE, EV_FAILED):
            self._snapshot()
        self.on_event(kind, node)

    def _snapshot(self) -> None:
        """Append current alive peers (serf's snapshotter keeps an
        append-only log; we append full lines and rewrite on overflow)."""
        path = self.config.snapshot_path
        if not path:
            return
        peers = [f"{n.addr}:{n.port}" for n in self.alive_members()
                 if n.name != self.config.node_name]
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            line = "peers: " + ",".join(peers) + "\n"
            mode = "a" if self._snapshot_lines < _SNAPSHOT_MAX_LINES else "w"
            with open(path, mode) as f:
                f.write(line)
            self._snapshot_lines = (self._snapshot_lines + 1
                                    if mode == "a" else 1)
        except OSError:
            pass

    @staticmethod
    def previous_peers(path: str) -> List[str]:
        """Peers recorded by the last run (rejoin source)."""
        try:
            with open(path) as f:
                lines = [ln for ln in f if ln.startswith("peers: ")]
        except OSError:
            return []
        if not lines:
            return []
        last = lines[-1][len("peers: "):].strip()
        return [p for p in last.split(",") if p]


# -- Consul's serf tag scheme (consul/server.go:292-304, consul/util.go) ----


def _vsn_tags(protocol: Optional[int]) -> Dict[str, str]:
    """vsn/vsn_min/vsn_max per consul/server.go:294-296 /
    consul/client.go:130-132."""
    from consul_tpu.version import (PROTOCOL_VERSION, PROTOCOL_VERSION_MAX,
                                    PROTOCOL_VERSION_MIN)
    v = PROTOCOL_VERSION if protocol is None else protocol
    return {"vsn": str(v), "vsn_min": str(PROTOCOL_VERSION_MIN),
            "vsn_max": str(PROTOCOL_VERSION_MAX)}


def server_tags(dc: str, rpc_port: int, bootstrap: bool = False,
                expect: int = 0,
                protocol: Optional[int] = None) -> Dict[str, str]:
    t = {"role": "consul", "dc": dc, "port": str(rpc_port),
         **_vsn_tags(protocol)}
    if bootstrap:
        t["bootstrap"] = "1"
    if expect:
        t["expect"] = str(expect)
    return t


def client_tags(dc: str, protocol: Optional[int] = None) -> Dict[str, str]:
    return {"role": "node", "dc": dc, **_vsn_tags(protocol)}


def parse_server(node: Node) -> Optional[Dict[str, Any]]:
    """serverParts equivalent (IsConsulServer, consul/util.go): None if
    the member is not a server in some DC."""
    t = node.tags
    if t.get("role") != "consul":
        return None
    try:
        port = int(t.get("port", "0"))
    except ValueError:
        return None
    return {"name": node.name, "dc": t.get("dc", ""), "addr": node.addr,
            "port": port, "rpc_addr": f"{node.addr}:{port}",
            "bootstrap": t.get("bootstrap") == "1",
            "expect": int(t.get("expect", "0") or 0),
            "version": int(t.get("vsn", "2") or 2)}
