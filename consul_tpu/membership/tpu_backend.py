"""gossip_backend=tpu: the serf-boundary pool backed by the TPU plane.

Drop-in counterpart of :class:`consul_tpu.membership.serf.SerfPool`
(same constructor shape, same event channel, same method surface), but
the membership substrate underneath is the SWIM kernel session hosted
by the gossip plane daemon (:mod:`consul_tpu.gossip.plane`) instead of
a per-agent asyncio memberlist.  The reference boundary this preserves
is ``consul/server.go:284-325`` (setupSerf config surface) +
``consul/serf.go:90-110`` (events upward into reconcile): the agent
code above cannot tell which backend it is on — ``consul members``,
server routing tables, serfHealth reconciliation, and user events all
flow the same way.

Transport: the native C++ bridge (``native/gbridge.cpp`` via
:mod:`consul_tpu.native.bridge`) — reader + heartbeat threads outside
the GIL.  A pure-asyncio fallback transport keeps the backend usable
where a C++ toolchain is unavailable.

What "join" means here: the plane is the pool's rendezvous — joining
an address means registering with that plane.  Stopping heartbeats
(process death) is the failure signal; the kernel's suspicion/
Lifeguard/refutation dynamics decide when the cluster believes it.
"""

from __future__ import annotations

import asyncio
import struct
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import msgpack

from consul_tpu.membership.serf import SerfConfig
from consul_tpu.membership.swim import (
    EV_FAILED, EV_JOIN, EV_LEAVE, Node, STATE_ALIVE, STATE_DEAD, STATE_LEFT)
from consul_tpu.obs import journey as _journey

EV_USER = "user"


class _AsyncioTransport:
    """Fallback bridge transport: same wire protocol, Python threads
    replaced by asyncio tasks (no native heartbeat guarantee)."""

    def __init__(self, host: str, port: int, unix_path: str = "") -> None:
        self._host, self._port, self._unix = host, port, unix_path
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._hb_task: Optional[asyncio.Task] = None
        self._inbox: asyncio.Queue = asyncio.Queue()
        self._pump_task: Optional[asyncio.Task] = None

    async def connect(self) -> None:
        if self._unix:
            self._reader, self._writer = await asyncio.open_unix_connection(
                self._unix)
        else:
            self._reader, self._writer = await asyncio.open_connection(
                self._host, self._port)
        self._pump_task = asyncio.get_event_loop().create_task(self._pump())

    async def _pump(self) -> None:
        try:
            while True:
                hdr = await self._reader.readexactly(4)
                (ln,) = struct.unpack(">I", hdr)
                raw = await self._reader.readexactly(ln)
                self._inbox.put_nowait(msgpack.unpackb(raw, raw=False))
        except (asyncio.IncompleteReadError, ConnectionError):
            self._inbox.put_nowait(None)  # closed sentinel
        except asyncio.CancelledError:
            self._inbox.put_nowait(None)  # cancelled at close: same sentinel
            raise

    def send(self, payload: Dict[str, Any]) -> None:
        raw = msgpack.packb(payload, use_bin_type=True)
        self._writer.write(struct.pack(">I", len(raw)) + raw)

    def set_heartbeat(self, payload: Dict[str, Any], period_s: float) -> None:
        async def beat():
            while True:
                try:
                    self.send(payload)
                except Exception:
                    return
                await asyncio.sleep(period_s)
        self.stop_heartbeat()
        self._hb_task = asyncio.get_event_loop().create_task(beat())

    def stop_heartbeat(self) -> None:
        if self._hb_task is not None:
            self._hb_task.cancel()
            self._hb_task = None

    def poll_nowait(self) -> Optional[Dict[str, Any]]:
        try:
            m = self._inbox.get_nowait()
        except asyncio.QueueEmpty:
            return None
        if m is None:
            raise ConnectionError("gossip plane connection closed")
        return m

    def close(self) -> None:
        self.stop_heartbeat()
        if self._pump_task is not None:
            self._pump_task.cancel()
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:  # noqa: E02 — best-effort close at teardown
                pass


class TpuSerfPool:
    """SerfPool-shaped backend over the TPU gossip plane."""

    def __init__(self, config: SerfConfig, keyring: Optional[Any] = None,
                 on_event: Optional[Callable[[str, Any], None]] = None,
                 member_filter: Optional[Callable[[Node], bool]] = None,
                 plane_addr: str = "", use_native: bool = True) -> None:
        # keyring: the gossip key doubles as the plane admission secret
        # (registration_proof) — an armed keyring means the plane
        # refuses unauthenticated registrations, so gossip_backend=tpu
        # keeps the encrypted-fabric security posture instead of
        # silently downgrading to an open port.
        self.keyring = keyring
        self.config = config
        self.on_event = on_event or (lambda kind, payload: None)
        self.member_filter = member_filter
        self.plane_addr = plane_addr
        self.use_native = use_native
        self.event_ltime = 0
        self._nodes: Dict[str, Node] = {}
        self._bridge = None          # BridgeClient | _AsyncioTransport
        self._native = False
        self._poll_task: Optional[asyncio.Task] = None
        self._registered = asyncio.Event()
        self._register_error = ""
        self._closed = False
        self._hb_interval = 0.5

    # -- lifecycle ---------------------------------------------------------

    async def start(self, retry_interval: float = 1.0) -> None:
        if not self.plane_addr:
            return
        try:
            await self._connect(self.plane_addr)
        except (ConnectionError, OSError, asyncio.TimeoutError):
            # Plane not up yet: keep dialing in the background (the
            # retry-join role for the rendezvous model).
            self._schedule_redial(retry_interval)

    async def stop(self) -> None:
        self._closed = True
        t = getattr(self, "_redial_task", None)
        if t is not None:
            t.cancel()
        if self._poll_task is not None:
            self._poll_task.cancel()
            try:
                await self._poll_task
            except asyncio.CancelledError:
                pass  # we just cancelled it
            except Exception:  # noqa: E02 — poll's own failure; shutting down
                pass
        if self._bridge is not None:
            self._bridge.close()
            self._bridge = None

    @staticmethod
    def _parse_addr(addr: str) -> Tuple[str, int, str]:
        if addr.startswith("unix://"):
            return "", 0, addr[len("unix://"):]
        host, _, port = addr.rpartition(":")
        return host or "127.0.0.1", int(port), ""

    async def _connect(self, addr: str) -> None:
        """Dial + register.  ``self._bridge`` is only left set on a
        CONFIRMED registration — a refused register (plane full, name
        conflict) or handshake timeout tears the transport down so the
        redial loop / a later join() can try again."""
        host, port, unix = self._parse_addr(addr)
        bridge = None
        native = False
        if self.use_native:
            try:
                # Off-loop: first use may g++-compile the library, and
                # the connect(2) is a blocking syscall — neither may
                # stall the agent's event loop.
                from consul_tpu.native.bridge import BridgeClient
                bridge = await asyncio.get_event_loop().run_in_executor(
                    None, BridgeClient, host, port, unix)
                native = True
            except (RuntimeError, ConnectionError, OSError):
                bridge = None
        if bridge is None:
            bridge = _AsyncioTransport(host, port, unix)
            await bridge.connect()
        self._registered.clear()
        self._register_error = ""
        self._bridge, self._native = bridge, native
        try:
            reg = {
                "t": "register", "name": self.config.node_name,
                "addr": self.config.advertise_addr or self.config.bind_addr,
                "port": self.config.bind_port,
                "tags": dict(self.config.tags)}
            if self.keyring is not None and \
                    getattr(self.keyring, "keys", None):
                import os as _os

                from consul_tpu.gossip.plane import registration_proof
                ts, nonce = int(time.time()), _os.urandom(8)
                reg.update({
                    "auth_ts": ts, "auth_nonce": nonce,
                    "auth": registration_proof(
                        self.keyring.primary, reg["name"], reg["addr"],
                        reg["port"], ts, nonce, reg["tags"])})
            bridge.send(reg)
            self._poll_task = asyncio.get_event_loop().create_task(
                self._poller())
            await asyncio.wait_for(self._registered.wait(), timeout=10.0)
            if self._register_error:
                raise ConnectionError(self._register_error)
        except (asyncio.TimeoutError, ConnectionError) as e:
            if self._poll_task is not None:
                self._poll_task.cancel()
                self._poll_task = None
            bridge.close()
            self._bridge = None
            reason = self._register_error or str(e) or "handshake timeout"
            raise ConnectionError(
                f"gossip plane registration failed: {reason}") from None

    async def _poller(self) -> None:
        """Drain plane frames into the event channel.  Native transport
        is polled (frames queue in C++); asyncio transport pushes."""
        try:
            while True:
                m = (self._bridge.poll() if self._native
                     else self._bridge.poll_nowait())
                if m is None:
                    await asyncio.sleep(0.01)
                    continue
                self._handle(m)
        except asyncio.CancelledError:
            raise
        except ConnectionError:
            # Plane gone (restart, or it killed a desynced session).
            # If we had an established session, tear down and redial —
            # the welcome snapshot is the resync.
            if self._closed or not self._registered.is_set() \
                    or self._register_error:
                return
            if self._bridge is not None:
                self._bridge.close()
                self._bridge = None
            self._poll_task = None
            self._schedule_redial()

    def _schedule_redial(self, interval: float = 1.0) -> None:
        async def redial():
            last_reason = ""
            while not self._closed and self._bridge is None:
                await asyncio.sleep(interval)
                try:
                    await self._connect(self.plane_addr)
                except (ConnectionError, OSError,
                        asyncio.TimeoutError) as e:
                    # Surface each DISTINCT refusal once: an agent
                    # stuck on "authentication failed" (keyring
                    # mismatch) must not look like a plane that is
                    # merely not up yet.
                    reason = str(e)
                    if reason and reason != last_reason:
                        last_reason = reason
                        import sys
                        print(f"[gossip-tpu] plane join failing "
                              f"({self.plane_addr}): {reason}; retrying",
                              file=sys.stderr)
                    continue
        self._redial_task = asyncio.get_event_loop().create_task(redial())

    def _handle(self, m: Dict[str, Any]) -> None:
        t = m.get("t")
        if t == "err":
            # Registration refused (plane full / live name conflict):
            # wake _connect immediately (don't burn its handshake
            # timeout) and tear the session down.
            self._register_error = m.get("error", "refused")
            self._registered.set()
            raise ConnectionError(self._register_error)
        if t == "welcome":
            self._hb_interval = float(m.get("hb_interval_s", 0.5))
            self._bridge.set_heartbeat(
                {"t": "hb", "name": self.config.node_name},
                self._hb_interval)
            for w in m.get("members", []):
                node = self._node_from_wire(w)
                # The merge delegate gates the snapshot too — admission
                # must not depend on connect ordering.
                if self.member_filter is not None and \
                        not self.member_filter(node):
                    continue
                known = node.name in self._nodes
                self._nodes[node.name] = node
                if not known and node.state == STATE_ALIVE:
                    self.on_event(EV_JOIN, node)
            self._registered.set()
        elif t == "ev":
            self._handle_member_event(m.get("kind"), m.get("node") or {})
        elif t == "evbatch":
            # One drain cadence's structured batch (PR 18): apply the
            # per-event logic in order.  on_event is synchronous, so
            # every transition lands in the server's reconcile queue
            # before the leader's batched reconcile task next wakes —
            # the burst coalesces into one raft envelope downstream.
            for ev in m.get("events") or []:
                self._handle_member_event(ev.get("kind"),
                                          ev.get("node") or {},
                                          ev.get("jt"))
        elif t == "stats":
            fut = getattr(self, "_stats_future", None)
            if fut is not None and not fut.done():
                fut.set_result(m)
        elif t == "flight":
            fut = getattr(self, "_flight_future", None)
            if fut is not None and not fut.done():
                fut.set_result(m)
        elif t == "slo":
            fut = getattr(self, "_slo_future", None)
            if fut is not None and not fut.done():
                fut.set_result(m)
        elif t == "profile":
            fut = getattr(self, "_profile_future", None)
            if fut is not None and not fut.done():
                fut.set_result(m)
        elif t == "device":
            fut = getattr(self, "_device_future", None)
            if fut is not None and not fut.done():
                fut.set_result(m)
        elif t == "autotune":
            fut = getattr(self, "_autotune_future", None)
            if fut is not None and not fut.done():
                fut.set_result(m)
        elif t == "user":
            ltime = int(m.get("ltime", 0))
            self.event_ltime = max(self.event_ltime, ltime)
            self.on_event(EV_USER, {
                "t": "uev", "ltime": ltime, "name": m.get("name", ""),
                "payload": m.get("payload", b""),
                "cc": m.get("coalesce", True)})

    def _handle_member_event(self, kind: str, wire: Dict[str, Any],
                             jt: Optional[List[float]] = None) -> None:
        """Shared by the single-event and batched frames: merge-gate,
        membership table update, agent notification.  ``jt`` is the
        journey stamp carriage from the evbatch frame ([t_detect,
        t_flush, detect_ms], obs/journey.py) — folded and re-attached
        to the Node so the reconcile path can keep the chain going."""
        node = self._node_from_wire(wire)
        if self.member_filter is not None and \
                not self.member_filter(node):
            return  # merge delegate (consul/merge.go) still applies
        jy = _journey.journey
        if jy is not None and jt:
            now = time.monotonic()
            t_flush = jt[1] if len(jt) > 1 else 0.0
            stages: Dict[str, float] = {}
            if len(jt) > 2 and jt[2] >= 0.0:
                stages["detect"] = jt[2]
            if t_flush:
                drain_ms = round((t_flush - jt[0]) * 1000.0, 3)
                decode_ms = round((now - t_flush) * 1000.0, 3)
                jy.stage_observe("decode", decode_ms)
                if drain_ms >= 0.0:
                    stages["drain"] = drain_ms
                if decode_ms >= 0.0:
                    stages["decode"] = decode_ms
            # Monotonic stamps only compare in-process: a cross-process
            # plane yields a bogus t0, so anchor the journey at decode
            # time unless the detect stamp is plausibly ours.
            t0 = jt[0] if 0.0 <= (now - jt[0]) else now
            node._journey = {"t0": t0, "prev": now, "stages": stages}
        if kind == EV_LEAVE:
            node.state = STATE_LEFT
            self._nodes.pop(node.name, None)
        elif kind == EV_FAILED:
            node.state = STATE_DEAD
            if node.name in self._nodes:
                self._nodes[node.name].state = STATE_DEAD
        else:
            self._nodes[node.name] = node
        self.on_event(kind, node)

    @staticmethod
    def _node_from_wire(w: Dict[str, Any]) -> Node:
        state = w.get("state", "alive")
        return Node(name=w.get("name", ""), addr=w.get("addr", ""),
                    port=int(w.get("port", 0) or 0),
                    state=(STATE_ALIVE if state == "alive" else
                           STATE_DEAD if state == "dead" else STATE_LEFT),
                    tags=dict(w.get("tags") or {}))

    # -- SerfPool surface --------------------------------------------------

    async def join(self, addrs: List[str]) -> int:
        """Register with the plane (the pool's rendezvous)."""
        if self._bridge is None:
            for a in addrs:
                try:
                    await self._connect(a)
                    break
                except (ConnectionError, OSError, asyncio.TimeoutError):
                    continue
            else:
                return 0
        return max(1, len(self.alive_members()) - 1)

    async def leave(self) -> None:
        if self._bridge is not None:
            try:
                self._bridge.stop_heartbeat()
                self._bridge.send({"t": "leave",
                                   "name": self.config.node_name})
                await asyncio.sleep(0.05)  # let the frame flush
            except Exception:  # noqa: E02 — best-effort leave notice
                pass

    def force_leave(self, name: str) -> bool:
        if self._bridge is None:
            return False
        try:
            self._bridge.send({"t": "force-leave", "node": name})
            return True
        except Exception:
            return False

    @property
    def local_addr(self) -> Tuple[str, int]:
        # The pool's rendezvous is the plane, not a local socket.
        host, port, unix = self._parse_addr(self.plane_addr) \
            if self.plane_addr else ("", 0, "")
        return (host or unix, port)

    def members(self) -> List[Node]:
        return list(self._nodes.values())

    def alive_members(self) -> List[Node]:
        return [n for n in self._nodes.values() if n.state == STATE_ALIVE]

    def set_tags(self, tags: Dict[str, str]) -> None:
        self.config.tags = dict(tags)
        if self._bridge is not None:
            try:
                self._bridge.send({"t": "tags", "tags": dict(tags)})
            except Exception:  # noqa: E02 — plane gone; redial re-pushes tags
                pass

    async def plane_stats(self, timeout: float = 5.0) -> Dict[str, Any]:
        """Kernel-session counters from the plane (serf Stats() role):
        round count, member states, pending joins, live event slots,
        detection/refute/drop totals.  Concurrent callers share one
        in-flight request — stats are idempotent, and overwriting a
        pending future would orphan the earlier caller into its full
        timeout."""
        if self._bridge is None:
            return {}
        fut = getattr(self, "_stats_future", None)
        if fut is None or fut.done():
            fut = self._stats_future = \
                asyncio.get_event_loop().create_future()
            self._bridge.send({"t": "stats"})
        try:
            return await asyncio.wait_for(asyncio.shield(fut), timeout)
        except asyncio.TimeoutError:
            return {}

    async def plane_flight(self, timeout: float = 5.0) -> Dict[str, Any]:
        """Kernel flight-recorder timeline from the plane (the agent
        side of /v1/agent/flight).  Same shared-future discipline as
        plane_stats: the query is idempotent and concurrent callers
        ride one in-flight request."""
        if self._bridge is None:
            return {}
        fut = getattr(self, "_flight_future", None)
        if fut is None or fut.done():
            fut = self._flight_future = \
                asyncio.get_event_loop().create_future()
            self._bridge.send({"t": "flight"})
        try:
            return await asyncio.wait_for(asyncio.shield(fut), timeout)
        except asyncio.TimeoutError:
            return {}

    async def plane_slo(self, timeout: float = 5.0) -> Dict[str, Any]:
        """Detection-latency SLO observatory from the plane (the agent
        side of /v1/agent/slo): burn-rate snapshot, exact latency
        percentiles, cumulative histogram families.  Same shared-future
        discipline as plane_stats."""
        if self._bridge is None:
            return {}
        fut = getattr(self, "_slo_future", None)
        if fut is None or fut.done():
            fut = self._slo_future = \
                asyncio.get_event_loop().create_future()
            self._bridge.send({"t": "slo"})
        try:
            return await asyncio.wait_for(asyncio.shield(fut), timeout)
        except asyncio.TimeoutError:
            return {}

    async def plane_device(self, timeout: float = 5.0) -> Dict[str, Any]:
        """Device/kernel observatory from the plane (the agent side of
        /v1/agent/device): dispatch-latency hists, rounds/s EWMA, HBM
        occupancy + live-buffer census, compile + roofline telemetry.
        Same shared-future discipline as plane_stats."""
        if self._bridge is None:
            return {}
        fut = getattr(self, "_device_future", None)
        if fut is None or fut.done():
            fut = self._device_future = \
                asyncio.get_event_loop().create_future()
            self._bridge.send({"t": "device"})
        try:
            return await asyncio.wait_for(asyncio.shield(fut), timeout)
        except asyncio.TimeoutError:
            return {}

    async def plane_autotune(self, timeout: float = 5.0) -> Dict[str, Any]:
        """Autotune observatory from the plane (the agent side of
        /v1/operator/autotune): the knob resolution the kernel session
        booted with — per-knob value, source, evidence keys, reason.
        Same shared-future discipline as plane_stats."""
        if self._bridge is None:
            return {}
        fut = getattr(self, "_autotune_future", None)
        if fut is None or fut.done():
            fut = self._autotune_future = \
                asyncio.get_event_loop().create_future()
            self._bridge.send({"t": "autotune"})
        try:
            return await asyncio.wait_for(asyncio.shield(fut), timeout)
        except asyncio.TimeoutError:
            return {}

    async def plane_profile(self, steps: int = 32, phases: bool = False,
                            timeout: float = 60.0) -> Dict[str, Any]:
        """On-demand device profiling of ``steps`` kernel rounds on the
        plane (the agent side of /v1/agent/profile).  The capture blocks
        the plane-side connection loop, so the timeout is generous;
        concurrent callers share one in-flight capture."""
        if self._bridge is None:
            return {}
        fut = getattr(self, "_profile_future", None)
        if fut is None or fut.done():
            fut = self._profile_future = \
                asyncio.get_event_loop().create_future()
            self._bridge.send({"t": "profile", "steps": int(steps),
                               "phases": bool(phases)})
        try:
            return await asyncio.wait_for(asyncio.shield(fut), timeout)
        except asyncio.TimeoutError:
            return {}

    def user_event(self, name: str, payload: bytes,
                   coalesce: bool = True) -> None:
        if self._bridge is None:
            return
        try:
            self._bridge.send({"t": "event", "name": name,
                               "payload": payload, "coalesce": coalesce})
        except Exception:  # noqa: E02 — plane gone; events are best-effort
            pass

    # interface parity with SerfPool
    @staticmethod
    def previous_peers(path: str) -> List[str]:
        from consul_tpu.membership.serf import SerfPool
        return SerfPool.previous_peers(path)
