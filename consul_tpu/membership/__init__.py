"""Real-network cluster membership: the memberlist/Serf role.

The TPU gossip kernel (consul_tpu.gossip) is this framework's flagship
substrate — a batched SWIM simulator/engine for huge N.  This package
is the *wire* plane for small real clusters (BASELINE config #1): an
asyncio SWIM implementation over UDP/TCP on real sockets, carrying the
same protocol semantics the reference consumes from hashicorp
memberlist + serf (behavior contract:
``website/source/docs/internals/gossip.html.markdown:10-43``, consumed
at ``consul/server.go:257-273``).

Layering mirrors the reference split:

- :mod:`swim` — failure detection + dissemination (memberlist role):
  UDP probe/ack/indirect-probe, suspicion + refutation, piggybacked
  broadcasts, TCP push/pull anti-entropy, AES-GCM gossip encryption.
- :mod:`serf` — the Serf role on top: node tags, user events with
  Lamport clocks, membership snapshots for rejoin, join/leave
  choreography.
"""

from consul_tpu.membership.swim import (  # noqa: F401
    Memberlist, MemberConfig, Node, STATE_ALIVE, STATE_DEAD, STATE_LEFT,
    STATE_SUSPECT)
from consul_tpu.membership.serf import SerfPool, SerfConfig  # noqa: F401
