"""Remote-exec orchestration: the client side of ``consul exec``.

Parity target: ``command/exec.go`` (128-601): create a short-TTL
session (+renew), upload the job spec to KV ``_rexec/<session>/job``,
fire the ``_rexec`` user event, then poll the KV prefix streaming
acks / output chunks / exit codes until the quiet-wait elapses.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from consul_tpu.api.client import Client, KVPair, QueryOptions

REXEC_PREFIX = "_rexec"
SESSION_TTL = "15s"
QUIET_WAIT = 2.0          # rExecQuietWait: no new data -> done
DEFAULT_WAIT = 60.0


@dataclass
class ExecResult:
    acks: List[str] = field(default_factory=list)
    outputs: Dict[str, bytes] = field(default_factory=dict)
    exits: Dict[str, int] = field(default_factory=dict)


class ExecJob:
    def __init__(self, client: Client, command: str,
                 node_filter: str = "", service_filter: str = "",
                 tag_filter: str = "", wait: float = DEFAULT_WAIT) -> None:
        self.c = client
        self.command = command
        self.node_filter = node_filter
        self.service_filter = service_filter
        self.tag_filter = tag_filter
        self.wait = wait

    def run(self, on_output: Optional[Callable[[str, bytes], None]] = None,
            on_exit: Optional[Callable[[str, int], None]] = None
            ) -> ExecResult:
        session = self.c.session.create({
            "Name": "Remote Exec", "TTL": SESSION_TTL,
            "Behavior": "delete"})
        stop_renew = threading.Event()

        def renew_loop() -> None:
            while not stop_renew.wait(5.0):
                try:
                    if self.c.session.renew(session) is None:
                        return
                except Exception:
                    continue

        threading.Thread(target=renew_loop, daemon=True).start()
        try:
            return self._run(session, on_output, on_exit)
        finally:
            stop_renew.set()
            try:
                self.c.session.destroy(session)
            except Exception:  # noqa: E02 — best-effort cleanup
                pass  # session TTLs out on its own anyway

    def _run(self, session: str, on_output, on_exit) -> ExecResult:
        prefix = f"{REXEC_PREFIX}/{session}"
        # Upload the spec (exec.go:547-575), then announce it.
        spec = json.dumps({"Command": self.command,
                           "Wait": self.wait}).encode()
        if not self.c.kv.acquire(KVPair(key=f"{prefix}/job", value=spec,
                                        session=session)):
            raise RuntimeError("failed to upload exec spec")
        self.c.event.fire(
            REXEC_PREFIX,
            payload=json.dumps({"Prefix": REXEC_PREFIX,
                                "Session": session}).encode(),
            node_filter=self.node_filter,
            service_filter=self.service_filter,
            tag_filter=self.tag_filter)

        # Poll the prefix, streaming results (waitForJob, exec.go:251-416).
        result = ExecResult()
        seen: set = set()
        deadline = time.monotonic() + self.wait
        last_activity = time.monotonic()
        wait_index = 0
        while time.monotonic() < deadline:
            pairs, meta = self.c.kv.list(prefix + "/", QueryOptions(
                wait_index=wait_index, wait_time=1.0))
            wait_index = meta.last_index
            for p in pairs:
                if p.key in seen or p.key == f"{prefix}/job":
                    continue
                seen.add(p.key)
                last_activity = time.monotonic()
                rel = p.key[len(prefix) + 1:]
                parts = rel.split("/")
                if parts[-1] == "ack":
                    result.acks.append(parts[0])
                elif parts[-1] == "exit":
                    code = int(p.value.decode() or "0")
                    result.exits[parts[0]] = code
                    if on_exit:
                        on_exit(parts[0], code)
                elif len(parts) >= 2 and parts[1] == "out":
                    node = parts[0]
                    result.outputs[node] = result.outputs.get(node, b"") + p.value
                    if on_output:
                        on_output(node, p.value)
            # All acked nodes have exited and things are quiet -> done.
            done = (result.acks
                    and all(n in result.exits for n in result.acks))
            if done and time.monotonic() - last_activity >= QUIET_WAIT:
                break
        return result
