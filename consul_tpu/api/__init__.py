"""Client SDK: the ``api/`` package of the reference (2661 LoC) —
an HTTP client with per-domain endpoints plus the Lock/Semaphore
coordination recipes built on the KV + session substrate.
"""

from consul_tpu.api.client import (
    Client, Config, QueryMeta, QueryOptions, WriteOptions, APIError,
    KVPair)
from consul_tpu.api.lock import Lock, LockError, LOCK_FLAG_VALUE
from consul_tpu.api.semaphore import Semaphore, SemaphoreError

__all__ = [
    "Client", "Config", "QueryMeta", "QueryOptions", "WriteOptions",
    "APIError", "KVPair", "Lock", "LockError", "LOCK_FLAG_VALUE",
    "Semaphore", "SemaphoreError",
]
