"""Distributed mutex on the KV + session substrate.

Parity target: ``api/lock.go`` (115-219): session + ``?acquire`` CAS +
a monitor thread watching the key with blocking queries, returning a
"lost lock" event; contention waits ride blocking queries on the key.
"""

from __future__ import annotations

import threading
from typing import Optional

from consul_tpu.api.client import APIError, Client, KVPair, QueryOptions

# Flag marking a KV entry as lock-managed (api/lock.go LockFlagValue —
# a published protocol constant, kept for wire compatibility).
LOCK_FLAG_VALUE = 0x2DDCCBC058A50C18

DEFAULT_SESSION_NAME = "Consul API Lock"
DEFAULT_SESSION_TTL = "15s"
DEFAULT_WAIT = 15.0  # retry pace when contended (lock.go DefaultLockWaitTime)


class LockError(Exception):
    pass


class Lock:
    def __init__(self, client: Client, key: str, value: bytes = b"",
                 session: str = "", session_name: str = DEFAULT_SESSION_NAME,
                 session_ttl: str = DEFAULT_SESSION_TTL,
                 wait_time: float = DEFAULT_WAIT) -> None:
        if not key:
            raise LockError("missing key")
        self.c = client
        self.key = key
        self.value = value
        self.session = session
        self.session_name = session_name
        self.session_ttl = session_ttl
        self.wait_time = wait_time
        self.is_held = False
        self._owns_session = False
        self._renew_stop: Optional[threading.Event] = None
        self._lost = threading.Event()

    # -- session plumbing (lock.go createSession + RenewPeriodic) -----------

    def _create_session(self) -> str:
        sid = self.c.session.create({
            "Name": self.session_name, "TTL": self.session_ttl})
        self._owns_session = True
        stop = threading.Event()
        self._renew_stop = stop
        ttl_s = float(self.session_ttl.rstrip("s"))

        def renew_loop() -> None:
            while not stop.wait(ttl_s / 2):
                try:
                    if self.c.session.renew(sid) is None:
                        self._lost.set()  # session gone server-side
                        return
                except Exception:
                    # Transport blip: keep trying each tick; if the session
                    # TTL-expires meanwhile the monitor thread fires lost.
                    continue

        threading.Thread(target=renew_loop, daemon=True).start()
        return sid

    def _cleanup_session(self) -> None:
        if self._renew_stop is not None:
            self._renew_stop.set()
            self._renew_stop = None
        if self._owns_session and self.session:
            try:
                self.c.session.destroy(self.session)
            except APIError:
                pass
            self.session = ""
            self._owns_session = False

    # -- acquire / release --------------------------------------------------

    def acquire(self, stop: Optional[threading.Event] = None
                ) -> Optional[threading.Event]:
        """Block until held (or ``stop`` is set).  Returns an Event that
        fires if the lock is subsequently lost, None if aborted."""
        if self.is_held:
            raise LockError("lock is already held")
        if not self.session:
            self.session = self._create_session()
        self._lost.clear()

        try:
            wait_index = 0
            while stop is None or not stop.is_set():
                # Wait for the current holder to go away (blocking query).
                pair, meta = self.c.kv.get(self.key, QueryOptions(
                    wait_index=wait_index, wait_time=self.wait_time))
                wait_index = meta.last_index
                if pair is not None and pair.flags != LOCK_FLAG_VALUE:
                    raise LockError("existing key does not match lock use")
                if pair is not None and pair.session:
                    if pair.session == self.session:
                        self.is_held = True
                        self._start_monitor()
                        return self._lost
                    continue  # held by someone else; re-poll

                acquired = self.c.kv.acquire(KVPair(
                    key=self.key, value=self.value, session=self.session,
                    flags=LOCK_FLAG_VALUE))
                if acquired:
                    self.is_held = True
                    self._start_monitor()
                    return self._lost
                # Lost the race (or lock-delay active): brief pause, retry.
                if stop is not None and stop.wait(0.25):
                    break
                elif stop is None:
                    import time
                    time.sleep(0.25)
            return None
        finally:
            # Every failed/aborted path must tear down the session we
            # created, or its renew thread keeps the orphan alive forever.
            if not self.is_held:
                self._cleanup_session()

    def _start_monitor(self) -> None:
        """monitorLock (lock.go:221-255): blocking-watch the key; if our
        session no longer holds it, fire the lost event."""

        def monitor() -> None:
            import time
            wait_index = 0
            while self.is_held:
                try:
                    pair, meta = self.c.kv.get(self.key, QueryOptions(
                        wait_index=wait_index, wait_time=self.wait_time))
                except Exception:
                    time.sleep(1.0)  # transport error: back off, re-watch
                    continue
                wait_index = meta.last_index
                if not self.is_held:
                    return
                if pair is None or pair.session != self.session:
                    self._lost.set()
                    return

        threading.Thread(target=monitor, daemon=True).start()

    def release(self) -> None:
        if not self.is_held:
            raise LockError("lock is not held")
        self.is_held = False
        try:
            # Keep the lock flag on the entry so future contenders still see
            # a lock-managed key (the reference's Unlock sends the full
            # lockEntry).
            self.c.kv.release(KVPair(key=self.key, value=self.value,
                                     session=self.session,
                                     flags=LOCK_FLAG_VALUE))
        finally:
            # Even if the release RPC failed, destroying the session frees
            # the lock server-side (session invalidation cascade).
            self._cleanup_session()

    def destroy(self) -> None:
        """Remove the lock entry if it isn't held (lock.go Destroy)."""
        if self.is_held:
            raise LockError("lock is held, release first")
        pair, _ = self.c.kv.get(self.key)
        if pair is None:
            return
        if pair.flags != LOCK_FLAG_VALUE:
            raise LockError("existing key does not match lock use")
        if pair.session:
            raise LockError("lock in use")
        if not self.c.kv.delete_cas(pair):
            raise LockError("failed to remove lock entry")
