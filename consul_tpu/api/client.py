"""HTTP API client.

Parity target: ``api/api.go`` (client core, env config, QueryOptions/
QueryMeta at api.go:20-46/118-177/384-410) plus the per-domain endpoint
files (``kv.go``, ``agent.go``, ``catalog.go``, ``health.go``,
``session.go``, ``event.go``, ``acl.go``, ``status.go``, ``raw.go``).

Synchronous (the reference's client is, too); uses httpx under the
hood.  Blocking queries: pass ``QueryOptions(wait_index=N)`` and the
call long-polls until the index moves or the wait elapses.
"""

from __future__ import annotations

import base64
import json
import os
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import httpx


class APIError(Exception):
    def __init__(self, status: int, body: str) -> None:
        super().__init__(f"Unexpected response code: {status} ({body})")
        self.status = status
        self.body = body


@dataclass
class Config:
    """Client config; env fallbacks mirror api.go:118-177.

    ``address`` may be ``host:port`` or ``unix:///path/to/socket`` (the
    reference dials unix sockets when the address carries the scheme).
    ``verify_ssl``/``ca_file`` control HTTPS verification
    (CONSUL_HTTP_SSL_VERIFY / CONSUL_CACERT)."""

    address: str = "127.0.0.1:8500"
    scheme: str = "http"
    datacenter: str = ""
    token: str = ""
    timeout: float = 610.0  # > max blocking query wait
    verify_ssl: bool = True
    ca_file: str = ""

    @classmethod
    def default(cls) -> "Config":
        cfg = cls()
        addr = os.environ.get("CONSUL_HTTP_ADDR")
        if addr:
            cfg.address = addr
        token = os.environ.get("CONSUL_HTTP_TOKEN")
        if token:
            cfg.token = token
        if os.environ.get("CONSUL_HTTP_SSL", "").lower() in ("1", "true"):
            cfg.scheme = "https"
        if os.environ.get("CONSUL_HTTP_SSL_VERIFY", "").lower() in ("0", "false"):
            cfg.verify_ssl = False
        cacert = os.environ.get("CONSUL_CACERT")
        if cacert:
            cfg.ca_file = cacert
        return cfg


@dataclass
class QueryOptions:
    datacenter: str = ""
    allow_stale: bool = False
    require_consistent: bool = False
    wait_index: int = 0
    wait_time: float = 0.0
    token: str = ""


@dataclass
class WriteOptions:
    datacenter: str = ""
    token: str = ""


@dataclass
class QueryMeta:
    last_index: int = 0
    last_contact: float = 0.0
    known_leader: bool = False
    request_time: float = 0.0


@dataclass
class KVPair:
    key: str = ""
    create_index: int = 0
    modify_index: int = 0
    lock_index: int = 0
    flags: int = 0
    value: bytes = b""
    session: str = ""

    @classmethod
    def from_api(cls, d: Dict[str, Any]) -> "KVPair":
        value = d.get("Value")
        return cls(
            key=d.get("Key", ""),
            create_index=d.get("CreateIndex", 0),
            modify_index=d.get("ModifyIndex", 0),
            lock_index=d.get("LockIndex", 0),
            flags=d.get("Flags", 0),
            value=base64.b64decode(value) if value else b"",
            session=d.get("Session", ""))


def _fmt_dur(seconds: float) -> str:
    ms = int(seconds * 1000)
    return f"{ms}ms"


class Client:
    def __init__(self, config: Optional[Config] = None) -> None:
        self.config = config or Config.default()
        if self.config.address.startswith("unix://"):
            # Dial the agent's unix-socket HTTP listener; the base URL
            # host is a placeholder (ignored by the UDS transport).
            transport = httpx.HTTPTransport(
                uds=self.config.address[len("unix://"):])
            self._http = httpx.Client(base_url="http://localhost",
                                      timeout=self.config.timeout,
                                      transport=transport)
        else:
            base = f"{self.config.scheme}://{self.config.address}"
            verify: Any = self.config.verify_ssl
            if self.config.scheme == "https" and self.config.ca_file:
                import ssl
                verify = ssl.create_default_context(cafile=self.config.ca_file)
            self._http = httpx.Client(base_url=base,
                                      timeout=self.config.timeout,
                                      verify=verify)
        self.kv = KV(self)
        self.agent = AgentAPI(self)
        self.catalog = CatalogAPI(self)
        self.health = HealthAPI(self)
        self.session = SessionAPI(self)
        self.event = EventAPI(self)
        self.acl = ACLAPI(self)
        self.status = StatusAPI(self)

    def close(self) -> None:
        self._http.close()

    def __enter__(self) -> "Client":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- raw request machinery (api.go newRequest/doRequest) ----------------

    def _params(self, q: Optional[QueryOptions] = None,
                w: Optional[WriteOptions] = None) -> Dict[str, str]:
        params: Dict[str, str] = {}
        dc = (q.datacenter if q else "") or (w.datacenter if w else "") or \
            self.config.datacenter
        if dc:
            params["dc"] = dc
        token = (q.token if q else "") or (w.token if w else "") or \
            self.config.token
        if token:
            params["token"] = token
        if q is not None:
            if q.allow_stale:
                params["stale"] = ""
            if q.require_consistent:
                params["consistent"] = ""
            if q.wait_index:
                params["index"] = str(q.wait_index)
            if q.wait_time:
                params["wait"] = _fmt_dur(q.wait_time)
        return params

    def request(self, method: str, path: str,
                q: Optional[QueryOptions] = None,
                w: Optional[WriteOptions] = None,
                body: Any = None, raw_body: Optional[bytes] = None,
                extra_params: Optional[Dict[str, str]] = None,
                ok_statuses: Tuple[int, ...] = (200,),
                ) -> Tuple[httpx.Response, QueryMeta]:
        import time
        params = self._params(q, w)
        if extra_params:
            params.update(extra_params)
        kwargs: Dict[str, Any] = {"params": params}
        if raw_body is not None:
            kwargs["content"] = raw_body
        elif body is not None:
            kwargs["content"] = json.dumps(body)
        start = time.monotonic()
        resp = self._http.request(method, path, **kwargs)
        meta = QueryMeta(request_time=time.monotonic() - start)
        h = resp.headers
        if "X-Consul-Index" in h:
            meta.last_index = int(h["X-Consul-Index"])
        if "X-Consul-LastContact" in h:
            meta.last_contact = int(h["X-Consul-LastContact"]) / 1000.0
        meta.known_leader = h.get("X-Consul-KnownLeader", "") == "true"
        if resp.status_code not in ok_statuses:
            raise APIError(resp.status_code, resp.text)
        return resp, meta


class KV:
    """api/kv.go."""

    def __init__(self, c: Client) -> None:
        self.c = c

    def get(self, key: str, q: Optional[QueryOptions] = None
            ) -> Tuple[Optional[KVPair], QueryMeta]:
        resp, meta = self.c.request("GET", f"/v1/kv/{key}", q=q,
                                    ok_statuses=(200, 404))
        if resp.status_code == 404:
            return None, meta
        return KVPair.from_api(resp.json()[0]), meta

    def list(self, prefix: str, q: Optional[QueryOptions] = None
             ) -> Tuple[List[KVPair], QueryMeta]:
        resp, meta = self.c.request("GET", f"/v1/kv/{prefix}", q=q,
                                    extra_params={"recurse": ""},
                                    ok_statuses=(200, 404))
        if resp.status_code == 404:
            return [], meta
        return [KVPair.from_api(d) for d in resp.json()], meta

    def keys(self, prefix: str, separator: str = "",
             q: Optional[QueryOptions] = None) -> Tuple[List[str], QueryMeta]:
        extra = {"keys": ""}
        if separator:
            extra["separator"] = separator
        resp, meta = self.c.request("GET", f"/v1/kv/{prefix}", q=q,
                                    extra_params=extra, ok_statuses=(200, 404))
        if resp.status_code == 404:
            return [], meta
        return resp.json(), meta

    def put(self, pair: KVPair, w: Optional[WriteOptions] = None) -> bool:
        extra = {}
        if pair.flags:
            extra["flags"] = str(pair.flags)
        resp, _ = self.c.request("PUT", f"/v1/kv/{pair.key}", w=w,
                                 raw_body=pair.value, extra_params=extra)
        return resp.json() is True

    def cas(self, pair: KVPair, w: Optional[WriteOptions] = None) -> bool:
        extra = {"cas": str(pair.modify_index)}
        if pair.flags:
            extra["flags"] = str(pair.flags)
        resp, _ = self.c.request("PUT", f"/v1/kv/{pair.key}", w=w,
                                 raw_body=pair.value, extra_params=extra)
        return resp.json() is True

    def acquire(self, pair: KVPair, w: Optional[WriteOptions] = None) -> bool:
        extra = {"acquire": pair.session}
        if pair.flags:
            extra["flags"] = str(pair.flags)
        resp, _ = self.c.request("PUT", f"/v1/kv/{pair.key}", w=w,
                                 raw_body=pair.value, extra_params=extra)
        return resp.json() is True

    def release(self, pair: KVPair, w: Optional[WriteOptions] = None) -> bool:
        extra = {"release": pair.session}
        if pair.flags:
            extra["flags"] = str(pair.flags)
        resp, _ = self.c.request("PUT", f"/v1/kv/{pair.key}", w=w,
                                 raw_body=pair.value, extra_params=extra)
        return resp.json() is True

    def delete(self, key: str, w: Optional[WriteOptions] = None) -> bool:
        resp, _ = self.c.request("DELETE", f"/v1/kv/{key}", w=w)
        return True

    def delete_cas(self, pair: KVPair, w: Optional[WriteOptions] = None) -> bool:
        resp, _ = self.c.request("DELETE", f"/v1/kv/{pair.key}", w=w,
                                 extra_params={"cas": str(pair.modify_index)})
        return resp.json() is True

    def delete_tree(self, prefix: str, w: Optional[WriteOptions] = None) -> bool:
        resp, _ = self.c.request("DELETE", f"/v1/kv/{prefix}", w=w,
                                 extra_params={"recurse": ""})
        return True


class AgentAPI:
    """api/agent.go."""

    def __init__(self, c: Client) -> None:
        self.c = c

    def self_(self) -> Dict[str, Any]:
        resp, _ = self.c.request("GET", "/v1/agent/self")
        return resp.json()

    def node_name(self) -> str:
        return self.self_()["Config"]["NodeName"]

    def members(self) -> List[Dict[str, Any]]:
        resp, _ = self.c.request("GET", "/v1/agent/members")
        return resp.json()

    def services(self) -> Dict[str, Any]:
        resp, _ = self.c.request("GET", "/v1/agent/services")
        return resp.json()

    def checks(self) -> Dict[str, Any]:
        resp, _ = self.c.request("GET", "/v1/agent/checks")
        return resp.json()

    def service_register(self, definition: Dict[str, Any]) -> None:
        self.c.request("PUT", "/v1/agent/service/register", body=definition)

    def service_deregister(self, service_id: str) -> None:
        self.c.request("PUT", f"/v1/agent/service/deregister/{service_id}")

    def check_register(self, definition: Dict[str, Any]) -> None:
        self.c.request("PUT", "/v1/agent/check/register", body=definition)

    def check_deregister(self, check_id: str) -> None:
        self.c.request("PUT", f"/v1/agent/check/deregister/{check_id}")

    def pass_ttl(self, check_id: str, note: str = "") -> None:
        self.c.request("PUT", f"/v1/agent/check/pass/{check_id}",
                       extra_params={"note": note} if note else None)

    def warn_ttl(self, check_id: str, note: str = "") -> None:
        self.c.request("PUT", f"/v1/agent/check/warn/{check_id}",
                       extra_params={"note": note} if note else None)

    def fail_ttl(self, check_id: str, note: str = "") -> None:
        self.c.request("PUT", f"/v1/agent/check/fail/{check_id}",
                       extra_params={"note": note} if note else None)

    def join(self, addr: str, wan: bool = False) -> None:
        extra = {"wan": "1"} if wan else None
        self.c.request("PUT", f"/v1/agent/join/{addr}", extra_params=extra)

    def force_leave(self, node: str) -> None:
        self.c.request("PUT", f"/v1/agent/force-leave/{node}")

    def enable_node_maintenance(self, reason: str = "") -> None:
        self.c.request("PUT", "/v1/agent/maintenance",
                       extra_params={"enable": "true", "reason": reason})

    def disable_node_maintenance(self) -> None:
        self.c.request("PUT", "/v1/agent/maintenance",
                       extra_params={"enable": "false"})

    def enable_service_maintenance(self, service_id: str, reason: str = "") -> None:
        self.c.request("PUT", f"/v1/agent/service/maintenance/{service_id}",
                       extra_params={"enable": "true", "reason": reason})

    def disable_service_maintenance(self, service_id: str) -> None:
        self.c.request("PUT", f"/v1/agent/service/maintenance/{service_id}",
                       extra_params={"enable": "false"})


class CatalogAPI:
    """api/catalog.go."""

    def __init__(self, c: Client) -> None:
        self.c = c

    def register(self, reg: Dict[str, Any],
                 w: Optional[WriteOptions] = None) -> None:
        self.c.request("PUT", "/v1/catalog/register", w=w, body=reg)

    def deregister(self, dereg: Dict[str, Any],
                   w: Optional[WriteOptions] = None) -> None:
        self.c.request("PUT", "/v1/catalog/deregister", w=w, body=dereg)

    def datacenters(self) -> List[str]:
        resp, _ = self.c.request("GET", "/v1/catalog/datacenters")
        return resp.json()

    def nodes(self, q: Optional[QueryOptions] = None):
        resp, meta = self.c.request("GET", "/v1/catalog/nodes", q=q)
        return resp.json(), meta

    def services(self, q: Optional[QueryOptions] = None):
        resp, meta = self.c.request("GET", "/v1/catalog/services", q=q)
        return resp.json(), meta

    def service(self, name: str, tag: str = "",
                q: Optional[QueryOptions] = None):
        extra = {"tag": tag} if tag else None
        resp, meta = self.c.request("GET", f"/v1/catalog/service/{name}",
                                    q=q, extra_params=extra)
        return resp.json(), meta

    def node(self, name: str, q: Optional[QueryOptions] = None):
        resp, meta = self.c.request("GET", f"/v1/catalog/node/{name}", q=q)
        return resp.json(), meta


class HealthAPI:
    """api/health.go."""

    def __init__(self, c: Client) -> None:
        self.c = c

    def node(self, name: str, q: Optional[QueryOptions] = None):
        resp, meta = self.c.request("GET", f"/v1/health/node/{name}", q=q)
        return resp.json(), meta

    def checks(self, service: str, q: Optional[QueryOptions] = None):
        resp, meta = self.c.request("GET", f"/v1/health/checks/{service}", q=q)
        return resp.json(), meta

    def service(self, name: str, tag: str = "", passing_only: bool = False,
                q: Optional[QueryOptions] = None):
        extra: Dict[str, str] = {}
        if tag:
            extra["tag"] = tag
        if passing_only:
            extra["passing"] = ""
        resp, meta = self.c.request("GET", f"/v1/health/service/{name}",
                                    q=q, extra_params=extra or None)
        return resp.json(), meta

    def state(self, state: str, q: Optional[QueryOptions] = None):
        resp, meta = self.c.request("GET", f"/v1/health/state/{state}", q=q)
        return resp.json(), meta


class SessionAPI:
    """api/session.go."""

    def __init__(self, c: Client) -> None:
        self.c = c

    def create(self, entry: Optional[Dict[str, Any]] = None,
               w: Optional[WriteOptions] = None) -> str:
        resp, _ = self.c.request("PUT", "/v1/session/create", w=w,
                                 body=entry or {})
        return resp.json()["ID"]

    def destroy(self, session_id: str,
                w: Optional[WriteOptions] = None) -> None:
        self.c.request("PUT", f"/v1/session/destroy/{session_id}", w=w)

    def renew(self, session_id: str,
              w: Optional[WriteOptions] = None) -> Optional[Dict[str, Any]]:
        resp, _ = self.c.request("PUT", f"/v1/session/renew/{session_id}",
                                 w=w, ok_statuses=(200, 404))
        if resp.status_code == 404:
            return None
        entries = resp.json()
        return entries[0] if entries else None

    def info(self, session_id: str, q: Optional[QueryOptions] = None):
        resp, meta = self.c.request("GET", f"/v1/session/info/{session_id}", q=q)
        entries = resp.json()
        return (entries[0] if entries else None), meta

    def node(self, node: str, q: Optional[QueryOptions] = None):
        resp, meta = self.c.request("GET", f"/v1/session/node/{node}", q=q)
        return resp.json(), meta

    def list(self, q: Optional[QueryOptions] = None):
        resp, meta = self.c.request("GET", "/v1/session/list", q=q)
        return resp.json(), meta


class EventAPI:
    """api/event.go."""

    def __init__(self, c: Client) -> None:
        self.c = c

    def fire(self, name: str, payload: bytes = b"",
             node_filter: str = "", service_filter: str = "",
             tag_filter: str = "",
             w: Optional[WriteOptions] = None) -> str:
        extra: Dict[str, str] = {}
        if node_filter:
            extra["node"] = node_filter
        if service_filter:
            extra["service"] = service_filter
        if tag_filter:
            extra["tag"] = tag_filter
        resp, _ = self.c.request("PUT", f"/v1/event/fire/{name}", w=w,
                                 raw_body=payload, extra_params=extra or None)
        return resp.json().get("ID", "")

    def list(self, name: str = "", q: Optional[QueryOptions] = None):
        extra = {"name": name} if name else None
        resp, meta = self.c.request("GET", "/v1/event/list", q=q,
                                    extra_params=extra)
        return resp.json(), meta


class ACLAPI:
    """api/acl.go."""

    def __init__(self, c: Client) -> None:
        self.c = c

    def create(self, entry: Dict[str, Any],
               w: Optional[WriteOptions] = None) -> str:
        resp, _ = self.c.request("PUT", "/v1/acl/create", w=w, body=entry)
        return resp.json()["ID"]

    def update(self, entry: Dict[str, Any],
               w: Optional[WriteOptions] = None) -> None:
        self.c.request("PUT", "/v1/acl/update", w=w, body=entry)

    def destroy(self, acl_id: str, w: Optional[WriteOptions] = None) -> None:
        self.c.request("PUT", f"/v1/acl/destroy/{acl_id}", w=w)

    def clone(self, acl_id: str, w: Optional[WriteOptions] = None) -> str:
        resp, _ = self.c.request("PUT", f"/v1/acl/clone/{acl_id}", w=w)
        return resp.json()["ID"]

    def info(self, acl_id: str, q: Optional[QueryOptions] = None):
        resp, meta = self.c.request("GET", f"/v1/acl/info/{acl_id}", q=q)
        entries = resp.json()
        return (entries[0] if entries else None), meta

    def list(self, q: Optional[QueryOptions] = None):
        resp, meta = self.c.request("GET", "/v1/acl/list", q=q)
        return resp.json(), meta


class StatusAPI:
    """api/status.go."""

    def __init__(self, c: Client) -> None:
        self.c = c

    def leader(self) -> str:
        resp, _ = self.c.request("GET", "/v1/status/leader")
        return resp.json()

    def peers(self) -> List[str]:
        resp, _ = self.c.request("GET", "/v1/status/peers")
        return resp.json()
