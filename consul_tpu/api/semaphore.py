"""Slot-limited distributed semaphore on the KV + session substrate.

Parity target: ``api/semaphore.go`` (135-247): each contender holds a
session-bound entry under ``<prefix>/<session>``, and the shared state
lives in ``<prefix>/.lock`` as JSON {"Limit": N, "Holders": {...}}
updated by CAS.  Dead contenders vanish with their sessions; pruning
happens on the next CAS.
"""

from __future__ import annotations

import json
import threading
from typing import Dict, Optional

from consul_tpu.api.client import APIError, Client, KVPair, QueryOptions

SEMAPHORE_FLAG_VALUE = 0xE0F69A2BAA414DE0  # api/semaphore.go magic
DEFAULT_SESSION_NAME = "Consul API Semaphore"
DEFAULT_SESSION_TTL = "15s"
DEFAULT_WAIT = 15.0


class SemaphoreError(Exception):
    pass


class Semaphore:
    def __init__(self, client: Client, prefix: str, limit: int,
                 session_name: str = DEFAULT_SESSION_NAME,
                 session_ttl: str = DEFAULT_SESSION_TTL,
                 wait_time: float = DEFAULT_WAIT) -> None:
        if not prefix:
            raise SemaphoreError("missing prefix")
        if limit <= 0:
            raise SemaphoreError("semaphore limit must be positive")
        self.c = client
        self.prefix = prefix.rstrip("/")
        self.limit = limit
        self.session_name = session_name
        self.session_ttl = session_ttl
        self.wait_time = wait_time
        self.session = ""
        self.is_held = False
        self._owns_session = False
        self._renew_stop: Optional[threading.Event] = None
        self._lost = threading.Event()

    @property
    def _lock_key(self) -> str:
        return f"{self.prefix}/.lock"

    @property
    def _contender_key(self) -> str:
        return f"{self.prefix}/{self.session}"

    def _create_session(self) -> str:
        sid = self.c.session.create({
            "Name": self.session_name, "TTL": self.session_ttl,
            "Behavior": "delete"})
        self._owns_session = True
        stop = threading.Event()
        self._renew_stop = stop
        ttl_s = float(self.session_ttl.rstrip("s"))

        def renew_loop() -> None:
            while not stop.wait(ttl_s / 2):
                try:
                    if self.c.session.renew(sid) is None:
                        self._lost.set()  # session gone server-side
                        return
                except Exception:
                    continue  # transport blip: retry next tick

        threading.Thread(target=renew_loop, daemon=True).start()
        return sid

    def _cleanup_session(self) -> None:
        if self._renew_stop is not None:
            self._renew_stop.set()
            self._renew_stop = None
        if self._owns_session and self.session:
            try:
                self.c.session.destroy(self.session)
            except APIError:
                pass
            self.session = ""
            self._owns_session = False

    # -- state helpers ------------------------------------------------------

    def _live_sessions(self) -> set:
        pairs, _ = self.c.kv.list(self.prefix)
        return {p.session for p in pairs
                if p.key != self._lock_key and p.session}

    def _read_state(self) -> tuple:
        pair, meta = self.c.kv.get(self._lock_key)
        if pair is None:
            return {"Limit": self.limit, "Holders": {}}, KVPair(
                key=self._lock_key, flags=SEMAPHORE_FLAG_VALUE), meta
        if pair.flags != SEMAPHORE_FLAG_VALUE:
            raise SemaphoreError("existing key does not match semaphore use")
        state = json.loads(pair.value.decode() or "{}")
        state.setdefault("Limit", self.limit)
        state.setdefault("Holders", {})
        return state, pair, meta

    def _write_state(self, state: Dict, pair: KVPair) -> bool:
        return self.c.kv.cas(KVPair(
            key=self._lock_key, flags=SEMAPHORE_FLAG_VALUE,
            value=json.dumps(state).encode(),
            modify_index=pair.modify_index))

    # -- acquire / release --------------------------------------------------

    def acquire(self, stop: Optional[threading.Event] = None
                ) -> Optional[threading.Event]:
        if self.is_held:
            raise SemaphoreError("semaphore is already held")
        if not self.session:
            self.session = self._create_session()
        self._lost.clear()

        try:
            # Contender entry bound to our session (semaphore.go:167-184).
            if not self.c.kv.acquire(KVPair(
                    key=self._contender_key, session=self.session,
                    flags=SEMAPHORE_FLAG_VALUE)):
                raise SemaphoreError("failed to create contender entry")

            wait_index = 0
            while stop is None or not stop.is_set():
                state, pair, meta = self._read_state()
                live = self._live_sessions()
                holders = {s: True for s in state["Holders"] if s in live}
                if len(holders) < state["Limit"]:
                    holders[self.session] = True
                    state["Holders"] = holders
                    if self._write_state(state, pair):
                        self.is_held = True
                        self._start_monitor()
                        return self._lost
                    continue  # CAS race; retry immediately
                # Slots full: block until the lock state changes.
                wait_index = meta.last_index
                self.c.kv.get(self._lock_key, QueryOptions(
                    wait_index=wait_index, wait_time=self.wait_time))
            return None
        finally:
            if not self.is_held:
                self._abort_contender()

    def _abort_contender(self) -> None:
        try:
            self.c.kv.delete(self._contender_key)
        except APIError:
            pass
        self._cleanup_session()

    def _start_monitor(self) -> None:
        """Watch the lock state; fire lost if our session drops out."""

        def monitor() -> None:
            import time
            wait_index = 0
            while self.is_held:
                try:
                    pair, meta = self.c.kv.get(self._lock_key, QueryOptions(
                        wait_index=wait_index, wait_time=self.wait_time))
                except Exception:
                    time.sleep(1.0)  # transport error: back off, re-watch
                    continue
                wait_index = meta.last_index
                if not self.is_held:
                    return
                if pair is None:
                    self._lost.set()
                    return
                state = json.loads(pair.value.decode() or "{}")
                if self.session not in state.get("Holders", {}):
                    self._lost.set()
                    return

        threading.Thread(target=monitor, daemon=True).start()

    def release(self) -> None:
        if not self.is_held:
            raise SemaphoreError("semaphore is not held")
        self.is_held = False
        try:
            while True:
                state, pair, _ = self._read_state()
                if self.session in state["Holders"]:
                    del state["Holders"][self.session]
                    if not self._write_state(state, pair):
                        continue
                break
            self.c.kv.delete(self._contender_key)
        finally:
            # Session teardown frees the slot server-side even if the CAS
            # dance above failed (delete-behavior session reaps the entry).
            self._cleanup_session()

    def destroy(self) -> None:
        """Remove the semaphore prefix if nobody holds a slot."""
        if self.is_held:
            raise SemaphoreError("semaphore is held, release first")
        state, pair, _ = self._read_state()
        live = self._live_sessions()
        if any(s in live for s in state["Holders"]):
            raise SemaphoreError("semaphore in use")
        self.c.kv.delete_tree(self.prefix + "/")
