"""CLI entry point and subcommand dispatch.

Parity target: ``main.go`` + ``commands.go:19-141`` + ``command/``
(2654 LoC): agent, configtest, event, exec, force-leave, info, join,
keygen, keyring, leave, lock, maint, members, monitor, reload,
version, watch.  Cluster-facing commands use the HTTP SDK
(``-http-addr``) or the IPC socket (``-rpc-addr``), matching which
surface the reference command uses.
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import re
import signal
import sys
from typing import List, Optional

from consul_tpu.version import VERSION

DEFAULT_HTTP = "127.0.0.1:8500"
DEFAULT_RPC = "127.0.0.1:8400"


def _http_client(args):
    from consul_tpu.api import Client, Config
    return Client(Config(address=args.http_addr,
                         token=getattr(args, "token", "") or ""))


def _ipc(args):
    from consul_tpu.ipc import IPCClient
    return IPCClient(args.rpc_addr)


def _add_http_flag(p) -> None:
    p.add_argument("-http-addr", dest="http_addr", default=DEFAULT_HTTP)
    p.add_argument("-token", dest="token", default="")


def _add_rpc_flag(p) -> None:
    p.add_argument("-rpc-addr", dest="rpc_addr", default=DEFAULT_RPC)


# -- agent (the daemon; command/agent/command.go serve choreography) --------


def cmd_agent(args) -> int:
    import asyncio

    from consul_tpu.agent.agent import Agent
    from consul_tpu.agent.config import (
        Config, decode_config, merge_config, read_config_paths,
        to_agent_config, validate_config)

    cfg = Config()
    if args.config_file or args.config_dir:
        paths = list(args.config_file or []) + list(args.config_dir or [])
        cfg = read_config_paths(paths)
    # flag overlay (flags beat files, command.go readConfig)
    flag_doc = {}
    for name, value in (("node_name", args.node), ("datacenter", args.dc),
                        ("data_dir", args.data_dir),
                        ("client_addr", args.client),
                        ("bind_addr", args.bind)):
        if value:
            flag_doc[name] = value
    if args.server:
        flag_doc["server"] = True
    if args.bootstrap:
        flag_doc["bootstrap"] = True
    if args.protocol is not None:
        flag_doc["protocol"] = args.protocol
    if args.http_workers is not None:
        flag_doc["http_workers"] = args.http_workers
    if flag_doc:
        cfg = merge_config(cfg, decode_config(json.dumps(flag_doc)))
    role_configured = cfg._set_fields & {"server", "bootstrap",
                                         "bootstrap_expect"}
    if not cfg.server and not cfg.bootstrap and not role_configured:
        # dev-style default: when nothing configured the role, run as a
        # single bootstrap server.  Any explicit role statement —
        # server=false, bootstrap=false, or a bootstrap_expect — must
        # be honored as written; promoting it would make a would-be
        # client or joining node its own one-node leader.  (Config
        # files that only carry service/check stanzas still get the
        # dev default: _set_fields tracks exactly what was written.)
        cfg.server = cfg.bootstrap = True
    problems = validate_config(cfg)
    if problems:
        for p in problems:
            print(f"==> {p}", file=sys.stderr)
        return 1

    acfg = to_agent_config(cfg)
    if args.http_port is not None:
        acfg.http_port = args.http_port
    if args.dns_port is not None:
        acfg.dns_port = args.dns_port
    acfg.extra["ipc_port"] = (args.rpc_port if args.rpc_port is not None
                              else cfg.ports.rpc)
    acfg.extra["log_level"] = cfg.log_level

    agent = Agent(acfg)

    if cfg.enable_syslog:
        # -syslog (command.go:272-281): fatal when the local syslog
        # socket cannot be opened, exactly like the reference after its
        # retries.
        from consul_tpu.agent.log import syslog_sink
        try:
            agent.log.add_sink(syslog_sink(cfg.syslog_facility),
                               level=cfg.log_level, replay=False)
        except OSError as e:
            print(f"==> Syslog setup failed: {e}", file=sys.stderr)
            return 1

    # Telemetry sinks + SIGUSR1 dump (command.go:569-605): the inmem
    # sink is always on; statsd/statsite attach from the config block.
    from consul_tpu.utils.telemetry import metrics
    metrics.configure(statsd_addr=cfg.telemetry.statsd_addr,
                      statsite_addr=cfg.telemetry.statsite_addr,
                      hostname=acfg.node_name,
                      disable_hostname=cfg.telemetry.disable_hostname)
    # Stamp spans with this node's name so cross-process traces show
    # which hop ran where (obs/trace.py).
    from consul_tpu.obs.trace import tracer
    tracer.node_name = acfg.node_name

    async def serve() -> None:
        await agent.start()
        http_disp = ("unix://" + agent.http.unix_path
                     if agent.http.unix_path else agent.http.addr)
        ipc_disp = ("unix://" + agent.ipc.unix_path
                    if agent.ipc.unix_path else agent.ipc.addr)
        print(f"==> consul-tpu agent running! Node: {acfg.node_name}, "
              f"HTTP: {http_disp}, DNS: {agent.dns.addr}, "
              f"IPC: {ipc_disp}")
        sys.stdout.flush()
        # register config-defined services/checks/watches (command.go
        # serve: service/check stanzas + watch plans :710-718)
        from consul_tpu.agent.agent import _check_type_from_api
        from consul_tpu.structs.structs import HealthCheck, NodeService

        def norm(d):
            return {k[0].upper() + k[1:] if k and k[0].islower() else k: v
                    for k, v in d.items()}

        for svc in cfg.services:
            raw = norm(svc)
            service = NodeService(
                id=raw.get("Id", raw.get("ID", "")),
                service=raw.get("Name", ""), tags=raw.get("Tags") or [],
                port=raw.get("Port", 0))
            cts = []
            if raw.get("Check"):
                cts.append(_check_type_from_api(norm(raw["Check"])))
            await agent.add_service(service, cts, persist=False)
        for chk in cfg.checks:
            raw = norm(chk)
            ct = _check_type_from_api(raw)
            check = HealthCheck(
                node=acfg.node_name,
                check_id=raw.get("Id", raw.get("ID", "")) or raw.get("Name", ""),
                name=raw.get("Name", ""), notes=raw.get("Notes", ""))
            await agent.add_check(check, ct if ct.valid() else None,
                                  persist=False)
        watch_plans = []
        if cfg.watches:
            from consul_tpu.watch import parse as watch_parse
            # Watch plans dial whichever HTTP listener exists (the api
            # client speaks unix:// addresses too).
            http_addr = ("unix://" + agent.http.unix_path
                         if agent.http.unix_path
                         else "%s:%s" % agent.http.addr)
            for wp in cfg.watches:
                plan = watch_parse(dict(wp))
                plan.run_in_thread(http_addr)
                watch_plans.append(plan)
        loop = asyncio.get_event_loop()
        stop = asyncio.Event()
        reload_tasks: set = set()  # anchor against mid-reload GC

        def on_term() -> None:
            stop.set()

        def on_hup() -> None:
            task = loop.create_task(agent.reload())
            reload_tasks.add(task)
            task.add_done_callback(reload_tasks.discard)

        def on_usr1() -> None:
            print(metrics.dump(), file=sys.stderr, flush=True)

        loop.add_signal_handler(signal.SIGINT, on_term)
        loop.add_signal_handler(signal.SIGTERM, on_term)
        loop.add_signal_handler(signal.SIGHUP, on_hup)
        loop.add_signal_handler(signal.SIGUSR1, on_usr1)
        leave_task = loop.create_task(agent.wait_for_leave())
        stop_task = loop.create_task(stop.wait())
        await asyncio.wait({leave_task, stop_task},
                           return_when=asyncio.FIRST_COMPLETED)
        print("==> Gracefully shutting down...")
        for plan in watch_plans:
            plan.stop()
        await agent.stop()

    asyncio.run(serve())
    return 0


# -- gossipd -----------------------------------------------------------------


def cmd_gossipd(args) -> int:
    """Run the TPU gossip plane daemon (gossip/plane.py): the kernel
    session that real agents with ``gossip_backend=tpu`` delegate their
    LAN membership to."""
    import asyncio
    import os as _os

    # Honor an explicit CPU request before jax's backend initializes
    # (the interpreter-start hook would otherwise dial the TPU tunnel;
    # same dance as bench.py / tests/conftest.py).
    if _os.environ.get("JAX_PLATFORMS", "").split(",")[0].strip() == "cpu":
        _os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        try:
            import jax
            jax.config.update("jax_platforms", "cpu")
        except Exception:  # noqa: E02 — jax absent or too old to force cpu
            pass

    # Persistent compile cache: a restarted plane must not pay the
    # full kernel compile again (same discipline as bench.py).
    try:
        import jax
        jax.config.update(
            "jax_compilation_cache_dir",
            _os.path.join(_os.path.dirname(_os.path.dirname(_os.path.dirname(
                _os.path.abspath(__file__)))), ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:  # noqa: E02 — cache knobs are version-dependent
        pass

    from consul_tpu.gossip.plane import GossipPlane, PlaneConfig

    keys = list(args.encrypt)
    if args.keyring_file:
        # Keyring parses AND validates the serf keyring file format —
        # a malformed file must fail loudly here, not arm the plane
        # with garbage keys that refuse every agent.
        from consul_tpu.agent.keyring import Keyring
        keys.extend(k for k in Keyring(path=args.keyring_file).list_keys()
                    if k not in keys)
    if args.nemesis:
        # Validate against the catalog before the kernel session boots —
        # a typo'd scenario must fail here, not deep in plane startup.
        from consul_tpu.gossip.nemesis import names as nemesis_names
        if args.nemesis not in nemesis_names():
            print(f"Unknown nemesis scenario {args.nemesis!r}; catalog: "
                  f"{', '.join(nemesis_names())}", file=sys.stderr)
            return 1
    cfg = PlaneConfig(
        bind_addr=args.bind, bind_port=args.port, unix_path=args.unix,
        capacity=args.capacity, sim_nodes=args.sim_nodes,
        gossip_interval_s=args.gossip_interval,
        hb_lapse_s=args.hb_lapse, suspicion_mult=args.suspicion_mult,
        slots=args.slots, encrypt_keys=keys, nemesis=args.nemesis,
        dissem=args.dissem, shard_devices=args.shard_devices)

    async def serve() -> None:
        plane = GossipPlane(cfg)
        await plane.start()
        addr = cfg.unix_path or "%s:%s" % plane.local_addr
        nem = f", nemesis={cfg.nemesis}" if cfg.nemesis else ""
        print(f"==> gossip plane running at {addr} "
              f"(capacity={cfg.capacity}, sim_nodes={cfg.sim_nodes}, "
              f"round={cfg.gossip_interval_s * 1000:.0f}ms{nem})", flush=True)
        loop = asyncio.get_event_loop()
        stop = asyncio.Event()
        loop.add_signal_handler(signal.SIGINT, stop.set)
        loop.add_signal_handler(signal.SIGTERM, stop.set)
        await stop.wait()
        await plane.stop()

    asyncio.run(serve())
    return 0


# -- configtest --------------------------------------------------------------


def cmd_configtest(args) -> int:
    from consul_tpu.agent.config import (
        ConfigError, read_config_paths, validate_config)
    paths = list(args.config_file or []) + list(args.config_dir or [])
    if not paths:
        print("Must specify config file or directory", file=sys.stderr)
        return 1
    try:
        cfg = read_config_paths(paths)
    except (ConfigError, OSError) as e:
        print(f"Config validation failed: {e}", file=sys.stderr)
        return 1
    problems = validate_config(cfg)
    if problems:
        for p in problems:
            print(f"Config validation failed: {p}", file=sys.stderr)
        return 1
    print("Configuration is valid!")
    return 0


# -- debug (the `consul debug` one-shot capture) -----------------------------


def cmd_debug(args) -> int:
    import time as _time
    import urllib.error
    import urllib.request

    url = (f"http://{args.http_addr}/v1/agent/debug/bundle"
           f"?seconds={args.seconds}")
    req = urllib.request.Request(url)
    if getattr(args, "token", ""):
        req.add_header("X-Consul-Token", args.token)
    try:
        with urllib.request.urlopen(req,
                                    timeout=args.seconds + 30.0) as resp:
            data = resp.read()
    except urllib.error.HTTPError as e:
        detail = ("capture requires enable_debug on the agent"
                  if e.code == 404 else e.reason)
        print(f"Error capturing bundle: {e.code} {detail}", file=sys.stderr)
        return 1
    except (urllib.error.URLError, OSError) as e:
        print(f"Error capturing bundle: {e}", file=sys.stderr)
        return 1
    out = args.output or _time.strftime("consul-debug-%Y%m%d-%H%M%S.tar.gz")
    with open(out, "wb") as f:
        f.write(data)
    # Surface the manifest so the operator sees what was captured.
    import io
    import json as _json
    import tarfile
    try:
        with tarfile.open(fileobj=io.BytesIO(data), mode="r:gz") as tar:
            m = tar.extractfile("manifest.json")
            manifest = _json.load(m) if m is not None else {}
    except (tarfile.TarError, _json.JSONDecodeError):
        manifest = {}
    print(f"Wrote {out} ({len(data)} bytes)")
    if manifest:
        print(f"  node:     {manifest.get('node', '?')}")
        print(f"  window:   {manifest.get('seconds', '?')}s")
        print(f"  sections: {', '.join(manifest.get('sections', []))}")
    return 0


# -- event -------------------------------------------------------------------


def cmd_event(args) -> int:
    with _http_client(args) as c:
        eid = c.event.fire(args.name, payload=(args.payload or "").encode(),
                           node_filter=args.node or "",
                           service_filter=args.service or "",
                           tag_filter=args.tag or "")
    print(f"Event ID: {eid}")
    return 0


# -- exec --------------------------------------------------------------------


def cmd_exec(args) -> int:
    from consul_tpu.api.exec import ExecJob
    command = " ".join(args.command)
    if not command:
        print("Must specify a command to execute", file=sys.stderr)
        return 1
    with _http_client(args) as c:
        job = ExecJob(c, command, node_filter=args.node or "",
                      service_filter=args.service or "",
                      tag_filter=args.tag or "", wait=args.wait)

        def on_output(node: str, chunk: bytes) -> None:
            for line in chunk.decode(errors="replace").splitlines():
                print(f"    {node}: {line}")

        def on_exit(node: str, code: int) -> None:
            print(f"==> {node}: finished with exit code {code}")

        result = job.run(on_output=on_output, on_exit=on_exit)
    n_done = len(result.exits)
    print(f"{n_done} / {len(result.acks) or n_done} node(s) completed / "
          f"acknowledged")
    return 0 if all(c == 0 for c in result.exits.values()) else 2


# -- membership commands (IPC) ----------------------------------------------


def cmd_force_leave(args) -> int:
    with _ipc(args) as c:
        c.force_leave(args.node)
    return 0


def cmd_info(args) -> int:
    with _ipc(args) as c:
        stats = c.stats()
    for section in sorted(stats):
        print(f"{section}:")
        for k in sorted(stats[section]):
            print(f"\t{k} = {stats[section][k]}")
    return 0


def cmd_join(args) -> int:
    with _ipc(args) as c:
        n = c.join(args.address, wan=args.wan)
    print(f"Successfully joined cluster by contacting {n} nodes.")
    return 0


def cmd_leave(args) -> int:
    with _ipc(args) as c:
        c.leave()
    print("Graceful leave complete")
    return 0


def cmd_members(args) -> int:
    with _ipc(args) as c:
        members = c.members_wan() if args.wan else c.members_lan()
    # -status / -role regex filters + -detailed protocol column
    # (command/members.go flags).
    try:
        status_pat = re.compile(args.status) if args.status else None
        role_pat = re.compile(args.role) if args.role else None
    except re.error as e:
        print(f"Failed to compile filter regexp: {e}", file=sys.stderr)
        return 1
    if status_pat is not None:
        members = [m for m in members
                   if status_pat.search(m.get("Status", ""))]
    if role_pat is not None:
        members = [m for m in members
                   if role_pat.search(m.get("Tags", {}).get("role", ""))]
    for m in members:
        tags = ",".join(f"{k}={v}" for k, v in sorted(m.get("Tags", {}).items()))
        line = (f"{m['Name']:<20} {m['Addr']}:{m['Port']:<6} "
                f"{m.get('Status', '?'):<8} {tags}")
        if args.detailed:
            line += f"  protocol={m.get('ProtocolCur', '?')}"
        print(line)
    # Filters that leave nothing signal exit 2 (command/members.go),
    # so scripts can branch on presence.
    if (status_pat is not None or role_pat is not None) and not members:
        return 2
    return 0


def cmd_monitor(args) -> int:
    with _ipc(args) as c:
        def handler(line: str) -> None:
            print(line)

        c.monitor(handler, log_level=args.log_level)
        try:
            while True:
                c.pump(timeout=1.0)
        except KeyboardInterrupt:
            return 0
        except Exception as e:
            print(f"Error streaming logs: {e}", file=sys.stderr)
            return 1
    return 0


def cmd_reload(args) -> int:
    with _ipc(args) as c:
        c.reload()
    print("Configuration reload triggered")
    return 0


# -- keygen / keyring --------------------------------------------------------


def cmd_keygen(args) -> int:
    print(base64.b64encode(os.urandom(16)).decode("ascii"))
    return 0


def cmd_keyring(args) -> int:
    ops = [(args.install, "install"), (args.use, "use"),
           (args.remove, "remove")]
    chosen = [(v, op) for v, op in ops if v]
    if len(chosen) > 1 or (chosen and args.list):
        print("Only a single action is allowed", file=sys.stderr)
        return 1
    with _ipc(args) as c:
        try:
            if args.list:
                result = c.keyring("list")
                for key, count in result.get("Keys", {}).items():
                    print(f"  {key} [{count}]")
            elif chosen:
                key, op = chosen[0]
                c.keyring(op, key)
                print("Done!")
            else:
                print("Must specify an action", file=sys.stderr)
                return 1
        except Exception as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
    return 0


# -- lock --------------------------------------------------------------------


def cmd_lock(args) -> int:
    """Lock (or semaphore with -n>1) holder spawning a child process
    (command/lock.go:73-339)."""
    import subprocess

    from consul_tpu.api import Lock, Semaphore
    child_cmd = " ".join(args.child)
    if not child_cmd:
        print("Must specify a command to run", file=sys.stderr)
        return 1
    with _http_client(args) as c:
        prefix = args.prefix.strip("/")
        if args.n > 1:
            holder = Semaphore(c, prefix, args.n)
        else:
            holder = Lock(c, f"{prefix}/.lock")
        lost = holder.acquire()
        if lost is None:
            print("Failed to acquire lock", file=sys.stderr)
            return 1
        try:
            proc = subprocess.Popen(["/bin/sh", "-c", child_cmd])
            while True:
                try:
                    code = proc.wait(timeout=0.5)
                    break
                except subprocess.TimeoutExpired:
                    if lost.is_set():
                        proc.terminate()
                        code = proc.wait()
                        print("Lock lost, child terminated", file=sys.stderr)
                        return 1
            return code
        finally:
            if holder.is_held:
                holder.release()


# -- maint -------------------------------------------------------------------


def cmd_maint(args) -> int:
    with _http_client(args) as c:
        if args.enable and args.disable:
            print("Only one of -enable or -disable may be provided",
                  file=sys.stderr)
            return 1
        if not args.enable and not args.disable:
            # show current maintenance state
            checks = c.agent.checks()
            found = False
            for cid, chk in checks.items():
                if cid == "_node_maintenance":
                    print("Node:")
                    print(f"  Name:   {chk.get('Node', '')}")
                    print(f"  Reason: {chk.get('Notes', '')}")
                    found = True
                elif cid.startswith("_service_maintenance:"):
                    print("Service:")
                    print(f"  ID:     {cid.split(':', 1)[1]}")
                    print(f"  Reason: {chk.get('Notes', '')}")
                    found = True
            if not found:
                print("Node and all services are in normal mode.")
            return 0
        if args.service:
            if args.enable:
                c.agent.enable_service_maintenance(args.service,
                                                   args.reason or "")
            else:
                c.agent.disable_service_maintenance(args.service)
        else:
            if args.enable:
                c.agent.enable_node_maintenance(args.reason or "")
            else:
                c.agent.disable_node_maintenance()
    print("Maintenance mode updated")
    return 0


# -- version / watch ---------------------------------------------------------


def cmd_version(args) -> int:
    print(f"consul-tpu v{VERSION}")
    return 0


def cmd_watch(args) -> int:
    from consul_tpu.watch import parse
    params = {"type": args.type}
    for name in ("key", "prefix", "service", "tag", "state", "name"):
        v = getattr(args, name, None)
        if v:
            params[name] = v
    if args.passingonly:
        params["passingonly"] = True
    if args.handler:
        params["handler"] = args.handler
    try:
        plan = parse(params)
    except Exception as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    if not args.handler:
        plan.handler = lambda idx, result: print(
            json.dumps(_jsonable(result), indent=2))
    try:
        plan.run(args.http_addr)
    except KeyboardInterrupt:
        plan.stop()
    return 0


def _jsonable(v):
    if isinstance(v, bytes):
        return base64.b64encode(v).decode()
    if isinstance(v, dict):
        return {k: _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    return v


# -- dispatch ----------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="consul-tpu")
    sub = ap.add_subparsers(dest="command", required=True)

    p = sub.add_parser("agent", help="Runs an agent")
    p.add_argument("-config-file", action="append", dest="config_file")
    p.add_argument("-config-dir", action="append", dest="config_dir")
    p.add_argument("-node", default="")
    p.add_argument("-dc", default="")
    p.add_argument("-data-dir", dest="data_dir", default="")
    p.add_argument("-client", default="")
    p.add_argument("-bind", default="")
    p.add_argument("-server", action="store_true")
    p.add_argument("-bootstrap", action="store_true")
    p.add_argument("-http-port", dest="http_port", type=int, default=None)
    p.add_argument("-dns-port", dest="dns_port", type=int, default=None)
    p.add_argument("-rpc-port", dest="rpc_port", type=int, default=None)
    p.add_argument("-http-workers", dest="http_workers", type=int,
                   default=None,
                   help="total HTTP serving processes on the public port "
                        "(1 = agent only; N > 1 adds N-1 SO_REUSEPORT "
                        "workers)")
    p.add_argument("-protocol", dest="protocol", type=int, default=None,
                   help="protocol version to speak (vsn tag; "
                        "consul/config.go:92-94)")
    p.set_defaults(fn=cmd_agent)

    p = sub.add_parser("gossipd", help="Runs the TPU gossip plane daemon")
    p.add_argument("-bind", default="127.0.0.1")
    p.add_argument("-port", type=int, default=8310)
    p.add_argument("-unix", default="", help="serve on a unix socket")
    p.add_argument("-capacity", type=int, default=256,
                   help="real-agent universe size")
    p.add_argument("-sim-nodes", dest="sim_nodes", type=int, default=0,
                   help="simulated nodes sharing the kernel arrays")
    p.add_argument("-gossip-interval", dest="gossip_interval", type=float,
                   default=0.2, help="kernel round length (seconds)")
    p.add_argument("-hb-lapse", dest="hb_lapse", type=float, default=2.0,
                   help="heartbeat lapse before a node fails probes")
    p.add_argument("-suspicion-mult", dest="suspicion_mult", type=float,
                   default=4.0)
    p.add_argument("-slots", type=int, default=64)
    p.add_argument("-dissem", default="",
                   choices=("swar", "planes", "prefused", "fused"),
                   help="dissemination strategy; omit to take the "
                        "autotune verdict (obs/tuner.py), falling back "
                        "to swar when no verdict applies")
    p.add_argument("-shard-devices", dest="shard_devices", type=int,
                   default=-1,
                   help="device shards for the kernel round: -1 takes "
                        "the autotune verdict, 0 uses the all-devices "
                        "heuristic, >=1 forces that shard count")
    p.add_argument("-encrypt", action="append", default=[],
                   help="gossip key (base64); registrations must carry "
                        "a keyring HMAC proof (repeatable for rotation)")
    p.add_argument("-keyring-file", dest="keyring_file", default="",
                   help="load accepted keys from a serf keyring file")
    p.add_argument("-nemesis", default="",
                   help="run the kernel under a correlated-fault scenario "
                        "from the nemesis catalog (gossip/nemesis.py); "
                        "detection SLOs come back scenario-labeled at "
                        "/v1/agent/slo and in the Prometheus scrape")
    p.set_defaults(fn=cmd_gossipd)

    p = sub.add_parser("configtest", help="Validates config files/dirs")
    p.add_argument("-config-file", action="append", dest="config_file")
    p.add_argument("-config-dir", action="append", dest="config_dir")
    p.set_defaults(fn=cmd_configtest)

    p = sub.add_parser("debug", help="Capture a debug bundle from an agent")
    _add_http_flag(p)
    p.add_argument("-seconds", type=float, default=5.0,
                   help="metrics sample window (clamped to 0..30 agent-side)")
    p.add_argument("-output", default="",
                   help="output path (default consul-debug-<ts>.tar.gz)")
    p.set_defaults(fn=cmd_debug)

    p = sub.add_parser("event", help="Fire a user event")
    _add_http_flag(p)
    p.add_argument("-name", required=True)
    p.add_argument("-payload", default="")
    p.add_argument("-node", default="")
    p.add_argument("-service", default="")
    p.add_argument("-tag", default="")
    p.set_defaults(fn=cmd_event)

    p = sub.add_parser("exec", help="Remote execution across the cluster")
    _add_http_flag(p)
    p.add_argument("-node", default="")
    p.add_argument("-service", default="")
    p.add_argument("-tag", default="")
    p.add_argument("-wait", type=float, default=60.0)
    p.add_argument("command", nargs=argparse.REMAINDER)
    p.set_defaults(fn=cmd_exec)

    p = sub.add_parser("force-leave", help="Force a member to leave")
    _add_rpc_flag(p)
    p.add_argument("node")
    p.set_defaults(fn=cmd_force_leave)

    p = sub.add_parser("info", help="Agent runtime info")
    _add_rpc_flag(p)
    p.set_defaults(fn=cmd_info)

    p = sub.add_parser("join", help="Join a cluster")
    _add_rpc_flag(p)
    p.add_argument("-wan", action="store_true")
    p.add_argument("address", nargs="+")
    p.set_defaults(fn=cmd_join)

    p = sub.add_parser("keygen", help="Generate a gossip encryption key")
    p.set_defaults(fn=cmd_keygen)

    p = sub.add_parser("keyring", help="Manage gossip keyring")
    _add_rpc_flag(p)
    p.add_argument("-install", default="")
    p.add_argument("-use", default="")
    p.add_argument("-remove", default="")
    p.add_argument("-list", action="store_true")
    p.set_defaults(fn=cmd_keyring)

    p = sub.add_parser("leave", help="Gracefully leave the cluster")
    _add_rpc_flag(p)
    p.set_defaults(fn=cmd_leave)

    p = sub.add_parser("lock", help="Run a command holding a lock")
    _add_http_flag(p)
    p.add_argument("-n", type=int, default=1,
                   help="semaphore slots (1 = mutex)")
    p.add_argument("prefix")
    p.add_argument("child", nargs=argparse.REMAINDER)
    p.set_defaults(fn=cmd_lock)

    p = sub.add_parser("maint", help="Maintenance mode control")
    _add_http_flag(p)
    p.add_argument("-enable", action="store_true")
    p.add_argument("-disable", action="store_true")
    p.add_argument("-reason", default="")
    p.add_argument("-service", default="")
    p.set_defaults(fn=cmd_maint)

    p = sub.add_parser("members", help="List cluster members")
    _add_rpc_flag(p)
    p.add_argument("-wan", action="store_true")
    p.add_argument("-detailed", action="store_true",
                   help="show protocol versions")
    p.add_argument("-status", default="", help="regex filter on status")
    p.add_argument("-role", default="", help="regex filter on role tag")
    p.set_defaults(fn=cmd_members)

    p = sub.add_parser("monitor", help="Stream agent logs")
    _add_rpc_flag(p)
    p.add_argument("-log-level", dest="log_level", default="INFO")
    p.set_defaults(fn=cmd_monitor)

    p = sub.add_parser("reload", help="Trigger config reload")
    _add_rpc_flag(p)
    p.set_defaults(fn=cmd_reload)

    p = sub.add_parser("version", help="Print version")
    p.set_defaults(fn=cmd_version)

    p = sub.add_parser("watch", help="Run a watch plan")
    _add_http_flag(p)
    p.add_argument("-type", required=True)
    p.add_argument("-key", default="")
    p.add_argument("-prefix", default="")
    p.add_argument("-service", default="")
    p.add_argument("-tag", default="")
    p.add_argument("-state", default="")
    p.add_argument("-name", default="")
    p.add_argument("-passingonly", action="store_true")
    p.add_argument("-handler", default="")
    p.set_defaults(fn=cmd_watch)

    return ap


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
