"""Command-line interface: the reference's 16 subcommands
(``commands.go:19-141``) plus ``version``."""
