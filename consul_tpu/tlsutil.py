"""TLS wrappers for the RPC mesh.

Parity target: ``tlsutil/config.go`` (281 LoC): a Config producing an
incoming (server-side) SSLContext with optional client-cert
verification, and per-DC outgoing wrappers that verify the server
hostname as ``server.<dc>.<domain>`` (consul/config.go:107-113 — the
name every consul server presents in its certificate).
"""

from __future__ import annotations

import ssl
from dataclasses import dataclass
from typing import Optional


@dataclass
class TLSConfig:
    verify_incoming: bool = False
    verify_outgoing: bool = False
    ca_file: str = ""
    cert_file: str = ""
    key_file: str = ""
    domain: str = "consul."
    server_name: str = ""  # override for outgoing verification

    def incoming_context(self) -> Optional[ssl.SSLContext]:
        """IncomingTLSConfig: server side of the RPC listener."""
        if not (self.cert_file and self.key_file):
            return None
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(self.cert_file, self.key_file)
        if self.verify_incoming:
            if not self.ca_file:
                raise ValueError(
                    "VerifyIncoming set, and no CA certificate provided!")
            ctx.load_verify_locations(self.ca_file)
            ctx.verify_mode = ssl.CERT_REQUIRED
        return ctx

    def outgoing_wrapper(self) -> Optional["DCWrapper"]:
        """OutgoingTLSWrapper: per-DC client-side contexts."""
        if not self.verify_outgoing:
            return None
        if not self.ca_file:
            raise ValueError(
                "VerifyOutgoing set, and no CA certificate provided!")
        return DCWrapper(self)


class DCWrapper:
    """Callable(dc) -> SSLContext with server-hostname verification of
    ``server.<dc>.<domain>`` (tlsutil.SpecificDC, consul/server.go:457)."""

    def __init__(self, cfg: TLSConfig) -> None:
        self.cfg = cfg

    def __call__(self, dc: str) -> ssl.SSLContext:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.load_verify_locations(self.cfg.ca_file)
        if self.cfg.cert_file and self.cfg.key_file:
            ctx.load_cert_chain(self.cfg.cert_file, self.cfg.key_file)
        ctx.check_hostname = True
        ctx.verify_mode = ssl.CERT_REQUIRED
        return ctx

    def server_hostname(self, dc: str) -> str:
        if self.cfg.server_name:
            return self.cfg.server_name
        domain = self.cfg.domain.rstrip(".")
        return f"server.{dc or 'dc1'}.{domain}"
