"""Consensus plane: deterministic FSM + (soon) Raft replication.

Parity layer for the reference's consul/fsm.go + hashicorp/raft glue
(SURVEY.md §2.2-2.3).
"""

from consul_tpu.consensus.fsm import ConsulFSM

__all__ = ["ConsulFSM"]
