"""Raft log stores: durable append-only entry log + stable kv.

Parity target: the reference wires `raft-boltdb` as both LogStore and
StableStore plus a LogCache of 512 entries (consul/server.go:51-53,
357-368).  Here: a single append-only segment file with CRC-framed
records (msgpack body) and an in-memory index, fsync'd per append batch;
the stable store (term/vote) is a tiny JSON file written atomically.
An in-memory variant backs the compressed-timer test tier.
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from dataclasses import dataclass
from typing import Dict, List, Optional

import msgpack

# Entry types (hashicorp/raft LogType equivalents).
LOG_COMMAND = 0
LOG_NOOP = 1
LOG_BARRIER = 2
LOG_CONFIGURATION = 3  # peer-set change; data = msgpack list of peer ids


@dataclass
class LogEntry:
    index: int
    term: int
    type: int = LOG_COMMAND
    data: bytes = b""

    def pack(self) -> bytes:
        return msgpack.packb([self.index, self.term, self.type, self.data],
                             use_bin_type=True)

    @classmethod
    def unpack(cls, buf: bytes) -> "LogEntry":
        i, t, ty, d = msgpack.unpackb(buf, raw=False)
        return cls(index=i, term=t, type=ty, data=d)


class MemoryLogStore:
    """Volatile log + stable store for in-process cluster tests."""

    def __init__(self) -> None:
        self._entries: Dict[int, LogEntry] = {}
        self._first = 0
        self._last = 0
        self._stable: Dict[str, int | str] = {}

    def first_index(self) -> int:
        return self._first

    def last_index(self) -> int:
        return self._last

    def get(self, index: int) -> Optional[LogEntry]:
        return self._entries.get(index)

    def append(self, entries: List[LogEntry], sync: bool = True) -> None:
        for e in entries:
            self._entries[e.index] = e
            if self._first == 0:
                self._first = e.index
            self._last = max(self._last, e.index)

    def delete_from(self, index: int) -> None:
        """Drop index.. (conflict truncation)."""
        for i in range(index, self._last + 1):
            self._entries.pop(i, None)
        self._last = max(index - 1, 0)
        if self._last < self._first:
            self._first = 0

    def delete_to(self, index: int) -> None:
        """Drop ..index inclusive (post-snapshot compaction)."""
        lo = self._first or 1
        for i in range(lo, index + 1):
            self._entries.pop(i, None)
        self._first = index + 1 if self._last > index else 0
        if self._first == 0:
            self._last = 0

    def set_stable(self, key: str, val) -> None:
        self._stable[key] = val

    def get_stable(self, key: str, default=None):
        return self._stable.get(key, default)

    def sync(self) -> None:
        pass

    def close(self) -> None:
        pass


_REC_HDR = struct.Struct("<II")  # length, crc32


class FileLogStore(MemoryLogStore):
    """Append-only segment file with CRC framing, replayed at open.

    Truncations rewrite a compacted segment (logs are small between
    snapshots; snapshot+compact bounds replay cost the way the
    reference's BoltDB + FileSnapshotStore pairing does).
    """

    def __init__(self, path: str) -> None:
        super().__init__()
        self._dir = path
        os.makedirs(path, exist_ok=True)
        self._seg_path = os.path.join(path, "log.seg")
        self._stable_path = os.path.join(path, "stable.json")
        if os.path.exists(self._stable_path):
            with open(self._stable_path) as f:
                self._stable = json.load(f)
        self._replay()
        self._f = open(self._seg_path, "ab")

    def _replay(self) -> None:
        if not os.path.exists(self._seg_path):
            return
        with open(self._seg_path, "rb") as f:
            while True:
                hdr = f.read(_REC_HDR.size)
                if len(hdr) < _REC_HDR.size:
                    break
                length, crc = _REC_HDR.unpack(hdr)
                body = f.read(length)
                if len(body) < length or zlib.crc32(body) != crc:
                    break  # torn tail write — stop at last good record
                e = LogEntry.unpack(body)
                super().append([e])

    def append(self, entries: List[LogEntry], sync: bool = True) -> None:
        super().append(entries)
        for e in entries:
            body = e.pack()
            self._f.write(_REC_HDR.pack(len(body), zlib.crc32(body)) + body)
        self._f.flush()
        if sync:
            os.fsync(self._f.fileno())

    def sync(self) -> None:
        # fd-level only — safe to run in an executor thread while the
        # event loop keeps appending (the raft durability pump does).
        os.fsync(self._f.fileno())

    def _rewrite(self) -> None:
        self._f.close()
        tmp = self._seg_path + ".tmp"
        with open(tmp, "wb") as f:
            for i in sorted(self._entries):
                body = self._entries[i].pack()
                f.write(_REC_HDR.pack(len(body), zlib.crc32(body)) + body)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._seg_path)
        self._f = open(self._seg_path, "ab")

    def delete_from(self, index: int) -> None:
        super().delete_from(index)
        self._rewrite()

    def delete_to(self, index: int) -> None:
        super().delete_to(index)
        self._rewrite()

    def set_stable(self, key: str, val) -> None:
        super().set_stable(key, val)
        tmp = self._stable_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._stable, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._stable_path)

    def close(self) -> None:
        self._f.close()
