"""Raft consensus: leader election, replicated log, snapshots.

Parity target: the reference embeds `hashicorp/raft` wired up at
``consul/server.go:328-411`` (BoltDB log store, FileSnapshotStore
retaining 2, `raftApply` at ``consul/rpc.go:280-297``, leadership
watching via ``monitorLeadership`` → ``consul/leader.go:29``).  This is
a fresh asyncio implementation of the Raft protocol (Ongaro & Ousterhout)
— not a port: goroutine-per-connection becomes one task per follower
replication stream plus one role loop per node, and all message handlers
are synchronous (await-free) so each RPC is atomic under the event loop,
which stands in for the reference's per-struct mutexes.

Transport is pluggable: `MemoryTransport` wires an in-process cluster
for the compressed-timer test tier (SURVEY.md §4); the RPC mesh provides
the TCP transport (rpc/transport.py) the way the reference multiplexes
Raft onto port 8300 via RaftLayer (consul/raft_rpc.go).
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from consul_tpu.consensus.log import (
    LOG_BARRIER, LOG_COMMAND, LOG_CONFIGURATION, LOG_NOOP, LogEntry,
    MemoryLogStore)
from consul_tpu.consensus.snapshot import MemorySnapshotStore
from consul_tpu.obs import raftstats
from consul_tpu.obs import trace as obs_trace

import msgpack

FOLLOWER = "Follower"
CANDIDATE = "Candidate"
LEADER = "Leader"
SHUTDOWN = "Shutdown"


class TransportError(Exception):
    pass


class NotLeaderError(Exception):
    def __init__(self, leader: Optional[str] = None) -> None:
        super().__init__(f"node is not the leader (leader={leader})")
        self.leader = leader


def _fail_abandoned(fut: asyncio.Future, err: Exception) -> None:
    """Fail a future whose submitter may already be gone (timed out,
    cancelled, disconnected).  Pre-retrieving the exception keeps loop
    teardown from logging "exception was never retrieved"; a submitter
    still awaiting the future receives the error unchanged."""
    fut.set_exception(err)
    fut.add_done_callback(lambda f: f.cancelled() or f.exception())


@dataclass
class RaftConfig:
    """Timing knobs; the test tier compresses these the way the
    reference's testServerConfig does (consul/server_test.go:64-69)."""

    heartbeat_interval: float = 0.25
    election_timeout_min: float = 1.0
    election_timeout_max: float = 2.0
    rpc_timeout: float = 1.0
    max_append_entries: int = 64
    snapshot_threshold: int = 8192
    trailing_logs: int = 128
    # Leader lease (Raft §6.4 / "Scaling Strongly Consistent
    # Replication"): base lease window renewed by each acked
    # replication round.  0 = auto (election_timeout_min); negative
    # disables leases entirely.  The effective window is
    # min(lease_timeout, election_timeout_min) * (1 - lease_clock_skew)
    # so a deposed or partitioned leader's lease always expires before
    # any follower's election timer can fire, even with clock-rate
    # skew up to the configured margin.
    lease_timeout: float = 0.0
    lease_clock_skew: float = 0.15


@dataclass
class VoteReq:
    term: int
    candidate: str
    last_log_index: int
    last_log_term: int


@dataclass
class VoteResp:
    term: int
    granted: bool


@dataclass
class AppendReq:
    term: int
    leader: str
    prev_log_index: int
    prev_log_term: int
    entries: List[LogEntry]
    leader_commit: int


@dataclass
class AppendResp:
    term: int
    success: bool
    match_index: int = 0


@dataclass
class SnapReq:
    term: int
    leader: str
    last_index: int
    last_term: int
    peers: List[str]
    data: bytes


@dataclass
class SnapResp:
    term: int
    success: bool


class MemoryTransport:
    """In-process cluster fabric with partition injection for tests.

    ``faults`` (a chaos/broker.FaultBroker or None) adds directional
    drop/delay on top of the binary partition set: the broker is
    consulted once for the request leg and once for the reply leg, so
    asymmetric faults ("acks die, appends arrive") are expressible."""

    def __init__(self, latency: float = 0.0, faults: Any = None) -> None:
        self._nodes: Dict[str, "RaftNode"] = {}
        self._blocked: set[Tuple[str, str]] = set()
        self._latency = latency
        self.faults = faults

    def register(self, node: "RaftNode") -> None:
        self._nodes[node.id] = node

    def partition(self, a: str, b: str) -> None:
        self._blocked.add((a, b))
        self._blocked.add((b, a))

    def heal(self, a: str, b: str) -> None:
        self._blocked.discard((a, b))
        self._blocked.discard((b, a))

    def isolate(self, node: str) -> None:
        for other in self._nodes:
            if other != node:
                self.partition(node, other)

    def rejoin(self, node: str) -> None:
        for other in list(self._nodes):
            self.heal(node, other)

    async def call(self, src: str, dst: str, method: str, msg: Any) -> Any:
        if (src, dst) in self._blocked or dst not in self._nodes:
            raise TransportError(f"{src} -> {dst} unreachable")
        if self._latency:
            await asyncio.sleep(self._latency)
        if self.faults is not None:
            await self.faults.on_message(src, dst)  # request leg
        target = self._nodes[dst]
        if target.role == SHUTDOWN:
            raise TransportError(f"{dst} is down")
        resp = await target.handle(method, msg)
        if (dst, src) in self._blocked:  # reply lost
            raise TransportError(f"{dst} -> {src} reply dropped")
        if self.faults is not None:
            await self.faults.on_message(dst, src)  # reply leg
        return resp


class RaftNode:
    """One Raft participant.  `fsm` needs apply(index, data) -> Any,
    snapshot(last_index) -> bytes, restore(buf) -> int."""

    def __init__(self, node_id: str, peers: List[str], fsm: Any,
                 transport: Any, config: Optional[RaftConfig] = None,
                 log_store: Optional[MemoryLogStore] = None,
                 snap_store: Optional[Any] = None,
                 faults: Any = None) -> None:
        self.id = node_id
        self.peers = list(peers)  # includes self
        self.fsm = fsm
        self.transport = transport
        self.config = config or RaftConfig()
        # Fault seam (chaos/broker.NodeFaults or None).  Every time
        # read that feeds lease/election SAFETY goes through _now so a
        # chaos campaign can skew or jump this node's clock; wall-clock
        # measurements for the observatory stay on time.monotonic.
        self.faults = faults
        self._now: Callable[[], float] = (
            faults.clock.monotonic if faults is not None
            else time.monotonic)
        self.log = log_store if log_store is not None else MemoryLogStore()
        self.snaps = snap_store if snap_store is not None else MemorySnapshotStore()

        self.role = FOLLOWER
        self.current_term: int = self.log.get_stable("term", 0)
        self.voted_for: Optional[str] = self.log.get_stable("voted_for", None)
        self.leader_id: Optional[str] = None
        self.commit_index = 0
        self.last_applied = 0
        self._snap_index = 0
        self._snap_term = 0
        self._snap_peers: List[str] = list(peers)

        self.next_index: Dict[str, int] = {}
        self.match_index: Dict[str, int] = {}
        self._pending: Dict[int, asyncio.Future] = {}
        # Trace contexts of pending commands, by log index: the
        # durability pump applies committed entries OUTSIDE any request
        # task, so the submitting request's span context is stashed
        # here and re-activated around fsm.apply (obs/trace.py).
        self._trace_ctx: Dict[int, Any] = {}
        # Group-commit buffer (see _submit/_flush_appends).
        self._append_buf: List[LogEntry] = []
        self._buf_tail = 0
        self._flush_scheduled = False
        # Durability pump: appends hit the OS immediately (sync=False);
        # a background task fsyncs and advances durable_index, off the
        # event loop so heartbeats never stall behind the disk.  Quorum
        # accounting only ever counts durable entries.
        self.durable_index = 0
        self._dirty_evt = asyncio.Event()
        self._durable_waiters: List[Tuple[int, asyncio.Future]] = []
        # Staleness metadata: monotonic stamp of the last message from a
        # live leader (feeds QueryMeta.last_contact, consul/rpc.go:406).
        self.last_leader_contact: float = self._now()
        self._heartbeat_evt = asyncio.Event()
        self._step_down_evt = asyncio.Event()
        self._peer_evts: Dict[str, asyncio.Event] = {}
        self._tasks: List[asyncio.Task] = []
        self._repl_tasks: List[asyncio.Task] = []
        self._leader_obs: List[Callable[[bool], None]] = []
        self._snapshotting = False
        # Leader lease: per-peer monotonic SEND time of the most recent
        # replication round that peer acknowledged at our term.  The
        # lease anchor is the quorum-th most recent of these (self acks
        # implicitly); anchoring at send time bounds what a follower
        # could have promised before it reset its election timer.
        self._lease_ack: Dict[str, float] = {}
        # Own-term no-op index from _become_leader: until it commits, a
        # fresh leader's commit_index may lag entries its predecessor
        # acked, so the lease may not serve reads (Raft §6.4).
        self._lease_guard_index = 0
        # Consensus observatory (obs/raftstats.py).  None when compiled
        # out via CONSUL_TPU_RAFT_OBS=0 — every hot-path hook below is
        # then a single is-None test (the bench A/B leg).
        self.obs: Optional[raftstats.RaftStats] = (
            raftstats.RaftStats(node_id) if raftstats.enabled() else None)

        latest = self.snaps.latest()
        if latest is not None:
            meta, state = latest
            self.fsm.restore(state)
            self._snap_index, self._snap_term = meta.index, meta.term
            self._snap_peers = list(meta.peers)
            if meta.peers:
                self.peers = list(meta.peers)
            self.last_applied = meta.index
            self.commit_index = meta.index
            if self.log.first_index() and self.log.first_index() <= meta.index:
                self.log.delete_to(meta.index)
        # Replay any configuration entries so the peer set survives restart.
        for i in range(self.log.first_index() or 1, self.log.last_index() + 1):
            e = self.log.get(i)
            if e is not None and e.type == LOG_CONFIGURATION:
                self.peers = list(msgpack.unpackb(e.data, raw=False))

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if hasattr(self.transport, "register"):
            self.transport.register(self)
        loop = asyncio.get_event_loop()
        self.durable_index = self.last_log_index()
        self._tasks.append(loop.create_task(self._sync_pump()))
        if self.peers == [self.id]:
            # Single-node bootstrap: skip the election timeout and elect
            # immediately (the reference's EnableSingleNode fast path).
            self._tasks.append(loop.create_task(self._start_election()))
        self._tasks.append(loop.create_task(self._run()))

    async def _sync_pump(self) -> None:
        """Background group fsync: coalesces all appends that landed
        since the last sync into one fsync (executor thread, fd-level
        only), then advances durable_index, wakes durability waiters,
        and lets the leader's commit accounting move."""
        loop = asyncio.get_event_loop()
        # Chaos seam: the fsync callable may be wrapped with injected
        # stalls/errors (chaos/broker.NodeFaults.wrap_fsync).  The
        # wrapper runs in the executor thread, so an injected stall
        # blocks exactly what a seized disk would block — the fsync,
        # never the event loop — and an injected OSError rides the
        # retry path below.
        sync_fn = (self.faults.wrap_fsync(self.log.sync)
                   if self.faults is not None else self.log.sync)
        try:
            while self.role != SHUTDOWN:
                await self._dirty_evt.wait()
                self._dirty_evt.clear()
                target = self.log.last_index()
                if target <= self.durable_index:
                    continue
                try:
                    await loop.run_in_executor(None, sync_fn)
                except Exception:
                    # fd can vanish mid-fsync when a truncation rewrite
                    # swaps the segment file under us; the rewrite is
                    # itself fsynced, so just retry on the new fd.
                    self._dirty_evt.set()
                    await asyncio.sleep(0.01)
                    continue
                self.durable_index = max(self.durable_index, target)
                if self._durable_waiters:
                    rest = []
                    for idx, fut in self._durable_waiters:
                        if idx <= self.durable_index:
                            if not fut.done():
                                fut.set_result(None)
                        else:
                            rest.append((idx, fut))
                    self._durable_waiters = rest
                if self.role == LEADER:
                    self._maybe_advance_commit()
        except asyncio.CancelledError:
            pass
        finally:
            # The pump is the only resolver of durability waiters while
            # the node runs; if it dies — cancellation, or a bug
            # escaping the retry path — every _wait_durable caller
            # would hang until shutdown() drains the list.  Fail them
            # now: the append was never acknowledged as durable.
            for _idx, fut in self._durable_waiters:
                if not fut.done():
                    _fail_abandoned(fut, NotLeaderError(None))
            self._durable_waiters = []

    async def _wait_durable(self, index: int) -> None:
        if index <= self.durable_index:
            return
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._durable_waiters.append((index, fut))
        self._dirty_evt.set()
        await fut

    async def shutdown(self) -> None:
        self.role = SHUTDOWN
        # Durability waiters would hang forever once the pump dies; an
        # exception is the only honest answer (the append was never
        # acknowledged as durable).
        for _idx, fut in self._durable_waiters:
            if not fut.done():
                _fail_abandoned(fut, NotLeaderError(None))
        self._durable_waiters = []
        for t in self._repl_tasks + self._tasks:
            t.cancel()
        for t in self._repl_tasks + self._tasks:
            try:
                await t
            except asyncio.CancelledError:
                pass  # we just cancelled it
            except Exception:  # noqa: E02 — task's own failure; shutting down
                pass
        self._fail_pending(NotLeaderError(None))
        self.log.close() if hasattr(self.log, "close") else None

    def on_leader_change(self, cb: Callable[[bool], None]) -> None:
        """Register a leadership observer (monitorLeadership equivalent,
        consul/server.go:409)."""
        self._leader_obs.append(cb)

    # -- public API --------------------------------------------------------

    def is_leader(self) -> bool:
        return self.role == LEADER

    def last_log_index(self) -> int:
        return max(self.log.last_index(), self._snap_index)

    def last_log_term(self) -> int:
        last = self.log.last_index()
        if last:
            return self.log.get(last).term
        return self._snap_term

    async def apply(self, data: bytes, timeout: float = 30.0) -> Any:
        """Append a command; resolves with the FSM's return once committed
        (raft.Apply / raftApply, consul/rpc.go:280-297)."""
        span = obs_trace.child_span("raft-commit")
        try:
            return await self._submit(LOG_COMMAND, data, timeout)
        finally:
            obs_trace.finish_span(span)

    async def barrier(self, timeout: float = 30.0) -> int:
        """Commit round-trip proving current leadership (raft.Barrier /
        VerifyLeader, consul/rpc.go:413-417).  Returns the barrier
        entry's log index: once it commits, every entry below it is
        committed under the CURRENT term — the Raft §6.4 precondition
        for serving ReadIndex (a fresh leader's commit_index may lag
        entries its predecessor acked until its first own-term commit)."""
        _, index = await self._submit(LOG_BARRIER, b"", timeout,
                                      with_index=True)
        return index

    async def wait_applied(self, index: int, timeout: float = 30.0) -> None:
        """Block until the local FSM has applied up through ``index`` —
        the follower half of the ReadIndex protocol (Raft §6.4): after a
        leadership-verified commit index is known, a local read at
        applied >= index is linearizable."""
        loop = asyncio.get_event_loop()
        deadline = loop.time() + timeout
        while self.last_applied < index:
            if loop.time() > deadline:
                raise TimeoutError(
                    f"apply lag: {self.last_applied} < {index}")
            await asyncio.sleep(0.005)

    # -- leader lease ------------------------------------------------------

    def _lease_duration(self) -> float:
        """Effective lease window in seconds (<= 0 disables).

        Clamped to election_timeout_min regardless of config: the
        safety argument is that a quorum of followers reset their
        election timers no EARLIER than the lease anchor, so no new
        leader can exist until anchor + election_timeout_min — the
        lease must expire strictly before that, with margin for
        clock-rate skew."""
        lt = self.config.lease_timeout
        if lt < 0:
            return 0.0
        if lt == 0:
            lt = self.config.election_timeout_min
        lt = min(lt, self.config.election_timeout_min)
        return lt * (1.0 - self.config.lease_clock_skew)

    def _lease_anchor(self) -> float:
        """Quorum-th most recent acked-round send time (0.0 = none)."""
        need = self._quorum() - 1  # self acknowledges implicitly
        if need <= 0:
            return self._now()  # single-node: always freshly anchored
        acks = sorted((self._lease_ack.get(p, 0.0)
                       for p in self.peers if p != self.id), reverse=True)
        if len(acks) < need:
            return 0.0
        return acks[need - 1]

    def lease_valid(self, now: Optional[float] = None) -> bool:
        """True while this leader may serve consistent reads locally
        with no barrier/ReadIndex round-trip: it holds a live
        quorum-renewed lease AND has committed an entry of its own
        term (so commit_index is current, Raft §6.4)."""
        if self.role != LEADER:
            return False
        dur = self._lease_duration()
        if dur <= 0.0:
            return False
        if self.commit_index < self._lease_guard_index:
            return False
        anchor = self._lease_anchor()
        if anchor <= 0.0:
            return False
        if now is None:
            now = self._now()
        return now < anchor + dur

    def lease_read_index(self) -> Optional[int]:
        """Read-safe index under the leader lease, or None when the
        lease doesn't hold (caller falls back to ReadIndex)."""
        if not self.lease_valid():
            return None
        return self.commit_index

    def lease_remaining(self) -> float:
        """Seconds of lease validity left (0.0 when invalid)."""
        if not self.lease_valid():
            return 0.0
        return max(0.0, self._lease_anchor() + self._lease_duration()
                   - self._now())

    async def add_peer(self, peer: str, timeout: float = 30.0) -> None:
        if peer in self.peers:
            return
        new = self.peers + [peer]
        await self._submit(LOG_CONFIGURATION,
                           msgpack.packb(new, use_bin_type=True), timeout)

    async def remove_peer(self, peer: str, timeout: float = 30.0) -> None:
        if peer not in self.peers:
            return
        new = [p for p in self.peers if p != peer]
        await self._submit(LOG_CONFIGURATION,
                           msgpack.packb(new, use_bin_type=True), timeout)

    async def _submit(self, type_: int, data: bytes, timeout: float,
                      with_index: bool = False) -> Any:
        """Group commit (hashicorp/raft's applyBatch): entries submitted
        in the same event-loop tick are buffered and land in ONE
        log.append — one fsync for the whole batch — before replication
        is kicked.  Commit quorum only ever counts flushed entries
        (last_log_index reads the log, not the buffer)."""
        if self.role != LEADER:
            raise NotLeaderError(self.leader_id)
        if self._buf_tail == 0:
            self._buf_tail = self.last_log_index()
        self._buf_tail += 1
        entry = LogEntry(index=self._buf_tail, term=self.current_term,
                         type=type_, data=data)
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._pending[entry.index] = fut
        if type_ == LOG_COMMAND:
            ctx = obs_trace.current_context()
            if ctx is not None:
                self._trace_ctx[entry.index] = ctx
        self._append_buf.append(entry)
        if type_ == LOG_CONFIGURATION:
            # Apply eagerly, not at flush: a second membership change in
            # the same tick must see the first one's peer set.
            self._apply_configuration(entry)
        if not self._flush_scheduled:
            self._flush_scheduled = True
            asyncio.get_event_loop().call_soon(self._flush_appends)
        try:
            result = await asyncio.wait_for(fut, timeout)
        except (asyncio.CancelledError, asyncio.TimeoutError):
            # The submitter abandoned the entry; a later step-down may
            # still set NotLeaderError on fut.  Mark it retrieved so
            # loop teardown doesn't log "exception was never
            # retrieved" for an entry nobody is waiting on.
            fut.add_done_callback(lambda f: f.cancelled() or f.exception())
            raise
        return (result, entry.index) if with_index else result

    def _flush_appends(self) -> None:
        self._flush_scheduled = False
        batch, self._append_buf = self._append_buf, []
        self._buf_tail = 0
        if not batch or self.role != LEADER:
            for e in batch:
                fut = self._pending.pop(e.index, None)
                self._trace_ctx.pop(e.index, None)
                if fut is not None and not fut.done():
                    _fail_abandoned(fut, NotLeaderError(self.leader_id))
            return
        self.log.append(batch, sync=False)
        self._dirty_evt.set()
        if self.obs is not None:
            self.obs.note_append(batch[-1].index)
        # (LOG_CONFIGURATION entries were applied eagerly in _submit.)
        # Replication is kicked immediately (pipelined past our own
        # fsync); _maybe_advance_commit counts only durable_index for
        # self, so nothing commits before local durability.
        self._kick_replication()

    # -- role loop ---------------------------------------------------------

    async def _run(self) -> None:
        try:
            while self.role != SHUTDOWN:
                if self.role in (FOLLOWER, CANDIDATE):
                    timeout = random.uniform(self.config.election_timeout_min,
                                             self.config.election_timeout_max)
                    # The election timer ticks on THIS node's (possibly
                    # skewed) oscillator: a virtual duration T elapses
                    # in T/rate real seconds, which is what wait_for
                    # (real loop time) must be handed.
                    if self.faults is not None:
                        rate = self.faults.clock.rate
                        if rate > 0.0:
                            timeout /= rate
                    self._heartbeat_evt.clear()
                    try:
                        await asyncio.wait_for(self._heartbeat_evt.wait(), timeout)
                    except asyncio.TimeoutError:
                        if self.id in self.peers:
                            await self._start_election()
                elif self.role == LEADER:
                    self._step_down_evt.clear()
                    await self._step_down_evt.wait()
                    self._stop_leading()
        except asyncio.CancelledError:
            pass

    async def _start_election(self) -> None:
        self._become_candidate()
        term = self.current_term
        if self.obs is not None:
            self.obs.note_election(term)
        votes = 1  # self
        if votes >= self._quorum():
            self._become_leader()
            return

        async def ask(peer: str) -> bool:
            try:
                resp = await asyncio.wait_for(
                    self.transport.call(self.id, peer, "request_vote",
                                        VoteReq(term, self.id,
                                                self.last_log_index(),
                                                self.last_log_term())),
                    self.config.rpc_timeout)
            except (TransportError, asyncio.TimeoutError):
                return False
            if resp.term > self.current_term:
                self._become_follower(resp.term, None)
                return False
            return resp.granted

        results = await asyncio.gather(
            *(ask(p) for p in self.peers if p != self.id))
        if self.role != CANDIDATE or self.current_term != term:
            return
        votes += sum(results)
        if votes >= self._quorum():
            self._become_leader()

    def _quorum(self) -> int:
        return len(self.peers) // 2 + 1

    def _become_candidate(self) -> None:
        """The candidate transition ritual: bump the term, vote for
        self, persist BOTH before any RPC leaves (Raft §5.1 — a vote
        that does not survive a restart can be cast twice), and drop
        any lease state a prior leadership left behind."""
        self.role = CANDIDATE
        self.current_term += 1
        self.voted_for = self.id
        self._persist_term()
        self._lease_ack = {}  # a candidate holds no lease

    def _become_leader(self) -> None:
        self.role = LEADER
        self.leader_id = self.id
        last = self.last_log_index()
        self.next_index = {p: last + 1 for p in self.peers if p != self.id}
        self.match_index = {p: 0 for p in self.peers if p != self.id}
        self._peer_evts = {p: asyncio.Event() for p in self.peers if p != self.id}
        loop = asyncio.get_event_loop()
        self._repl_tasks = [loop.create_task(self._replicate(p))
                            for p in self.peers if p != self.id]
        # Commit-term guard: a no-op at the new term lets prior-term
        # entries commit (Raft §5.4.2).  Its index doubles as the lease
        # guard: the lease may not serve reads until it commits.
        entry = LogEntry(index=last + 1, term=self.current_term, type=LOG_NOOP)
        self._lease_guard_index = entry.index
        self._lease_ack = {}
        if self.obs is not None:
            self.obs.note_leader(self.current_term)
        self.log.append([entry])
        self._kick_replication()
        self._maybe_advance_commit()
        for cb in self._leader_obs:
            cb(True)

    def _stop_leading(self) -> None:
        for t in self._repl_tasks:
            t.cancel()
        self._repl_tasks = []
        self._lease_ack = {}  # deposed: the lease is gone with the role
        if self.obs is not None:
            self.obs.note_deposed(self.current_term, self.leader_id)
        self._fail_pending(NotLeaderError(self.leader_id))
        for cb in self._leader_obs:
            cb(False)

    def _become_follower(self, term: int, leader: Optional[str]) -> None:
        was_leader = self.role == LEADER
        if term > self.current_term:
            self.current_term = term
            self.voted_for = None
            self._persist_term()
        self.role = FOLLOWER
        # Deposed-leader-never-serves: drop the lease acks HERE, not
        # just in _stop_leading — the role loop runs _stop_leading a
        # scheduling turn later, and a lease_valid() caller in between
        # must not count a dead quorum as fresh.
        self._lease_ack = {}
        if leader is not None:
            if self.obs is not None and leader != self.leader_id:
                self.obs.note_new_leader(self.current_term, leader)
            self.leader_id = leader
        if was_leader:
            self._step_down_evt.set()

    def _persist_term(self) -> None:
        self.log.set_stable("term", self.current_term)
        self.log.set_stable("voted_for", self.voted_for)

    def _fail_pending(self, err: Exception) -> None:
        for fut in self._pending.values():
            if not fut.done():
                _fail_abandoned(fut, err)
        self._pending.clear()
        self._trace_ctx.clear()

    # -- replication (leader side) ----------------------------------------

    def _kick_replication(self) -> None:
        for evt in self._peer_evts.values():
            evt.set()

    async def _replicate(self, peer: str) -> None:
        """One follower's replication stream — the task-per-follower
        equivalent of hashicorp/raft's replicate goroutine."""
        cfg = self.config
        try:
            while self.role == LEADER:
                try:
                    await self._replicate_once(peer)
                except (TransportError, asyncio.TimeoutError):
                    if self.obs is not None:
                        self.obs.peer_fail(peer)
                    await asyncio.sleep(cfg.heartbeat_interval)
                    continue
                evt = self._peer_evts.get(peer)
                if evt is None:
                    return
                caught_up = self.next_index.get(peer, 1) > self.log.last_index()
                if caught_up:
                    try:
                        await asyncio.wait_for(evt.wait(), cfg.heartbeat_interval)
                    except asyncio.TimeoutError:
                        pass
                    evt.clear()
        except asyncio.CancelledError:
            pass

    async def _replicate_once(self, peer: str) -> None:
        ni = self.next_index.get(peer, 1)
        first = self.log.first_index()
        if self._snap_index and ni <= self._snap_index and (
                not first or ni < first):
            await self._send_snapshot(peer)
            return
        prev_index = ni - 1
        prev_term = self._term_at(prev_index)
        entries = []
        last = self.log.last_index()
        for i in range(ni, min(last, ni + self.config.max_append_entries - 1) + 1):
            e = self.log.get(i)
            if e is None:
                break
            entries.append(e)
        req = AppendReq(self.current_term, self.id, prev_index, prev_term,
                        entries, self.commit_index)
        sent = self._now()  # lease anchor: the node's own oscillator
        term = self.current_term
        if self.obs is not None:
            # Send-time sample: the renewal-time sample below can never
            # see an expired lease (the ack that triggers it has just
            # re-anchored the window), so a lease lost *between*
            # renewals — a clock jump, a stalled quorum — would leave
            # no timeline trace without this pre-send observation.
            self.obs.lease_observe(self.lease_remaining() * 1000.0, term)
        resp = await asyncio.wait_for(
            self.transport.call(self.id, peer, "append_entries", req),
            self.config.rpc_timeout)
        if resp.term > self.current_term:
            self._become_follower(resp.term, None)
            return
        if self.role != LEADER:
            return
        if self.current_term == term:
            # Lease renewal: any same-term response (even a log
            # conflict) means the follower processed our AppendEntries
            # at our term and reset its election timer no earlier than
            # `sent` — it cannot vote a new leader in before
            # sent + election_timeout_min.
            prev = self._lease_ack.get(peer, 0.0)
            if sent > prev:
                self._lease_ack[peer] = sent
            if self.obs is not None:
                self.obs.peer_ok(peer, sent)
                self.obs.lease_observe(
                    self.lease_remaining() * 1000.0, term)
        if resp.success:
            if entries:
                self.match_index[peer] = entries[-1].index
                self.next_index[peer] = entries[-1].index + 1
            self._maybe_advance_commit()
        else:
            # Conflict: fall back (follower hints its last index).
            self.next_index[peer] = max(1, min(ni - 1, resp.match_index + 1))

    async def _send_snapshot(self, peer: str) -> None:
        latest = self.snaps.latest()
        if latest is None:
            return
        meta, state = latest
        req = SnapReq(self.current_term, self.id, meta.index, meta.term,
                      meta.peers, state)
        t0 = time.monotonic()
        resp = await asyncio.wait_for(
            self.transport.call(self.id, peer, "install_snapshot", req),
            self.config.rpc_timeout * 4)
        if resp.term > self.current_term:
            self._become_follower(resp.term, None)
            return
        if resp.success:
            self.match_index[peer] = meta.index
            self.next_index[peer] = meta.index + 1
            if self.obs is not None:
                self.obs.snapshot_install.observe(
                    (time.monotonic() - t0) * 1000.0)
                self.obs.event("snapshot-sent", peer=peer,
                               index=meta.index)

    def _term_at(self, index: int) -> int:
        if index == 0:
            return 0
        if index == self._snap_index:
            return self._snap_term
        e = self.log.get(index)
        return e.term if e is not None else 0

    def _maybe_advance_commit(self) -> None:
        if self.role != LEADER:
            return
        # Self contributes its DURABLE prefix, not the buffered tail —
        # an entry may only count toward quorum where it is on stable
        # storage (the durability pump advances this and re-calls us).
        self_match = max(self._snap_index, self.durable_index)
        matches = sorted([self_match]
                         + [self.match_index.get(p, 0)
                            for p in self.peers if p != self.id],
                         reverse=True)
        n = matches[self._quorum() - 1]
        if n > self.commit_index and self._term_at(n) == self.current_term:
            self.commit_index = n
            if self.obs is not None:
                self.obs.note_commit(n)
            self._apply_committed()

    # -- apply -------------------------------------------------------------

    def _apply_committed(self) -> None:
        while self.last_applied < self.commit_index:
            # Batch the contiguous LOG_COMMAND run ending at the commit
            # point into ONE fsm.apply_batch call (PR 11): with a device
            # store attached that run becomes a single device scatter +
            # watch-match dispatch; without one it is the same
            # sequential loop as before. Trace contexts ride per-entry
            # (fsm._apply_one re-activates each submitter's span).
            run: list = []
            while self.last_applied + len(run) < self.commit_index:
                j = self.last_applied + len(run) + 1
                ej = self.log.get(j)
                if ej is None or ej.type != LOG_COMMAND:
                    break
                run.append((ej.index, ej.data, self._trace_ctx.pop(j, None)))
            if run:
                results = self._apply_run(run)
                for (idx, _, _), result in zip(run, results):
                    self.last_applied = idx
                    fut = self._pending.pop(idx, None)
                    if fut is not None and not fut.done():
                        if isinstance(result, Exception):
                            fut.set_exception(result)
                        else:
                            fut.set_result(result)
                continue
            i = self.last_applied + 1
            e = self.log.get(i)
            self.last_applied = i
            if e is None:  # compacted under us — snapshot already covers it
                continue
            # Non-command entry (noop/configuration): resolve its future.
            fut = self._pending.pop(i, None)
            if fut is not None and not fut.done():
                fut.set_result(None)
        if self.obs is not None:
            self.obs.note_applied(self.last_applied)
        self._maybe_snapshot()

    def _apply_run(self, run: list) -> list:
        """One contiguous LOG_COMMAND run → per-entry results. FSMs
        without an apply_batch hook (duck-typed test FSMs) get the
        pre-PR-11 sequential loop."""
        apply_batch = getattr(self.fsm, "apply_batch", None)
        if apply_batch is not None:
            return apply_batch(run)
        results = []
        for idx, data, ctx in run:
            token = obs_trace.set_context(ctx) if ctx is not None else None
            try:
                results.append(self.fsm.apply(idx, data))
            except Exception as exc:  # FSM errors surface to the caller
                results.append(exc)
            finally:
                if token is not None:
                    obs_trace.reset_context(token)
        return results

    def _apply_configuration(self, e: LogEntry) -> None:
        """Peer-set changes take effect as soon as they're appended
        (Raft one-at-a-time membership change rule)."""
        new_peers = list(msgpack.unpackb(e.data, raw=False))
        old = set(self.peers)
        self.peers = new_peers
        if self.role == LEADER:
            loop = asyncio.get_event_loop()
            for p in new_peers:
                if p not in old and p != self.id:
                    self.next_index[p] = self.last_log_index() + 1
                    self.match_index[p] = 0
                    self._peer_evts[p] = asyncio.Event()
                    self._repl_tasks.append(loop.create_task(self._replicate(p)))
            if self.id not in new_peers:
                self._become_follower(self.current_term, None)

    def _maybe_snapshot(self) -> None:
        since = self.last_applied - self._snap_index
        if since < self.config.snapshot_threshold or self._snapshotting:
            return
        self.take_snapshot()

    def take_snapshot(self) -> None:
        """Snapshot the FSM at last_applied and compact the log, keeping
        trailing_logs entries for laggards (FileSnapshotStore retain=2,
        consul/server.go:371)."""
        self._snapshotting = True
        try:
            state = self.fsm.snapshot(self.last_applied)
            term = self._term_at(self.last_applied) or self.current_term
            self.snaps.create(self.last_applied, term, list(self.peers), state)
            self._snap_index = self.last_applied
            self._snap_term = term
            cut = self.last_applied - self.config.trailing_logs
            if cut > 0 and self.log.first_index() and cut >= self.log.first_index():
                self.log.delete_to(cut)
            if self.obs is not None:
                self.obs.event("snapshot-taken", index=self._snap_index,
                               term=term)
        finally:
            self._snapshotting = False

    # -- handlers (synchronous => atomic under the event loop) -------------

    async def handle(self, method: str, msg: Any) -> Any:
        if method == "request_vote":
            return self._on_request_vote(msg)
        if method == "append_entries":
            resp = self._on_append_entries(msg)
            # A successful ack promises the entries are durable HERE —
            # the leader counts this node toward quorum on it.
            if resp.success and resp.match_index > self.durable_index:
                await self._wait_durable(resp.match_index)
            return resp
        if method == "install_snapshot":
            return self._on_install_snapshot(msg)
        raise ValueError(f"unknown raft rpc {method}")

    def _on_request_vote(self, req: VoteReq) -> VoteResp:
        if req.term < self.current_term:
            return VoteResp(self.current_term, False)
        if req.term > self.current_term:
            self._become_follower(req.term, None)
        up_to_date = (req.last_log_term, req.last_log_index) >= (
            self.last_log_term(), self.last_log_index())
        if up_to_date and self.voted_for in (None, req.candidate):
            self.voted_for = req.candidate
            self._persist_term()
            self._heartbeat_evt.set()  # granting a vote resets the timer
            return VoteResp(self.current_term, True)
        return VoteResp(self.current_term, False)

    def _on_append_entries(self, req: AppendReq) -> AppendResp:
        if req.term < self.current_term:
            return AppendResp(self.current_term, False, self.last_log_index())
        if req.term > self.current_term or self.role != FOLLOWER:
            self._become_follower(req.term, req.leader)
        if self.obs is not None and req.leader != self.leader_id:
            # First contact from a leader we voted for arrives with role
            # already FOLLOWER at its term — it bypasses
            # _become_follower, so the timeline event lands here.
            self.obs.note_new_leader(self.current_term, req.leader)
        self.leader_id = req.leader
        self.last_leader_contact = self._now()
        self._heartbeat_evt.set()

        if req.prev_log_index > 0:
            if req.prev_log_index > self.last_log_index():
                return AppendResp(self.current_term, False, self.last_log_index())
            if req.prev_log_index > self._snap_index:
                local = self.log.get(req.prev_log_index)
                if local is None or local.term != req.prev_log_term:
                    return AppendResp(self.current_term, False,
                                      max(self._snap_index,
                                          req.prev_log_index - 1))

        match = req.prev_log_index
        to_append: List[LogEntry] = []
        for e in req.entries:
            local = self.log.get(e.index)
            if local is not None and local.term != e.term:
                self.log.delete_from(e.index)
                # Re-written indexes are NOT durable until re-fsynced:
                # roll the watermark back or the ACK gate + sync pump
                # would treat the replacements as already on disk.
                self.durable_index = min(self.durable_index, e.index - 1)
                for i in list(self._pending):
                    if i >= e.index:
                        fut = self._pending.pop(i)
                        self._trace_ctx.pop(i, None)
                        if not fut.done():
                            _fail_abandoned(fut, NotLeaderError(req.leader))
                local = None
            if local is None and e.index > self.log.last_index() + len(to_append):
                to_append.append(e)
            match = e.index
        if to_append:
            # One buffered append for the whole batch; the ACK is held
            # until the durability pump has fsynced it (handle()).
            self.log.append(to_append, sync=False)
            self._dirty_evt.set()
            for e in to_append:
                if e.type == LOG_CONFIGURATION:
                    self._apply_configuration(e)

        if req.leader_commit > self.commit_index:
            self.commit_index = min(req.leader_commit, self.last_log_index())
            if self.obs is not None:
                self.obs.note_commit(self.commit_index)
            self._apply_committed()
        return AppendResp(self.current_term, True, match)

    def _on_install_snapshot(self, req: SnapReq) -> SnapResp:
        if req.term < self.current_term:
            return SnapResp(self.current_term, False)
        self._become_follower(req.term, req.leader)
        self.last_leader_contact = self._now()
        self._heartbeat_evt.set()
        if req.last_index <= self._snap_index:
            return SnapResp(self.current_term, True)
        t0 = time.monotonic()
        self.fsm.restore(req.data)
        self.snaps.create(req.last_index, req.last_term, req.peers, req.data)
        if self.log.first_index():
            self.log.delete_from(self.log.first_index())
        self._snap_index, self._snap_term = req.last_index, req.last_term
        self.peers = list(req.peers)
        self.commit_index = req.last_index
        self.last_applied = req.last_index
        if self.obs is not None:
            self.obs.snapshot_install.observe(
                (time.monotonic() - t0) * 1000.0)
            self.obs.event("snapshot-installed", leader=req.leader,
                           index=req.last_index)
        return SnapResp(self.current_term, True)

    # -- introspection -----------------------------------------------------

    def stats(self) -> Dict[str, str]:
        out = {
            "state": self.role,
            "term": str(self.current_term),
            "last_log_index": str(self.last_log_index()),
            "last_log_term": str(self.last_log_term()),
            "commit_index": str(self.commit_index),
            "applied_index": str(self.last_applied),
            "last_snapshot_index": str(self._snap_index),
            "num_peers": str(len(self.peers)),
            "lease": "valid" if self.lease_valid() else "invalid",
            "lease_remaining_ms": str(int(self.lease_remaining() * 1000)),
        }
        if self.obs is not None:
            out.update(self.obs.stats_rows())
        return out
