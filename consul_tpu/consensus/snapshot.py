"""File snapshot store, retaining the newest 2 (consul/server.go:38,371).

Each snapshot is a directory `snap-<term>-<index>` holding `meta.json`
and `state.bin` (the FSM's typed record stream).  Writes go to a temp
dir then rename — a crash never leaves a half-visible snapshot.
"""

from __future__ import annotations

import json
import os
import shutil
from dataclasses import dataclass
from typing import List, Optional, Tuple

RETAIN = 2


@dataclass
class SnapshotMeta:
    index: int
    term: int
    peers: List[str]
    size: int = 0


class FileSnapshotStore:
    def __init__(self, path: str, retain: int = RETAIN) -> None:
        self._dir = path
        self._retain = retain
        os.makedirs(path, exist_ok=True)

    def _snap_dir(self, term: int, index: int) -> str:
        return os.path.join(self._dir, f"snap-{term:020d}-{index:020d}")

    def create(self, index: int, term: int, peers: List[str], state: bytes) -> None:
        final = self._snap_dir(term, index)
        tmp = final + ".tmp"
        os.makedirs(tmp, exist_ok=True)
        with open(os.path.join(tmp, "state.bin"), "wb") as f:
            f.write(state)
            f.flush()
            os.fsync(f.fileno())
        meta = SnapshotMeta(index=index, term=term, peers=peers, size=len(state))
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump(meta.__dict__, f)
            f.flush()
            os.fsync(f.fileno())
        if os.path.exists(final):
            shutil.rmtree(final)
        os.replace(tmp, final)
        self._reap()

    def list(self) -> List[SnapshotMeta]:
        """Newest first."""
        metas = []
        for name in sorted(os.listdir(self._dir), reverse=True):
            if not name.startswith("snap-") or name.endswith(".tmp"):
                continue
            try:
                with open(os.path.join(self._dir, name, "meta.json")) as f:
                    metas.append(SnapshotMeta(**json.load(f)))
            except (OSError, json.JSONDecodeError, TypeError):
                continue
        return metas

    def latest(self) -> Optional[Tuple[SnapshotMeta, bytes]]:
        for meta in self.list():
            try:
                with open(os.path.join(self._snap_dir(meta.term, meta.index),
                                       "state.bin"), "rb") as f:
                    return meta, f.read()
            except OSError:
                continue
        return None

    def _reap(self) -> None:
        names = sorted((n for n in os.listdir(self._dir)
                        if n.startswith("snap-") and not n.endswith(".tmp")),
                       reverse=True)
        for name in names[self._retain:]:
            shutil.rmtree(os.path.join(self._dir, name), ignore_errors=True)


class MemorySnapshotStore:
    """Test-tier variant: same interface, no disk."""

    def __init__(self) -> None:
        self._snaps: List[Tuple[SnapshotMeta, bytes]] = []

    def create(self, index: int, term: int, peers: List[str], state: bytes) -> None:
        meta = SnapshotMeta(index=index, term=term, peers=peers, size=len(state))
        self._snaps.insert(0, (meta, state))
        del self._snaps[RETAIN:]

    def list(self) -> List[SnapshotMeta]:
        return [m for m, _ in self._snaps]

    def latest(self) -> Optional[Tuple[SnapshotMeta, bytes]]:
        return self._snaps[0] if self._snaps else None
