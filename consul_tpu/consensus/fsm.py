"""The replicated state machine: typed log entries -> state store.

Parity target: ``consul/fsm.go`` (537 LoC) — Apply dispatches on the
leading MessageType byte (fsm.go:76-110), unknown types with the
ignore-flag bit are skipped (fsm.go:83-88), snapshots are a header plus
a stream of typed msgpack records (fsm.go:262-404), and Restore rebuilds
a fresh store (fsm.go:275-363).

Determinism contract: Apply derives everything from (index, payload).
No clocks, no UUIDs, no map-iteration order leaks (the store sorts its
scans).  The guard test (test_determinism_guard.py) lints this module
and the store for wall-clock/uuid reads the way the reference's
verify_no_uuid.sh gates its FSM (Makefile:37).
"""

from __future__ import annotations

import io
from typing import Any, Callable, Dict, Optional

import msgpack

from consul_tpu.state.store import StateStore
from consul_tpu.structs import codec
from consul_tpu.structs.structs import (
    ACL,
    ACLOp,
    ACLRequest,
    DeregisterRequest,
    DirEntry,
    KVSOp,
    KVSRequest,
    MessageType,
    RegisterRequest,
    Session,
    SessionOp,
    SessionRequest,
    TombstoneRequest,
)

from time import monotonic as _monotonic

from consul_tpu.obs import journey as _journey
from consul_tpu.obs import trace as obs_trace
from consul_tpu.utils.telemetry import metrics

IGNORE_UNKNOWN_FLAG = 0x80  # high bit: safe-to-skip for old versions (fsm.go:25-30)

# Pre-built metric keys — apply() is the consistency hot loop.
_FSM_METRIC_KEYS = {int(t): ("consul", "fsm", t.name.lower())
                    for t in MessageType}
# Pre-built span names (spans are observational only — trace context is
# node-local and never enters replicated state).
_FSM_SPAN_NAMES = {int(t): f"fsm:{t.name.lower()}" for t in MessageType}

# Snapshot record kinds (one byte each, mirroring fsm.go's persist order).
SNAP_HEADER = "header"
SNAP_REGISTRATION = "registration"
SNAP_SERVICE = "service"
SNAP_CHECK = "check"
SNAP_KVS = "kvs"
SNAP_TOMBSTONE = "tombstone"
SNAP_SESSION = "session"
SNAP_ACL = "acl"


class ConsulFSM:
    """Applies Raft log entries to a StateStore."""

    def __init__(self, gc_hint: Optional[Callable[[int], None]] = None,
                 kv_backend_factory: Optional[Callable[[], Any]] = None) -> None:
        self._gc_hint = gc_hint
        # Factory, not instance: restore() rebuilds a FRESH store
        # (fsm.go:275-363), so the backend must be recreatable.
        self._kv_backend_factory = kv_backend_factory
        self.store = StateStore(gc_hint=gc_hint, kv_backend=self._new_backend())
        # Optional device twin (state/device_store.DeviceStoreBridge):
        # when attached, apply_batch ships each committed batch to the
        # device as one scatter + one watch-match dispatch.
        self.device: Optional[Any] = None
        # Batch-boundary health render hook (PR 18): called with the set
        # of service names a BATCH envelope touched, synchronously inside
        # the apply path — watch waiters only run at the next event-loop
        # iteration, so bytes rendered here are hot before the first
        # watcher wakes.  Observational only; never allowed to fail apply.
        self.health_render_hook: Optional[Callable[[Any], None]] = None
        self._handlers: Dict[int, Callable[[int, bytes], Any]] = {
            MessageType.REGISTER: self._apply_register,
            MessageType.DEREGISTER: self._apply_deregister,
            MessageType.KVS: self._apply_kvs,
            MessageType.SESSION: self._apply_session,
            MessageType.ACL: self._apply_acl,
            MessageType.TOMBSTONE: self._apply_tombstone,
            MessageType.BATCH: self._apply_batch_envelope,
        }

    def _new_backend(self):
        if self._kv_backend_factory is None:
            return None
        return self._kv_backend_factory()

    # -- apply -------------------------------------------------------------

    def apply(self, index: int, buf: bytes) -> Any:
        """Dispatch one log entry (fsm.go:76-110).  Returns the op result
        (None, bool for CAS-style ops, or an error string surfaced to the
        caller via raftApply)."""
        msg_type = buf[0]
        handler = self._handlers.get(msg_type & ~IGNORE_UNKNOWN_FLAG)
        if handler is None:
            if msg_type & IGNORE_UNKNOWN_FLAG:
                return None  # newer-version entry marked safe to ignore
            raise ValueError(f"failed to apply request: unknown type {msg_type}")
        # MeasureSince per message type (fsm.go:121 et al.)
        t0 = _monotonic()
        span = obs_trace.child_span(
            _FSM_SPAN_NAMES[msg_type & ~IGNORE_UNKNOWN_FLAG],
            tags={"index": index})
        try:
            return handler(index, buf[1:])
        finally:
            obs_trace.finish_span(span)
            metrics.measure_since(_FSM_METRIC_KEYS[msg_type & ~IGNORE_UNKNOWN_FLAG], t0)

    def attach_device_store(self, bridge: Any) -> None:
        """Attach the device twin and seed it from the current store
        (PR 11). Idempotent; restore() re-seeds automatically."""
        self.device = bridge
        bridge.rebuild_from_store(self.store)

    def _apply_one(self, index: int, data: bytes, ctx: Any) -> Any:
        """One entry with its submitter's trace context re-activated
        (moved from raft._apply_committed so batched and single apply
        share the span/metric/error contract). FSM errors are returned,
        not raised — raftApply surfaces them to the caller."""
        token = obs_trace.set_context(ctx) if ctx is not None else None
        try:
            return self.apply(index, data)
        except Exception as exc:
            return exc
        finally:
            if token is not None:
                obs_trace.reset_context(token)

    def apply_batch(self, entries) -> list:
        """Apply a contiguous run of committed entries — the commit→
        apply boundary batching hook (consensus/raft.py collects the
        runs; obs/raftstats.py already instruments the boundary).

        Without a device twin this is exactly the sequential loop
        (identical notify ordering, zero added work). With one, the
        whole run applies inside a ``store.capture_apply()`` scope:
        watch firing is deferred, the bridge ships the batch as one
        device scatter + one watch-match dispatch, cross-checks the
        verdicts, and fires the NotifyGroups. A bridge failure degrades
        to the host flush path — serving never depends on the device.
        """
        if self.device is None:
            return [self._apply_one(i, d, c) for i, d, c in entries]
        results = []
        with self.store.capture_apply() as cap:
            for index, data, ctx in entries:
                results.append(self._apply_one(index, data, ctx))
            try:
                self.device.on_batch(cap, self.store)
            except Exception:
                # cap stays unconsumed → scope exit host-fires it.
                metrics.incr_counter(("consul", "fsm", "device_batch_error"))
        return results

    def _apply_batch_envelope(self, index: int, payload: bytes) -> Any:
        """BATCH envelope (PR 18): a msgpack list of sub-entry buffers
        applied in order at the envelope's single raft index — the
        batched reconcile pass pays append→quorum once per cadence
        instead of once per transition.  Per-sub failures are isolated:
        the result list carries an error string in that slot (wire-safe
        for the leader-forward hop) and the remaining subs still apply,
        mirroring how N independent sequential entries would behave.

        With a device twin attached the envelope runs inside the run's
        ``capture_apply`` scope (apply_batch → _apply_one → here), so
        the whole batch is still one device scatter.  BATCH never
        appears in snapshots — the sub-effects are plain store records.
        """
        jy = _journey.journey
        t_j0 = _monotonic() if jy is not None else 0.0
        subs = msgpack.unpackb(payload, raw=False)
        touched = self._batch_touched_services(subs)
        results: list = []
        for sub in subs:
            sub = bytes(sub)
            try:
                results.append(self.apply(index, sub))
            except Exception as exc:
                results.append(f"{type(exc).__name__}: {exc}")
        if jy is not None:
            jy.note_fsm_apply((_monotonic() - t_j0) * 1000.0)
        hook = self.health_render_hook
        if hook is not None:
            self._batch_touched_services(subs, touched)
            try:
                hook(touched)
            except Exception:
                metrics.incr_counter(("consul", "fsm", "render_hook_error"))
        return results

    def _batch_touched_services(self, subs, acc: Optional[set] = None) -> set:
        """Service names a batch's catalog subs affect: explicit service
        registrations plus every service on a node whose node-level
        state (address, serfHealth) the batch writes.  Called before
        apply (pre-image: services a node deregister removes) and again
        after (post-image: services the batch created)."""
        out: set = set() if acc is None else acc
        nodes: set = set()
        for sub in subs:
            sub = bytes(sub)
            t = sub[0] & ~IGNORE_UNKNOWN_FLAG
            try:
                if t == MessageType.REGISTER:
                    req = codec.decode_payload(sub[1:], RegisterRequest)
                    if req.service is not None and req.service.service:
                        out.add(req.service.service)
                    nodes.add(req.node)
                elif t == MessageType.DEREGISTER:
                    req = codec.decode_payload(sub[1:], DeregisterRequest)
                    nodes.add(req.node)
            except Exception:
                continue  # malformed sub fails in apply(), not here
        for node in nodes:
            _, svcs = self.store.node_services(node)
            for svc in (svcs or {}).values():
                if svc.service:
                    out.add(svc.service)
        return out

    def _apply_register(self, index: int, payload: bytes) -> Any:
        req = codec.decode_payload(payload, RegisterRequest)
        self.store.ensure_registration(index, req)
        return None

    def _apply_deregister(self, index: int, payload: bytes) -> Any:
        """Granularity: check > service > whole node (fsm.go:130-155)."""
        req = codec.decode_payload(payload, DeregisterRequest)
        if req.check_id:
            self.store.delete_node_check(index, req.node, req.check_id)
        elif req.service_id:
            self.store.delete_node_service(index, req.node, req.service_id)
        else:
            self.store.delete_node(index, req.node)
        return None

    def _apply_kvs(self, index: int, payload: bytes) -> Any:
        req = codec.decode_payload(payload, KVSRequest)
        d = req.dir_ent
        op = req.op
        if op == KVSOp.SET.value:
            self.store.kvs_set(index, d)
            return None
        if op == KVSOp.DELETE.value:
            self.store.kvs_delete(index, d.key)
            return None
        if op == KVSOp.DELETE_TREE.value:
            self.store.kvs_delete_tree(index, d.key)
            return None
        if op == KVSOp.DELETE_CAS.value:
            return self.store.kvs_delete_check_and_set(index, d.key, d.modify_index)
        if op == KVSOp.CAS.value:
            return self.store.kvs_check_and_set(index, d)
        if op == KVSOp.LOCK.value:
            return self.store.kvs_lock(index, d)
        if op == KVSOp.UNLOCK.value:
            return self.store.kvs_unlock(index, d)
        raise ValueError(f"invalid KVS operation '{op}'")

    def _apply_session(self, index: int, payload: bytes) -> Any:
        req = codec.decode_payload(payload, SessionRequest)
        if req.op == SessionOp.CREATE.value:
            self.store.session_create(index, req.session)
            return req.session.id
        if req.op == SessionOp.DESTROY.value:
            self.store.session_destroy(index, req.session.id)
            return None
        raise ValueError(f"invalid session operation '{req.op}'")

    def _apply_acl(self, index: int, payload: bytes) -> Any:
        req = codec.decode_payload(payload, ACLRequest)
        if req.op == ACLOp.SET.value:
            self.store.acl_set(index, req.acl)
            return req.acl.id
        if req.op == ACLOp.DELETE.value:
            self.store.acl_delete(index, req.acl.id)
            return None
        raise ValueError(f"invalid ACL operation '{req.op}'")

    def _apply_tombstone(self, index: int, payload: bytes) -> Any:
        req = codec.decode_payload(payload, TombstoneRequest)
        self.store.reap_tombstones(req.reap_index)
        return None

    # -- snapshot / restore --------------------------------------------------

    def snapshot(self, last_index: int) -> bytes:
        """Serialize to a typed record stream (fsm.go:365-404): header with
        LastIndex, then every store record."""
        out = io.BytesIO()
        packer = msgpack.Packer(use_bin_type=True)
        out.write(packer.pack([SNAP_HEADER, {"last_index": last_index}]))
        for kind, payload in self.store.snapshot_records():
            if kind == SNAP_SERVICE:
                node, svc = payload
                wire = {"node": node, "service": svc.to_wire()}
            else:
                wire = payload.to_wire()
            out.write(packer.pack([kind, wire]))
        return out.getvalue()

    def restore(self, buf: bytes) -> int:
        """Rebuild a fresh store from a snapshot stream (fsm.go:275-363).
        Returns the snapshot's last_index."""
        # Close the old backend BEFORE recreating it — the native table
        # holds an mmap+fd on a file the new one rmtree's.
        self.store.close()
        self.store = StateStore(gc_hint=self._gc_hint,
                                kv_backend=self._new_backend())
        last_index = 0
        unpacker = msgpack.Unpacker(raw=False, strict_map_key=False)
        unpacker.feed(buf)
        from consul_tpu.structs.structs import HealthCheck, NodeService
        for kind, wire in unpacker:
            if kind == SNAP_HEADER:
                last_index = wire["last_index"]
            elif kind == SNAP_REGISTRATION:
                req = RegisterRequest.from_wire(wire)
                self.store.ensure_registration(last_index, req)
            elif kind == SNAP_SERVICE:
                svc = NodeService.from_wire(wire["service"])
                self.store.ensure_service(last_index, wire["node"], svc)
            elif kind == SNAP_CHECK:
                self.store.ensure_check(last_index, HealthCheck.from_wire(wire))
            elif kind == SNAP_KVS:
                self.store.kvs_restore(DirEntry.from_wire(wire))
            elif kind == SNAP_TOMBSTONE:
                self.store.tombstone_restore(DirEntry.from_wire(wire))
            elif kind == SNAP_SESSION:
                self.store.session_restore(Session.from_wire(wire))
            elif kind == SNAP_ACL:
                self.store.acl_restore(ACL.from_wire(wire))
            else:
                raise ValueError(f"unrecognized snapshot record kind {kind!r}")
        if self.device is not None:
            # The restore built a FRESH store — the device table follows.
            self.device.rebuild_from_store(self.store)
        return last_index
