"""Local health-check runners: script, HTTP, and TTL checks.

Parity target: ``command/agent/check.go`` (404 LoC).  A check type is
one of Script+Interval / HTTP+Interval / TTL (check.go:38-70); runners
push status transitions into the local state (the ``CheckNotifier``
contract), which anti-entropy then syncs to the catalog.

The reference runs each check on its own goroutine with timers; here
every runner is one asyncio task owned by the agent's event loop.
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass
from typing import Dict, Optional

from consul_tpu.structs.structs import (
    HEALTH_CRITICAL, HEALTH_PASSING, HEALTH_WARNING)

MIN_INTERVAL = 1.0        # checks faster than this are clamped (check.go:17-20)
OUTPUT_MAX = 4 * 1024     # CheckBufSize circular buffer (check.go:26)


@dataclass
class CheckType:
    """A check definition from config/API (check.go:38-70): exactly one
    of script/http/ttl must be set; script+http need an interval."""

    script: str = ""
    http: str = ""
    interval: float = 0.0
    ttl: float = 0.0
    notes: str = ""
    timeout: float = 0.0

    def valid(self) -> bool:
        return self.is_ttl() or self.is_monitor() or self.is_http()

    def is_ttl(self) -> bool:
        return self.ttl > 0

    def is_monitor(self) -> bool:
        return bool(self.script) and self.interval > 0

    def is_http(self) -> bool:
        return bool(self.http) and self.interval > 0


def _clip_output(data: bytes) -> str:
    """Keep the LAST 4KB, like the reference's circular buffer."""
    if len(data) > OUTPUT_MAX:
        data = data[-OUTPUT_MAX:]
    return data.decode("utf-8", errors="replace")


class CheckMonitor:
    """Periodic shell-out (check.go:79-200): exit 0 = passing,
    1 = warning, anything else (or timeout/spawn failure) = critical."""

    def __init__(self, notify, check_id: str, script: str, interval: float,
                 logger=None) -> None:
        self.notify = notify
        self.check_id = check_id
        self.script = script
        self.interval = max(interval, MIN_INTERVAL)
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        self._task = asyncio.get_event_loop().create_task(self._run())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _run(self) -> None:
        try:
            # Initial random stagger so a fleet of agents doesn't thundering-
            # herd its targets (check.go runs after one full interval).
            await asyncio.sleep(random.uniform(0, self.interval))
            while True:
                try:
                    await self._check()
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    # A runner must never die silently — a frozen check
                    # would keep serving its last (possibly passing) status.
                    self.notify.update_check(self.check_id, HEALTH_CRITICAL,
                                             f"check runner error: {e}")
                await asyncio.sleep(self.interval)
        except asyncio.CancelledError:
            pass

    async def _check(self) -> None:
        try:
            proc = await asyncio.create_subprocess_shell(
                self.script,
                stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.STDOUT)
        except OSError as e:
            self.notify.update_check(self.check_id, HEALTH_CRITICAL, str(e))
            return
        # 30s hard timeout (check.go:160-170 kills after 30s).
        try:
            out, _ = await asyncio.wait_for(proc.communicate(), timeout=30.0)
        except asyncio.TimeoutError:
            try:
                proc.kill()
            except ProcessLookupError:
                pass  # exited in the timeout window
            self.notify.update_check(self.check_id, HEALTH_CRITICAL,
                                     "Check timed out")
            return
        output = _clip_output(out or b"")
        code = proc.returncode
        if code == 0:
            status = HEALTH_PASSING
        elif code == 1:
            status = HEALTH_WARNING
        else:
            status = HEALTH_CRITICAL
        self.notify.update_check(self.check_id, status, output)


class CheckHTTP:
    """Periodic GET (check.go:302+): 2xx = passing, 429 = warning,
    anything else = critical; body is the check output."""

    def __init__(self, notify, check_id: str, url: str, interval: float,
                 timeout: float = 0.0) -> None:
        self.notify = notify
        self.check_id = check_id
        self.url = url
        self.interval = max(interval, MIN_INTERVAL)
        self.timeout = timeout if timeout > 0 else min(10.0, self.interval)
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        self._task = asyncio.get_event_loop().create_task(self._run())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _run(self) -> None:
        try:
            import httpx
            async with httpx.AsyncClient(timeout=self.timeout) as client:
                await asyncio.sleep(random.uniform(0, self.interval))
                while True:
                    await self._check(client)
                    await asyncio.sleep(self.interval)
        except asyncio.CancelledError:
            pass

    async def _check(self, client) -> None:
        try:
            resp = await client.get(self.url)
        except Exception as e:
            self.notify.update_check(self.check_id, HEALTH_CRITICAL, str(e))
            return
        output = _clip_output(resp.content)
        if 200 <= resp.status_code < 300:
            self.notify.update_check(self.check_id, HEALTH_PASSING, output)
        elif resp.status_code == 429:
            self.notify.update_check(self.check_id, HEALTH_WARNING, output)
        else:
            self.notify.update_check(
                self.check_id, HEALTH_CRITICAL,
                f"HTTP GET {self.url}: {resp.status_code} Output: {output}")


class CheckTTL:
    """Deadman timer (check.go:202-265): the app must call set_status
    within the TTL or the check flips critical."""

    def __init__(self, notify, check_id: str, ttl: float) -> None:
        self.notify = notify
        self.check_id = check_id
        self.ttl = ttl
        self._handle: Optional[asyncio.TimerHandle] = None

    def start(self) -> None:
        self._arm()

    def stop(self) -> None:
        if self._handle is not None:
            self._handle.cancel()
            self._handle = None

    def _arm(self) -> None:
        self.stop()
        self._handle = asyncio.get_event_loop().call_later(self.ttl, self._expire)

    def _expire(self) -> None:
        self._handle = None
        self.notify.update_check(
            self.check_id, HEALTH_CRITICAL,
            f"TTL expired (no update within {self.ttl}s)")

    def set_status(self, status: str, output: str) -> None:
        """App heartbeat: record status and re-arm the timer."""
        self.notify.update_check(self.check_id, status, output)
        self._arm()


class CheckRunnerSet:
    """Owns every live runner for an agent; keyed by check id."""

    def __init__(self) -> None:
        self.monitors: Dict[str, CheckMonitor] = {}
        self.https: Dict[str, CheckHTTP] = {}
        self.ttls: Dict[str, CheckTTL] = {}

    def start_check(self, notify, check_id: str, ct: CheckType) -> None:
        self.stop_check(check_id)
        if ct.is_ttl():
            r = CheckTTL(notify, check_id, ct.ttl)
            self.ttls[check_id] = r
        elif ct.is_http():
            r = CheckHTTP(notify, check_id, ct.http, ct.interval, ct.timeout)
            self.https[check_id] = r
        elif ct.is_monitor():
            r = CheckMonitor(notify, check_id, ct.script, ct.interval)
            self.monitors[check_id] = r
        else:
            raise ValueError("check must define Script+Interval, "
                             "HTTP+Interval, or TTL")
        r.start()

    def stop_check(self, check_id: str) -> None:
        for pool in (self.monitors, self.https, self.ttls):
            r = pool.pop(check_id, None)
            if r is not None:
                r.stop()

    def stop_all(self) -> None:
        for pool in (self.monitors, self.https, self.ttls):
            for r in pool.values():
                r.stop()
            pool.clear()

    def ttl_check(self, check_id: str) -> Optional[CheckTTL]:
        return self.ttls.get(check_id)
