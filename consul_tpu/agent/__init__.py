"""Edge agent: HTTP/DNS surfaces over an embedded server or client.

Parity layer for the reference's command/agent/ (SURVEY.md §2.6).
"""

from consul_tpu.agent.agent import Agent, AgentConfig

__all__ = ["Agent", "AgentConfig"]
