"""Remote execution: run a shell job delivered by a ``_rexec`` event and
stream results back through the KV store.

Parity target: ``command/agent/remote_exec.go`` (321 LoC): on a
``_rexec`` event the agent fetches the job spec from KV
``<prefix>/<session>/job``, verifies the session is still alive, writes
an ack under ``<prefix>/<session>/<node>/ack``, spawns the shell, and
streams chunked output (4KB / 500ms flush, :28-37) to
``.../<node>/out/<NNNNN>`` plus the exit code to ``.../<node>/exit``.
"""

from __future__ import annotations

import asyncio
import json
from typing import Optional

from consul_tpu.structs.structs import (
    DirEntry, KVSOp, KVSRequest, KeyRequest, UserEvent)

CHUNK_SIZE = 4 * 1024        # remoteExecOutputSize
FLUSH_INTERVAL = 0.5         # remoteExecOutputDeadline
EXEC_TIMEOUT = 60.0


class RemoteExecutor:
    """One agent's _rexec handler; KV access goes through the embedded
    server (client mode will route via RPC)."""

    def __init__(self, agent) -> None:
        self.agent = agent

    async def _kv_get(self, key: str) -> Optional[DirEntry]:
        _, ents = await self.agent.server.kvs.get(KeyRequest(key=key))
        return ents[0] if ents else None

    async def _kv_put(self, key: str, value: bytes,
                      session: str = "") -> bool:
        """Session-acquired writes (the reference acquires every result key
        with the job session) so Behavior=delete reaps them with the job."""
        d = DirEntry(key=key, value=value)
        op = KVSOp.SET.value
        if session:
            d.session = session
            op = KVSOp.LOCK.value
        return bool(await self.agent.server.kvs.apply(
            KVSRequest(op=op, dir_ent=d)))

    async def handle(self, event: UserEvent) -> None:
        """handleRemoteExec (remote_exec.go:53-145)."""
        try:
            payload = json.loads(event.payload.decode() or "{}")
            prefix = payload.get("Prefix", "_rexec")
            session = payload.get("Session", "")
            if not session:
                return
            # Verify the session is still alive — the orchestrator holds it
            # for the job's lifetime (remote_exec.go:76-90).
            from consul_tpu.structs.structs import QueryOptions
            _, sess = await self.agent.server.session.get(
                session, QueryOptions(allow_stale=True))
            if sess is None:
                return
            spec_ent = await self._kv_get(f"{prefix}/{session}/job")
            if spec_ent is None:
                return
            spec = json.loads(spec_ent.value.decode())
            cmd = spec.get("Command", "")
            if not cmd:
                return
            node = self.agent.node_name
            if not await self._kv_put(f"{prefix}/{session}/{node}/ack", b"",
                                      session=session):
                return  # session died while acking; job is void
            await self._run(prefix, session, node, cmd,
                            spec.get("Wait", 0) or EXEC_TIMEOUT)
        except (json.JSONDecodeError, ValueError):
            return

    async def _run(self, prefix: str, session: str, node: str,
                   cmd: str, timeout: float) -> None:
        """Spawn + stream (remote_exec.go:147-260)."""
        try:
            proc = await asyncio.create_subprocess_shell(
                cmd, stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.STDOUT)
        except OSError:
            await self._kv_put(f"{prefix}/{session}/{node}/exit",
                               str(127).encode(), session=session)
            return

        loop = asyncio.get_event_loop()
        deadline = loop.time() + timeout
        chunk_idx = 0
        buf = b""
        last_flush = loop.time()

        async def flush(force: bool = False) -> None:
            nonlocal buf, chunk_idx, last_flush
            now = loop.time()
            if buf and (force or len(buf) >= CHUNK_SIZE
                        or now - last_flush >= FLUSH_INTERVAL):
                await self._kv_put(
                    f"{prefix}/{session}/{node}/out/{chunk_idx:05x}", buf,
                    session=session)
                chunk_idx += 1
                buf = b""
                last_flush = now

        # The deadline bounds the WHOLE run, not just the post-EOF wait —
        # a never-exiting command must not leak a subprocess per job.
        timed_out = False
        while True:
            remaining = deadline - loop.time()
            if remaining <= 0:
                timed_out = True
                break
            try:
                data = await asyncio.wait_for(
                    proc.stdout.read(CHUNK_SIZE),
                    min(FLUSH_INTERVAL, remaining))
            except asyncio.TimeoutError:
                await flush()
                continue
            if not data:
                break
            buf += data
            await flush()
        if not timed_out:
            try:
                await asyncio.wait_for(proc.wait(),
                                       max(0.0, deadline - loop.time()))
            except asyncio.TimeoutError:
                timed_out = True
        if timed_out:
            try:
                proc.kill()
            except ProcessLookupError:
                pass
            await proc.wait()
        await flush(force=True)
        code = proc.returncode if proc.returncode is not None else 0
        await self._kv_put(f"{prefix}/{session}/{node}/exit",
                           str(code).encode(), session=session)
