"""HTTP API server: the `/v1/...` surface.

Parity target: ``command/agent/http.go`` (route table :194-279, wrapper
:282-346, blocking-query params :418-441, consistency :443-457, index
headers :383-409) plus the per-domain endpoint files
(``kvs_endpoint.go``, ``session_endpoint.go``, ``catalog_endpoint.go``,
``health_endpoint.go``, ``status_endpoint.go``, ``ui_endpoint.go``).

JSON key casing follows the reference's Go marshaling (CamelCase with
ID/TTL acronyms preserved), so existing Consul clients parse our
responses unchanged; ``Value`` is base64 as in the reference API.
"""

from __future__ import annotations

import base64
import json
from typing import Any, Dict, Optional

from aiohttp import web

from consul_tpu.server.endpoints import EndpointError, parse_duration
from consul_tpu.structs.structs import (
    DeregisterRequest,
    DirEntry,
    HealthCheck,
    KeyListRequest,
    KeyRequest,
    KVSOp,
    KVSRequest,
    NodeService,
    QueryMeta,
    QueryOptions,
    RegisterRequest,
    SERF_CHECK_ID,
    Session,
    SessionOp,
    SessionRequest,
)

# snake_case wire names -> reference JSON keys (Go marshaling).
_KEY_OVERRIDES = {
    "id": "ID", "check_id": "CheckID", "service_id": "ServiceID",
    "ttl": "TTL", "ltime": "LTime",
}


def api_key(name: str) -> str:
    if name in _KEY_OVERRIDES:
        return _KEY_OVERRIDES[name]
    return "".join(_KEY_OVERRIDES.get(p, p.capitalize()) for p in name.split("_"))


def to_api(obj: Any) -> Any:
    """Wire dict/struct -> reference-shaped JSON value."""
    if hasattr(obj, "to_wire"):
        obj = obj.to_wire()
    if isinstance(obj, dict):
        return {api_key(k): to_api(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_api(v) for v in obj]
    if isinstance(obj, bytes):
        return base64.b64encode(obj).decode("ascii")
    return obj


def session_to_api(sess: Session) -> Dict[str, Any]:
    out = to_api(sess)
    # Go marshals time.Duration as integer nanoseconds.
    out["LockDelay"] = int(sess.lock_delay * 1e9)
    return out


class HTTPServer:
    """Routes + the wrap() conventions of the reference."""

    def __init__(self, agent) -> None:
        self.agent = agent
        self.app = web.Application()
        self._register_routes()
        self._runner: Optional[web.AppRunner] = None
        self.addr: Optional[tuple] = None
        self.https_addr: Optional[tuple] = None
        self.unix_path: Optional[str] = None
        self.internal_unix_path: Optional[str] = None

    @property
    def srv(self):
        return self.agent.server

    # -- lifecycle ---------------------------------------------------------

    async def start(self, host: str = "127.0.0.1", port: int = 8500,
                    unix_path: str | None = None,
                    https_port: int = -1,
                    ssl_context=None,
                    reuse_port: bool = False,
                    internal_unix_path: str | None = None) -> None:
        """Mount the API on every configured listener.

        The reference serves the same mux over plain HTTP, HTTPS, and
        unix sockets through one route table
        (``command/agent/http.go:44-173``; unix-socket addresses from
        ``config.go`` UnixSockets).  Here: one aiohttp app, one runner,
        N sites — ``port`` (TCP) or ``unix_path`` for HTTP (port < 0
        disables TCP), plus an HTTPS TCPSite when ``https_port > 0``.
        """
        import os

        # Don't let in-flight blocking queries (up to 600s) stall shutdown.
        self._runner = web.AppRunner(self.app, access_log=None,
                                     shutdown_timeout=0.5)
        await self._runner.setup()
        if unix_path:
            # The reference unlinks a stale socket before binding
            # (http.go:71-76).
            try:
                os.unlink(unix_path)
            except FileNotFoundError:
                pass
            site = web.UnixSite(self._runner, unix_path)
            await site.start()
            self.unix_path = unix_path
        elif port >= 0:
            # reuse_port: SO_REUSEPORT so the http_workers processes
            # can bind the same port (agent/workers.py); the kernel
            # spreads accepted connections across all listeners.
            site = web.TCPSite(self._runner, host, port,
                               reuse_port=reuse_port or None)
            await site.start()
            self.addr = site._server.sockets[0].getsockname()[:2]
        if internal_unix_path:
            # Workers proxy every non-hot route here — the same app,
            # reachable without racing the public-port load balancing.
            try:
                os.unlink(internal_unix_path)
            except FileNotFoundError:
                pass
            isite = web.UnixSite(self._runner, internal_unix_path)
            await isite.start()
            self.internal_unix_path = internal_unix_path
        if https_port > 0 and ssl_context is not None:
            ssite = web.TCPSite(self._runner, host, https_port,
                                ssl_context=ssl_context)
            await ssite.start()
            self.https_addr = ssite._server.sockets[0].getsockname()[:2]

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()

    # -- request plumbing ---------------------------------------------------

    def _register_routes(self) -> None:
        """Route table (command/agent/http.go:194-279)."""
        r = self.app.router
        h = self._handler
        r.add_get("/v1/status/leader", h(self._status_leader))
        r.add_get("/v1/status/peers", h(self._status_peers))
        r.add_get("/v1/status/lease", h(self._status_lease))

        r.add_put("/v1/catalog/register", h(self._catalog_register))
        r.add_put("/v1/catalog/deregister", h(self._catalog_deregister))
        r.add_get("/v1/catalog/datacenters", h(self._catalog_datacenters))
        r.add_get("/v1/catalog/nodes", h(self._catalog_nodes))
        r.add_get("/v1/catalog/services", h(self._catalog_services))
        r.add_get("/v1/catalog/service/{service}", h(self._catalog_service_nodes))
        r.add_get("/v1/catalog/node/{node}", h(self._catalog_node_services))

        r.add_get("/v1/health/node/{node}", h(self._health_node_checks))
        r.add_get("/v1/health/checks/{service}", h(self._health_service_checks))
        r.add_get("/v1/health/state/{state}", h(self._health_checks_in_state))
        r.add_get("/v1/health/service/{service}", h(self._health_service_nodes))

        for method in ("GET", "PUT", "DELETE"):
            r.add_route(method, "/v1/kv/{key:.*}", h(self._kvs))

        r.add_put("/v1/session/create", h(self._session_create))
        r.add_put("/v1/session/destroy/{id}", h(self._session_destroy))
        r.add_put("/v1/session/renew/{id}", h(self._session_renew))
        r.add_get("/v1/session/info/{id}", h(self._session_info))
        r.add_get("/v1/session/node/{node}", h(self._session_node))
        r.add_get("/v1/session/list", h(self._session_list))

        r.add_put("/v1/acl/create", h(self._acl_create))
        r.add_put("/v1/acl/update", h(self._acl_update))
        r.add_put("/v1/acl/destroy/{id}", h(self._acl_destroy))
        r.add_get("/v1/acl/info/{id}", h(self._acl_info))
        r.add_put("/v1/acl/clone/{id}", h(self._acl_clone))
        r.add_get("/v1/acl/list", h(self._acl_list))

        r.add_get("/v1/internal/ui/nodes", h(self._ui_nodes))
        r.add_get("/v1/internal/ui/node/{node}", h(self._ui_node_info))
        r.add_get("/v1/internal/ui/services", h(self._ui_services))

        # Bundled web UI at /ui/ (the reference serves its Ember app the
        # same way, command/agent/http.go:267-270); config ui_dir
        # overrides the packaged app.
        import os as _os
        ui_dir = (self.agent.config.extra.get("ui_dir")
                  or _os.path.join(_os.path.dirname(_os.path.dirname(
                      _os.path.abspath(__file__))), "ui"))
        index = _os.path.join(ui_dir, "index.html")
        if _os.path.isfile(index):

            async def ui_root(request):
                raise web.HTTPFound("/ui/")

            async def ui_index(request):
                return web.FileResponse(index)

            r.add_get("/ui", h(ui_root))
            r.add_get("/ui/", h(ui_index))
            r.add_static("/ui/", ui_dir)

        # pprof-role profiling endpoints, gated exactly like the
        # reference's EnableDebug (command/agent/http.go:259-264).
        if self.agent.config.enable_debug:
            from consul_tpu.agent import debug
            debug.register(r, h)

        self.agent.register_http_routes(r, h)

    def _handler(self, fn):
        """wrap() (http.go:282-346): invoke, time, map errors, JSON-encode.

        Each request is also the ROOT of a distributed trace: every
        RPC the handler forwards carries this span's context over the
        wire, and the backhauled remote spans land in this node's
        trace ring (obs/trace.py)."""
        import time as _time

        from consul_tpu.obs import trace as obs_trace
        from consul_tpu.obs.reqstats import reqstats
        from consul_tpu.utils.telemetry import metrics
        name = fn.__name__.lstrip("_")
        mkey = ("consul", "http", name)

        async def handle(request: web.Request) -> web.Response:
            t0 = _time.monotonic()
            span = obs_trace.root_span(
                f"http:{name}",
                tags={"method": request.method, "path": request.path})
            try:
                resp = await fn(request)
                if isinstance(resp, web.StreamResponse):
                    return resp  # covers Response AND FileResponse
                return self._json(request, resp)
            except web.HTTPException:
                raise  # redirects/aiohttp statuses pass through untouched
            except EndpointError as e:
                span.set_error(e)
                return web.Response(status=400, text=str(e))
            except PermissionError as e:
                span.set_error(e)
                return web.Response(status=403, text=str(e) or "Permission denied")
            except NotFound as e:
                span.set_error(e)
                return web.Response(status=404, text=str(e))
            except Exception as e:  # 500 + message, as the reference wrap()
                span.set_error(e)
                return web.Response(status=500, text=f"{type(e).__name__}: {e}")
            finally:
                span.finish()
                metrics.measure_since(mkey, t0)
                reqstats.record(name, (_time.monotonic() - t0) * 1000)

        return handle

    def _json(self, request: web.Request, value: Any,
              meta: Optional[QueryMeta] = None) -> web.Response:
        # Compact separators on the hot path — json.dumps pads with
        # ", "/": " when indent=None; pretty only on explicit ?pretty.
        if "pretty" in request.query:
            body = json.dumps(value, indent=4)
        else:
            body = json.dumps(value, separators=(",", ":"))
        resp = web.Response(text=body, content_type="application/json")
        if meta is not None:
            self._set_index_headers(resp, meta)
        return resp

    def _hot_response(self, status: int, hdrs: Dict[str, str], ct: str,
                      body: bytes) -> web.Response:
        # charset matches the text= responses of the generic path so
        # hot/generic stay header-identical (tests/test_serving.py).
        return web.Response(status=status, body=body, content_type=ct,
                            charset="utf-8" if ct.startswith(
                                ("application/json", "text/")) else None,
                            headers=hdrs or None)

    def _set_index_headers(self, resp: web.Response, meta: QueryMeta) -> None:
        """X-Consul-* headers (http.go:383-409)."""
        resp.headers["X-Consul-Index"] = str(meta.index)
        resp.headers["X-Consul-KnownLeader"] = "true" if meta.known_leader else "false"
        resp.headers["X-Consul-LastContact"] = str(int(meta.last_contact * 1000))

    def _token(self, request: web.Request) -> str:
        """?token with fallback to the agent's configured default token
        (http.go parseToken: explicit > agent ACLToken)."""
        return request.query.get("token", "") or self.agent.config.acl_token

    def _query_opts(self, request: web.Request) -> QueryOptions:
        """parseWait + parseConsistency + dc/token (http.go:411-485)."""
        q = request.query
        opts = QueryOptions(
            token=self._token(request),
            datacenter=q.get("dc", ""),
        )
        if "index" in q:
            try:
                opts.min_query_index = int(q["index"])
            except ValueError:
                raise EndpointError("Invalid index")
        if "wait" in q:
            try:
                opts.max_query_time = parse_duration(q["wait"])
            except ValueError:
                raise EndpointError("Invalid wait time")
        if "stale" in q:
            opts.allow_stale = True
        if "consistent" in q:
            opts.require_consistent = True
        if opts.allow_stale and opts.require_consistent:
            raise EndpointError("Cannot specify ?stale with ?consistent, conflicting semantics.")
        return opts

    async def _body_json(self, request: web.Request) -> Any:
        raw = await request.read()
        if not raw:
            return {}
        try:
            return json.loads(raw)
        except json.JSONDecodeError as e:
            raise EndpointError(f"Request decode failed: {e}")

    # -- status -------------------------------------------------------------

    async def _status_leader(self, request):
        return await self.srv.status.leader()

    async def _status_peers(self, request):
        return await self.srv.status.peers()

    async def _status_lease(self, request):
        """Leader-lease state of this server (serving-plane routing +
        the lease-safety test surface; no reference parity route)."""
        return await self.srv.status.lease()

    # -- catalog ------------------------------------------------------------

    async def _catalog_register(self, request):
        body = await self._body_json(request)
        args = RegisterRequest(
            node=body.get("Node", ""), address=body.get("Address", ""),
            datacenter=body.get("Datacenter", ""),
            token=self._token(request))
        if body.get("Service"):
            args.service = _service_from_api(body["Service"])
        if body.get("Check"):
            args.check = _check_from_api(body["Check"])
        for c in body.get("Checks") or []:
            args.checks.append(_check_from_api(c))
        await self.srv.catalog.register(args)
        return True

    async def _catalog_deregister(self, request):
        body = await self._body_json(request)
        args = DeregisterRequest(
            node=body.get("Node", ""), service_id=body.get("ServiceID", ""),
            check_id=body.get("CheckID", ""),
            datacenter=body.get("Datacenter", ""))
        await self.srv.catalog.deregister(args)
        return True

    async def _catalog_datacenters(self, request):
        return await self.srv.catalog.list_datacenters()

    async def _catalog_nodes(self, request):
        opts = self._query_opts(request)
        meta, nodes = await self.srv.catalog.list_nodes(opts)
        return self._json(request, to_api(nodes), meta)

    async def _catalog_services(self, request):
        opts = self._query_opts(request)
        meta, services = await self.srv.catalog.list_services(opts)
        return self._json(request, services, meta)

    async def _catalog_service_nodes(self, request):
        opts = self._query_opts(request)
        service = request.match_info["service"]
        tag = request.query.get("tag", "")
        meta, nodes = await self.srv.catalog.service_nodes(service, opts, tag)
        return self._json(request, to_api(nodes), meta)

    async def _catalog_node_services(self, request):
        opts = self._query_opts(request)
        meta, ns = await self.srv.catalog.node_services(request.match_info["node"], opts)
        if ns is None:
            return self._json(request, None, meta)
        _, info = await self.srv.internal.node_info(
            request.match_info["node"], opts)
        addr = info[0]["address"] if info else ""
        out = {
            "Node": {"Node": request.match_info["node"], "Address": addr},
            "Services": {sid: to_api(svc) for sid, svc in ns.items()},
        }
        return self._json(request, out, meta)

    # -- health -------------------------------------------------------------

    async def _health_node_checks(self, request):
        opts = self._query_opts(request)
        meta, checks = await self.srv.health.node_checks(request.match_info["node"], opts)
        return self._json(request, to_api(checks), meta)

    async def _health_service_checks(self, request):
        opts = self._query_opts(request)
        meta, checks = await self.srv.health.service_checks(
            request.match_info["service"], opts)
        return self._json(request, to_api(checks), meta)

    async def _health_checks_in_state(self, request):
        opts = self._query_opts(request)
        meta, checks = await self.srv.health.checks_in_state(
            request.match_info["state"], opts)
        return self._json(request, to_api(checks), meta)

    async def _health_service_nodes(self, request):
        opts = self._query_opts(request)
        service = request.match_info["service"]
        tag = request.query.get("tag", "")
        passing = "passing" in request.query
        meta, csns = await self.srv.health.service_nodes(service, opts, tag, passing)
        return self._json(request, to_api(csns), meta)

    # -- KV -----------------------------------------------------------------

    async def _kvs(self, request):
        """command/agent/kvs_endpoint.go dispatch by method + params."""
        key = request.match_info["key"]
        if request.method == "GET":
            return await self._kvs_get(request, key)
        if request.method == "PUT":
            return await self._kvs_put(request, key)
        return await self._kvs_delete(request, key)

    # Query keys each hot-path op may see; anything else (index/wait
    # blocking, recurse, pretty, dc, cas…) takes the generic path.
    _HOT_GET = frozenset(("stale", "consistent", "token", "raw"))
    _HOT_PUT = frozenset(("flags", "cas", "acquire", "release", "token"))
    _HOT_DELETE = frozenset(("recurse", "cas", "token"))

    async def _kvs_get(self, request, key: str):
        if not request.query_string and self._hot_capable:
            # Bare GET (the dominant request in every KV workload):
            # skip the MultiDict query parse entirely.
            from consul_tpu.agent import hotpath
            return self._hot_response(*await hotpath.kv_get(
                self.srv, key, token=self.agent.config.acl_token))
        q = request.query
        if self._hot_ok(q, self._HOT_GET):
            from consul_tpu.agent import hotpath
            return self._hot_response(*await hotpath.kv_get(
                self.srv, key, stale="stale" in q,
                consistent="consistent" in q, raw="raw" in q,
                token=self._token(request)))
        opts = self._query_opts(request)
        if "keys" in q:
            args = KeyListRequest(prefix=key, separator=q.get("separator", ""),
                                  **_opt_kw(opts))
            meta, keys = await self.srv.kvs.list_keys(args)
            return self._json(request, keys, meta)
        if "recurse" in q:
            args = KeyListRequest(prefix=key, **_opt_kw(opts))
            meta, ents = await self.srv.kvs.list(args)
            if not ents:
                resp = web.Response(status=404, text="")
                self._set_index_headers(resp, meta)
                return resp
            return self._json(request, to_api(ents), meta)
        args = KeyRequest(key=key, **_opt_kw(opts))
        meta, ents = await self.srv.kvs.get(args)
        if not ents:
            resp = web.Response(status=404, text="")
            self._set_index_headers(resp, meta)
            return resp
        if "raw" in q:
            resp = web.Response(body=ents[0].value,
                                content_type="application/octet-stream")
            self._set_index_headers(resp, meta)
            return resp
        return self._json(request, to_api(ents), meta)

    @property
    def _hot_capable(self) -> bool:
        # The fast path reads raft/store/ACLs locally; a client-mode
        # agent (server/client.py proxy object, no raft) must keep
        # taking the generic mesh-forwarded path.
        return getattr(self.agent.server, "raft", None) is not None

    def _hot_ok(self, q, allowed: frozenset) -> bool:
        if not self._hot_capable:
            return False
        keys = set(q.keys())
        if not keys <= allowed:
            return False
        return not ("stale" in keys and "consistent" in keys)

    async def _kvs_put(self, request, key: str):
        q = request.query
        value = await request.read()
        if self._hot_ok(q, self._HOT_PUT):
            from consul_tpu.agent import hotpath
            return self._hot_response(*await hotpath.kv_put(
                self.srv, key, value,
                flags=int(q["flags"]) if "flags" in q else None,
                cas=int(q["cas"]) if "cas" in q else None,
                acquire=q.get("acquire", ""), release=q.get("release", ""),
                token=self._token(request)))
        d = DirEntry(key=key, value=value)
        if "flags" in q:
            d.flags = int(q["flags"])
        op = KVSOp.SET.value
        if "cas" in q:
            d.modify_index = int(q["cas"])
            op = KVSOp.CAS.value
        elif "acquire" in q:
            d.session = q["acquire"]
            op = KVSOp.LOCK.value
        elif "release" in q:
            d.session = q["release"]
            op = KVSOp.UNLOCK.value
        args = KVSRequest(op=op, dir_ent=d, token=self._token(request))
        return await self.srv.kvs.apply(args)

    async def _kvs_delete(self, request, key: str):
        q = request.query
        if self._hot_ok(q, self._HOT_DELETE):
            from consul_tpu.agent import hotpath
            return self._hot_response(*await hotpath.kv_delete(
                self.srv, key, recurse="recurse" in q,
                cas=int(q["cas"]) if "cas" in q else None,
                token=self._token(request)))
        d = DirEntry(key=key)
        op = KVSOp.DELETE.value
        if "recurse" in q:
            op = KVSOp.DELETE_TREE.value
        elif "cas" in q:
            d.modify_index = int(q["cas"])
            op = KVSOp.DELETE_CAS.value
        args = KVSRequest(op=op, dir_ent=d, token=self._token(request))
        return await self.srv.kvs.apply(args)

    # -- sessions -----------------------------------------------------------

    async def _session_create(self, request):
        """Defaults: node = this agent, checks = [serfHealth]
        (command/agent/session_endpoint.go:20-74)."""
        body = await self._body_json(request)
        session = Session(
            name=body.get("Name", ""),
            node=body.get("Node") or self.agent.node_name,
            checks=body.get("Checks") if body.get("Checks") is not None
                   else [SERF_CHECK_ID],
            behavior=body.get("Behavior", ""),
            ttl=body.get("TTL", "") or "",
        )
        if "LockDelay" in body:
            session.lock_delay = _parse_lock_delay(body["LockDelay"])
        args = SessionRequest(op=SessionOp.CREATE.value, session=session,
                              token=self._token(request))
        sid = await self.srv.session.apply(args)
        return {"ID": sid}

    async def _session_destroy(self, request):
        args = SessionRequest(op=SessionOp.DESTROY.value,
                              session=Session(id=request.match_info["id"]))
        await self.srv.session.apply(args)
        return True

    async def _session_renew(self, request):
        sess = await self.srv.session.renew(request.match_info["id"])
        if sess is None:
            raise NotFound(f'Session id \'{request.match_info["id"]}\' not found')
        return [session_to_api(sess)]

    async def _session_info(self, request):
        opts = self._query_opts(request)
        meta, sess = await self.srv.session.get(request.match_info["id"], opts)
        out = [session_to_api(sess)] if sess else []
        return self._json(request, out, meta)

    async def _session_node(self, request):
        opts = self._query_opts(request)
        meta, sessions = await self.srv.session.node_sessions(
            request.match_info["node"], opts)
        return self._json(request, [session_to_api(s) for s in sessions], meta)

    async def _session_list(self, request):
        opts = self._query_opts(request)
        meta, sessions = await self.srv.session.list(opts)
        return self._json(request, [session_to_api(s) for s in sessions], meta)

    # -- ACL ----------------------------------------------------------------
    # command/agent/acl_endpoint.go (197 LoC)

    async def _acl_write(self, request, update: bool):
        from consul_tpu.structs.structs import (
            ACL, ACL_TYPE_CLIENT, ACLOp, ACLRequest)
        body = await self._body_json(request)
        acl = ACL(id=body.get("ID", ""), name=body.get("Name", ""),
                  type=body.get("Type") or ACL_TYPE_CLIENT,
                  rules=body.get("Rules", ""))
        if update and not acl.id:
            raise EndpointError("ACL ID must be set")
        args = ACLRequest(op=ACLOp.SET.value, acl=acl,
                          token=self._token(request))
        aid = await self.srv.acl.apply(args)
        return {"ID": aid}

    async def _acl_create(self, request):
        return await self._acl_write(request, update=False)

    async def _acl_update(self, request):
        return await self._acl_write(request, update=True)

    async def _acl_destroy(self, request):
        from consul_tpu.structs.structs import ACL, ACLOp, ACLRequest
        args = ACLRequest(op=ACLOp.DELETE.value,
                          acl=ACL(id=request.match_info["id"]),
                          token=self._token(request))
        await self.srv.acl.apply(args)
        return True

    async def _acl_info(self, request):
        opts = self._query_opts(request)
        meta, out = await self.srv.acl.get(request.match_info["id"], opts)
        return self._json(request, to_api(out), meta)

    async def _acl_clone(self, request):
        from consul_tpu.structs.structs import ACL, ACLOp, ACLRequest
        opts = self._query_opts(request)
        _, out = await self.srv.acl.get(request.match_info["id"], opts)
        if not out:
            raise NotFound("ACL not found")
        src = out[0]
        args = ACLRequest(op=ACLOp.SET.value,
                          acl=ACL(name=src.name, type=src.type, rules=src.rules),
                          token=opts.token)
        aid = await self.srv.acl.apply(args)
        return {"ID": aid}

    async def _acl_list(self, request):
        opts = self._query_opts(request)
        meta, acls = await self.srv.acl.list(opts)
        return self._json(request, to_api(acls), meta)

    # -- internal UI --------------------------------------------------------

    async def _ui_nodes(self, request):
        opts = self._query_opts(request)
        meta, dump = await self.srv.internal.node_dump(opts)
        return self._json(request, to_api(dump), meta)

    async def _ui_node_info(self, request):
        opts = self._query_opts(request)
        meta, dump = await self.srv.internal.node_info(
            request.match_info["node"], opts)
        if not dump:
            raise NotFound("Node not found")
        return self._json(request, to_api(dump[0]), meta)

    async def _ui_services(self, request):
        """Service summary rows (command/agent/ui_endpoint.go)."""
        opts = self._query_opts(request)
        meta, dump = await self.srv.internal.node_dump(opts)
        summary: Dict[str, Dict[str, Any]] = {}
        for node in dump:
            node_checks = [c for c in node["checks"] if not c.service_id]
            for svc in node["services"]:
                row = summary.setdefault(svc.service, {
                    "Name": svc.service, "Nodes": [], "ChecksPassing": 0,
                    "ChecksWarning": 0, "ChecksCritical": 0})
                row["Nodes"].append(node["node"])
                svc_checks = [c for c in node["checks"] if c.service_id == svc.id]
                for c in node_checks + svc_checks:
                    key = {"passing": "ChecksPassing", "warning": "ChecksWarning",
                           "critical": "ChecksCritical"}.get(c.status)
                    if key:
                        row[key] += 1
        return self._json(request, sorted(summary.values(), key=lambda r: r["Name"]), meta)


class NotFound(Exception):
    pass


def _check_from_api(c: Dict[str, Any]) -> HealthCheck:
    return HealthCheck(
        node=c.get("Node", ""), check_id=c.get("CheckID", ""),
        name=c.get("Name", ""), status=c.get("Status", ""),
        notes=c.get("Notes", ""), output=c.get("Output", ""),
        service_id=c.get("ServiceID", ""),
        service_name=c.get("ServiceName", ""))


def _service_from_api(s: Dict[str, Any]) -> NodeService:
    return NodeService(
        id=s.get("ID", ""), service=s.get("Service", ""),
        tags=s.get("Tags") or [], address=s.get("Address", ""),
        port=s.get("Port", 0))


def _opt_kw(opts: QueryOptions) -> Dict[str, Any]:
    return dict(token=opts.token, datacenter=opts.datacenter,
                min_query_index=opts.min_query_index,
                max_query_time=opts.max_query_time,
                allow_stale=opts.allow_stale,
                require_consistent=opts.require_consistent)


def _parse_lock_delay(v: Any) -> float:
    """Accepts Go duration string or nanoseconds int (reference
    session_endpoint.go FixupLockDelay)."""
    if isinstance(v, str):
        return parse_duration(v)
    n = float(v)
    # Heuristic from the reference: integers <= 60 are seconds, larger
    # values are nanoseconds.
    return n if n <= 60 else n / 1e9
