"""SO_REUSEPORT HTTP worker processes for the serving plane.

One agent process saturates a single core parsing HTTP and encoding
JSON long before the Raft core does; the reference sidesteps this with
Go's multi-core runtime.  Here the serving plane scales out as worker
PROCESSES: the master binds the public HTTP port with ``SO_REUSEPORT``
and spawns ``http_workers - 1`` copies of this module, each binding
the same port (the kernel load-balances accepted connections across
listeners).  Workers own only edge work — HTTP parse, query
classification, response write:

  * HOT requests (KV GET/PUT/DELETE, health service, catalog, status —
    query string inside the hot subsets below) become one ``serve``
    command over the agent's IPC layer (ipc/server.py); the reply is
    the precomputed ``(status, headers, content_type, body)`` quadruple
    from agent/hotpath.py, written straight out the worker's socket
    with no decode/re-encode hop.
  * Everything else (blocking queries, ``?pretty``, recurse, UI,
    agent-local endpoints) proxies verbatim to the master's internal
    unix-socket HTTP listener, so every route keeps working with
    byte-identical semantics.

Lifecycle: the master tracks each worker's Popen and terminates by
PID on shutdown (SIGTERM, bounded wait, SIGKILL) — never by process
name.  A worker that loses its gateway connection retries once, then
serves 502 until the master returns.
"""

from __future__ import annotations

import argparse
import asyncio
import itertools
import os
import signal
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

import msgpack

# Query keys each hot op may see — kept in lockstep with
# http_api.HTTPServer._HOT_* (tests/test_serving.py asserts parity).
# ``stale`` + ``consistent`` together is rejected edge-side the same
# way the master's _hot_ok does.
HOT_GET = frozenset(("stale", "consistent", "token", "raw"))
HOT_PUT = frozenset(("flags", "cas", "acquire", "release", "token"))
HOT_DELETE = frozenset(("recurse", "cas", "token"))
HOT_HEALTH = frozenset(("tag", "passing", "stale", "consistent", "token"))
HOT_CATALOG = frozenset(("stale", "consistent", "token"))
HOT_CATALOG_SVC = frozenset(("tag", "stale", "consistent", "token"))

# Hop-by-hop / recomputed headers stripped when proxying.
_SKIP_REQ = frozenset(("host", "content-length", "transfer-encoding",
                       "connection"))
_SKIP_RESP = frozenset(("content-length", "transfer-encoding", "connection",
                        "content-type", "content-encoding", "date", "server"))


def _hot_ok(q, allowed: frozenset) -> bool:
    keys = set(q.keys())
    if not keys <= allowed:
        return False
    return not ("stale" in keys and "consistent" in keys)


class GatewayClient:
    """Multiplexing client for the IPC ``serve`` command.

    One persistent unix-socket connection per worker; requests carry
    client-assigned Seq numbers and replies resolve out-of-order via a
    Seq -> Future map, so a slow consistent read never head-of-line
    blocks a stale one.  Header + body are written back-to-back with
    no await in between — frames from concurrent callers can't tear.
    """

    def __init__(self, path: str) -> None:
        self.path = path
        self._seq = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        self._writer: Optional[asyncio.StreamWriter] = None
        self._reader_task: Optional[asyncio.Task] = None
        self._conn_lock = asyncio.Lock()

    async def connect(self) -> None:
        async with self._conn_lock:
            if self._writer is not None:
                return
            reader, writer = await asyncio.open_unix_connection(self.path)
            unpacker = msgpack.Unpacker(raw=False)
            writer.write(msgpack.packb({"Command": "handshake", "Seq": 0},
                                       use_bin_type=True))
            writer.write(msgpack.packb({"Version": 1}, use_bin_type=True))
            await writer.drain()
            hdr = await _next_obj(reader, unpacker)
            if hdr.get("Error"):
                writer.close()
                raise ConnectionError(f"gateway handshake: {hdr['Error']}")
            self._writer = writer
            self._reader_task = asyncio.get_event_loop().create_task(
                self._read_loop(reader, unpacker))

    async def close(self) -> None:
        if self._reader_task is not None:
            self._reader_task.cancel()
            self._reader_task = None
        if self._writer is not None:
            self._writer.close()
            self._writer = None

    async def _read_loop(self, reader, unpacker) -> None:
        try:
            while True:
                hdr = await _next_obj(reader, unpacker)
                fut = self._pending.pop(hdr.get("Seq"), None)
                if hdr.get("Error"):
                    if fut is not None and not fut.done():
                        fut.set_exception(ConnectionError(hdr["Error"]))
                    continue
                body = await _next_obj(reader, unpacker)
                if fut is not None and not fut.done():
                    fut.set_result(body)
        except (ConnectionError, asyncio.IncompleteReadError):
            pass    # normal gateway loss; the finally fails callers
        finally:
            # This loop is the ONLY resolver of self._pending futures,
            # so any exit — cancellation, connection loss, or an
            # unexpected decode error — must fail the in-flight
            # callers, or request() hangs forever on a dead reader.
            self._fail_pending()

    def _fail_pending(self) -> None:
        self._writer = None
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(ConnectionError("gateway lost"))
        self._pending.clear()

    async def request(self, op: str,
                      args: Dict[str, Any]) -> Tuple[int, Dict, str, bytes]:
        if self._writer is None:
            await self.connect()
        seq = next(self._seq)
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._pending[seq] = fut
        self._writer.write(msgpack.packb({"Command": "serve", "Seq": seq},
                                         use_bin_type=True))
        self._writer.write(msgpack.packb({"Op": op, "Args": args},
                                         use_bin_type=True))
        await self._writer.drain()
        body = await fut
        return (body["Status"], body.get("Hdrs") or {},
                body.get("CT", "application/json"), body.get("Body", b""))


async def _next_obj(reader: asyncio.StreamReader,
                    unpacker: msgpack.Unpacker) -> Any:
    while True:
        try:
            return next(unpacker)
        except StopIteration:
            data = await reader.read(4096)
            if not data:
                raise ConnectionError("gateway closed")
            unpacker.feed(data)


class WorkerFront:
    """One worker's aiohttp app: hot routes -> gateway, rest -> proxy."""

    def __init__(self, gateway_path: str, upstream_path: str) -> None:
        self.gw = GatewayClient(gateway_path)
        self.upstream_path = upstream_path
        self._session = None  # lazy aiohttp.ClientSession over unix socket

    def build_app(self):
        from aiohttp import web
        app = web.Application()
        r = app.router
        r.add_get("/v1/kv/{key:.*}", self._kv_get)
        r.add_put("/v1/kv/{key:.*}", self._kv_put)
        r.add_delete("/v1/kv/{key:.*}", self._kv_delete)
        r.add_get("/v1/health/service/{service}", self._health_service)
        r.add_get("/v1/catalog/nodes", self._catalog_nodes)
        r.add_get("/v1/catalog/services", self._catalog_services)
        r.add_get("/v1/catalog/service/{service}", self._catalog_service)
        r.add_get("/v1/status/leader", self._status_leader)
        r.add_get("/v1/status/lease", self._status_lease)
        r.add_route("*", "/{tail:.*}", self._proxy)
        return app

    def _respond(self, quad: Tuple[int, Dict, str, bytes]):
        from aiohttp import web
        status, hdrs, ct, body = quad
        # charset parity with the master edge's _hot_response.
        return web.Response(status=status, body=body, content_type=ct,
                            charset="utf-8" if ct.startswith(
                                ("application/json", "text/")) else None,
                            headers=hdrs or None)

    async def _hot(self, request, op: str, args: Dict[str, Any]):
        """One gateway round-trip; a lost master answers 502 (the
        reverse-proxy convention for a dead upstream)."""
        from aiohttp import web
        try:
            return self._respond(await self.gw.request(op, args))
        except ConnectionError as e:
            return web.Response(status=502, text=f"gateway: {e}")

    # -- hot handlers -------------------------------------------------------

    async def _kv_get(self, request):
        key = request.match_info["key"]
        if not request.query_string:
            return await self._hot(request, "kv_get",
                                   {"_args": [key], "token": None})
        q = request.query
        if not _hot_ok(q, HOT_GET):
            return await self._proxy(request)
        return await self._hot(request, "kv_get", {
            "_args": [key], "stale": "stale" in q,
            "consistent": "consistent" in q, "raw": "raw" in q,
            "token": q.get("token") or None})

    async def _kv_put(self, request):
        q = request.query
        if not _hot_ok(q, HOT_PUT):
            return await self._proxy(request)
        key = request.match_info["key"]
        value = await request.read()
        try:
            flags = int(q["flags"]) if "flags" in q else None
            cas = int(q["cas"]) if "cas" in q else None
        except ValueError:
            return await self._proxy(request)  # master shapes the error
        return await self._hot(request, "kv_put", {
            "_args": [key, value], "flags": flags, "cas": cas,
            "acquire": q.get("acquire", ""), "release": q.get("release", ""),
            "token": q.get("token") or None})

    async def _kv_delete(self, request):
        q = request.query
        if not _hot_ok(q, HOT_DELETE):
            return await self._proxy(request)
        try:
            cas = int(q["cas"]) if "cas" in q else None
        except ValueError:
            return await self._proxy(request)
        return await self._hot(request, "kv_delete", {
            "_args": [request.match_info["key"]], "recurse": "recurse" in q,
            "cas": cas, "token": q.get("token") or None})

    async def _health_service(self, request):
        q = request.query
        if not _hot_ok(q, HOT_HEALTH):
            return await self._proxy(request)
        return await self._hot(request, "health_service", {
            "_args": [request.match_info["service"]],
            "tag": q.get("tag", ""), "passing": "passing" in q,
            "stale": "stale" in q, "consistent": "consistent" in q,
            "token": q.get("token") or None})

    async def _catalog_nodes(self, request):
        return await self._catalog(request, "catalog_nodes", HOT_CATALOG)

    async def _catalog_services(self, request):
        return await self._catalog(request, "catalog_services", HOT_CATALOG)

    async def _catalog_service(self, request):
        q = request.query
        if not _hot_ok(q, HOT_CATALOG_SVC):
            return await self._proxy(request)
        return await self._hot(request, "catalog_service", {
            "_args": [request.match_info["service"]], "tag": q.get("tag", ""),
            "stale": "stale" in q, "consistent": "consistent" in q,
            "token": q.get("token") or None})

    async def _catalog(self, request, op: str, allowed: frozenset):
        q = request.query
        if not _hot_ok(q, allowed):
            return await self._proxy(request)
        return await self._hot(request, op, {
            "stale": "stale" in q, "consistent": "consistent" in q,
            "token": q.get("token") or None})

    async def _status_leader(self, request):
        if request.query_string:
            return await self._proxy(request)
        return await self._hot(request, "status_leader", {})

    async def _status_lease(self, request):
        if request.query_string:
            return await self._proxy(request)
        return await self._hot(request, "status_lease", {})

    # -- everything else ----------------------------------------------------

    async def _proxy(self, request):
        """Verbatim passthrough to the master's internal unix listener."""
        import aiohttp
        from aiohttp import web
        if self._session is None:
            self._session = aiohttp.ClientSession(
                connector=aiohttp.UnixConnector(path=self.upstream_path),
                auto_decompress=False)
        body = await request.read()
        headers = {k: v for k, v in request.headers.items()
                   if k.lower() not in _SKIP_REQ}
        async with self._session.request(
                request.method, f"http://agent{request.path_qs}",
                data=body, headers=headers) as up:
            data = await up.read()
            out = {k: v for k, v in up.headers.items()
                   if k.lower() not in _SKIP_RESP}
            return web.Response(status=up.status, body=data,
                                content_type=up.content_type,
                                charset=up.charset, headers=out)

    async def close(self) -> None:
        await self.gw.close()
        # Swap-then-close: a second close() arriving while this one is
        # suspended in session.close() must see None, not a session
        # mid-teardown.
        session, self._session = self._session, None
        if session is not None:
            await session.close()


class WorkerPool:
    """Master-side registry of worker processes.

    Shutdown is strictly by TRACKED PID: SIGTERM each live child,
    bounded wait, SIGKILL stragglers.  Never signals by process name —
    a name match can catch unrelated processes (including the test
    harness itself)."""

    # Respawn budget: a worker that dies is restarted with the same
    # argv, but a crash-looping worker must not fork-bomb the box.
    MAX_RESPAWNS = 16

    def __init__(self) -> None:
        self.procs: List[subprocess.Popen] = []
        self._cmds: Dict[int, List[str]] = {}   # pid -> argv for respawn
        self._env: Optional[Dict[str, str]] = None
        self.respawned = 0

    def spawn(self, count: int, host: str, port: int,
              gateway_path: str, upstream_path: str) -> None:
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        self._env = env
        for i in range(count):
            cmd = [sys.executable, "-m", "consul_tpu.agent.workers",
                   "--host", host, "--port", str(port),
                   "--gateway", gateway_path, "--upstream", upstream_path,
                   "--id", str(i + 1)]
            p = subprocess.Popen(cmd, env=env)
            self.procs.append(p)
            self._cmds[p.pid] = cmd

    # -- fault-injection / supervision surface (chaos/) ----------------------

    def pids(self) -> List[int]:
        """PIDs of workers currently believed alive (poll()-checked)."""
        return [p.pid for p in self.procs if p.poll() is None]

    def kill_one(self, pid: Optional[int] = None,
                 sig: int = signal.SIGKILL) -> Optional[int]:
        """Signal ONE tracked worker (by pid, or the first live one).
        Returns the signalled pid, or None when no live worker matches.
        Tracked-PID only — same rule as stop(): never by name."""
        for p in self.procs:
            if p.poll() is not None:
                continue
            if pid is not None and p.pid != pid:
                continue
            p.send_signal(sig)
            return p.pid
        return None

    def reap_dead(self) -> List[int]:
        """PIDs of tracked workers that have exited (kept in ``procs``
        so respawn_dead can replace them in place)."""
        return [p.pid for p in self.procs if p.poll() is not None]

    def respawn_dead(self) -> List[int]:
        """Replace each dead worker with a fresh process running the
        same argv.  Returns the new pids; respects MAX_RESPAWNS so a
        crash loop degrades to a smaller pool instead of a fork storm."""
        new_pids: List[int] = []
        for i, p in enumerate(self.procs):
            if p.poll() is None:
                continue
            cmd = self._cmds.pop(p.pid, None)
            if cmd is None or self.respawned >= self.MAX_RESPAWNS:
                continue
            fresh = subprocess.Popen(cmd, env=self._env)
            self.procs[i] = fresh
            self._cmds[fresh.pid] = cmd
            self.respawned += 1
            new_pids.append(fresh.pid)
        return new_pids

    async def stop(self, timeout: float = 5.0) -> None:
        for p in self.procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + timeout
        for p in self.procs:
            while p.poll() is None and time.monotonic() < deadline:
                await asyncio.sleep(0.05)
            if p.poll() is None:
                p.kill()
                p.wait()
        self.procs.clear()
        self._cmds.clear()


# -- worker process entry ---------------------------------------------------

async def _amain(args) -> None:
    import signal as _signal

    from aiohttp import web
    front = WorkerFront(args.gateway, args.upstream)
    # The master starts the gateway before spawning us, but give a
    # slow box a few grace rounds before giving up.
    for attempt in range(20):
        try:
            await front.gw.connect()
            break
        except (ConnectionError, OSError, FileNotFoundError):
            if attempt == 19:
                raise
            await asyncio.sleep(0.25)
    runner = web.AppRunner(front.build_app(), access_log=None,
                           shutdown_timeout=0.5)
    await runner.setup()
    site = web.TCPSite(runner, args.host, args.port, reuse_port=True)
    await site.start()
    stop_evt = asyncio.Event()
    loop = asyncio.get_event_loop()
    for sig in (_signal.SIGTERM, _signal.SIGINT):
        loop.add_signal_handler(sig, stop_evt.set)
    await stop_evt.wait()
    await runner.cleanup()
    await front.close()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="consul-http-worker",
        description="SO_REUSEPORT HTTP worker (spawned by the agent)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--gateway", required=True,
                   help="agent worker-gateway unix socket")
    p.add_argument("--upstream", required=True,
                   help="agent internal HTTP unix socket (non-hot proxy)")
    p.add_argument("--id", default="0", help="worker index (logs only)")
    args = p.parse_args(argv)
    try:
        asyncio.run(_amain(args))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
