"""The edge agent: embeds a server, runs local checks, syncs the catalog.

Parity target: ``command/agent/agent.go`` (1319 LoC) + the serve()
choreography of ``command/agent/command.go``.  Owns the local
service/check registries (persisted to data-dir and reloaded at boot,
agent.go:540-612/890-959/1040-1227), the check runners, the
anti-entropy loop (local.py), maintenance mode (agent.go:1229-1320),
and the HTTP/DNS front-ends.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import tempfile
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set

from consul_tpu.agent.checks import CheckRunnerSet, CheckType
from consul_tpu.agent.dns import DNSServer
from consul_tpu.agent.http_api import HTTPServer, _service_from_api, to_api
from consul_tpu.agent.local import LocalState
from consul_tpu.server.server import Server, ServerConfig
from consul_tpu.structs.structs import (
    CONSUL_SERVICE_ID,
    CONSUL_SERVICE_NAME,
    HEALTH_CRITICAL,
    HEALTH_PASSING,
    HealthCheck,
    NodeService,
    QueryOptions,
    RegisterRequest,
    SERF_ALIVE_OUTPUT,
    SERF_CHECK_ID,
    SERF_CHECK_NAME,
)
from consul_tpu.version import VERSION

# Maintenance-mode faux checks (agent.go:24-38)
NODE_MAINT_CHECK_ID = "_node_maintenance"
SERVICE_MAINT_PREFIX = "_service_maintenance:"
DEFAULT_NODE_MAINT_REASON = ("Maintenance mode is enabled for this node, "
                             "but no reason was provided. This is a default "
                             "message.")
DEFAULT_SERVICE_MAINT_REASON = ("Maintenance mode is enabled for this "
                                "service, but no reason was provided. This "
                                "is a default message.")


@dataclass
class AgentConfig:
    node_name: str = "node1"
    datacenter: str = "dc1"
    bind_addr: str = "127.0.0.1"
    advertise_addr: str = ""
    domain: str = "consul."
    http_port: int = 8500
    https_port: int = -1   # >0 mounts the API on TLS too (http.go:44-173)
    dns_port: int = 8600
    # Per-listener address overrides (config.go AddressConfig +
    # UnixSockets): keys "http"/"rpc", values an IP or "unix:///path".
    addresses: Dict[str, str] = field(default_factory=dict)
    # TLS material for the HTTPS listener (tlsutil; config.go:107-113)
    verify_incoming: bool = False
    ca_file: str = ""
    cert_file: str = ""
    key_file: str = ""
    server: bool = True
    bootstrap: bool = True
    data_dir: str = ""  # "" = no persistence (dev mode)
    dns_only_passing: bool = False
    dns_allow_stale: bool = False
    dns_max_stale: float = 5.0   # seconds; re-query the leader past this
    dns_enable_truncate: bool = False  # set TC when capping UDP answers
    recursors: List[str] = field(default_factory=list)
    node_ttl: float = 0.0
    service_ttl: float = 0.0
    ae_interval: float = 60.0
    # ACL passthrough (command/agent/config.go ACL* fields)
    acl_datacenter: str = ""
    acl_ttl: float = 30.0
    acl_default_policy: str = "allow"
    acl_down_policy: str = "extend-cache"
    acl_master_token: str = ""
    acl_token: str = ""  # agent's own default token
    encrypt: str = ""    # base64 16-byte gossip key (enables the keyring)
    protocol: int = 2    # -protocol: operating protocol version (vsn tag)
    # LAN membership substrate: "swim" = per-agent asyncio memberlist;
    # "tpu" = delegate to the TPU gossip plane daemon (the kernel IS the
    # failure detector; gossip/plane.py).  The WAN pool always runs the
    # asyncio backend (tiny, servers-only).
    gossip_backend: str = "swim"
    gossip_plane: str = ""  # plane rendezvous: host:port or unix://path
    # -- membership plane (command/agent/config.go ports + retry-join) ----
    serf_lan_port: int = 0         # 0 = ephemeral (production: 8301)
    serf_wan_port: int = 0         # servers only (production: 8302)
    # None = no TCP RPC mesh (single-node in-memory raft, dev mode);
    # an int (0 = ephemeral; production 8300) attaches the mesh listener.
    rpc_mesh_port: Optional[int] = None
    bootstrap_expect: int = 0      # self-assembly quorum size (serf.go:185)
    # One-shot synchronous join at startup; failure is FATAL
    # (startupJoin, command.go:692-701).  retry_join loops instead.
    start_join: List[str] = field(default_factory=list)
    retry_join: List[str] = field(default_factory=list)
    retry_interval: float = 30.0
    retry_max: int = 0             # 0 = retry forever
    rejoin_after_leave: bool = False
    # compressed-timer overrides for tests (SerfConfig field -> value)
    serf_timing: Dict[str, float] = field(default_factory=dict)
    raft_config: Optional[Any] = None   # RaftConfig override (tests)
    # Lease-timeout floor resolved by the autotune verdict (obs/tuner.py
    # "lease_timeout_floor_s": 0 = auto lease window, negative disables
    # lease reads).  None = auto; an explicit float wins over the
    # verdict.  Only applied when raft_config is None — a full
    # RaftConfig override (tests) is already explicit about leases.
    lease_timeout_floor_s: Optional[float] = None
    reconcile_interval: float = 60.0    # leader full-reconcile cadence
    enable_debug: bool = False  # route /debug/pprof/* (http.go:259-264)
    # Serving-plane fan-out: total HTTP serving processes on the public
    # TCP port (1 = master only).  N > 1 spawns N-1 SO_REUSEPORT worker
    # processes that run hot ops over the IPC gateway and proxy the
    # rest (agent/workers.py).  Ignored for unix-socket HTTP listeners.
    http_workers: int = 1
    # Device-resident state store (server mode only, PR 11): batched
    # FSM apply + device-side watch matching, host authoritative.
    device_store: bool = False
    device_store_capacity: int = 1 << 16
    # Batched reconcile (PR 18): max catalog writes folded into one
    # BATCH raft envelope per flush (0 = autotune verdict > default),
    # and the plane drain cadence the reconcile linger couples to
    # (0 = autotune verdict > kernel default; the same knob the plane
    # resolves for its flight-ring drain).
    reconcile_batch_max: int = 0
    flight_drain_every: int = 0
    extra: Dict[str, Any] = field(default_factory=dict)


# AgentConfig knobs resolved through the autotune verdict — the serving
# plane's consumer-side claim for the ``autotune-knob`` vet group
# (tools/vet/table_drift.py): the union of every TUNED_FIELDS literal
# must equal the obs/tuner.py KNOBS key set.  ``flight_drain_every``
# is ALSO claimed by the gossip plane (gossip/plane.py) — the union
# check permits the overlap; the agent's read only drives the
# reconcile-linger cadence coupling, never the kernel.
TUNED_FIELDS = ("http_workers", "device_store", "lease_timeout_floor_s",
                "reconcile_batch_max", "flight_drain_every")

# The per-field AUTO sentinel (the dataclass default): any other value
# is an explicit operator setting and skips the verdict.
_TUNED_AUTO = {"http_workers": 1, "device_store": False,
               "lease_timeout_floor_s": None,
               "reconcile_batch_max": 0, "flight_drain_every": 0}


class Agent:
    def __init__(self, config: Optional[AgentConfig] = None) -> None:
        self.config = config or AgentConfig()
        if not self.config.advertise_addr:
            self.config.advertise_addr = self.config.bind_addr
        # Resolve the autotuned serving knobs before anything consumes
        # them: explicit config value > persisted per-platform verdict >
        # registry default (obs/tuner.py).  jax is never imported here —
        # a chipless serving host resolves against "cpu"; when jax is
        # already up (device_store, tests) the live backend wins.
        from consul_tpu.obs import tuner
        if "jax" in sys.modules:
            _jx = sys.modules["jax"]
            _plat, _ndev = _jx.default_backend(), len(_jx.devices())
        else:
            _plat, _ndev = "cpu", 1
        explicit = {f: getattr(self.config, f) for f in TUNED_FIELDS
                    if getattr(self.config, f) != _TUNED_AUTO[f]}
        self.autotune = tuner.resolve(list(TUNED_FIELDS), explicit,
                                      platform=_plat, device_count=_ndev)
        # Resolved values are written back so every downstream reader
        # (worker pool sizing, ServerConfig, bundle config dump) sees
        # what the agent actually runs.
        self.config.http_workers = int(self.autotune.value("http_workers"))
        self.config.device_store = bool(self.autotune.value("device_store"))
        self.config.reconcile_batch_max = int(
            self.autotune.value("reconcile_batch_max") or 0)
        self.config.flight_drain_every = int(
            self.autotune.value("flight_drain_every") or 0)
        raft_override = self.config.raft_config
        if raft_override is None:
            floor = float(self.autotune.value("lease_timeout_floor_s") or 0.0)
            if floor != 0.0:
                from consul_tpu.consensus.raft import RaftConfig
                raft_override = RaftConfig(lease_timeout=floor)
        if self.config.server:
            # Embedded full server: Raft + state store + endpoints
            # (consul.NewServer, agent.go:63-66 server branch).
            # Reconcile linger rides the plane's drain cadence: a slower
            # flight drain means membership verdicts surface in coarser
            # bursts, so the leader waits proportionally longer to fold
            # a whole burst into one BATCH envelope (capped at 250ms so
            # detection latency never hides behind coalescing).
            from consul_tpu.agent.reconcile import DEFAULT_LINGER_S
            from consul_tpu.gossip.plane import FLIGHT_DRAIN_EVERY
            _drain = (self.config.flight_drain_every
                      or FLIGHT_DRAIN_EVERY)
            _linger = min(0.25, DEFAULT_LINGER_S
                          * (_drain / float(FLIGHT_DRAIN_EVERY)))
            self.server = Server(ServerConfig(
                node_name=self.config.node_name,
                datacenter=self.config.datacenter,
                domain=self.config.domain,
                bootstrap=self.config.bootstrap,
                bootstrap_expect=self.config.bootstrap_expect,
                data_dir=(os.path.join(self.config.data_dir, "server")
                          if self.config.data_dir else ""),
                **({"raft": raft_override}
                   if raft_override is not None else {}),
                reconcile_interval=self.config.reconcile_interval,
                acl_datacenter=self.config.acl_datacenter,
                acl_ttl=self.config.acl_ttl,
                acl_default_policy=self.config.acl_default_policy,
                acl_down_policy=self.config.acl_down_policy,
                acl_master_token=self.config.acl_master_token,
                device_store=self.config.device_store,
                device_store_capacity=self.config.device_store_capacity,
                extra={"reconcile_batch_max":
                       self.config.reconcile_batch_max,
                       "reconcile_linger_s": _linger,
                       **self.config.extra.get("server_extra", {})},
            ))
            from consul_tpu.agent import hotpath
            # Health endpoint bytes render at the FSM batch boundary
            # (fsm.health_render_hook) so they are hot before the first
            # watcher wakes — device store or not.
            hotpath.attach_health_cache(self.server)
            # Server mode exposes the one-raft-entry batched catalog
            # path; LocalState.sync_changes folds its dirty entries
            # through it when armed (client mode stays sequential).
            self.catalog_apply_batch = self._catalog_apply_batch
            if self.config.device_store:
                bridge = self.server.fsm.device
                if bridge is not None:
                    # Device watch verdicts invalidate + refresh the KV
                    # byte cache (hotpath.py) right at the batch boundary.
                    hotpath.attach_kv_cache(self.server, bridge)
        else:
            # Client mode: no Raft, no store — LAN gossip + RPC
            # forwarding with last-server affinity (consul.NewClient,
            # consul/client.go:72).
            from consul_tpu.server.client import ClientConfig, ConsulClient
            self.server = ConsulClient(ClientConfig(
                node_name=self.config.node_name,
                datacenter=self.config.datacenter,
                domain=self.config.domain,
            ))
        self.http = HTTPServer(self)
        self.dns = DNSServer(self, domain=self.config.domain,
                             node_ttl=self.config.node_ttl,
                             service_ttl=self.config.service_ttl,
                             only_passing=self.config.dns_only_passing,
                             allow_stale=self.config.dns_allow_stale,
                             max_stale=self.config.dns_max_stale,
                             recursors=self.config.recursors,
                             enable_truncate=self.config.dns_enable_truncate)
        self.local = LocalState(self, sync_interval=self.config.ae_interval)
        self.runners = CheckRunnerSet()
        from consul_tpu.agent.events import EventManager
        from consul_tpu.agent.log import LogHub
        from consul_tpu.agent.remote_exec import RemoteExecutor
        from consul_tpu.ipc.server import IPCServer
        self.events = EventManager(self)
        self.rexec = RemoteExecutor(self)
        self.server.add_event_sink(self._receive_event)
        self.log = LogHub(self.config.extra.get("log_level", "INFO"))
        self.ipc = IPCServer(self)
        self.ipc_port: Optional[int] = self.config.extra.get("ipc_port")
        # Multi-worker serving front (created in _start_http when
        # http_workers > 1): dedicated IPC listener for the workers'
        # `serve` command + the tracked worker Popen pool.
        self.worker_pool = None
        self._worker_gateway = None
        self._worker_supervisor: Optional[asyncio.Task] = None
        self._left: Optional[asyncio.Event] = None  # armed in start()
        # Gossip keyring (setupKeyrings, agent.go:350-388): an encrypt key
        # or an existing keyring file arms it.
        keyring_path = (os.path.join(self.config.data_dir, "serf",
                                     "local.keyring")
                        if self.config.data_dir else "")
        if self.config.encrypt or (keyring_path
                                   and os.path.exists(keyring_path)):
            from consul_tpu.agent.keyring import Keyring
            self.server.keyring = Keyring(path=keyring_path,
                                          initial_key=self.config.encrypt)
        # Gossip pools (setupSerf, consul/server.go:257-273): LAN always,
        # WAN for servers.  Created in start() (ports bind there).
        self.lan_pool = None
        self.wan_pool = None
        self.rpc_addr: str = ""     # our RPC mesh addr once attached
        self._bootstrapped = self.config.bootstrap_expect == 0
        self._wan_servers: Dict[str, Dict[str, str]] = {}  # dc -> name -> addr
        self._retry_join_task: Optional[asyncio.Task] = None
        self._check_state_dir_made = False
        # Fire-and-forget task anchor: the loop keeps only weak refs, so
        # an unanchored create_task() can be GC-cancelled mid-run.
        self._bg_tasks: Set[asyncio.Task] = set()

    def _spawn(self, coro) -> asyncio.Task:
        """create_task with a strong reference until completion."""
        task = asyncio.get_event_loop().create_task(coro)
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)
        return task

    @property
    def node_name(self) -> str:
        return self.config.node_name

    @property
    def advertise_addr(self) -> str:
        return self.config.advertise_addr

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        self._left = asyncio.Event()
        self.log.info(f"consul-tpu agent running, node={self.config.node_name}")
        if self.config.server and self.config.rpc_mesh_port is not None:
            host, port = await self.server.attach_rpc(
                self.config.bind_addr, self.config.rpc_mesh_port)
            self.rpc_addr = f"{self.config.advertise_addr}:{port}"
        await self.server.start()
        await self._start_gossip()
        if self.config.server and self.config.bootstrap \
                and not self.config.bootstrap_expect:
            # Single-node semantics: leadership is immediate; register
            # ourselves now.  Clustered agents converge via the leader's
            # reconcile pipeline instead.
            await self.server.wait_for_leader()
            await self._register_self()
        self._load_persisted()
        self.local.start()
        await self._start_http()
        await self.dns.start(self.config.bind_addr, self.config.dns_port)
        ipc_addr = self.config.addresses.get("rpc", "")
        if ipc_addr.startswith("unix://"):
            await self.ipc.start(unix_path=ipc_addr[len("unix://"):])
        elif self.ipc_port is not None:
            await self.ipc.start(self.config.bind_addr, self.ipc_port)

    async def _start_http(self) -> None:
        """Mount the HTTP API on every configured listener: plain TCP,
        unix socket (addresses.http = unix://...), and HTTPS when
        ports.https > 0 (command/agent/http.go:44-173)."""
        http_addr = self.config.addresses.get("http", "")
        unix_path = (http_addr[len("unix://"):]
                     if http_addr.startswith("unix://") else None)
        ssl_ctx = None
        if self.config.https_port > 0:
            from consul_tpu.tlsutil import TLSConfig
            tls = TLSConfig(verify_incoming=self.config.verify_incoming,
                            ca_file=self.config.ca_file,
                            cert_file=self.config.cert_file,
                            key_file=self.config.key_file)
            ssl_ctx = tls.incoming_context()
            if ssl_ctx is None:
                raise ValueError(
                    "ports.https set but cert_file/key_file missing")
        workers = max(1, int(self.config.http_workers))
        # Workers dispatch hot ops against the local raft/store, so the
        # front only multiplies on server-mode agents (a client proxies
        # every request over the mesh anyway).
        multi = (workers > 1 and unix_path is None
                 and self.config.http_port >= 0
                 and getattr(self.server, "raft", None) is not None)
        internal_unix = self._serving_sock("proxy") if multi else None
        await self.http.start(self.config.bind_addr, self.config.http_port,
                              unix_path=unix_path,
                              https_port=self.config.https_port,
                              ssl_context=ssl_ctx,
                              reuse_port=multi,
                              internal_unix_path=internal_unix)
        if multi:
            from consul_tpu.agent.workers import WorkerPool
            from consul_tpu.ipc.server import IPCServer
            gw_path = self._serving_sock("gw")
            self._worker_gateway = IPCServer(self)
            await self._worker_gateway.start(unix_path=gw_path)
            self.worker_pool = WorkerPool()
            # Spawn against the BOUND port (ephemeral :0 support).
            self.worker_pool.spawn(workers - 1, self.config.bind_addr,
                                   self.http.addr[1], gw_path, internal_unix)
            self._worker_supervisor = self._spawn(self._supervise_workers())

    async def _supervise_workers(self) -> None:
        """Worker supervisor: poll the tracked PIDs and respawn dead
        workers with the same argv (WorkerPool.respawn_dead bounds the
        budget, so a crash loop degrades instead of fork-storming).
        SO_REUSEPORT keeps the port serving through the gap — the
        kernel just stops balancing onto the dead listener."""
        try:
            while self.worker_pool is not None:
                await asyncio.sleep(0.5)
                pool = self.worker_pool
                if pool is None:
                    return
                dead = pool.reap_dead()
                if dead:
                    fresh = pool.respawn_dead()
                    if fresh:
                        self.log.warn(
                            f"agent: worker(s) {dead} died; "
                            f"respawned as {fresh}")
        except asyncio.CancelledError:
            pass

    def _serving_sock(self, name: str) -> str:
        """Unix-socket path for the worker plumbing: under data_dir when
        persistent, else the system tmpdir, always pid-qualified so
        parallel test agents never collide."""
        base = (self.config.data_dir if self.config.data_dir
                else tempfile.gettempdir())
        return os.path.join(base, f"consul-{os.getpid()}-{name}.sock")

    async def _start_gossip(self) -> None:
        """Arm the LAN (+WAN for servers) pools, rejoin from snapshots,
        spawn the retry-join loop (setupSerf + startupJoin + retryJoin,
        command/agent/command.go:467-528/692-701)."""
        from consul_tpu.membership import SerfConfig, SerfPool
        from consul_tpu.membership.serf import client_tags, server_tags
        rpc_port = int(self.rpc_addr.rpartition(":")[2] or 8300)
        tags = (server_tags(self.config.datacenter, rpc_port,
                            bootstrap=self.config.bootstrap,
                            expect=self.config.bootstrap_expect,
                            protocol=self.config.protocol)
                if self.config.server else
                client_tags(self.config.datacenter,
                            protocol=self.config.protocol))
        snap_dir = (os.path.join(self.config.data_dir, "serf")
                    if self.config.data_dir else "")
        timing = dict(self.config.serf_timing)
        # Merge delegates (consul/merge.go): the LAN pool only admits
        # members of its own datacenter (:12-38); the WAN pool only
        # admits consul servers (:39-50).
        dc = self.config.datacenter

        def lan_ok(node) -> bool:
            return node.tags.get("dc", dc) == dc

        def wan_ok(node) -> bool:
            return node.tags.get("role") == "consul"

        lan_cfg = SerfConfig(
            node_name=self.config.node_name,
            bind_addr=self.config.bind_addr,
            bind_port=self.config.serf_lan_port,
            advertise_addr=self.config.advertise_addr,
            tags=tags,
            protocol_version=self.config.protocol,
            snapshot_path=(os.path.join(snap_dir, "local.snapshot")
                           if snap_dir else ""),
            **timing)
        if self.config.gossip_backend == "tpu":
            # The graft: membership substrate = the kernel session in
            # the gossip plane daemon, behind the same serf boundary.
            from consul_tpu.membership.tpu_backend import TpuSerfPool
            self.lan_pool = TpuSerfPool(
                lan_cfg, keyring=self.server.keyring,
                on_event=self._on_lan_event, member_filter=lan_ok,
                plane_addr=self.config.gossip_plane)
        else:
            self.lan_pool = SerfPool(
                lan_cfg, keyring=self.server.keyring,
                on_event=self._on_lan_event, member_filter=lan_ok)
        await self.lan_pool.start()
        if self.config.server:
            # WAN member names are qualified node.dc (consul/server.go:288)
            self.wan_pool = SerfPool(SerfConfig(
                node_name=f"{self.config.node_name}.{self.config.datacenter}",
                bind_addr=self.config.bind_addr,
                bind_port=self.config.serf_wan_port,
                advertise_addr=self.config.advertise_addr,
                tags=server_tags(self.config.datacenter, rpc_port,
                                 protocol=self.config.protocol),
                protocol_version=self.config.protocol,
                snapshot_path=(os.path.join(snap_dir, "remote.snapshot")
                               if snap_dir else ""),
                **timing),
                keyring=self.server.keyring, on_event=self._on_wan_event,
                member_filter=wan_ok)
            await self.wan_pool.start()
        self.server.lan_members_fn = self.lan_pool.members
        self.server.user_event_broadcaster = self._broadcast_via_gossip
        # serf snapshot rejoin (consul/server.go:34-35)
        if snap_dir and self.config.rejoin_after_leave:
            from consul_tpu.membership import SerfPool as _SP
            prev = _SP.previous_peers(os.path.join(snap_dir, "local.snapshot"))
            if prev:
                await self.lan_pool.join(prev)
        if self.config.start_join:
            # Synchronous, fatal on total failure (startupJoin,
            # command.go:692-701) — unlike the retry loop below.
            n = await self.lan_pool.join(list(self.config.start_join))
            if n == 0:
                raise RuntimeError(
                    f"agent: failed to join: {self.config.start_join}")
            self.log.info(f"agent: (LAN) joined: {n}")
        if self.config.retry_join:
            self._retry_join_task = asyncio.get_event_loop().create_task(
                self._retry_join_loop())

    async def _retry_join_loop(self) -> None:
        """retryJoin (command.go:467-528): keep dialing until one seed
        answers; bounded by retry_max when configured."""
        attempt = 0
        try:
            while True:
                n = await self.lan_pool.join(list(self.config.retry_join))
                if n > 0:
                    self.log.info(f"agent: (LAN) joined: {n}")
                    return
                attempt += 1
                if self.config.retry_max and attempt >= self.config.retry_max:
                    self.log.error("agent: max join retry exhausted")
                    await self.graceful_leave()
                    return
                await asyncio.sleep(self.config.retry_interval)
        except asyncio.CancelledError:
            pass

    async def stop(self) -> None:
        self.runners.stop_all()
        self.local.stop()
        if self._retry_join_task is not None:
            self._retry_join_task.cancel()
        await self.ipc.stop()
        if self._worker_supervisor is not None:
            # Supervisor before workers, or SIGTERMed children would be
            # "reaped" and respawned mid-shutdown.
            self._worker_supervisor.cancel()
            self._worker_supervisor = None
        # Claim the pool before the first await: a concurrent stop()
        # (signal handler racing a test teardown) must see None, not a
        # half-stopped pool it would try to stop again.
        pool, self.worker_pool = self.worker_pool, None
        if pool is not None:
            # Workers first (by tracked PID), then their gateway — a
            # worker mid-request sees a clean connection close, not a
            # half-up master.
            await pool.stop()
        if self._worker_gateway is not None:
            await self._worker_gateway.stop()
            gw_path = self._worker_gateway.unix_path
            if gw_path:
                try:
                    os.unlink(gw_path)
                except FileNotFoundError:
                    pass
            self._worker_gateway = None
        await self.dns.stop()
        await self.http.stop()
        if self.http.internal_unix_path:
            try:
                os.unlink(self.http.internal_unix_path)
            except FileNotFoundError:
                pass
        if self.wan_pool is not None:
            await self.wan_pool.stop()
        if self.lan_pool is not None:
            await self.lan_pool.stop()
        await self.server.stop()

    async def wait_for_leave(self) -> None:
        """Block until graceful_leave fires (the daemon's signal loop)."""
        if self._left is not None:
            await self._left.wait()

    # -- gossip event plumbing (lanEventHandler, consul/serf.go:35-88) ------

    def _on_lan_event(self, kind: str, payload: Any) -> None:
        from consul_tpu.membership.serf import EV_USER, parse_server
        if kind == EV_USER:
            self._ingest_gossip_event(payload)
            return
        node = payload
        sp = parse_server(node)
        if sp is not None and sp["dc"] == self.config.datacenter and \
                node.name != self.config.node_name:
            # server routing table (nodeJoined/nodeFailed, serf.go:239-275)
            if node.state == "alive":
                self.server.set_route(sp["name"], sp["rpc_addr"])
                self._maybe_bootstrap()
            else:
                self.server.route_table.pop(sp["name"], None)
        self.server.membership_notify(kind, node)

    def _on_wan_event(self, kind: str, payload: Any) -> None:
        from consul_tpu.membership.serf import EV_USER, parse_server
        if kind == EV_USER:
            return  # WAN pool carries no user events (serf.go:65-86)
        node = payload
        sp = parse_server(node)
        if sp is None or sp["dc"] == self.config.datacenter:
            return
        dc_map = self._wan_servers.setdefault(sp["dc"], {})
        if node.state == "alive":
            dc_map[node.name] = sp["rpc_addr"]
        else:
            dc_map.pop(node.name, None)
        if dc_map:
            self.server.set_remote_dc(sp["dc"], list(dc_map.values()))
        else:
            self.server.remote_dcs.pop(sp["dc"], None)
            self._wan_servers.pop(sp["dc"], None)

    def _maybe_bootstrap(self) -> None:
        """bootstrap-expect self-assembly (maybeBootstrap,
        consul/serf.go:185-236): once ``expect`` servers are visible, every
        server independently installs the same sorted peer set and normal
        election proceeds.  One-shot."""
        if self._bootstrapped or not self.config.server:
            return
        from consul_tpu.membership.serf import parse_server
        servers = [parse_server(n) for n in self.lan_pool.alive_members()]
        names = sorted(s["name"] for s in servers
                       if s and s["dc"] == self.config.datacenter)
        if len(names) < self.config.bootstrap_expect:
            return
        names = names[:self.config.bootstrap_expect]
        if self.config.node_name not in names:
            return  # late arrival: wait for the leader's AddPeer instead
        self.log.info(f"agent: bootstrap_expect quorum found: {names}")
        self.server.raft.peers = names
        self._bootstrapped = True

    def _broadcast_via_gossip(self, event) -> None:
        """user_event_broadcaster target: flood the encoded UserEvent on
        the LAN pool; local delivery loops back via _on_lan_event."""
        import msgpack
        self.lan_pool.user_event(
            event.name, msgpack.packb(event.to_wire(), use_bin_type=True))

    def _ingest_gossip_event(self, msg: Dict[str, Any]) -> None:
        import msgpack
        from consul_tpu.structs.structs import UserEvent
        try:
            ev = UserEvent.from_wire(msgpack.unpackb(
                msg["payload"], raw=False, strict_map_key=False))
        except Exception:
            return
        ev.ltime = int(msg.get("ltime", 0))
        self._receive_event(ev)

    # -- IPC-facing operations (command/agent/rpc.go dispatch targets) ------

    async def join(self, addrs: List[str], wan: bool = False) -> int:
        """Gossip join (agent.go JoinLAN/JoinWAN)."""
        self.log.info(f"agent: join {'wan ' if wan else ''}{addrs}")
        pool = self.wan_pool if wan else self.lan_pool
        if pool is None:
            raise RuntimeError(
                "WAN pool requires server mode" if wan
                else "agent not started: no gossip pool")
        return await pool.join(addrs)

    @staticmethod
    def _member_wire(n, default_port: int) -> Dict[str, Any]:
        return {
            "Name": n.name, "Addr": n.addr,
            "Port": n.port or default_port,
            "Status": n.state, "ProtocolCur": 2,
            "Tags": dict(n.tags),
        }

    def gossip_stats(self) -> Dict[str, Dict[str, str]]:
        """`consul info` serf sections (serf.Stats() role): member
        counts per state and which membership substrate is serving the
        LAN pool (the graft's observability hook)."""
        out: Dict[str, Dict[str, str]] = {}

        def _pool_stats(pool) -> Dict[str, str]:
            # Keyed by the Node.state constants (STATE_DEAD is "failed",
            # not "dead" — a literal lookup here once made `consul info`
            # report failed=0 during an outage).
            from consul_tpu.membership.swim import (STATE_ALIVE,
                                                    STATE_DEAD, STATE_LEFT)
            members = pool.members()
            by_state: Dict[str, int] = {}
            for n in members:
                by_state[n.state] = by_state.get(n.state, 0) + 1
            return {"members": str(len(members)),
                    "alive": str(by_state.get(STATE_ALIVE, 0)),
                    "failed": str(by_state.get(STATE_DEAD, 0)),
                    "left": str(by_state.get(STATE_LEFT, 0)),
                    "event_time": str(getattr(pool, "event_ltime", 0))}

        if self.lan_pool is not None:
            out["serf_lan"] = {
                **_pool_stats(self.lan_pool),
                "backend": self.config.gossip_backend,
            }
        if self.wan_pool is not None:
            out["serf_wan"] = _pool_stats(self.wan_pool)
        return out

    def lan_members(self) -> List[Dict[str, Any]]:
        if self.lan_pool is not None:
            return [self._member_wire(n, 8301)
                    for n in self.lan_pool.members()]
        return [{
            "Name": self.config.node_name,
            "Addr": self.config.advertise_addr,
            "Port": 8301,
            "Status": "alive",
            "ProtocolCur": 2,
            "Tags": {"role": "consul" if self.config.server else "node",
                     "dc": self.config.datacenter},
        }]

    def wan_members(self) -> List[Dict[str, Any]]:
        if not self.config.server:
            return []
        if self.wan_pool is not None:
            return [self._member_wire(n, 8302)
                    for n in self.wan_pool.members()]
        m = self.lan_members()[0].copy()
        m["Name"] = f"{self.config.node_name}.{self.config.datacenter}"
        m["Port"] = 8302
        return [m]

    async def graceful_leave(self) -> None:
        """Leave choreography (consul/server.go:516-581): broadcast the
        leave intent so peers mark us left (not failed), then signal the
        daemon loop to shut down."""
        self.log.info("agent: requesting graceful leave")
        if self.wan_pool is not None:
            await self.wan_pool.leave()
        if self.lan_pool is not None:
            await self.lan_pool.leave()
        if self._left is not None:
            self._left.set()

    async def force_leave(self, node: str) -> None:
        """Operator override: failed → left so the catalog reaps it
        (RemoveFailedNode, consul/server.go:624-632)."""
        self.log.info(f"agent: force leave {node}")
        if self.lan_pool is not None:
            self.lan_pool.force_leave(node)
        if self.wan_pool is not None:
            self.wan_pool.force_leave(f"{node}.{self.config.datacenter}")

    async def reload(self) -> None:
        """SIGHUP/IPC reload (command.go:835-908): re-sync local state.
        The daemon wrapper re-reads config files and re-registers
        services/checks/watches around this hook."""
        self.log.info("agent: reloading")
        self.local.resume()

    def log_sink_add(self, sink, level: str = "INFO") -> None:
        self.log.add_sink(sink, level)

    def log_sink_remove(self, sink) -> None:
        self.log.remove_sink(sink)

    async def keyring_operation(self, op: str, key: str = "") -> Dict[str, Any]:
        """Keyring op fanned across every known DC and merged
        (KeyringOperation via globalRPC, consul/internal_endpoint.go:68+)."""
        local = await self.server.keyring_operation_local(op, key)
        merged = {"Keys": dict(local.get("Keys", {})),
                  "NumNodes": local.get("NumNodes", 1),
                  "Messages": dict(local.get("Messages", {}))}
        for dc in list(self.server.remote_dcs):
            out = await self.server.forward_dc(
                dc, "Internal.KeyringOperation", {"op": op, "key": key})
            for k, c in (out or {}).get("Keys", {}).items():
                merged["Keys"][k] = merged["Keys"].get(k, 0) + c
            merged["NumNodes"] += (out or {}).get("NumNodes", 0)
        return merged

    async def _register_self(self) -> None:
        """What handleAliveMember does for each live node on the leader
        (consul/leader.go:354-421): catalog entry + serfHealth check +
        the consul service for servers."""
        req = RegisterRequest(
            node=self.config.node_name,
            address=self.config.advertise_addr,
            check=HealthCheck(
                node=self.config.node_name,
                check_id=SERF_CHECK_ID, name=SERF_CHECK_NAME,
                status=HEALTH_PASSING, output=SERF_ALIVE_OUTPUT),
        )
        if self.config.server:
            req.service = NodeService(
                id=CONSUL_SERVICE_ID, service=CONSUL_SERVICE_NAME, port=8300)
            # The reference's NewAgent seeds the consul service into local
            # state in server mode so /v1/agent/services reports it.
            self.local.services[CONSUL_SERVICE_ID] = req.service
            self.local._service_sync[CONSUL_SERVICE_ID] = True
        await self.server.catalog.register(req)

    # -- catalog interface for the anti-entropy loop ------------------------
    # The embedded-server agent talks to its own endpoints; client mode
    # points these at the RPC mesh.

    async def catalog_register(self, req: RegisterRequest) -> None:
        await self.server.catalog.register(req)

    async def catalog_deregister(self, req) -> None:
        await self.server.catalog.deregister(req)

    async def _catalog_apply_batch(self, ops):
        """Fold N catalog writes into ONE raft entry (PR 18).

        ``ops`` is a list of ``(MessageType, request)`` pairs.  Each op
        gets the same normalization + ACL gate Catalog.register /
        deregister would apply, then the whole list rides a single
        BATCH envelope through consensus — append + quorum paid once.
        Returns the per-sub result list (None = applied, str = the
        sub's error); armed as ``self.catalog_apply_batch`` in server
        mode only, so callers probe with getattr and fall back to the
        sequential per-request path.
        """
        from consul_tpu.agent.reconcile import normalize_register
        from consul_tpu.server.endpoints import EndpointError
        from consul_tpu.structs.structs import MessageType
        for t, req in ops:
            if t == MessageType.REGISTER:
                try:
                    normalize_register(req)
                except ValueError as e:
                    raise EndpointError(str(e)) from e
                svc = req.service
                if svc is not None and svc.service != CONSUL_SERVICE_NAME:
                    acl = await self.server.resolve_token(req.token)
                    if acl is not None and not acl.service_write(svc.service):
                        raise PermissionError("Permission denied")
            elif t == MessageType.DEREGISTER:
                if not req.node:
                    raise EndpointError("Must provide node")
        return await self.server.raft_apply_batch(list(ops))

    async def catalog_node_services(self, node: str):
        _, services = await self.server.catalog.node_services(
            node, QueryOptions(allow_stale=True))
        return services

    async def catalog_node_checks(self, node: str):
        _, checks = await self.server.health.node_checks(
            node, QueryOptions(allow_stale=True))
        return checks

    def cluster_size(self) -> int:
        """aeScale input: LAN pool size when gossip is armed, else the
        catalog (command/agent/util.go:27-37 uses LANMembers)."""
        if self.lan_pool is not None:
            return max(1, len(self.lan_pool.members()))
        fsm = getattr(self.server, "fsm", None)
        if fsm is None:
            return 1  # client with no gossip armed yet
        _, nodes = fsm.store.nodes()
        return max(1, len(nodes))

    # -- user events (user_event.go receive path) ---------------------------

    async def broadcast_event(self, event) -> None:
        """Fire through the server's event plane (Internal.EventFire)."""
        await self.server.fire_user_event(event)

    def _receive_event(self, event) -> None:
        """Gossip/local delivery: filter against local state, then ingest
        into the ring (handleEvents → ingestUserEvent)."""
        if self.events.should_process(event):
            self.events.ingest(event)

    async def handle_remote_exec(self, event) -> None:
        await self.rexec.handle(event)

    # -- service/check registry (agent.go:54-99 API) ------------------------

    async def add_service(self, service: NodeService,
                          check_types: Optional[List[CheckType]] = None,
                          token: str = "", persist: bool = True) -> None:
        """AddService (agent.go:390-470): register locally, spawn runners
        for attached checks, persist, trigger sync."""
        if not service.id and service.service:
            service.id = service.service
        if not service.service:
            raise ValueError("Service name missing")
        for ct in check_types or []:
            if not ct.valid():
                raise ValueError("Check type is not valid")
        # Re-registration replaces the service's checks wholesale —
        # stop stale runners so an orphaned TTL can't flip critical later.
        # Threads this call's persist flag so standalone-check files don't
        # outlive the checks they describe.
        for cid in [cid for cid, c in list(self.local.checks.items())
                    if c.service_id == service.id]:
            await self.remove_check(cid, persist=persist)
        self.local.add_service(service, token)
        for i, ct in enumerate(check_types or []):
            suffix = "" if len(check_types) == 1 else f":{i + 1}"
            check_id = f"service:{service.id}{suffix}"
            check = HealthCheck(
                node=self.config.node_name, check_id=check_id,
                name=f"Service '{service.service}' check",
                status=HEALTH_CRITICAL, notes=ct.notes,
                service_id=service.id, service_name=service.service)
            self.local.add_check(check, token)
            self.runners.start_check(self.local, check_id, ct)
        if persist:
            self._persist("services", service.id, {
                "service": to_api(service),
                "check_types": [vars(ct) for ct in (check_types or [])],
                "token": token})

    async def remove_service(self, service_id: str, persist: bool = True) -> None:
        self.local.remove_service(service_id)
        for cid in [cid for cid, c in list(self.local.checks.items())
                    if c.service_id == service_id]:
            await self.remove_check(cid, persist=persist)
        if persist:
            self._unpersist("services", service_id)

    async def add_check(self, check: HealthCheck,
                        check_type: Optional[CheckType] = None,
                        token: str = "", persist: bool = True) -> None:
        """AddCheck (agent.go:472-538): a standalone check, optionally
        bound to a local service."""
        if check.service_id:
            svc = self.local.services.get(check.service_id)
            if svc is None:
                raise ValueError(
                    f"ServiceID \"{check.service_id}\" does not exist")
            check.service_name = svc.service
        if check_type is not None:
            if not check_type.valid():
                raise ValueError("Check type is not valid")
            # TTL checks with unexpired saved state resume the app's
            # last heartbeat instead of critical (loadCheckState lives
            # in AddCheck in the reference, agent.go:929-959 — this
            # covers config-defined checks too, not just persisted
            # definitions).
            if check_type.is_ttl():
                st = self._load_check_state(check.check_id)
                if st is not None:
                    check.status = st["status"]
                    check.output = st.get("output", "")
            self.runners.start_check(self.local, check.check_id, check_type)
        self.local.add_check(check, token)
        if persist:
            self._persist("checks", check.check_id, {
                "check": to_api(check),
                "check_type": vars(check_type) if check_type else None,
                "token": token})

    async def remove_check(self, check_id: str, persist: bool = True) -> None:
        self.runners.stop_check(check_id)
        self.local.remove_check(check_id)
        if persist:
            self._unpersist("checks", check_id)
            if self.config.data_dir:
                try:
                    os.remove(self._check_state_path(check_id))
                except OSError:
                    pass

    def update_ttl_check(self, check_id: str, status: str, output: str) -> None:
        """TTL heartbeat from the app (agent_endpoint.go pass/warn/fail)."""
        ttl = self.runners.ttl_check(check_id)
        if ttl is None:
            raise ValueError(f'CheckID "{check_id}" does not have '
                             f'associated TTL')
        ttl.set_status(status, output)
        self._persist_check_state(check_id, status, output, ttl.ttl)

    # -- TTL check-state persistence (persistCheckState/loadCheckState,
    # agent.go:890-959): a restart inside the TTL window restores the
    # app's last heartbeat instead of flipping critical. ------------------

    def _check_state_path(self, check_id: str) -> str:
        import hashlib
        h = hashlib.sha1(check_id.encode()).hexdigest()[:16]
        return os.path.join(self.config.data_dir, "checks", "state", h)

    def _persist_check_state(self, check_id: str, status: str, output: str,
                             ttl: float) -> None:
        if not self.config.data_dir:
            return
        import time as _t
        path = self._check_state_path(check_id)
        try:
            if not self._check_state_dir_made:
                os.makedirs(os.path.dirname(path), exist_ok=True)
                self._check_state_dir_made = True
            # Atomic replace: heartbeats rewrite this file constantly,
            # and a torn write would lose the state in exactly the
            # crash-restart case it exists for (same idiom as _persist).
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"check_id": check_id, "status": status,
                           "output": output,
                           "expires": _t.time() + ttl}, f)
            os.replace(tmp, path)
        except OSError:
            pass

    def _load_check_state(self, check_id: str):
        """Saved TTL state, or None if absent/expired (agent.go:929-959
        discards stale state)."""
        if not self.config.data_dir:
            return None
        import time as _t
        try:
            with open(self._check_state_path(check_id)) as f:
                st = json.load(f)
        except (OSError, json.JSONDecodeError):
            return None
        if st.get("check_id") != check_id or st.get("expires", 0) < _t.time():
            return None
        return st

    # -- maintenance mode (agent.go:1229-1320) ------------------------------

    def enable_node_maintenance(self, reason: str = "") -> None:
        if NODE_MAINT_CHECK_ID in self.local.checks:
            return
        self.local.add_check(HealthCheck(
            node=self.config.node_name, check_id=NODE_MAINT_CHECK_ID,
            name="Node Maintenance Mode", status=HEALTH_CRITICAL,
            notes=reason or DEFAULT_NODE_MAINT_REASON))

    def disable_node_maintenance(self) -> None:
        if NODE_MAINT_CHECK_ID in self.local.checks:
            self.local.remove_check(NODE_MAINT_CHECK_ID)

    def enable_service_maintenance(self, service_id: str, reason: str = "") -> None:
        svc = self.local.services.get(service_id)
        if svc is None:
            raise ValueError(f'No service registered with ID "{service_id}"')
        check_id = SERVICE_MAINT_PREFIX + service_id
        if check_id in self.local.checks:
            return
        self.local.add_check(HealthCheck(
            node=self.config.node_name, check_id=check_id,
            name="Service Maintenance Mode", status=HEALTH_CRITICAL,
            notes=reason or DEFAULT_SERVICE_MAINT_REASON,
            service_id=service_id, service_name=svc.service))

    def disable_service_maintenance(self, service_id: str) -> None:
        if service_id not in self.local.services:
            raise ValueError(f'No service registered with ID "{service_id}"')
        check_id = SERVICE_MAINT_PREFIX + service_id
        if check_id in self.local.checks:
            self.local.remove_check(check_id)

    # -- persistence (agent.go:540-612, 890-959; load :1040-1227) -----------

    def _persist_dir(self, kind: str) -> Optional[str]:
        if not self.config.data_dir:
            return None
        d = os.path.join(self.config.data_dir, kind)
        os.makedirs(d, exist_ok=True)
        return d

    @staticmethod
    def _safe_id(ident: str) -> str:
        import hashlib
        return hashlib.sha1(ident.encode()).hexdigest()

    def _persist(self, kind: str, ident: str, payload: dict) -> None:
        d = self._persist_dir(kind)
        if d is None:
            return
        path = os.path.join(d, self._safe_id(ident))
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)

    def _unpersist(self, kind: str, ident: str) -> None:
        d = self._persist_dir(kind)
        if d is None:
            return
        try:
            os.remove(os.path.join(d, self._safe_id(ident)))
        except FileNotFoundError:
            pass

    def _load_persisted(self) -> None:
        """Reload persisted definitions at boot (loadServices/loadChecks).
        Persisted checks resume in critical until their runner reports
        (agent.go:1109-1127)."""
        if not self.config.data_dir:
            return
        d = os.path.join(self.config.data_dir, "services")
        if os.path.isdir(d):
            for fn in sorted(os.listdir(d)):
                try:
                    with open(os.path.join(d, fn)) as f:
                        payload = json.load(f)
                    svc = _service_from_api(payload["service"])
                    cts = [CheckType(**ct) for ct in payload.get("check_types", [])]
                    self._spawn(self.add_service(
                        svc, cts, payload.get("token", ""), persist=False))
                except (json.JSONDecodeError, KeyError, TypeError):
                    continue
        d = os.path.join(self.config.data_dir, "checks")
        if os.path.isdir(d):
            from consul_tpu.agent.http_api import _check_from_api
            for fn in sorted(os.listdir(d)):
                if not os.path.isfile(os.path.join(d, fn)):
                    continue  # e.g. the state/ subdir of TTL heartbeats
                try:
                    with open(os.path.join(d, fn)) as f:
                        payload = json.load(f)
                    check = _check_from_api(payload["check"])
                    check.node = self.config.node_name
                    # persisted checks resume critical until their runner
                    # reports (agent.go:1109-1127)...
                    check.status = HEALTH_CRITICAL
                    check.output = ""
                    ct = (CheckType(**payload["check_type"])
                          if payload.get("check_type") else None)
                    # (TTL saved-state restore happens inside add_check)
                    self._spawn(self.add_check(
                        check, ct, payload.get("token", ""), persist=False))
                except (json.JSONDecodeError, KeyError, TypeError):
                    continue

    # -- HTTP routes owned by the agent (command/agent/agent_endpoint.go) --

    def register_http_routes(self, router, h) -> None:
        router.add_get("/v1/agent/self", h(self._self))
        router.add_get("/v1/agent/services", h(self._services))
        router.add_get("/v1/agent/checks", h(self._checks))
        router.add_get("/v1/agent/members", h(self._members))
        router.add_put("/v1/agent/service/register", h(self._service_register))
        router.add_put("/v1/agent/service/deregister/{id}",
                       h(self._service_deregister))
        router.add_put("/v1/agent/service/maintenance/{id}",
                       h(self._service_maintenance))
        router.add_put("/v1/agent/check/register", h(self._check_register))
        router.add_put("/v1/agent/check/deregister/{id}", h(self._check_deregister))
        router.add_put("/v1/agent/check/pass/{id}", h(self._check_pass))
        router.add_put("/v1/agent/check/warn/{id}", h(self._check_warn))
        router.add_put("/v1/agent/check/fail/{id}", h(self._check_fail))
        router.add_put("/v1/agent/maintenance", h(self._node_maintenance))
        router.add_put("/v1/agent/join/{address}", h(self._join))
        router.add_put("/v1/agent/force-leave/{node}", h(self._force_leave))
        router.add_put("/v1/event/fire/{name}", h(self._event_fire))
        router.add_get("/v1/event/list", h(self._event_list))
        router.add_get("/v1/agent/metrics", h(self._metrics))
        # Detection-latency SLO: an operator health surface like
        # /v1/agent/metrics, not a debug surface — always on.
        router.add_get("/v1/agent/slo", h(self._slo))
        # Device/kernel observatory (obs/devstats.py): dispatch-latency
        # hists, rounds/s EWMA, HBM occupancy, compile + roofline
        # telemetry.  Operator surface like /v1/agent/slo — always on
        # (reports enabled=false when CONSUL_TPU_DEV_OBS=0).
        router.add_get("/v1/agent/device", h(self._device))
        # Consensus-plane observatory (obs/raftstats.py): raft stats +
        # latency histograms + per-peer replication state + the
        # leadership/lease event timeline.  Operator surface like
        # /v1/agent/slo — always on (empty-ish in client mode).
        router.add_get("/v1/operator/raft/telemetry", h(self._raft_telemetry))
        # Autotune observatory (obs/tuner.py): the knob resolution this
        # agent (and its gossip plane) actually booted with — per-knob
        # value, resolution source (flag | verdict | default), evidence
        # keys, reason.  Operator surface like /v1/agent/slo — always on.
        router.add_get("/v1/operator/autotune", h(self._autotune))
        # Transition journey ledger (obs/journey.py): per-stage latency
        # banks + end-to-end SLO over the fused membership→catalog
        # path.  Operator surface like /v1/agent/slo — always on
        # (reports enabled=false when CONSUL_TPU_JOURNEY=0).
        router.add_get("/v1/operator/journey", h(self._journey))
        # Observability surfaces, gated like /debug/pprof/* (http.go
        # EnableDebug): finished traces, the kernel flight recorder,
        # on-demand device profiling, and the one-shot incident bundle.
        if self.config.enable_debug:
            router.add_get("/v1/agent/traces", h(self._traces))
            router.add_get("/v1/agent/flight", h(self._flight))
            router.add_get("/v1/agent/profile", h(self._profile))
            router.add_get("/v1/agent/debug/bundle", h(self._debug_bundle))

    async def _metrics(self, request):
        """Telemetry snapshot: the inmem sink's interval ring (the
        go-metrics dump the reference wires to SIGUSR1), served as JSON
        or — with ``?format=prometheus`` — in the Prometheus text
        exposition format (obs/prom.py)."""
        from consul_tpu.utils.telemetry import metrics
        if request.query.get("format") == "prometheus":
            from aiohttp import web
            return web.Response(text=await self._prom_text(),
                                content_type="text/plain")
        return metrics.snapshot()

    async def _prom_text(self) -> str:
        """Assemble the full Prometheus exposition: telemetry registry,
        kernel flight-recorder fold, detection-latency banks, request
        stats, and the consensus-plane observatory.  Shared by the
        scrape endpoint and the debug bundle's metrics snapshot."""
        from consul_tpu.obs import raftstats
        from consul_tpu.obs.prom import render_prometheus
        from consul_tpu.obs.reqstats import reqstats
        from consul_tpu.utils.telemetry import metrics
        # Scrape-time collection of the kernel flight recorder: it
        # lives in the plane process, so pull its summary over the
        # bridge and mirror it here as consul.flight.* gauges.
        getter = getattr(self.lan_pool, "plane_flight", None)
        if getter is not None:
            from consul_tpu.obs.flight import fold_summary
            fr = await getter(timeout=2.0)
            fold_summary(metrics, fr.get("summary") or {})
        # Same for the detection-latency banks: cumulative histogram
        # families rendered with le/_sum/_count per the text format.
        hists = []
        slo_getter = getattr(self.lan_pool, "plane_slo", None)
        if slo_getter is not None:
            hists += (await slo_getter(timeout=2.0)).get("hists") or []
        # Serving-plane request stats: per-endpoint counters +
        # p50/p99 latency summaries (obs/reqstats.py).  Gateway hot
        # ops and edge handlers share this registry.
        counter_rows, summaries = reqstats.prom_families()
        labeled_counters = []
        if counter_rows:
            labeled_counters.append({
                "name": "consul_http_requests_total",
                "help": "HTTP requests served, by endpoint.",
                "rows": counter_rows,
            })
        # Consensus-plane observatory: raft latency ladders + per-peer
        # replication series (client mode has no raft — skip).
        labeled_gauges = []
        raft = getattr(self.server, "raft", None)
        if raft is not None:
            r_hists, r_gauges, r_counters = raftstats.prom_families(raft)
            hists += r_hists
            labeled_gauges += r_gauges
            labeled_counters += r_counters
        ae_hists, ae_counters = raftstats.aestats.families()
        hists += ae_hists
        labeled_counters += ae_counters
        # Batched reconcile observatory (agent/reconcile.py): batch
        # shape, coalescing yield, detection→watcher-visible latency.
        from consul_tpu.agent import reconcile as _reconcile
        rc_hists, rc_summaries, rc_counters = \
            _reconcile.reconstats.families()
        hists += rc_hists
        summaries += rc_summaries
        labeled_counters += rc_counters
        # Transition journey ledger (obs/journey.py): stage-labeled
        # latency ladder + end-to-end detection→visible histogram over
        # the fused membership→catalog path.  Absent families mean the
        # ledger is compiled out (CONSUL_TPU_JOURNEY=0).
        from consul_tpu.obs import journey as _journey
        if _journey.journey is not None:
            jy_hists, jy_counters = _journey.journey.families()
            hists += jy_hists
            labeled_counters += jy_counters
        # Device state-store observatory (obs/storestats.py): apply/match
        # dispatch ladders, batch shape, table health.  Present only when
        # device_store is on AND the CONSUL_TPU_DEV_OBS gate left the
        # bridge with a StoreStats.
        fsm = getattr(self.server, "fsm", None)
        bridge = getattr(fsm, "device", None) if fsm is not None else None
        if bridge is not None and bridge.stats is not None:
            s_hists, s_gauges, s_counters = bridge.stats.families(
                occupancy=bridge.occupancy(), capacity=bridge.capacity)
            hists += s_hists
            labeled_gauges += s_gauges
            labeled_counters += s_counters
        # Device/kernel observatory: dispatch hists, HBM gauges, compile
        # counters pulled over the bridge (absent when CONSUL_TPU_DEV_OBS=0
        # or for backends without a kernel plane).
        dev_getter = getattr(self.lan_pool, "plane_device", None)
        if dev_getter is not None:
            fams = (await dev_getter(timeout=2.0)).get("families") or {}
            hists += fams.get("histograms") or []
            labeled_gauges += fams.get("gauges") or []
            labeled_counters += fams.get("counters") or []
        # Autotune observatory (obs/tuner.py): per-knob value/source
        # gauges, evidence age, re-settle counter over the merged
        # agent + plane resolution.
        import time as _time

        from consul_tpu.obs import tuner
        at_gauges, at_counters = tuner.prom_families(
            await self._autotune_merged(), _time.time())
        labeled_gauges += at_gauges
        labeled_counters += at_counters
        # Standard scrape hygiene, never gated: build identity + liveness.
        from consul_tpu.obs import devstats
        bi_gauges = devstats.build_info_families(self.config.gossip_backend)
        labeled_gauges += bi_gauges
        # Rendered as a label-less family (not a telemetry point: the
        # registry would interpose the node name and break the stable
        # consul_antientropy_* schema across agents).
        labeled_gauges.append({
            "name": "consul_antientropy_pending_ops",
            "help": "Catalog operations the next anti-entropy pass "
                    "would issue.",
            "rows": [({}, float(self.local.pending_ops()))],
        })
        snap = metrics.snapshot()
        # Lease-vs-barrier consistent-read split as one labeled family
        # (the registry names may carry the node name between the first
        # two key parts — match by suffix).  Both rows always render so
        # lease efficacy is graphable from the first scrape.
        reads = {"lease": 0.0, "barrier": 0.0}
        for iv in snap:
            for k, c in iv.get("Counters", {}).items():
                for path in reads:
                    if k.endswith("read." + path):
                        reads[path] += float(c["sum"])
        labeled_counters.append({
            "name": "consul_consistent_reads_total",
            "help": "Consistent reads served, by confirmation path "
                    "(lease fast path vs barrier/ReadIndex).",
            "rows": [({"path": p}, v) for p, v in sorted(reads.items())],
        })
        return render_prometheus(snap, histograms=hists or None,
                                 summaries=summaries,
                                 labeled_counters=labeled_counters,
                                 labeled_gauges=labeled_gauges or None)

    async def _autotune_merged(self) -> Dict[str, Any]:
        """The full autotune picture for this node: the agent's own
        serving-knob resolution, the gossip plane's kernel-knob
        resolution pulled over the bridge, and a fill-in resolve for
        registry knobs neither process applies directly (the
        device-store matcher floor) — so the operator route and the
        ``consul_autotune_*`` families always cover the whole registry."""
        from consul_tpu.obs import tuner
        out = dict(self.autotune.wire())
        out["knobs"] = dict(out.get("knobs") or {})
        getter = getattr(self.lan_pool, "plane_autotune", None)
        if getter is not None:
            pl = dict(await getter(timeout=2.0))
            pl.pop("t", None)
            out["knobs"].update(pl.get("knobs") or {})
            # The kernel session's fingerprint/verdict metadata is the
            # authoritative chip identity when a plane is attached.
            for k in ("fingerprint", "verdict_path", "verdict_found",
                      "evidence_epoch_unix"):
                if pl.get(k) is not None:
                    out[k] = pl[k]
            out["resettles"] = max(int(out.get("resettles", 0)),
                                   int(pl.get("resettles", 0)))
        missing = [k for k in sorted(tuner.KNOBS) if k not in out["knobs"]]
        if missing:
            fp = out.get("fingerprint") or {}
            fill = tuner.resolve(missing, {},
                                 platform=fp.get("platform") or "cpu",
                                 device_count=fp.get("device_count") or 1)
            out["knobs"].update(fill.rows)
        return out

    async def _autotune(self, request):
        """Autotune observatory JSON (/v1/operator/autotune): each
        registry knob's resolved value, source, evidence keys and
        reason, plus the backend fingerprint and verdict location."""
        out = await self._autotune_merged()
        out.setdefault("backend", self.config.gossip_backend)
        return out

    async def _journey(self, request):
        """Transition journey ledger JSON (/v1/operator/journey):
        per-stage latency banks, end-to-end histogram + SLO burn rate,
        and the recent per-transition record ring (obs/journey.py)."""
        from consul_tpu.obs import journey as _journey
        if _journey.journey is None:
            return _journey.disabled_wire()
        return _journey.journey.wire()

    async def _raft_telemetry(self, request):
        """Consensus-plane telemetry JSON: raft stats, latency
        histograms, per-peer replication state, the leadership/lease
        event timeline, and anti-entropy sync state."""
        from consul_tpu.obs import raftstats
        return raftstats.telemetry(getattr(self.server, "raft", None),
                                   local=self.local)

    async def _debug_bundle(self, request):
        """One-shot incident capture (the `consul debug` analog):
        sample over a short window, return a tar.gz."""
        from aiohttp import web

        from consul_tpu.agent import bundle
        try:
            seconds = float(request.query.get("seconds", "2"))
        except ValueError:
            seconds = 2.0
        seconds = max(0.0, min(30.0, seconds))
        data = await bundle.capture(self, seconds)
        return web.Response(
            body=data, content_type="application/gzip",
            headers={"Content-Disposition":
                     'attachment; filename="consul-debug.tar.gz"'})

    async def _slo(self, request):
        """Detection-latency SLO observatory: burn-rate snapshot, exact
        latency percentiles, cumulative histogram families — drained
        live from the gossip plane's on-device banks.  Empty shell for
        backends without a kernel."""
        getter = getattr(self.lan_pool, "plane_slo", None)
        if getter is None:
            return {"backend": self.config.gossip_backend,
                    "slo": {}, "latency": {}, "hists": []}
        out = dict(await getter())
        out.pop("t", None)  # bridge frame tag, not API surface
        out.setdefault("backend", self.config.gossip_backend)
        out.setdefault("slo", {})
        out.setdefault("latency", {})
        out.setdefault("hists", [])
        return out

    async def _device(self, request):
        """Device/kernel observatory JSON twin of the consul_device_*/
        consul_kernel_* scrape families: dispatch-latency histograms,
        rounds/s EWMA, per-device HBM + live-buffer rows, compile wall
        times + cache counters, and the derived roofline-utilization
        figure.  Empty shell for backends without a kernel."""
        from consul_tpu.obs import devstats
        getter = getattr(self.lan_pool, "plane_device", None)
        if getter is None:
            out = {"backend": self.config.gossip_backend,
                   "enabled": devstats.enabled(), "devices": []}
        else:
            out = dict(await getter())
            out.pop("t", None)  # bridge frame tag, not API surface
            out.setdefault("backend", self.config.gossip_backend)
            out.setdefault("devices", [])
        out["build"] = devstats.build_info(self.config.gossip_backend)
        return out

    async def _profile(self, request):
        """On-demand device profiling (debug-gated): capture a
        jax.profiler trace of K kernel rounds on the plane and return
        the trace directory + timing summary."""
        getter = getattr(self.lan_pool, "plane_profile", None)
        if getter is None:
            return {"backend": self.config.gossip_backend,
                    "error": "no kernel gossip plane attached"}
        try:
            steps = int(request.query.get("steps", "32"))
        except ValueError:
            steps = 32
        phases = request.query.get("phases", "") in ("1", "true", "yes")
        out = dict(await getter(steps=steps, phases=phases))
        out.pop("t", None)
        out.setdefault("backend", self.config.gossip_backend)
        return out

    async def _traces(self, request):
        """Recent finished traces (obs/trace.py ring), newest first."""
        from consul_tpu.obs.trace import tracer
        try:
            limit = int(request.query.get("limit", "50"))
        except ValueError:
            limit = 50
        return tracer.traces(limit)

    async def _flight(self, request):
        """Kernel flight-recorder timeline: per-round SWIM counters
        drained from the gossip plane's HBM ring.  Served from the
        plane over the bridge for the TPU backend; empty for backends
        without a kernel."""
        pool = self.lan_pool
        getter = getattr(pool, "plane_flight", None)
        if getter is None:
            return {"backend": self.config.gossip_backend,
                    "cols": [], "rows": [], "summary": {}}
        out = dict(await getter())
        out.pop("t", None)  # bridge frame tag, not API surface
        out.setdefault("backend", self.config.gossip_backend)
        out.setdefault("cols", [])
        out.setdefault("rows", [])
        out.setdefault("summary", {})
        return out

    async def _self(self, request):
        """/v1/agent/self (agent_endpoint.go:24-34): config + stats."""
        stats = self.server.stats()
        # Device observatory rows (stringly-typed like the reference's
        # runtime stats); only present when a kernel plane is attached.
        getter = getattr(self.lan_pool, "plane_device", None)
        if getter is not None:
            from consul_tpu.obs import devstats
            rows = devstats.stats_rows(await getter(timeout=2.0))
            if rows:
                stats = dict(stats)
                stats["device"] = rows
        return {
            "Config": {
                "Datacenter": self.config.datacenter,
                "NodeName": self.config.node_name,
                "Server": self.config.server,
                "Bootstrap": self.config.bootstrap,
                "Domain": self.config.domain,
                "Version": VERSION,
            },
            "Stats": stats,
        }

    async def _services(self, request):
        """Local state, not catalog (agent_endpoint.go:36-40)."""
        return {sid: to_api(svc) for sid, svc in self.local.services.items()}

    async def _checks(self, request):
        """Local checks plus the node's own serfHealth (which is
        leader-owned, so it lives in the catalog, not local state)."""
        out = {c.check_id: to_api(c) for c in self.local.checks.values()}
        try:
            _, checks = await self.server.health.node_checks(
                self.config.node_name, QueryOptions(allow_stale=True))
        except Exception:
            checks = []
        for c in checks:
            if c.check_id == SERF_CHECK_ID:
                out.setdefault(c.check_id, to_api(c))
        return out

    async def _members(self, request):
        """LAN members; one entry until gossip lands."""
        return [{
            "Name": self.config.node_name,
            "Addr": self.config.advertise_addr,
            "Port": 8301,
            "Status": 1,  # alive
            "Tags": {"role": "consul" if self.config.server else "node",
                     "dc": self.config.datacenter},
        }]

    async def _service_register(self, request):
        """PUT /v1/agent/service/register (agent_endpoint.go:113-163):
        a ServiceDefinition with inline Check/Checks."""
        from consul_tpu.server.endpoints import EndpointError
        body = await self.http._body_json(request)
        svc = NodeService(
            id=body.get("ID", ""), service=body.get("Name", ""),
            tags=body.get("Tags") or [], address=body.get("Address", ""),
            port=body.get("Port", 0))
        cts = []
        raw_checks = body.get("Checks") or []
        if body.get("Check"):
            raw_checks.append(body["Check"])
        for rc in raw_checks:
            cts.append(_check_type_from_api(rc))
        try:
            await self.add_service(svc, cts, self.http._token(request))
        except ValueError as e:
            raise EndpointError(str(e))
        return ""

    async def _service_deregister(self, request):
        await self.remove_service(request.match_info["id"])
        return ""

    async def _service_maintenance(self, request):
        enable = request.query.get("enable", "").lower()
        if enable not in ("true", "false"):
            from consul_tpu.server.endpoints import EndpointError
            raise EndpointError("Missing value for enable")
        try:
            if enable == "true":
                self.enable_service_maintenance(
                    request.match_info["id"], request.query.get("reason", ""))
            else:
                self.disable_service_maintenance(request.match_info["id"])
        except ValueError as e:
            from consul_tpu.agent.http_api import NotFound
            raise NotFound(str(e))
        return ""

    async def _check_register(self, request):
        """PUT /v1/agent/check/register (agent_endpoint.go:165-200)."""
        from consul_tpu.server.endpoints import EndpointError
        body = await self.http._body_json(request)
        ct = _check_type_from_api(body)
        if not ct.valid():
            raise EndpointError(
                "Must provide TTL or Script and Interval!")
        check = HealthCheck(
            node=self.config.node_name,
            check_id=body.get("ID") or body.get("Name", ""),
            name=body.get("Name", ""), notes=body.get("Notes", ""),
            status=HEALTH_CRITICAL,
            service_id=body.get("ServiceID", ""))
        if not check.check_id:
            raise EndpointError("Must provide a check name")
        try:
            await self.add_check(check, ct, self.http._token(request))
        except ValueError as e:
            raise EndpointError(str(e))
        return ""

    async def _check_deregister(self, request):
        await self.remove_check(request.match_info["id"])
        return ""

    def _ttl_update(self, request, status: str):
        from consul_tpu.agent.http_api import NotFound
        note = request.query.get("note", "")
        try:
            self.update_ttl_check(request.match_info["id"], status, note)
        except ValueError as e:
            raise NotFound(str(e))
        return ""

    async def _check_pass(self, request):
        return self._ttl_update(request, HEALTH_PASSING)

    async def _check_warn(self, request):
        from consul_tpu.structs.structs import HEALTH_WARNING
        return self._ttl_update(request, HEALTH_WARNING)

    async def _check_fail(self, request):
        return self._ttl_update(request, HEALTH_CRITICAL)

    async def _node_maintenance(self, request):
        enable = request.query.get("enable", "").lower()
        if enable not in ("true", "false"):
            from consul_tpu.server.endpoints import EndpointError
            raise EndpointError("Missing value for enable")
        if enable == "true":
            self.enable_node_maintenance(request.query.get("reason", ""))
        else:
            self.disable_node_maintenance()
        return ""

    async def _event_fire(self, request):
        """PUT /v1/event/fire/{name} (event_endpoint.go:24-88)."""
        from consul_tpu.server.endpoints import EndpointError
        from consul_tpu.structs.structs import UserEvent
        q = request.query
        event = UserEvent(
            name=request.match_info["name"],
            payload=await request.read(),
            node_filter=q.get("node", ""),
            service_filter=q.get("service", ""),
            tag_filter=q.get("tag", ""),
            datacenter=q.get("dc", ""))
        try:
            eid = await self.events.fire(event)
        except ValueError as e:
            raise EndpointError(str(e))
        return {"ID": eid, "Name": event.name,
                "Payload": to_api(event.payload) if event.payload else None,
                "NodeFilter": event.node_filter,
                "ServiceFilter": event.service_filter,
                "TagFilter": event.tag_filter,
                "Version": event.version, "LTime": 0}

    async def _event_list(self, request):
        """GET /v1/event/list with blocking support
        (event_endpoint.go:90-170)."""
        name = request.query.get("name", "")
        opts = self.http._query_opts(request)  # validated index/wait -> 400
        if opts.min_query_index:
            await self.events.wait_for_change(
                opts.min_query_index, opts.max_query_time or 300.0)
        out = [{
            "ID": e.id, "Name": e.name,
            "Payload": to_api(e.payload) if e.payload else None,
            "NodeFilter": e.node_filter, "ServiceFilter": e.service_filter,
            "TagFilter": e.tag_filter, "Version": e.version,
            "LTime": e.ltime,
        } for e in self.events.events(name)]
        from consul_tpu.structs.structs import QueryMeta
        meta = QueryMeta(index=self.events.index, known_leader=True)
        return self.http._json(request, out, meta)

    async def _join(self, request):
        """Gossip join lands with the network membership layer; the
        single-node agent accepts and no-ops (agent_endpoint.go:75-90)."""
        return ""

    async def _force_leave(self, request):
        return ""


def _check_type_from_api(rc: Dict[str, Any]) -> CheckType:
    from consul_tpu.server.endpoints import parse_duration

    def dur(key: str) -> float:
        v = rc.get(key, "")
        if not v:
            return 0.0
        return parse_duration(v) if isinstance(v, str) else float(v)

    return CheckType(script=rc.get("Script", ""), http=rc.get("HTTP", ""),
                     interval=dur("Interval"), ttl=dur("TTL"),
                     notes=rc.get("Notes", ""), timeout=dur("Timeout"))


