"""The edge agent: embeds a server, exposes HTTP + DNS.

Parity target: ``command/agent/agent.go`` (1319 LoC) + the serve()
choreography of ``command/agent/command.go``.  This slice is the
single-node "bootstrap" agent of SURVEY.md §7 step 3: embedded server,
self-registration with a passing serfHealth check (what the leader
reconcile loop does for real clusters, consul/leader.go:354-421), HTTP
and DNS front-ends.  Local check runners, anti-entropy, and the
client-mode agent land with the edge-features stage.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from consul_tpu.agent.dns import DNSServer
from consul_tpu.agent.http_api import HTTPServer, to_api
from consul_tpu.server.server import Server, ServerConfig
from consul_tpu.structs.structs import (
    CONSUL_SERVICE_ID,
    CONSUL_SERVICE_NAME,
    HEALTH_PASSING,
    HealthCheck,
    NodeService,
    RegisterRequest,
    SERF_ALIVE_OUTPUT,
    SERF_CHECK_ID,
    SERF_CHECK_NAME,
)
from consul_tpu.version import VERSION


@dataclass
class AgentConfig:
    node_name: str = "node1"
    datacenter: str = "dc1"
    bind_addr: str = "127.0.0.1"
    advertise_addr: str = ""
    domain: str = "consul."
    http_port: int = 8500
    dns_port: int = 8600
    server: bool = True
    bootstrap: bool = True
    dns_only_passing: bool = False
    node_ttl: float = 0.0
    service_ttl: float = 0.0
    # ACL passthrough (command/agent/config.go ACL* fields)
    acl_datacenter: str = ""
    acl_ttl: float = 30.0
    acl_default_policy: str = "allow"
    acl_down_policy: str = "extend-cache"
    acl_master_token: str = ""
    acl_token: str = ""  # agent's own default token
    extra: Dict[str, Any] = field(default_factory=dict)


class Agent:
    def __init__(self, config: Optional[AgentConfig] = None) -> None:
        self.config = config or AgentConfig()
        if not self.config.advertise_addr:
            self.config.advertise_addr = self.config.bind_addr
        self.server = Server(ServerConfig(
            node_name=self.config.node_name,
            datacenter=self.config.datacenter,
            domain=self.config.domain,
            bootstrap=self.config.bootstrap,
            acl_datacenter=self.config.acl_datacenter,
            acl_ttl=self.config.acl_ttl,
            acl_default_policy=self.config.acl_default_policy,
            acl_down_policy=self.config.acl_down_policy,
            acl_master_token=self.config.acl_master_token,
        ))
        self.http = HTTPServer(self)
        self.dns = DNSServer(self, domain=self.config.domain,
                             node_ttl=self.config.node_ttl,
                             service_ttl=self.config.service_ttl,
                             only_passing=self.config.dns_only_passing)

    @property
    def node_name(self) -> str:
        return self.config.node_name

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        await self.server.start()
        await self.server.wait_for_leader()
        await self._register_self()
        await self.http.start(self.config.bind_addr, self.config.http_port)
        await self.dns.start(self.config.bind_addr, self.config.dns_port)

    async def stop(self) -> None:
        await self.dns.stop()
        await self.http.stop()
        await self.server.stop()

    async def _register_self(self) -> None:
        """What handleAliveMember does for each live node on the leader
        (consul/leader.go:354-421): catalog entry + serfHealth check +
        the consul service for servers."""
        req = RegisterRequest(
            node=self.config.node_name,
            address=self.config.advertise_addr,
            check=HealthCheck(
                node=self.config.node_name,
                check_id=SERF_CHECK_ID, name=SERF_CHECK_NAME,
                status=HEALTH_PASSING, output=SERF_ALIVE_OUTPUT),
        )
        if self.config.server:
            req.service = NodeService(
                id=CONSUL_SERVICE_ID, service=CONSUL_SERVICE_NAME, port=8300)
        await self.server.catalog.register(req)

    # -- HTTP routes owned by the agent (command/agent/agent_endpoint.go) --

    def register_http_routes(self, router, h) -> None:
        router.add_get("/v1/agent/self", h(self._self))
        router.add_get("/v1/agent/services", h(self._services))
        router.add_get("/v1/agent/checks", h(self._checks))
        router.add_get("/v1/agent/members", h(self._members))

    async def _self(self, request):
        """/v1/agent/self (agent_endpoint.go:24-34): config + stats."""
        return {
            "Config": {
                "Datacenter": self.config.datacenter,
                "NodeName": self.config.node_name,
                "Server": self.config.server,
                "Bootstrap": self.config.bootstrap,
                "Domain": self.config.domain,
                "Version": VERSION,
            },
            "Stats": self.server.stats(),
        }

    async def _services(self, request):
        _, services = self.server.store.node_services(self.config.node_name)
        return {sid: to_api(svc) for sid, svc in (services or {}).items()}

    async def _checks(self, request):
        _, checks = self.server.store.node_checks(self.config.node_name)
        return {c.check_id: to_api(c) for c in checks}

    async def _members(self, request):
        """LAN members; one entry until gossip lands."""
        return [{
            "Name": self.config.node_name,
            "Addr": self.config.advertise_addr,
            "Port": 8301,
            "Status": 1,  # alive
            "Tags": {"role": "consul" if self.config.server else "node",
                     "dc": self.config.datacenter},
        }]
