"""DNS interface: service discovery over port 8600.

Parity target: ``command/agent/dns.go`` (683 LoC) — node lookups
(``<node>.node.<dc>.consul`` → A), PTR lookups (``in-addr.arpa``,
dns.go:164-217), service lookups (``[tag.]<name>.service.<dc>.consul``
→ A / SRV+A-extra), RFC2782 (``_name._tag.service...``), right-to-left
label dispatch (dns.go:272-340), critical-check filtering
(dns.go:522-541), answer shuffling for load balancing (dns.go:543-549),
the UDP 3-answer cap (dns.go:18,502-508), recursor forwarding for
out-of-domain names (dns.go:618-656), and the ``allow_stale`` /
``max_stale`` re-query loop (dns.go:360-372).

The reference rides miekg/dns; we carry a small wire codec instead —
the subset Consul serves (A/SRV/PTR/ANY queries, no EDNS, no
compression on write) is ~100 lines and keeps the agent
dependency-free.  All catalog reads go through the endpoint layer (not
the store), so the same server works for client-mode agents where the
endpoints proxy over the RPC mesh.
"""

from __future__ import annotations

import asyncio
import random
import struct
from dataclasses import dataclass, field
from typing import List, Optional, Set, Tuple

from time import monotonic as _monotonic

from consul_tpu.obs import trace as obs_trace
from consul_tpu.structs.structs import HEALTH_CRITICAL, QueryOptions
from consul_tpu.utils.telemetry import metrics

# Record types / classes
QTYPE_A = 1
QTYPE_PTR = 12
QTYPE_SRV = 33
QTYPE_ANY = 255
QCLASS_IN = 1

# Response codes
RCODE_OK = 0
RCODE_NXDOMAIN = 3
RCODE_REFUSED = 5

MAX_UDP_ANSWERS = 3  # dns.go:18 maxServiceResponses (UDP-safety)


# -- wire codec --------------------------------------------------------------


@dataclass
class Question:
    name: str
    qtype: int
    qclass: int


@dataclass
class Record:
    name: str
    rtype: int
    ttl: int
    rdata: bytes


@dataclass
class Message:
    msg_id: int = 0
    flags: int = 0
    questions: List[Question] = field(default_factory=list)
    answers: List[Record] = field(default_factory=list)
    authority: List[Record] = field(default_factory=list)
    additional: List[Record] = field(default_factory=list)


def _read_name(buf: bytes, off: int) -> Tuple[str, int]:
    """Parse a possibly-compressed DNS name."""
    labels = []
    jumped = False
    end = off
    seen = set()
    while True:
        if off in seen:
            raise ValueError("compression loop")
        seen.add(off)
        ln = buf[off]
        if ln == 0:
            if not jumped:
                end = off + 1
            break
        if ln & 0xC0 == 0xC0:
            ptr = ((ln & 0x3F) << 8) | buf[off + 1]
            if not jumped:
                end = off + 2
                jumped = True
            off = ptr
            continue
        labels.append(buf[off + 1: off + 1 + ln].decode("ascii", "replace"))
        off += 1 + ln
    return ".".join(labels) + ".", end


def _write_name(name: str) -> bytes:
    out = bytearray()
    for label in name.rstrip(".").split("."):
        if label:
            raw = label.encode("ascii")
            out.append(len(raw))
            out += raw
    out.append(0)
    return bytes(out)


def parse_message(buf: bytes) -> Message:
    msg_id, flags, qd, an, ns, ar = struct.unpack("!HHHHHH", buf[:12])
    msg = Message(msg_id=msg_id, flags=flags)
    off = 12
    for _ in range(qd):
        name, off = _read_name(buf, off)
        qtype, qclass = struct.unpack("!HH", buf[off: off + 4])
        off += 4
        msg.questions.append(Question(name, qtype, qclass))
    return msg  # answers in queries aren't parsed (we never recurse)


def build_response(query: Message, rcode: int, answers: List[Record],
                   additional: List[Record] = (), authoritative: bool = True,
                   truncated: bool = False) -> bytes:
    flags = 0x8000  # QR
    flags |= query.flags & 0x0100  # copy RD
    if authoritative:
        flags |= 0x0400
    if truncated:
        flags |= 0x0200
    flags |= rcode & 0xF
    out = bytearray(struct.pack(
        "!HHHHHH", query.msg_id, flags, len(query.questions), len(answers),
        0, len(additional)))
    for q in query.questions:
        out += _write_name(q.name) + struct.pack("!HH", q.qtype, q.qclass)
    for rec in list(answers) + list(additional):
        out += _write_name(rec.name)
        out += struct.pack("!HHIH", rec.rtype, QCLASS_IN, rec.ttl, len(rec.rdata))
        out += rec.rdata
    return bytes(out)


def a_record(name: str, addr: str, ttl: int) -> Optional[Record]:
    try:
        rdata = bytes(int(p) for p in addr.split("."))
        if len(rdata) != 4:
            return None
    except ValueError:
        return None  # non-IPv4 address: reference emits CNAME; we skip
    return Record(name, QTYPE_A, ttl, rdata)


def srv_record(name: str, port: int, target: str, ttl: int) -> Record:
    rdata = struct.pack("!HHH", 1, 1, port) + _write_name(target)
    return Record(name, QTYPE_SRV, ttl, rdata)


# -- server ------------------------------------------------------------------


class DNSServer:
    def __init__(self, agent, domain: str = "consul.",
                 node_ttl: float = 0.0, service_ttl: float = 0.0,
                 only_passing: bool = False, allow_stale: bool = False,
                 max_stale: float = 5.0,
                 recursors: Optional[List[str]] = None,
                 enable_truncate: bool = False) -> None:
        self.agent = agent
        self.domain = domain.rstrip(".").lower() + "."
        self.node_ttl = int(node_ttl)
        self.service_ttl = int(service_ttl)
        self.only_passing = only_passing
        self.allow_stale = allow_stale
        self.max_stale = max_stale
        self.recursors = list(recursors or [])
        self.enable_truncate = enable_truncate
        self._transport: Optional[asyncio.DatagramTransport] = None
        self._tcp_server: Optional[asyncio.AbstractServer] = None
        self.addr: Optional[tuple] = None

    # -- stale-tolerant catalog reads (dns.go:360-372) ----------------------

    def _opts(self) -> QueryOptions:
        return QueryOptions(allow_stale=self.allow_stale)

    async def _requery(self, run):
        """Run an endpoint read; when a stale answer is older than
        max_stale, retry against the leader (the reference's re-query
        loop flips AllowStale off for one attempt)."""
        meta, out = await run(self._opts())
        if self.allow_stale and meta.last_contact > self.max_stale:
            meta, out = await run(QueryOptions(allow_stale=False))
        return meta, out

    async def start(self, host: str = "127.0.0.1", port: int = 8600) -> None:
        loop = asyncio.get_running_loop()
        self._transport, _ = await loop.create_datagram_endpoint(
            lambda: _UDPProtocol(self), local_addr=(host, port))
        self.addr = self._transport.get_extra_info("sockname")[:2]
        self._tcp_server = await asyncio.start_server(
            self._handle_tcp, host, self.addr[1])

    async def stop(self) -> None:
        if self._transport:
            self._transport.close()
        if self._tcp_server:
            self._tcp_server.close()
            await self._tcp_server.wait_closed()

    async def _handle_tcp(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                hdr = await reader.readexactly(2)
                (ln,) = struct.unpack("!H", hdr)
                buf = await reader.readexactly(ln)
                resp = await self.handle(buf, udp=False)
                writer.write(struct.pack("!H", len(resp)) + resp)
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            writer.close()

    async def handle(self, buf: bytes, udp: bool) -> bytes:
        try:
            query = parse_message(buf)
        except Exception:
            return b""
        if not query.questions:
            return build_response(query, RCODE_REFUSED, [])
        q = query.questions[0]
        name = q.name.lower()
        t0 = _monotonic()
        if name.endswith(".in-addr.arpa."):
            span = obs_trace.root_span("dns:ptr_query", tags={"name": name})
            try:
                return await self._ptr_lookup(query, q, name)
            finally:
                span.finish()
                metrics.measure_since(("consul", "dns", "ptr_query"), t0)
        if not name.endswith(self.domain):
            # Out-of-domain: forward to recursors when configured
            # (handleRecurse, dns.go:618-656); refused otherwise.
            if self.recursors:
                resp = await self._recurse(buf)
                if resp is not None:
                    return resp
            return build_response(query, RCODE_REFUSED, [], authoritative=False)
        span = obs_trace.root_span("dns:domain_query", tags={"name": name})
        try:
            return await self._dispatch(query, q, name, udp)
        finally:
            span.finish()
            metrics.measure_since(("consul", "dns", "domain_query"), t0)

    async def _recurse(self, buf: bytes) -> Optional[bytes]:
        """Forward the raw query to each recursor in order; first answer
        wins (dns.go:618-656 tries recursors sequentially)."""
        loop = asyncio.get_running_loop()
        for rec in self.recursors:
            host, _, port = rec.partition(":")
            try:
                addr = (host, int(port) if port else 53)
            except ValueError:
                continue  # malformed recursor entry; try the next
            try:
                fut: asyncio.Future = loop.create_future()
                transport, _ = await loop.create_datagram_endpoint(
                    lambda: _RecurseProtocol(fut), remote_addr=addr)
                try:
                    transport.sendto(buf)
                    return await asyncio.wait_for(fut, 2.0)
                finally:
                    transport.close()
            except (OSError, asyncio.TimeoutError):
                continue
        return None

    async def _dispatch(self, query: Message, q: Question, name: str,
                        udp: bool) -> bytes:
        """Right-to-left label parse (dns.go:272-340)."""
        sub = name[: -len(self.domain)].rstrip(".")
        labels = sub.split(".") if sub else []
        if not labels:
            # Apex: reference serves SOA/NS; we answer empty-authoritative.
            return build_response(query, RCODE_OK, [])
        # [dc] comes last when it matches a known datacenter
        dc = ""
        if len(labels) >= 2 and labels[-1] not in ("node", "service") and \
                labels[-2] in ("node", "service"):
            dc = labels[-1]
            labels = labels[:-1]
            if dc != self.agent.server.config.datacenter:
                return build_response(query, RCODE_NXDOMAIN, [])
        kind = labels[-1] if labels else ""
        rest = labels[:-1]
        if kind == "node" and len(rest) >= 1:
            return await self._node_lookup(query, q, ".".join(rest), udp)
        if kind == "service" and rest:
            # RFC2782: _name._tag.service (dns.go:303-327)
            if len(rest) == 2 and rest[0].startswith("_") and rest[1].startswith("_"):
                svc, tag = rest[0][1:], rest[1][1:]
                if tag == "tcp":  # _svc._tcp means no tag filter in consul
                    tag = ""
                return await self._service_lookup(query, q, svc, tag, udp)
            if len(rest) == 1:
                return await self._service_lookup(query, q, rest[0], "", udp)
            if len(rest) == 2:
                tag, svc = rest[0], rest[1]
                return await self._service_lookup(query, q, svc, tag, udp)
        return build_response(query, RCODE_NXDOMAIN, [])

    async def _node_lookup(self, query: Message, q: Question, node: str,
                           udp: bool) -> bytes:
        """A record for a node (dns.go:343-450), via Internal.NodeInfo
        so client-mode agents resolve over the mesh."""
        async def run(opts):
            return await self.agent.server.internal.node_info(node, opts)
        try:
            _, dump = await self._requery(run)
        except Exception:
            return build_response(query, RCODE_REFUSED, [],
                                  authoritative=False)
        if not dump:
            return build_response(query, RCODE_NXDOMAIN, [])
        rec = a_record(q.name, dump[0]["address"], self.node_ttl)
        return build_response(query, RCODE_OK, [rec] if rec else [])

    async def _ptr_lookup(self, query: Message, q: Question,
                          name: str) -> bytes:
        """Reverse lookup: octets arrive reversed under in-addr.arpa
        (handlePtr, dns.go:164-217)."""
        octets = name[: -len(".in-addr.arpa.")].split(".")
        addr = ".".join(reversed(octets))
        async def run(opts):
            return await self.agent.server.catalog.list_nodes(opts)
        try:
            _, nodes = await self._requery(run)
        except Exception:
            return build_response(query, RCODE_REFUSED, [],
                                  authoritative=False)
        dc = self.agent.server.config.datacenter
        answers = [
            Record(q.name, QTYPE_PTR, self.node_ttl,
                   _write_name(f"{n.node}.node.{dc}.{self.domain}"))
            for n in nodes if n.address == addr]
        if not answers:
            return build_response(query, RCODE_NXDOMAIN, [])
        return build_response(query, RCODE_OK, answers)

    async def _service_lookup(self, query: Message, q: Question, service: str,
                              tag: str, udp: bool) -> bytes:
        """Service answers: filter, shuffle, cap (dns.go:452-616)."""
        async def run(opts):
            return await self.agent.server.health.service_nodes(
                service, opts, tag)
        try:
            _, csns = await self._requery(run)
        except Exception:
            return build_response(query, RCODE_REFUSED, [],
                                  authoritative=False)
        # Drop instances with any critical check (dns.go:522-541); with
        # only_passing, warning also drops.
        healthy = []
        for csn in csns:
            statuses = [c.status for c in csn.checks]
            if HEALTH_CRITICAL in statuses:
                continue
            if self.only_passing and any(s != "passing" for s in statuses):
                continue
            healthy.append(csn)
        if not healthy:
            return build_response(query, RCODE_NXDOMAIN, [])
        random.shuffle(healthy)  # poor-man's LB (dns.go:543-549)

        truncated = False
        if udp and len(healthy) > MAX_UDP_ANSWERS:
            healthy = healthy[:MAX_UDP_ANSWERS]
            # Default: cap silently to avoid TCP retries; with
            # enable_truncate the TC bit advertises the cut (the
            # reference's EnableTruncate knob, config.go DNSConfig).
            truncated = self.enable_truncate

        answers: List[Record] = []
        additional: List[Record] = []
        if q.qtype in (QTYPE_SRV,):
            dc = self.agent.server.config.datacenter
            for csn in healthy:
                target = f"{csn.node.node}.node.{dc}.{self.domain}"
                answers.append(srv_record(q.name, csn.service.port, target,
                                          self.service_ttl))
                addr = csn.service.address or csn.node.address
                rec = a_record(target, addr, self.service_ttl)
                if rec:
                    additional.append(rec)
        else:  # A / ANY
            for csn in healthy:
                addr = csn.service.address or csn.node.address
                rec = a_record(q.name, addr, self.service_ttl)
                if rec:
                    answers.append(rec)
        return build_response(query, RCODE_OK, answers, additional,
                              truncated=truncated)


class _RecurseProtocol(asyncio.DatagramProtocol):
    """One-shot upstream exchange for recursor forwarding."""

    def __init__(self, fut: asyncio.Future) -> None:
        self.fut = fut

    def datagram_received(self, data: bytes, addr) -> None:
        if not self.fut.done():
            self.fut.set_result(data)

    def error_received(self, exc: Exception) -> None:
        if not self.fut.done():
            self.fut.set_exception(exc)


class _UDPProtocol(asyncio.DatagramProtocol):
    def __init__(self, server: DNSServer) -> None:
        self.server = server
        self.transport: Optional[asyncio.DatagramTransport] = None
        # anchor per-query tasks: the loop keeps only weak refs, and a
        # GC'd task silently drops the DNS response
        self._tasks: Set[asyncio.Task] = set()

    def connection_made(self, transport) -> None:
        self.transport = transport

    def datagram_received(self, data: bytes, addr) -> None:
        task = asyncio.ensure_future(self._respond(data, addr))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def _respond(self, data: bytes, addr) -> None:
        resp = await self.server.handle(data, udp=True)
        if resp and self.transport:
            self.transport.sendto(resp, addr)
