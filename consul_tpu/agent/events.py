"""User events: fire, filter, buffer, and the remote-exec hook.

Parity target: ``command/agent/user_event.go`` (268 LoC) — the
UserEvent struct with node/service/tag regex filters (:19-44), the
fire path through Internal.EventFire per-DC, and the receive path that
validates filters against local state, stores into a 256-slot ring
buffer, and notifies event watches; ``_rexec`` events are intercepted
for remote execution (remote_exec.py).
"""

from __future__ import annotations

import asyncio
import re
import uuid
from typing import List

from consul_tpu.structs.structs import UserEvent

USER_EVENT_BUFFER = 256   # ring size (agent.go:87-94)
REMOTE_EXEC_EVENT = "_rexec"


class EventManager:
    """Owns the agent's received-event ring + lamport-ish event ids."""

    def __init__(self, agent) -> None:
        self.agent = agent
        self._ring: List[UserEvent] = []
        self._index = 0          # monotonic, serves blocking /v1/event/list
        self._waiters: List[asyncio.Future] = []
        self._ltime = 0
        self._tasks: set = set()  # strong refs to in-flight rexec handlers

    # -- fire path (user_event.go UserEvent + internal EventFire) -----------

    def validate(self, event: UserEvent) -> None:
        if not event.name:
            raise ValueError("User event missing name")
        for pat, what in ((event.node_filter, "node"),
                          (event.service_filter, "service"),
                          (event.tag_filter, "tag")):
            if pat:
                try:
                    re.compile(pat)
                except re.error as e:
                    raise ValueError(f"Invalid {what} filter: {e}")
        if event.tag_filter and not event.service_filter:
            raise ValueError("Cannot provide tag filter without service filter")

    async def fire(self, event: UserEvent) -> str:
        """Assign id + lamport time, broadcast (gossip once the network
        membership layer lands; local delivery always)."""
        self.validate(event)
        if not event.id:
            event.id = str(uuid.uuid4())
        self._ltime += 1
        event.ltime = self._ltime
        await self.agent.broadcast_event(event)
        return event.id

    # -- receive path (ingestUserEvent, user_event.go:120-210) --------------

    def should_process(self, event: UserEvent) -> bool:
        """Apply node/service/tag regex filters against local state."""
        if event.node_filter and not re.search(event.node_filter,
                                               self.agent.node_name):
            return False
        if event.service_filter:
            matched = False
            for svc in self.agent.local.services.values():
                if re.search(event.service_filter, svc.service):
                    if event.tag_filter:
                        if any(re.search(event.tag_filter, t)
                               for t in svc.tags):
                            matched = True
                            break
                    else:
                        matched = True
                        break
            if not matched:
                return False
        return True

    def ingest(self, event: UserEvent) -> None:
        """Store into the ring and wake blocking list queries."""
        if event.name == REMOTE_EXEC_EVENT:
            task = asyncio.get_event_loop().create_task(
                self.agent.handle_remote_exec(event))
            # asyncio keeps only weak refs; anchor until done so the job
            # can't be garbage-collected mid-run.
            self._tasks.add(task)
            task.add_done_callback(self._tasks.discard)
            return
        self._ring.append(event)
        if len(self._ring) > USER_EVENT_BUFFER:
            self._ring = self._ring[-USER_EVENT_BUFFER:]
        self._index += 1
        waiters, self._waiters = self._waiters, []
        for fut in waiters:
            if not fut.done():
                fut.set_result(None)

    # -- blocking list (event_endpoint.go:90-170) ---------------------------

    @property
    def index(self) -> int:
        return self._index

    def events(self, name: str = "") -> List[UserEvent]:
        if name:
            return [e for e in self._ring if e.name == name]
        return list(self._ring)

    async def wait_for_change(self, min_index: int, max_wait: float) -> None:
        if self._index > min_index:
            return
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._waiters.append(fut)
        try:
            await asyncio.wait_for(fut, max_wait)
        except asyncio.TimeoutError:
            pass
        finally:
            if fut in self._waiters:
                self._waiters.remove(fut)
