"""Gossip encryption keyring.

Parity target: ``command/agent/keyring.go`` (22-108: init/load of the
serf keyring file) + the Serf KeyManager semantics the CLI drives via
``consul keyring`` (install / use / remove / list) and
``Internal.KeyringOperation``'s cross-DC fan-out.

The ring is a JSON file of base64 16-byte keys with the primary first
(the serf snapshot format).  Keys gate the real-network gossip path;
the in-HBM simulator doesn't encrypt (no wire to protect), so the ring
is authoritative agent state that the UDP transport will consume.
"""

from __future__ import annotations

import base64
import json
import os
from typing import Dict, List

KEY_LEN = 16


class KeyringError(ValueError):
    pass


def _validate(key: str) -> bytes:
    try:
        raw = base64.b64decode(key, validate=True)
    except Exception:
        raise KeyringError(f"Invalid key: not base64")
    if len(raw) != KEY_LEN:
        raise KeyringError(f"Invalid key: expected {KEY_LEN} bytes, "
                           f"got {len(raw)}")
    return raw


class Keyring:
    """Primary + installed keys, optionally persisted to
    ``<data-dir>/serf/local.keyring`` (loadKeyringFile, keyring.go:57+)."""

    def __init__(self, path: str = "", initial_key: str = "") -> None:
        self.path = path
        self.keys: List[str] = []
        if path and os.path.exists(path):
            with open(path) as f:
                keys = json.load(f)
            if not isinstance(keys, list) or not keys:
                raise KeyringError(f"keyring file {path} is invalid")
            for k in keys:
                _validate(k)
            self.keys = keys
        elif initial_key:
            _validate(initial_key)
            self.keys = [initial_key]
            self._save()
        else:
            raise KeyringError("no keyring file and no initial key")

    @property
    def primary(self) -> str:
        return self.keys[0]

    def _save(self) -> None:
        if not self.path:
            return
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.keys, f)
        os.replace(tmp, self.path)

    # -- operations (serf KeyManager semantics) -----------------------------

    def install(self, key: str) -> None:
        _validate(key)
        if key not in self.keys:
            self.keys.append(key)
            self._save()

    def use(self, key: str) -> None:
        if key not in self.keys:
            raise KeyringError("key is not installed, install it first")
        self.keys.remove(key)
        self.keys.insert(0, key)
        self._save()

    def remove(self, key: str) -> None:
        if key == self.primary:
            raise KeyringError("Removing the primary key is not allowed")
        if key in self.keys:
            self.keys.remove(key)
            self._save()

    def list_keys(self) -> List[str]:
        return list(self.keys)

    def operation(self, op: str, key: str = "",
                  node: str = "") -> Dict:
        """One node's response to a keyring op; the fan-out layer merges
        these into the per-DC KeyringResponse shape."""
        if op == "list":
            return {"Keys": {k: 1 for k in self.keys}, "NumNodes": 1,
                    "Messages": {}}
        if op == "install":
            self.install(key)
        elif op == "use":
            self.use(key)
        elif op == "remove":
            self.remove(key)
        else:
            raise KeyringError(f"unknown keyring op: {op}")
        return {"Keys": {}, "NumNodes": 1, "Messages": {}}
