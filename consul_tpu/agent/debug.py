"""Runtime profiling endpoints: the ``/debug/pprof/*`` role.

The reference registers Go's pprof handlers when ``EnableDebug`` is set
(``command/agent/http.go:259-264``): CPU profile, goroutine dump, heap
profile.  The Python-runtime equivalents served here (text/plain, in
the spirit of ``pprof?debug=1`` output):

* ``/debug/pprof/profile?seconds=N`` — cProfile capture of the agent's
  event-loop thread for N seconds (the loop thread is where all agent
  work happens, so this is the CPU profile that matters).
* ``/debug/pprof/goroutine`` — every thread's current stack plus every
  asyncio task's stack (tasks are this runtime's goroutines).
* ``/debug/pprof/heap?seconds=N`` — tracemalloc growth capture: tracing
  is started for the sample window and stopped after (allocator tracing
  roughly doubles allocation cost, so it never stays armed); reports the
  top allocation sites and the delta over the window.

All three are read-only diagnostics; like the reference they are only
routed when ``enable_debug`` is set in the agent config.
"""

from __future__ import annotations

import asyncio
import cProfile
import io
import pstats
import sys
import threading
import traceback


def _clamp_seconds(request, default: float = 2.0, hi: float = 30.0) -> float:
    try:
        s = float(request.query.get("seconds", default))
    except ValueError:
        s = default
    return max(0.1, min(hi, s))


_profile_active = False


async def profile(request):
    """CPU profile of the event-loop thread over the sample window."""
    global _profile_active
    from aiohttp import web

    # cProfile is process-global: a second concurrent enable() raises.
    # Mirror net/http/pprof, which serves one CPU profile at a time.
    if _profile_active:
        return web.Response(status=503, text="cpu profile already running\n")
    seconds = _clamp_seconds(request)
    prof = cProfile.Profile()
    _profile_active = True
    try:
        prof.enable()
        try:
            await asyncio.sleep(seconds)
        finally:
            prof.disable()
    finally:
        _profile_active = False
    out = io.StringIO()
    stats = pstats.Stats(prof, stream=out)
    stats.sort_stats("cumulative").print_stats(60)
    return web.Response(
        text=f"# cpu profile: event-loop thread, {seconds:.1f}s window\n"
             + out.getvalue(),
        content_type="text/plain")


def task_dump() -> str:
    """All thread stacks + all asyncio task stacks, as text.  Shared by
    the ``/debug/pprof/goroutine`` route and the debug bundle's
    ``tasks.txt`` section (agent/bundle.py)."""
    out = io.StringIO()
    names = {t.ident: t.name for t in threading.enumerate()}
    frames = sys._current_frames()
    out.write(f"# {len(frames)} threads\n")
    for ident, frame in frames.items():
        out.write(f"\n-- thread {names.get(ident, '?')} ({ident}) --\n")
        out.write("".join(traceback.format_stack(frame)))

    tasks = [t for t in asyncio.all_tasks() if not t.done()]
    out.write(f"\n# {len(tasks)} asyncio tasks\n")
    for t in tasks:
        out.write(f"\n-- task {t.get_name()} --\n")
        buf = io.StringIO()
        t.print_stack(limit=12, file=buf)
        out.write(buf.getvalue())
    return out.getvalue()


async def goroutine(request):
    """All thread stacks + all asyncio task stacks."""
    from aiohttp import web

    return web.Response(text=task_dump(), content_type="text/plain")


_heap_windows = 0      # overlapping /heap captures in flight
_heap_we_started = False  # tracing was armed by this module


async def heap(request):
    """Top allocation sites and growth over the sample window."""
    global _heap_windows, _heap_we_started
    import tracemalloc

    from aiohttp import web

    # Tracing costs ~2x on every allocation; scope it to the union of
    # in-flight sample windows instead of leaving it armed for the life
    # of the agent (Go's heap profile has no such persistent cost).
    # Refcounted so overlapping captures don't stop each other's
    # tracing mid-window; tracing armed by someone else is left alone.
    if not tracemalloc.is_tracing():
        tracemalloc.start()
        _heap_we_started = True
    _heap_windows += 1
    try:
        seconds = _clamp_seconds(request)
        before = tracemalloc.take_snapshot()
        await asyncio.sleep(seconds)
        after = tracemalloc.take_snapshot()
        cur, peak = tracemalloc.get_traced_memory()
    finally:
        _heap_windows -= 1
        if _heap_windows == 0 and _heap_we_started:
            tracemalloc.stop()
            _heap_we_started = False

    out = io.StringIO()
    out.write(f"# heap: traced={cur / 1024:.0f}KiB peak={peak / 1024:.0f}KiB, "
              f"{seconds:.1f}s growth window\n\n== top sites ==\n")
    for stat in after.statistics("lineno")[:30]:
        out.write(f"{stat}\n")
    out.write("\n== growth over window ==\n")
    for stat in after.compare_to(before, "lineno")[:30]:
        out.write(f"{stat}\n")
    return web.Response(text=out.getvalue(), content_type="text/plain")


def register(router, h) -> None:
    """Mount the pprof-role routes (call only when enable_debug is set)."""
    router.add_get("/debug/pprof/profile", h(profile))
    router.add_get("/debug/pprof/goroutine", h(goroutine))
    router.add_get("/debug/pprof/heap", h(heap))
