"""Agent-local service/check state and catalog anti-entropy.

Parity target: ``command/agent/local.go`` (596 LoC).  The agent owns
the authoritative copy of ITS OWN services and checks; anti-entropy
diffs that local truth against the (possibly stale) catalog and issues
register/deregister calls until they agree — sync on change plus a
periodic full pass whose interval grows log2 with cluster size
(aeScale, command/agent/util.go:27-37) under random stagger.

The sync target is an async catalog interface; the embedded-server
agent wires it straight to its own endpoints, a client-mode agent to
the RPC mesh.  Either way the flow matches §3.2's write path.
"""

from __future__ import annotations

import asyncio
import math
import random
import time
from typing import Dict, Optional

# Module-attr access (not ``from ... import aestats``) so tests can
# swap the singleton under us.
from consul_tpu.obs import raftstats
from consul_tpu.structs.structs import (
    DeregisterRequest, HealthCheck, MessageType, NodeService,
    RegisterRequest, SERF_CHECK_ID)

AE_BASE_INTERVAL = 60.0   # sync interval floor (agent.go aeInterval)
AE_SCALE_THRESHOLD = 128  # nodes before the interval starts growing


def ae_scale(interval: float, n_nodes: int) -> float:
    """Scale the anti-entropy interval by ceil(log2(n/128))+1 so catalog
    write load stays ~constant as the cluster grows (util.go:27-37)."""
    if n_nodes <= AE_SCALE_THRESHOLD:
        return interval
    mult = math.ceil(math.log2(n_nodes) - math.log2(AE_SCALE_THRESHOLD)) + 1
    return interval * mult


class LocalState:
    def __init__(self, agent, sync_interval: float = AE_BASE_INTERVAL) -> None:
        self.agent = agent
        self.sync_interval = sync_interval
        self.services: Dict[str, NodeService] = {}
        self.checks: Dict[str, HealthCheck] = {}
        self.service_tokens: Dict[str, str] = {}
        self.check_tokens: Dict[str, str] = {}
        # sync bookkeeping: id -> in_sync; separate deregister maps for
        # remote entries we no longer own (local.go syncStatus).  Each
        # deregister intent carries the epoch it was queued under so the
        # consume after the catalog round-trip can tell "the intent I
        # pushed" from "a newer intent re-queued mid-flight" — the
        # snapshot-compare convention the register paths already use.
        self._service_sync: Dict[str, bool] = {}
        self._check_sync: Dict[str, bool] = {}
        self._deregister_services: Dict[str, int] = {}
        self._deregister_checks: Dict[str, int] = {}
        self._dereg_epoch = 0
        self._paused = False
        self._trigger = asyncio.Event()
        self._task: Optional[asyncio.Task] = None

    # -- registry mutations (local.go:108-246) ------------------------------

    def add_service(self, service: NodeService, token: str = "") -> None:
        self.services[service.id] = service
        self.service_tokens[service.id] = token
        self._service_sync[service.id] = False
        self._deregister_services.pop(service.id, None)
        self.changed()

    def remove_service(self, service_id: str) -> None:
        self.services.pop(service_id, None)
        self.service_tokens.pop(service_id, None)
        self._service_sync.pop(service_id, None)
        self._dereg_epoch += 1
        self._deregister_services[service_id] = self._dereg_epoch
        self.changed()

    def add_check(self, check: HealthCheck, token: str = "") -> None:
        self.checks[check.check_id] = check
        self.check_tokens[check.check_id] = token
        self._check_sync[check.check_id] = False
        self._deregister_checks.pop(check.check_id, None)
        self.changed()

    def remove_check(self, check_id: str) -> None:
        if check_id == SERF_CHECK_ID:
            # serfHealth is leader-owned (consul/leader.go:17-22); letting a
            # local deregister delete it would wipe the node from ?passing
            # queries with nothing to re-register it in single-node mode.
            return
        self.checks.pop(check_id, None)
        self.check_tokens.pop(check_id, None)
        self._check_sync.pop(check_id, None)
        self._dereg_epoch += 1
        self._deregister_checks[check_id] = self._dereg_epoch
        self.changed()

    def update_check(self, check_id: str, status: str, output: str) -> None:
        """Check runner callback (local.go UpdateCheck): no-op unless the
        visible state changed."""
        check = self.checks.get(check_id)
        if check is None:
            return
        if check.status == status and check.output == output:
            return
        check.status = status
        check.output = output
        self._check_sync[check_id] = False
        self.changed()

    # -- pause/resume for config reloads (local.go:79-104) ------------------

    def pause(self) -> None:
        self._paused = True

    def resume(self) -> None:
        self._paused = False
        self.changed()

    def changed(self) -> None:
        self._trigger.set()

    # -- the anti-entropy loop (local.go:290-338) ---------------------------

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_event_loop().create_task(self._loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _loop(self) -> None:
        try:
            while True:
                interval = ae_scale(self.sync_interval,
                                    self.agent.cluster_size())
                # stagger by up to interval/16 like aeStagger
                timeout = interval + random.uniform(0, interval / 16)
                try:
                    await asyncio.wait_for(self._trigger.wait(), timeout)
                except asyncio.TimeoutError:
                    pass
                self._trigger.clear()
                if self._paused:
                    continue
                try:
                    await self.sync_once()
                except Exception:
                    # Catalog unreachable: back off briefly, then re-arm the
                    # trigger so the retry is immediate rather than a full
                    # interval away (local.go:318-326 retries on short tick).
                    await asyncio.sleep(min(1.0, self.sync_interval))
                    self._trigger.set()
        except asyncio.CancelledError:
            pass

    async def sync_once(self) -> None:
        t0 = time.monotonic()
        try:
            await self.set_sync_state()
        except Exception:
            raftstats.aestats.failure("diff")
            raise
        await self.sync_changes()
        raftstats.aestats.sync_done((time.monotonic() - t0) * 1000.0)

    def pending_ops(self) -> int:
        """Catalog operations the next sync pass would issue: queued
        deregisters plus entries marked out of sync (the scrape-time
        ``consul_antientropy_pending_ops`` gauge)."""
        return (len(self._deregister_services)
                + len(self._deregister_checks)
                + sum(1 for sid, ok in self._service_sync.items()
                      if not ok and sid in self.services)
                + sum(1 for cid, ok in self._check_sync.items()
                      if not ok and cid in self.checks))

    # -- diff against the catalog (setSyncState, local.go:342-430) ----------

    async def set_sync_state(self) -> None:
        node = self.agent.node_name
        remote_services = await self.agent.catalog_node_services(node)
        remote_checks = await self.agent.catalog_node_checks(node)

        for sid, remote in (remote_services or {}).items():
            if sid == "consul":
                continue  # the embedded server's own entry is leader-owned
            local = self.services.get(sid)
            if local is None:
                self._dereg_epoch += 1
                self._deregister_services[sid] = self._dereg_epoch
            else:
                in_sync = (local.service == remote.service
                           and sorted(local.tags) == sorted(remote.tags)
                           and local.address == remote.address
                           and local.port == remote.port)
                self._service_sync[sid] = in_sync
        for sid in self.services:
            if sid not in (remote_services or {}):
                self._service_sync[sid] = False

        remote_by_id = {c.check_id: c for c in (remote_checks or [])}
        for cid, remote in remote_by_id.items():
            if cid == SERF_CHECK_ID:
                continue  # serfHealth belongs to the leader reconcile loop
            local = self.checks.get(cid)
            if local is None:
                self._dereg_epoch += 1
                self._deregister_checks[cid] = self._dereg_epoch
            else:
                self._check_sync[cid] = (local.status == remote.status
                                         and local.output == remote.output
                                         and local.name == remote.name)
        for cid in self.checks:
            if cid not in remote_by_id:
                self._check_sync[cid] = False

    # -- push the deltas (syncChanges, local.go:434-476) --------------------

    async def sync_changes(self) -> None:
        # Server-mode agents expose the one-raft-entry batched catalog
        # path (PR 18): fold every dirty entry into a single BATCH
        # envelope so anti-entropy pays append + quorum once per pass.
        submit = getattr(self.agent, "catalog_apply_batch", None)
        if submit is not None:
            await self._sync_changes_batched(submit)
            return
        node = self.agent.node_name
        addr = self.agent.advertise_addr

        for sid, epoch in list(self._deregister_services.items()):
            try:
                await self.agent.catalog_deregister(DeregisterRequest(
                    node=node, service_id=sid,
                    token=self.service_tokens.get(sid, "")))
            except Exception:
                raftstats.aestats.failure("service_deregister")
                raise
            # Only consume the intent we actually pushed: an intent
            # re-queued during the await carries a newer epoch and must
            # survive for the next pass.
            if self._deregister_services.get(sid) == epoch:
                self._deregister_services.pop(sid, None)
        for cid, epoch in list(self._deregister_checks.items()):
            try:
                await self.agent.catalog_deregister(DeregisterRequest(
                    node=node, check_id=cid,
                    token=self.check_tokens.get(cid, "")))
            except Exception:
                raftstats.aestats.failure("check_deregister")
                raise
            if self._deregister_checks.get(cid) == epoch:
                self._deregister_checks.pop(cid, None)

        for sid, in_sync in list(self._service_sync.items()):
            if in_sync or sid not in self.services:
                continue
            service = self.services[sid]
            try:
                await self.agent.catalog_register(RegisterRequest(
                    node=node, address=addr, service=service,
                    token=self.service_tokens.get(sid, "")))
            except Exception:
                raftstats.aestats.failure("service_register")
                raise
            # The register round-trip is a scheduling point: add_service()
            # may have swapped in a newer definition while it was in
            # flight.  Marking THAT synced would silently drop the update
            # until the next full anti-entropy pass (up to ae_scale
            # minutes) — only the definition we actually pushed counts.
            if self.services.get(sid) is service:
                self._service_sync[sid] = True
        for cid, in_sync in list(self._check_sync.items()):
            if in_sync or cid not in self.checks:
                continue
            check = self.checks[cid]
            pushed = (check.status, check.output)
            try:
                await self.agent.catalog_register(RegisterRequest(
                    node=node, address=addr, check=check,
                    token=self.check_tokens.get(cid, "")))
            except Exception:
                raftstats.aestats.failure("check_register")
                raise
            # update_check() mutates the check object in place, so the
            # identity test alone cannot see a status flip that landed
            # during the await — compare the pushed (status, output) too,
            # or a check that went critical mid-register would read
            # "passing" in the catalog until the next full pass.
            if self.checks.get(cid) is check and (check.status,
                                                  check.output) == pushed:
                self._check_sync[cid] = True

    async def _sync_changes_batched(self, submit) -> None:
        """syncChanges through ONE raft entry (PR 18).

        Builds the same op sequence the sequential loops would issue —
        deregisters first, then registers, preserving their relative
        order — and submits it as a single BATCH envelope.  Each op
        carries a finalize closure holding the pre-await snapshot
        (deregister epoch / service identity / pushed check state) so
        success bookkeeping follows the exact snapshot-compare
        convention of the sequential path.  Per-sub failures count the
        same aestats kinds and re-raise so the caller's retry tick
        stays armed.
        """
        node = self.agent.node_name
        addr = self.agent.advertise_addr
        ops = []        # (MessageType, request) pairs, submit order
        kinds = []      # aestats failure kind per op
        finalizers = []  # success bookkeeping per op

        for sid, epoch in list(self._deregister_services.items()):
            ops.append((MessageType.DEREGISTER, DeregisterRequest(
                node=node, service_id=sid,
                token=self.service_tokens.get(sid, ""))))
            kinds.append("service_deregister")

            def _fin(sid=sid, epoch=epoch):
                if self._deregister_services.get(sid) == epoch:
                    self._deregister_services.pop(sid, None)
            finalizers.append(_fin)
        for cid, epoch in list(self._deregister_checks.items()):
            ops.append((MessageType.DEREGISTER, DeregisterRequest(
                node=node, check_id=cid,
                token=self.check_tokens.get(cid, ""))))
            kinds.append("check_deregister")

            def _fin(cid=cid, epoch=epoch):
                if self._deregister_checks.get(cid) == epoch:
                    self._deregister_checks.pop(cid, None)
            finalizers.append(_fin)
        for sid, in_sync in list(self._service_sync.items()):
            if in_sync or sid not in self.services:
                continue
            service = self.services[sid]
            ops.append((MessageType.REGISTER, RegisterRequest(
                node=node, address=addr, service=service,
                token=self.service_tokens.get(sid, ""))))
            kinds.append("service_register")

            def _fin(sid=sid, service=service):
                if self.services.get(sid) is service:
                    self._service_sync[sid] = True
            finalizers.append(_fin)
        for cid, in_sync in list(self._check_sync.items()):
            if in_sync or cid not in self.checks:
                continue
            check = self.checks[cid]
            pushed = (check.status, check.output)
            ops.append((MessageType.REGISTER, RegisterRequest(
                node=node, address=addr, check=check,
                token=self.check_tokens.get(cid, ""))))
            kinds.append("check_register")

            def _fin(cid=cid, check=check, pushed=pushed):
                if self.checks.get(cid) is check and (
                        check.status, check.output) == pushed:
                    self._check_sync[cid] = True
            finalizers.append(_fin)

        if not ops:
            return
        try:
            results = await submit(ops)
        except Exception:
            # Transport/consensus failure: the whole batch is in doubt.
            # Count each kind once (the sequential path would have died
            # on its first op of that kind) and let the retry tick run.
            for kind in dict.fromkeys(kinds):
                raftstats.aestats.failure(kind)
            raise
        if not isinstance(results, (list, tuple)):
            results = [None] * len(ops)
        failed = 0
        for i, fin in enumerate(finalizers):
            err = results[i] if i < len(results) else None
            if err is None:
                fin()
            else:
                failed += 1
                raftstats.aestats.failure(kinds[i])
        if failed:
            raise RuntimeError(
                f"{failed}/{len(ops)} catalog ops failed in batch")
