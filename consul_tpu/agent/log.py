"""Agent logging: leveled hub with live sinks for ``monitor``.

Parity target: the reference's logging plumbing
(``command/agent/log_writer.go`` fan-out to monitors, ``log_levels.go``
level filter, ``gated_writer.go``): a ring of recent lines plus
attachable sinks, each with its own level filter — the IPC ``monitor``
command streams through one.
"""

from __future__ import annotations

import time
from typing import Callable, List, Tuple

LEVELS = {"TRACE": 0, "DEBUG": 1, "INFO": 2, "WARN": 3, "ERR": 4}
RING = 512  # logBuffer default (log_writer.go:14)


class LogHub:
    def __init__(self, level: str = "INFO") -> None:
        self.level = LEVELS.get(level.upper(), 2)
        self._ring: List[Tuple[int, str]] = []  # (level, line)
        self._sinks: List[Tuple[Callable[[str], None], int]] = []

    def log(self, level: str, msg: str) -> None:
        lvl = LEVELS.get(level.upper(), 2)
        if lvl < self.level:
            return
        stamp = time.strftime("%Y/%m/%d %H:%M:%S")
        line = f"{stamp} [{level.upper()}] {msg}"
        self._ring.append((lvl, line))
        if len(self._ring) > RING:
            self._ring = self._ring[-RING:]
        for sink, sink_lvl in list(self._sinks):
            if lvl >= sink_lvl:
                try:
                    sink(line)
                except Exception:
                    self.remove_sink(sink)

    def info(self, msg: str) -> None:
        self.log("INFO", msg)

    def warn(self, msg: str) -> None:
        self.log("WARN", msg)

    def err(self, msg: str) -> None:
        self.log("ERR", msg)

    def debug(self, msg: str) -> None:
        self.log("DEBUG", msg)

    def add_sink(self, sink: Callable[[str], None],
                 level: str = "INFO", replay: bool = True) -> None:
        """Attach a live sink; replays the ring first (logWriter behavior:
        monitors see recent history)."""
        lvl = LEVELS.get(level.upper(), 2)
        if replay:
            for line_lvl, line in self._ring:
                if line_lvl < lvl:
                    continue  # honor the sink's filter during replay too
                try:
                    sink(line)
                except Exception:
                    return
        self._sinks.append((sink, lvl))

    def remove_sink(self, sink: Callable[[str], None]) -> None:
        self._sinks = [(s, l) for s, l in self._sinks if s is not sink]


# -- syslog sink (command/agent/syslog.go + logutils wiring,
# command/agent/command.go:257-297) -----------------------------------------

_FACILITIES = {
    "KERN": 0, "USER": 1, "MAIL": 2, "DAEMON": 3, "AUTH": 4, "SYSLOG": 5,
    "LPR": 6, "NEWS": 7, "UUCP": 8, "CRON": 9, "AUTHPRIV": 10, "FTP": 11,
    "LOCAL0": 16, "LOCAL1": 17, "LOCAL2": 18, "LOCAL3": 19, "LOCAL4": 20,
    "LOCAL5": 21, "LOCAL6": 22, "LOCAL7": 23,
}
_SEVERITY = {0: 7, 1: 7, 2: 6, 3: 4, 4: 3}  # LEVELS idx -> syslog severity


def syslog_sink(facility: str = "LOCAL0",
                tag: str = "consul-tpu") -> Callable[[str], None]:
    """A LogHub sink writing RFC3164 datagrams to /dev/log (the
    gsyslog-role of the reference's -syslog support).  Raises OSError
    when no local syslog socket exists — the caller decides whether
    that is fatal (the reference retries 5x then dies,
    command.go:272-281)."""
    import socket

    fac = _FACILITIES.get(facility.upper(), 16)
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
    sock.connect("/dev/log")  # raises if unavailable

    def sink(line: str) -> None:
        # line = "Y/m/d H:M:S [LEVEL] msg"; recover the level for PRI
        lvl = 2
        l = line.find("[")
        r = line.find("]", l + 1)
        if 0 <= l < r:
            lvl = LEVELS.get(line[l + 1:r], 2)
        pri = fac * 8 + _SEVERITY.get(lvl, 6)
        msg = line[r + 2:] if 0 <= l < r else line
        try:
            sock.send(f"<{pri}>{tag}: {msg}".encode())
        except OSError:
            pass  # syslog going away must not take the agent down

    return sink
