"""Agent logging: leveled hub with live sinks for ``monitor``.

Parity target: the reference's logging plumbing
(``command/agent/log_writer.go`` fan-out to monitors, ``log_levels.go``
level filter, ``gated_writer.go``): a ring of recent lines plus
attachable sinks, each with its own level filter — the IPC ``monitor``
command streams through one.
"""

from __future__ import annotations

import time
from typing import Callable, List, Tuple

LEVELS = {"TRACE": 0, "DEBUG": 1, "INFO": 2, "WARN": 3, "ERR": 4}
RING = 512  # logBuffer default (log_writer.go:14)


class LogHub:
    def __init__(self, level: str = "INFO") -> None:
        self.level = LEVELS.get(level.upper(), 2)
        self._ring: List[Tuple[int, str]] = []  # (level, line)
        self._sinks: List[Tuple[Callable[[str], None], int]] = []

    def log(self, level: str, msg: str) -> None:
        lvl = LEVELS.get(level.upper(), 2)
        if lvl < self.level:
            return
        stamp = time.strftime("%Y/%m/%d %H:%M:%S")
        line = f"{stamp} [{level.upper()}] {msg}"
        self._ring.append((lvl, line))
        if len(self._ring) > RING:
            self._ring = self._ring[-RING:]
        for sink, sink_lvl in list(self._sinks):
            if lvl >= sink_lvl:
                try:
                    sink(line)
                except Exception:
                    self.remove_sink(sink)

    def info(self, msg: str) -> None:
        self.log("INFO", msg)

    def warn(self, msg: str) -> None:
        self.log("WARN", msg)

    def err(self, msg: str) -> None:
        self.log("ERR", msg)

    def debug(self, msg: str) -> None:
        self.log("DEBUG", msg)

    def add_sink(self, sink: Callable[[str], None],
                 level: str = "INFO", replay: bool = True) -> None:
        """Attach a live sink; replays the ring first (logWriter behavior:
        monitors see recent history)."""
        lvl = LEVELS.get(level.upper(), 2)
        if replay:
            for line_lvl, line in self._ring:
                if line_lvl < lvl:
                    continue  # honor the sink's filter during replay too
                try:
                    sink(line)
                except Exception:
                    return
        self._sinks.append((sink, lvl))

    def remove_sink(self, sink: Callable[[str], None]) -> None:
        self._sinks = [(s, l) for s, l in self._sinks if s is not sink]
