"""Compact-serialization fast path for the hot serving endpoints.

The generic HTTP path pays, per request: QueryOptions/KeyRequest
construction, the blocking-query prologue, ``to_wire``/``to_api`` dict
recursion with per-key case mapping, and a ``json.dumps`` whose output
aiohttp re-encodes from ``text``.  For the endpoints that dominate the
serving plane (KV GET/PUT/DELETE, health service, catalog, status)
this module computes the response ONCE as raw bytes plus headers — a
transport-neutral ``(status, headers, content_type, body)`` quadruple
consumed by

  * the in-process aiohttp handlers (``http_api.py`` routes delegate
    here when the query string stays inside the hot subset), and
  * the SO_REUSEPORT worker gateway (``workers.py``), which ships the
    quadruple to worker processes as one msgpack frame over the IPC
    layer — the body bytes go straight out the worker's socket, no
    decode/re-encode hop.

Wire shape parity: byte-identical to the generic path now that
``_json`` emits compact separators (tests/test_serving.py asserts it).
"""

from __future__ import annotations

import base64
import json
import time
from typing import Any, Dict, Optional, Tuple

from consul_tpu.obs import journey as _journey
from consul_tpu.structs.structs import (
    KVSOp, KVSRequest, DirEntry, QueryOptions)

HotResponse = Tuple[int, Dict[str, str], str, bytes]

_JSON = "application/json"
_OCTET = "application/octet-stream"


def _dumps(value: Any) -> bytes:
    return json.dumps(value, separators=(",", ":")).encode("utf-8")


def _index_headers(srv, index: int) -> Dict[str, str]:
    """X-Consul-* trio, mirroring endpoints._set_meta + the edge
    header formatting without a QueryMeta round-trip."""
    raft = srv.raft
    if raft.is_leader():
        known, contact = "true", "0"
    else:
        known = "true" if raft.leader_id else "false"
        last = getattr(raft, "last_leader_contact", None)
        contact = "0" if last is None else str(
            int(max(0.0, time.monotonic() - last) * 1000))
    return {"X-Consul-Index": str(index),
            "X-Consul-KnownLeader": known,
            "X-Consul-LastContact": contact}


def _dir_entry_obj(ent: DirEntry) -> Dict[str, Any]:
    """Reference-shaped KV entry (kvs_endpoint.go marshaling order)."""
    return {
        "Key": ent.key,
        "Value": base64.b64encode(ent.value or b"").decode("ascii"),
        "Flags": ent.flags,
        "Session": ent.session,
        "LockIndex": ent.lock_index,
        "CreateIndex": ent.create_index,
        "ModifyIndex": ent.modify_index,
    }


# -- device-store KV byte cache (PR 11) -------------------------------------

_KV_CACHE_MAX = 1024


class KVByteCache:
    """Rendered-bytes cache for hot KV GETs, wired up when the device
    state store is on (state/device_store.py).

    Validity is the KV table index: a row rendered at store index I is
    served only while ``store.last_index(kvs, tombstones)`` is still I,
    so ANY kv/tombstone mutation is an implicit full invalidation — the
    cache can never serve stale bytes, batched or not.  The device
    bridge's ``render_hook`` re-renders the keys a committed batch
    touched (only those already cached), so hot keys are warm again at
    the new index before the woken blocking queries re-read them.

    X-Consul-* headers are rebuilt per hit (leader-contact is live);
    only the status/content-type/body triple is cached.
    """

    __slots__ = ("srv", "max_entries", "entries", "hits", "misses")

    def __init__(self, srv, max_entries: int = _KV_CACHE_MAX) -> None:
        self.srv = srv
        self.max_entries = max_entries
        # key -> (valid_at_index, status, ctype, body, header_index)
        self.entries: Dict[str, Tuple[int, int, str, bytes, int]] = {}
        self.hits = 0
        self.misses = 0

    def _store_index(self) -> int:
        return self.srv.store.last_index("kvs", "tombstones")

    def lookup(self, key: str) -> Optional[Tuple[int, int, str, bytes, int]]:
        row = self.entries.get(key)
        if row is None or row[0] != self._store_index():
            self.misses += 1
            return None
        self.hits += 1
        return row

    def render(self, key: str) -> Tuple[int, int, str, bytes, int]:
        """Render one key through the store and remember the bytes."""
        idx, ent = self.srv.store.kvs_get(key)
        if ent is None:
            row = (idx, 404, "text/plain", b"", idx)
        else:
            row = (idx, 200, _JSON, _dumps([_dir_entry_obj(ent)]),
                   ent.modify_index)
        if key not in self.entries and len(self.entries) >= self.max_entries:
            self.entries.pop(next(iter(self.entries)))  # FIFO bound
        self.entries[key] = row
        return row

    def refresh(self, keys) -> None:
        """Device-bridge render hook: after a committed batch, re-render
        the touched keys that serving has already asked for."""
        for k in keys:
            if k in self.entries:
                self.render(k)


def attach_kv_cache(srv, bridge, max_entries: int = _KV_CACHE_MAX):
    """Hang a KVByteCache off the server and point the device bridge's
    render hook at it (called by Agent when device_store is on)."""
    cache = KVByteCache(srv, max_entries)
    srv.kv_byte_cache = cache
    bridge.render_hook = cache.refresh
    return cache


# -- health byte cache (PR 18: fused detect→render pipeline) -----------------


class HealthByteCache:
    """Rendered-bytes cache for the hot health-service endpoint, the
    last stage of the fused membership→catalog pipeline.

    Same validity contract as KVByteCache, over the catalog tables: a
    row rendered at ``last_index(nodes, services, checks)`` == I serves
    only while that index holds, so any catalog write invalidates
    implicitly — stale bytes are unservable by construction.  The FSM's
    batch-boundary render hook (consensus/fsm.py ``health_render_hook``)
    re-renders the cached variants of every service a committed BATCH
    envelope touched, synchronously inside the apply — watch waiters
    only run on the next event-loop iteration, so the bytes are hot
    before the first woken watcher re-reads.

    Byte parity with the generic path is the whole point: render() is
    exactly Health.service_nodes' pipeline (store join → passing filter,
    header index sampled pre-filter) followed by ``_dumps(to_api(...))``
    (tests/test_reconcile.py asserts identity against the cold path).
    Consulted only for default-consistency reads with ACLs disabled —
    consistent reads need their barrier, ACL'd reads their filter.
    """

    __slots__ = ("srv", "max_entries", "entries", "hits", "misses")

    def __init__(self, srv, max_entries: int = _KV_CACHE_MAX) -> None:
        self.srv = srv
        self.max_entries = max_entries
        # (service, tag, passing) -> (valid_at_index, status, ctype,
        #                             body, header_index)
        self.entries: Dict[Tuple[str, str, bool],
                           Tuple[int, int, str, bytes, int]] = {}
        self.hits = 0
        self.misses = 0

    def _store_index(self) -> int:
        return self.srv.store.last_index("nodes", "services", "checks")

    def lookup(self, key: Tuple[str, str, bool]
               ) -> Optional[Tuple[int, int, str, bytes, int]]:
        row = self.entries.get(key)
        if row is None or row[0] != self._store_index():
            self.misses += 1
            return None
        self.hits += 1
        return row

    def render(self, service: str, tag: str = "",
               passing: bool = False) -> Tuple[int, int, str, bytes, int]:
        """One service variant through the store join, bytes remembered."""
        from consul_tpu.agent.http_api import to_api
        from consul_tpu.structs.structs import HEALTH_PASSING
        idx, csns = self.srv.store.check_service_nodes(service, tag)
        if passing:
            csns = [c for c in csns
                    if all(ch.status == HEALTH_PASSING for ch in c.checks)]
        row = (idx, 200, _JSON, _dumps(to_api(csns)), idx)
        key = (service, tag, passing)
        if key not in self.entries and len(self.entries) >= self.max_entries:
            self.entries.pop(next(iter(self.entries)))  # FIFO bound
        self.entries[key] = row
        return row

    def refresh(self, services) -> None:
        """FSM batch-boundary render hook: re-render every cached
        variant of the services a committed batch touched."""
        jy = _journey.journey
        t0 = time.monotonic() if jy is not None else 0.0
        for key in list(self.entries):
            if key[0] in services:
                self.render(*key)
        if jy is not None:
            jy.note_render((time.monotonic() - t0) * 1000.0)


def attach_health_cache(srv, max_entries: int = _KV_CACHE_MAX):
    """Hang a HealthByteCache off the server and point the FSM's
    batch-boundary render hook at it (called by Agent in server mode)."""
    cache = HealthByteCache(srv, max_entries)
    srv.health_byte_cache = cache
    srv.fsm.health_render_hook = cache.refresh
    return cache


# -- hot operations ---------------------------------------------------------

async def kv_get(srv, key: str, *, stale: bool = False,
                 consistent: bool = False, token: str = "",
                 raw: bool = False) -> HotResponse:
    if consistent:
        # Lease short-circuit inline (skips the barrier span + shared
        # future machinery); expiry falls back to the full coalesced
        # barrier/ReadIndex path.
        from consul_tpu.utils.telemetry import metrics
        raft = srv.raft
        idx = raft.lease_read_index()
        if idx is not None:
            metrics.incr_counter(("consul", "read", "lease"))
            if raft.obs is not None:
                raft.obs.lease_observe(raft.lease_remaining() * 1000.0,
                                       raft.current_term)
            if raft.last_applied < idx:
                await raft.wait_applied(idx)
        else:
            await srv.consistent_read_barrier()
    if srv.acl_resolver.enabled:
        acl = await srv.resolve_token(token)
        if acl is not None and not acl.key_read(key):
            raise PermissionError("Permission denied")
    cache = getattr(srv, "kv_byte_cache", None)
    if cache is not None and not raw:
        # Index-validated rendered bytes (device store path); safe after
        # the ACL check above, self-invalidating on any kv write.
        row = cache.lookup(key) or cache.render(key)
        _vidx, status, ctype, body, hidx = row
        return status, _index_headers(srv, hidx), ctype, body
    idx, ent = srv.store.kvs_get(key)
    index = ent.modify_index if ent is not None else idx
    hdrs = _index_headers(srv, index)
    if ent is None:
        return 404, hdrs, "text/plain", b""
    if raw:
        return 200, hdrs, _OCTET, bytes(ent.value or b"")
    return 200, hdrs, _JSON, _dumps([_dir_entry_obj(ent)])


async def kv_put(srv, key: str, value: bytes, *, flags: Optional[int] = None,
                 cas: Optional[int] = None, acquire: str = "",
                 release: str = "", token: str = "") -> HotResponse:
    d = DirEntry(key=key, value=value)
    if flags is not None:
        d.flags = flags
    op = KVSOp.SET.value
    if cas is not None:
        d.modify_index = cas
        op = KVSOp.CAS.value
    elif acquire:
        d.session = acquire
        op = KVSOp.LOCK.value
    elif release:
        d.session = release
        op = KVSOp.UNLOCK.value
    ok = await srv.kvs.apply(KVSRequest(op=op, dir_ent=d, token=token))
    return 200, {}, _JSON, b"true" if ok else b"false"


async def kv_delete(srv, key: str, *, recurse: bool = False,
                    cas: Optional[int] = None,
                    token: str = "") -> HotResponse:
    d = DirEntry(key=key)
    op = KVSOp.DELETE.value
    if recurse:
        op = KVSOp.DELETE_TREE.value
    elif cas is not None:
        d.modify_index = cas
        op = KVSOp.DELETE_CAS.value
    ok = await srv.kvs.apply(KVSRequest(op=op, dir_ent=d, token=token))
    return 200, {}, _JSON, b"true" if ok else b"false"


async def health_service(srv, service: str, *, tag: str = "",
                         passing: bool = False, stale: bool = False,
                         consistent: bool = False,
                         token: str = "") -> HotResponse:
    from consul_tpu.agent.http_api import to_api
    cache = getattr(srv, "health_byte_cache", None)
    if cache is not None and service and not consistent \
            and not srv.acl_resolver.enabled:
        # Index-validated rendered bytes, pre-warmed at the batch
        # boundary by the FSM render hook (fused pipeline, PR 18).
        row = cache.lookup((service, tag, passing)) \
            or cache.render(service, tag, passing)
        _vidx, status, ctype, body, hidx = row
        return status, _index_headers(srv, hidx), ctype, body
    opts = QueryOptions(token=token, allow_stale=stale,
                        require_consistent=consistent)
    meta, csns = await srv.health.service_nodes(service, opts, tag, passing)
    return 200, _index_headers(srv, meta.index), _JSON, _dumps(to_api(csns))


async def catalog_nodes(srv, *, stale: bool = False, consistent: bool = False,
                        token: str = "") -> HotResponse:
    from consul_tpu.agent.http_api import to_api
    opts = QueryOptions(token=token, allow_stale=stale,
                        require_consistent=consistent)
    meta, nodes = await srv.catalog.list_nodes(opts)
    return 200, _index_headers(srv, meta.index), _JSON, _dumps(to_api(nodes))


async def catalog_services(srv, *, stale: bool = False,
                           consistent: bool = False,
                           token: str = "") -> HotResponse:
    opts = QueryOptions(token=token, allow_stale=stale,
                        require_consistent=consistent)
    meta, services = await srv.catalog.list_services(opts)
    return 200, _index_headers(srv, meta.index), _JSON, _dumps(services)


async def catalog_service(srv, service: str, *, tag: str = "",
                          stale: bool = False, consistent: bool = False,
                          token: str = "") -> HotResponse:
    from consul_tpu.agent.http_api import to_api
    opts = QueryOptions(token=token, allow_stale=stale,
                        require_consistent=consistent)
    meta, nodes = await srv.catalog.service_nodes(service, opts, tag)
    return 200, _index_headers(srv, meta.index), _JSON, _dumps(to_api(nodes))


async def status_leader(srv) -> HotResponse:
    return 200, {}, _JSON, _dumps(srv.leader_addr())


async def status_lease(srv) -> HotResponse:
    return 200, {}, _JSON, _dumps(srv.lease_state())


# -- gateway dispatch -------------------------------------------------------

OPS = {
    "kv_get": kv_get,
    "kv_put": kv_put,
    "kv_delete": kv_delete,
    "health_service": health_service,
    "catalog_nodes": catalog_nodes,
    "catalog_services": catalog_services,
    "catalog_service": catalog_service,
    "status_leader": status_leader,
    "status_lease": status_lease,
}


async def handle(srv, op: str, args: Dict[str, Any]) -> HotResponse:
    """Run one hot op for the worker gateway, mapping exceptions to
    the same statuses the HTTP edge layer produces (http.go wrap())."""
    from consul_tpu.server.endpoints import EndpointError
    fn = OPS.get(op)
    if fn is None:
        return 500, {}, "text/plain", f"unknown hot op: {op}".encode()
    positional = args.pop("_args", [])
    try:
        return await fn(srv, *positional, **args)
    except EndpointError as e:
        return 400, {}, "text/plain", str(e).encode()
    except PermissionError as e:
        return 403, {}, "text/plain", (str(e) or "Permission denied").encode()
    except Exception as e:
        return 500, {}, "text/plain", f"{type(e).__name__}: {e}".encode()
