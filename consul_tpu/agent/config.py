"""Agent configuration: JSON files, config-dir merging, validation.

Parity target: ``command/agent/config.go`` (1128 LoC) — the ~90-field
Config with port block defaults (DNS 8600, HTTP 8500, RPC 8400,
SerfLan 8301, SerfWan 8302, Server 8300; config.go:436+), duration
strings decoded from ``*Raw`` fields, JSON config files merged with a
lexically-ordered ``-config-dir`` (``ReadConfigPaths``/``MergeConfig``),
service/check definition stanzas, and the ``consul configtest``
validator (command/configtest.go).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field, fields
from typing import Any, Dict, List

from consul_tpu.server.endpoints import parse_duration


class ConfigError(ValueError):
    pass


@dataclass
class PortConfig:
    """config.go PortConfig + defaults."""

    dns: int = 8600
    http: int = 8500
    https: int = -1
    rpc: int = 8400
    serf_lan: int = 8301
    serf_wan: int = 8302
    server: int = 8300


@dataclass
class DNSConfig:
    """config.go DNSConfig."""

    node_ttl: float = 0.0
    service_ttl: Dict[str, float] = field(default_factory=dict)
    allow_stale: bool = False
    max_stale: float = 5.0
    enable_truncate: bool = False
    only_passing: bool = False


@dataclass
class Telemetry:
    statsite_addr: str = ""
    statsd_addr: str = ""
    disable_hostname: bool = False


@dataclass
class Config:
    """The full file-loadable agent configuration surface."""

    # identity / topology
    node_name: str = ""
    datacenter: str = "dc1"
    domain: str = "consul."
    server: bool = False
    bootstrap: bool = False
    bootstrap_expect: int = 0

    # storage / process
    data_dir: str = ""
    pid_file: str = ""
    log_level: str = "INFO"
    enable_syslog: bool = False
    syslog_facility: str = "LOCAL0"
    enable_debug: bool = False
    protocol: int = 2
    ui_dir: str = ""

    # addresses
    bind_addr: str = "0.0.0.0"
    advertise_addr: str = ""
    client_addr: str = "127.0.0.1"
    addresses: Dict[str, str] = field(default_factory=dict)
    ports: PortConfig = field(default_factory=PortConfig)
    # total HTTP serving processes on the public TCP port (1 = the
    # agent alone; N > 1 adds N-1 SO_REUSEPORT workers, agent/workers.py)
    http_workers: int = 1
    # device-resident state store (server mode, state/device_store.py):
    # batched FSM apply + device-side watch matching
    device_store: bool = False
    device_store_capacity: int = 1 << 16

    # clustering
    start_join: List[str] = field(default_factory=list)
    start_join_wan: List[str] = field(default_factory=list)
    retry_join: List[str] = field(default_factory=list)
    retry_interval: float = 30.0
    retry_max: int = 0
    retry_join_wan: List[str] = field(default_factory=list)
    retry_interval_wan: float = 30.0
    retry_max_wan: int = 0
    rejoin_after_leave: bool = False
    leave_on_terminate: bool = False
    skip_leave_on_interrupt: bool = False
    encrypt: str = ""  # base64 16-byte gossip key
    # LAN membership substrate: "swim" (asyncio memberlist role) or
    # "tpu" (kernel session in the gossip plane daemon)
    gossip_backend: str = "swim"
    gossip_plane: str = ""  # plane rendezvous (host:port or unix://path)

    # DNS
    dns_config: DNSConfig = field(default_factory=DNSConfig)
    recursors: List[str] = field(default_factory=list)

    # TLS
    verify_incoming: bool = False
    verify_outgoing: bool = False
    ca_file: str = ""
    cert_file: str = ""
    key_file: str = ""
    server_name: str = ""

    # ACL
    acl_datacenter: str = ""
    acl_ttl: float = 30.0
    acl_default_policy: str = "allow"
    acl_down_policy: str = "extend-cache"
    acl_master_token: str = ""
    acl_token: str = ""

    # behavior
    check_update_interval: float = 5 * 60.0
    disable_remote_exec: bool = False
    disable_update_check: bool = False
    disable_anonymous_signature: bool = False

    # telemetry
    telemetry: Telemetry = field(default_factory=Telemetry)

    # definitions
    services: List[Dict[str, Any]] = field(default_factory=list)
    checks: List[Dict[str, Any]] = field(default_factory=list)
    watches: List[Dict[str, Any]] = field(default_factory=list)

    # session
    session_ttl_min: float = 10.0

    # bookkeeping: which fields were explicitly set (drives merge)
    _set_fields: set = field(default_factory=set, repr=False, compare=False)


# JSON key -> (field name, kind). Kinds: plain, duration, list, dict.
_DURATION_KEYS = {
    "acl_ttl", "retry_interval", "retry_interval_wan",
    "check_update_interval", "session_ttl_min",
}

_NESTED = {
    "ports": PortConfig,
    "dns_config": DNSConfig,
    "telemetry": Telemetry,
}

_LIST_APPEND_KEYS = {"services", "checks", "watches", "start_join",
                     "start_join_wan", "retry_join", "retry_join_wan",
                     "recursors"}

# camel/snake aliases the reference's JSON uses
_ALIASES = {
    "service": "services",
    "check": "checks",
}


def _coerce(name: str, value: Any) -> Any:
    if name in _DURATION_KEYS and isinstance(value, str):
        return parse_duration(value)
    if name == "dns_config" and isinstance(value, dict):
        dc = DNSConfig()
        touched = set()
        for k, v in value.items():
            k = k.lower()
            if k in ("node_ttl", "max_stale") and isinstance(v, str):
                v = parse_duration(v)
            if k == "service_ttl" and isinstance(v, dict):
                v = {svc: parse_duration(t) if isinstance(t, str) else float(t)
                     for svc, t in v.items()}
            if hasattr(dc, k):
                setattr(dc, k, v)
                touched.add(k)
            else:
                raise ConfigError(f"Unknown dns_config key: {k}")
        dc._set = touched  # drives field-wise merge
        return dc
    if name == "ports" and isinstance(value, dict):
        pc = PortConfig()
        touched = set()
        for k, v in value.items():
            k = k.lower()
            if not hasattr(pc, k):
                raise ConfigError(f"Unknown port: {k}")
            setattr(pc, k, int(v))
            touched.add(k)
        pc._set = touched
        return pc
    if name == "telemetry" and isinstance(value, dict):
        t = Telemetry()
        touched = set()
        for k, v in value.items():
            k = k.lower()
            if not hasattr(t, k):
                raise ConfigError(f"Unknown telemetry key: {k}")
            setattr(t, k, v)
            touched.add(k)
        t._set = touched
        return t
    return value


def decode_config(text: str) -> Config:
    """Parse one JSON config document (DecodeConfig)."""
    try:
        raw = json.loads(text)
    except json.JSONDecodeError as e:
        raise ConfigError(f"Error parsing config: {e}")
    if not isinstance(raw, dict):
        raise ConfigError("Config must be a JSON object")
    cfg = Config()
    valid = {f.name for f in fields(Config)} - {"_set_fields"}
    for key, value in raw.items():
        name = key.lower()
        name = _ALIASES.get(name, name)
        if name not in valid:
            raise ConfigError(f"Unknown configuration key: {key}")
        if name in ("services", "checks", "watches") and isinstance(value, dict):
            value = [value]
        setattr(cfg, name, _coerce(name, value))
        cfg._set_fields.add(name)
    return cfg


def merge_config(a: Config, b: Config) -> Config:
    """b overlays a; list-valued definition keys append (MergeConfig)."""
    out = Config()
    # start from a
    for f in fields(Config):
        if f.name == "_set_fields":
            continue
        setattr(out, f.name, getattr(a, f.name))
    out._set_fields = set(a._set_fields)
    for name in b._set_fields:
        if name in _LIST_APPEND_KEYS:
            setattr(out, name, list(getattr(a, name)) + list(getattr(b, name)))
        elif name in _NESTED:
            # Field-wise overlay so a partial later block (e.g. just
            # {"ports": {"http": ...}}) doesn't reset earlier overrides
            # (config.go MergeConfig merges these per-field).
            merged = getattr(out, name)
            overlay = getattr(b, name)
            import copy
            merged = copy.copy(merged)
            for sub in getattr(overlay, "_set", ()):  # only explicit keys
                setattr(merged, sub, getattr(overlay, sub))
            prior = set(getattr(getattr(a, name), "_set", ()))
            merged._set = prior | set(getattr(overlay, "_set", ()))
            setattr(out, name, merged)
        else:
            setattr(out, name, getattr(b, name))
        out._set_fields.add(name)
    return out


def read_config_paths(paths: List[str]) -> Config:
    """Load + merge files and lexically-ordered config dirs
    (ReadConfigPaths)."""
    cfg = Config()
    for path in paths:
        if os.path.isdir(path):
            entries = sorted(os.listdir(path))
            for fn in entries:
                if not fn.endswith(".json"):
                    continue
                full = os.path.join(path, fn)
                with open(full) as f:
                    try:
                        cfg = merge_config(cfg, decode_config(f.read()))
                    except ConfigError as e:
                        raise ConfigError(f"{full}: {e}")
        else:
            with open(path) as f:
                try:
                    cfg = merge_config(cfg, decode_config(f.read()))
                except ConfigError as e:
                    raise ConfigError(f"{path}: {e}")
    return cfg


def validate_config(cfg: Config) -> List[str]:
    """configtest-style validation; returns a list of problems."""
    problems = []
    if cfg.bootstrap and not cfg.server:
        problems.append("Bootstrap mode requires server mode")
    if cfg.bootstrap_expect and not cfg.server:
        problems.append("Expect mode requires server mode")
    if cfg.bootstrap_expect and cfg.bootstrap:
        problems.append("Bootstrap cannot be provided with bootstrap-expect")
    if cfg.bootstrap_expect == 1:
        problems.append("A cluster with just a single server is fragile; "
                        "use bootstrap instead of bootstrap_expect=1")
    if cfg.encrypt:
        import base64
        try:
            key = base64.b64decode(cfg.encrypt)
            if len(key) != 16:
                problems.append("Encrypt key must be 16 bytes")
        except Exception:
            problems.append("Invalid encrypt key (must be base64)")
    try:
        from consul_tpu.version import check_protocol_version
        check_protocol_version(cfg.protocol)
    except ValueError as e:
        problems.append(str(e))
    if cfg.gossip_backend not in ("swim", "tpu"):
        problems.append(f"Invalid gossip_backend: {cfg.gossip_backend!r} "
                        "(must be 'swim' or 'tpu')")
    if cfg.gossip_backend == "tpu" and not cfg.gossip_plane:
        problems.append("gossip_backend=tpu requires gossip_plane "
                        "(the plane daemon's address)")
    if int(cfg.http_workers) < 1:
        problems.append(f"http_workers must be >= 1, got {cfg.http_workers}")
    if cfg.device_store and not cfg.server:
        problems.append("device_store requires server mode")
    cap = int(cfg.device_store_capacity)
    if cfg.device_store and (cap < 64 or cap & (cap - 1)):
        problems.append("device_store_capacity must be a power of two "
                        f">= 64, got {cfg.device_store_capacity}")
    if cfg.acl_datacenter and cfg.acl_default_policy not in ("allow", "deny"):
        problems.append(f"Invalid ACL default policy: {cfg.acl_default_policy}")
    if cfg.acl_datacenter and cfg.acl_down_policy not in (
            "allow", "deny", "extend-cache"):
        problems.append(f"Invalid ACL down policy: {cfg.acl_down_policy}")
    if cfg.verify_incoming and not (cfg.ca_file and cfg.cert_file
                                    and cfg.key_file):
        problems.append("verify_incoming requires ca_file, cert_file "
                        "and key_file")
    for watch in cfg.watches:
        try:
            from consul_tpu.watch import parse as watch_parse
            watch_parse(dict(watch))
        except Exception as e:
            problems.append(f"Invalid watch: {e}")
    for svc in cfg.services:
        if not (svc.get("name") or svc.get("Name")):
            problems.append("Service definition missing name")
    for chk in cfg.checks:
        if not (chk.get("name") or chk.get("Name")):
            problems.append("Check definition missing name")
    return problems


def to_agent_config(cfg: Config):
    """Adapt the file config to the runtime AgentConfig."""
    from consul_tpu.agent.agent import AgentConfig
    import socket
    node = cfg.node_name or socket.gethostname()
    bind = cfg.client_addr or "127.0.0.1"
    service_ttl = 0.0
    if cfg.dns_config.service_ttl:
        service_ttl = cfg.dns_config.service_ttl.get("*", 0.0)
    advertise = cfg.advertise_addr or (
        cfg.bind_addr if cfg.bind_addr != "0.0.0.0" else "127.0.0.1")
    return AgentConfig(
        node_name=node,
        datacenter=cfg.datacenter,
        bind_addr=bind,
        advertise_addr=advertise,
        domain=cfg.domain,
        http_port=cfg.ports.http,
        https_port=cfg.ports.https,
        addresses=dict(cfg.addresses),
        verify_incoming=cfg.verify_incoming,
        ca_file=cfg.ca_file,
        cert_file=cfg.cert_file,
        key_file=cfg.key_file,
        dns_port=cfg.ports.dns,
        server=cfg.server,
        bootstrap=cfg.bootstrap or (cfg.server and not cfg.bootstrap_expect),
        bootstrap_expect=cfg.bootstrap_expect,
        data_dir=cfg.data_dir,
        dns_only_passing=cfg.dns_config.only_passing,
        dns_allow_stale=cfg.dns_config.allow_stale,
        dns_max_stale=cfg.dns_config.max_stale,
        dns_enable_truncate=cfg.dns_config.enable_truncate,
        recursors=list(cfg.recursors),
        node_ttl=cfg.dns_config.node_ttl,
        service_ttl=service_ttl,
        # membership plane (PortConfig + retry-join, command/agent/config.go)
        serf_lan_port=cfg.ports.serf_lan,
        serf_wan_port=cfg.ports.serf_wan,
        rpc_mesh_port=cfg.ports.server if cfg.server else None,
        start_join=list(cfg.start_join),
        retry_join=list(cfg.retry_join),
        retry_interval=cfg.retry_interval,
        retry_max=cfg.retry_max,
        rejoin_after_leave=cfg.rejoin_after_leave,
        acl_datacenter=cfg.acl_datacenter,
        acl_ttl=cfg.acl_ttl,
        acl_default_policy=cfg.acl_default_policy,
        acl_down_policy=cfg.acl_down_policy,
        acl_master_token=cfg.acl_master_token,
        acl_token=cfg.acl_token,
        encrypt=cfg.encrypt,
        protocol=cfg.protocol,
        gossip_backend=cfg.gossip_backend,
        gossip_plane=cfg.gossip_plane,
        enable_debug=cfg.enable_debug,
        http_workers=int(cfg.http_workers),
        device_store=cfg.device_store,
        device_store_capacity=int(cfg.device_store_capacity),
    )
