"""Batched serf→catalog reconcile: the fused-planes write path (PR 18).

The per-agent loop the reference runs (consul/leader.go:310-339) pays
one raft append→quorum round per health transition; at gossip-plane
scale a drain cadence can surface hundreds of transitions at once and
the consensus plane becomes the serialization point.  This module
collects one drain cadence's worth of member transitions (plus the
agent's dirty local-state entries — agent/local.py routes its
sync_changes deltas through the same submit), folds them into ONE
``MessageType.BATCH`` raft envelope (consensus/fsm.py
``_apply_batch_envelope``), and lets the FSM's batch-boundary render
hook warm the health byte cache (agent/hotpath.py) before the first
watch waiter wakes.  Append→quorum is paid once per cadence, not once
per transition — the pipelined drain→apply→render shape of "The
Algorithm of Pipelined Gossiping" (PAPERS.md) rather than a barrier
per event.

Semantics match the sequential handlers exactly (the lockstep
equivalence suite in tests/test_reconcile.py holds batched and
sequential to byte-identical store snapshots + fired watch sets):

* latest-wins per member — a refute arriving after a detect within the
  same cadence coalesces to the final state, exactly what the
  sequential loop would leave behind after processing both;
* raft peer-set changes (add_peer/remove_peer) stay host-side awaits —
  they are consensus-membership ops, not catalog writes;
* a failed flush drops the pending set, the same repair contract as
  the sequential loop's swallowed exception (consul/leader.go:115):
  the periodic full reconcile re-derives the truth.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Tuple

from consul_tpu.obs import journey as _journey
from consul_tpu.obs.raftstats import LatencyHist
from consul_tpu.structs.structs import (
    CONSUL_SERVICE_ID,
    CONSUL_SERVICE_NAME,
    HEALTH_CRITICAL,
    HEALTH_PASSING,
    DeregisterRequest,
    HealthCheck,
    MessageType,
    NodeService,
    RegisterRequest,
    SERF_ALIVE_OUTPUT,
    SERF_CHECK_ID,
    SERF_CHECK_NAME,
)

# Entry-count edges (not milliseconds): the batch-size distribution
# reuses the LatencyHist bank/render machinery the apply-batch shape
# histograms already ride (obs/raftstats.py).
BATCH_EDGES: Tuple[float, ...] = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0,
                                  128.0, 256.0, 512.0)

DEFAULT_BATCH_MAX = 64     # knob default mirrored in obs/tuner.py KNOBS
DEFAULT_LINGER_S = 0.05    # event-burst linger when no cadence coupling


def normalize_register(req: RegisterRequest) -> RegisterRequest:
    """The non-ACL half of Catalog.register's normalization
    (server/endpoints.py catalog_endpoint.go:18-75), applied in place:
    batched submits bypass the endpoint object, so the envelope's subs
    must carry the same shape the sequential path would have encoded."""
    if not req.node or not req.address:
        raise ValueError("Must provide node and address")
    if req.service is not None:
        if not req.service.id and req.service.service:
            req.service.id = req.service.service
        if req.service.id and not req.service.service:
            raise ValueError("Must provide service name with ID")
    if req.check is not None:
        req.checks.append(req.check)
        req.check = None
    for check in req.checks:
        if not check.check_id and check.name:
            check.check_id = check.name
        if not check.node:
            check.node = req.node
    return req


class ReconcileStats:
    """Batched-reconcile observatory: batch shape, coalescing win, and
    the end-to-end detection→watcher-visible latency the fused pipeline
    exists to shrink.  Families always render (zeros included) so the
    scrape schema is stable from the first scrape — the obs_smoke gate
    and the autotune evidence rules both key off these names."""

    def __init__(self) -> None:
        self.batch_size = LatencyHist(
            "consul_reconcile_batch_size",
            "Catalog writes carried per reconcile batch envelope.",
            edges=BATCH_EDGES)
        # Internal bank; rendered as a quantile summary, not a
        # histogram — the ISSUE's operator-facing contract is p50/p99.
        self.visible = LatencyHist(
            "consul_reconcile_visible_ms",
            "Detection to watcher-visible latency, milliseconds.")
        self.batches_total = 0
        self.entries_coalesced = 0   # subs that skipped their own append
        self.events_merged = 0       # latest-wins overwrites within a cadence
        self.submit_failures = 0

    def batch_done(self, n_entries: int) -> None:
        self.batches_total += 1
        self.batch_size.observe(float(n_entries))
        # Every sub past the first rode an append→quorum round it would
        # otherwise have paid for itself.
        self.entries_coalesced += max(0, n_entries - 1)

    def visible_observe(self, ms: float) -> None:
        self.visible.observe(ms)

    def families(self) -> Tuple[List[Dict[str, Any]], List[Dict[str, Any]],
                                List[Dict[str, Any]]]:
        """(histograms, summaries, labeled_counters) for the scrape."""
        v = self.visible
        summaries = [{
            "name": "consul_reconcile_visible_latency_ms",
            "help": "Detection to watcher-visible latency through the "
                    "batched reconcile, milliseconds.",
            "quantiles": [("0.5", v.quantile_ms(0.50) or 0.0),
                          ("0.99", v.quantile_ms(0.99) or 0.0)],
            "sum": round(v._sum, 3), "count": v.count,
        }]
        counters = [{
            "name": "consul_reconcile_entries_coalesced_total",
            "help": "Catalog writes that shared a batch envelope's "
                    "append instead of paying their own quorum round.",
            "rows": [({}, float(self.entries_coalesced))],
        }, {
            "name": "consul_reconcile_batches_total",
            "help": "Reconcile batch envelopes submitted through raft.",
            "rows": [({}, float(self.batches_total))],
        }, {
            "name": "consul_reconcile_events_merged_total",
            "help": "Member transitions coalesced latest-wins before "
                    "submit (refute-after-detect within one cadence).",
            "rows": [({}, float(self.events_merged))],
        }, {
            "name": "consul_reconcile_submit_failures_total",
            "help": "Batch envelope submits that failed (repaired by "
                    "the periodic full reconcile).",
            "rows": [({}, float(self.submit_failures))],
        }]
        return [self.batch_size.family()], summaries, counters

    def wire(self) -> Dict[str, Any]:
        """reconcile/telemetry.json debug-bundle member."""
        return {
            "batch_size": self.batch_size.wire(),
            "visible_latency": self.visible.wire(),
            "batches_total": self.batches_total,
            "entries_coalesced": self.entries_coalesced,
            "events_merged": self.events_merged,
            "submit_failures": self.submit_failures,
        }

    def reset(self) -> None:
        self.__init__()


# Process-global, mirroring obs.raftstats.aestats (one agent per
# process; call sites use the module attribute so tests can swap it).
reconstats = ReconcileStats()


class Reconciler:
    """Collects member transitions across one cadence and flushes them
    as a single BATCH envelope.  Owned by the leader's reconcile loop
    (server/leader.py); the op builders mirror the sequential handlers
    (_handle_alive/_handle_failed/_handle_left) decision for decision,
    including the store-compare skips."""

    def __init__(self, server, batch_max: int = DEFAULT_BATCH_MAX) -> None:
        self.srv = server
        self.batch_max = max(1, int(batch_max))
        # name -> (member, t_detect); dict order is arrival order, and
        # a latest-wins overwrite keeps the member's original slot —
        # final state per member matches the sequential loop.
        self.pending: Dict[str, Tuple[Any, float]] = {}

    def __len__(self) -> int:
        return len(self.pending)

    def note(self, member) -> None:
        name = member.name
        if name in self.pending:
            reconstats.events_merged += 1
            # The first sighting's detection stamp is the honest one:
            # the coalesced write makes BOTH transitions visible.  The
            # journey record travels with the stamp for the same reason.
            old, t0 = self.pending[name]
            oj = getattr(old, "_journey", None)
            if oj is not None:
                member._journey = oj
        else:
            t0 = time.monotonic()
        self.pending[name] = (member, t0)

    async def flush(self) -> int:
        """Build ops for every pending member and submit one envelope.
        Returns the number of catalog writes shipped (0 = all skipped
        by the store-compare fast paths, or nothing pending)."""
        pending, self.pending = self.pending, {}
        if not pending:
            return 0
        jy = _journey.journey
        ops: List[Tuple[MessageType, Any]] = []
        stamps: List[float] = []
        jrecs: List[Dict[str, Any]] = []
        for member, t0 in pending.values():
            try:
                member_ops = await self._member_ops(member)
            except Exception:
                # Host-side peer-set change failed (lost leadership
                # mid-flight): same swallow as the sequential loop —
                # the next leader's full reconcile repairs.
                continue
            if member_ops:
                ops.extend(member_ops)
                stamps.append(t0)
                if jy is not None:
                    rec = getattr(member, "_journey", None)
                    if rec is None:
                        rec = {"t0": t0, "t_enq": t0, "stages": {}}
                    rec["name"] = member.name
                    jrecs.append(rec)
        if not ops:
            return 0
        # Arm the journey's single in-flight batch: the consensus/FSM/
        # render/wake hooks stamp into it while the submit is in flight
        # (one reconcile loop per leader — no overlap).
        if jy is not None:
            jy.arm(jrecs, time.monotonic())
        try:
            await self.srv.raft_apply_batch(ops)
        except Exception:
            reconstats.submit_failures += 1
            if jy is not None:
                jy.abort()
            return 0
        now = time.monotonic()
        for t0 in stamps:
            reconstats.visible_observe((now - t0) * 1000.0)
        reconstats.batch_done(len(ops))
        if jy is not None:
            jy.close()
        return len(ops)

    # -- op builders (mirror server/leader.py handlers 1:1) ----------------

    async def _member_ops(self, member) -> List[Tuple[MessageType, Any]]:
        from consul_tpu.membership.swim import (
            STATE_ALIVE, STATE_DEAD, STATE_LEFT, STATE_SUSPECT)
        state = getattr(member, "state", STATE_ALIVE)
        if state in (STATE_ALIVE, STATE_SUSPECT):
            return await self._alive_ops(member)
        if state == STATE_DEAD:
            return self._failed_ops(member)
        if state == STATE_LEFT:
            return await self._left_ops(member.name)
        return []

    async def _alive_ops(self, member) -> List[Tuple[MessageType, Any]]:
        """_handle_alive (leader.go:354-421) as an op builder; the raft
        join for a new server is NOT a catalog write and stays a
        host-side await."""
        from consul_tpu.membership.serf import parse_server
        if not member.addr:
            return []  # sequential path rejects at Catalog.register
        sp = parse_server(member)
        if sp is not None and sp["dc"] == self.srv.config.datacenter and \
                member.name != self.srv.config.node_name and \
                member.name not in self.srv.raft.peers:
            await self.srv.raft.add_peer(member.name)
        _, addr = self.srv.store.get_node(member.name)
        if addr == member.addr:
            _, checks = self.srv.store.node_checks(member.name)
            serf_ok = any(c.check_id == SERF_CHECK_ID
                          and c.status == HEALTH_PASSING for c in checks)
            _, svcs = self.srv.store.node_services(member.name)
            svc_ok = (sp is None or sp["dc"] != self.srv.config.datacenter
                      or bool(svcs and CONSUL_SERVICE_ID in svcs))
            if serf_ok and svc_ok:
                return []
        req = RegisterRequest(
            node=member.name, address=member.addr,
            check=HealthCheck(node=member.name, check_id=SERF_CHECK_ID,
                              name=SERF_CHECK_NAME, status=HEALTH_PASSING,
                              output=SERF_ALIVE_OUTPUT))
        if sp is not None and sp["dc"] == self.srv.config.datacenter:
            req.service = NodeService(id=CONSUL_SERVICE_ID,
                                      service=CONSUL_SERVICE_NAME,
                                      port=sp["port"])
        return [(MessageType.REGISTER, normalize_register(req))]

    def _failed_ops(self, member) -> List[Tuple[MessageType, Any]]:
        """_handle_failed (leader.go:423-460) as an op builder."""
        if not member.addr:
            return []
        _, checks = self.srv.store.node_checks(member.name)
        if any(c.check_id == SERF_CHECK_ID and c.status == HEALTH_CRITICAL
               for c in checks):
            return []
        req = RegisterRequest(
            node=member.name, address=member.addr,
            check=HealthCheck(node=member.name, check_id=SERF_CHECK_ID,
                              name=SERF_CHECK_NAME, status=HEALTH_CRITICAL,
                              output="Agent not live or unreachable"))
        return [(MessageType.REGISTER, normalize_register(req))]

    async def _left_ops(self, name: str) -> List[Tuple[MessageType, Any]]:
        """_handle_left (leader.go:462-501) as an op builder; the raft
        peer removal stays a host-side await."""
        if name == self.srv.config.node_name:
            return []
        if name in self.srv.raft.peers:
            await self.srv.raft.remove_peer(name)
        _, addr = self.srv.store.get_node(name)
        if addr is None:
            return []
        return [(MessageType.DEREGISTER, DeregisterRequest(node=name))]
