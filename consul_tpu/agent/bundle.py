"""One-shot debug bundle: the ``consul debug`` analog.

The reference ships a ``consul debug`` command that captures metrics,
pprof profiles, logs, and cluster state over a sample window into a
single archive an operator can attach to an incident.  This module is
that capture for this codebase: ``capture(agent, seconds)`` samples the
agent over the window and returns a gzipped tarball of:

* ``manifest.json``         — capture metadata + section list
* ``metrics/snapshot_start.json`` / ``snapshot_end.json`` — the inmem
  telemetry ring at both window edges (rates are derivable)
* ``metrics/prometheus.txt`` — the full scrape-format exposition,
  including the consensus-plane families (obs/raftstats.py)
* ``slo.json``              — detection-latency SLO observatory state
* ``traces.json``           — recent finished traces (obs/trace.py)
* ``flight.json``           — kernel flight-recorder drain
* ``raft/telemetry.json``   — raft stats + histograms + per-peer rows
  + the leadership/election/lease event timeline
* ``reconcile/telemetry.json`` — batched-reconcile observatory: batch
  shape, coalescing yield, detection→visible latency (agent/reconcile.py)
* ``journey/telemetry.json`` — transition journey ledger: per-stage
  latency banks, end-to-end SLO, recent per-transition records
  (obs/journey.py)
* ``device/telemetry.json`` — device/kernel observatory: dispatch
  hists, HBM occupancy, compile + roofline telemetry (obs/devstats.py)
* ``autotune/verdict.json`` — autotune observatory: the knob
  resolution this node booted with (obs/tuner.py) — per-knob value,
  source, evidence keys + the backend fingerprint
* ``tasks.txt``             — thread + asyncio task dump (agent/debug.py)
* ``config.json``           — agent config with secrets redacted

Served via ``/v1/agent/debug/bundle?seconds=N`` (enable_debug-gated,
like the pprof routes) and fetched by the ``consul-tpu debug`` CLI.
"""

from __future__ import annotations

import asyncio
import dataclasses
import io
import json
import tarfile
import time
from typing import Any, Dict

from consul_tpu.version import VERSION

# AgentConfig fields whose values must never leave the process in a
# bundle (gossip key, ACL tokens).
SECRET_FIELDS = ("encrypt", "acl_master_token", "acl_token")

SECTIONS = ("metrics", "slo", "traces", "flight", "raft", "reconcile",
            "journey", "device", "autotune", "tasks", "config")


def redacted_config(config: Any) -> Dict[str, Any]:
    cfg = dataclasses.asdict(config)
    for k in SECRET_FIELDS:
        if cfg.get(k):
            cfg[k] = "<redacted>"
    return cfg


async def capture(agent: Any, seconds: float) -> bytes:
    """Sample ``agent`` over ``seconds`` and return the tar.gz bytes."""
    from consul_tpu.obs import raftstats
    from consul_tpu.obs.trace import tracer
    from consul_tpu.utils.telemetry import metrics

    from consul_tpu.agent import debug

    start_snap = metrics.snapshot()
    if seconds > 0:
        await asyncio.sleep(seconds)
    end_snap = metrics.snapshot()

    files: Dict[str, bytes] = {}

    def put_json(name: str, obj: Any) -> None:
        files[name] = json.dumps(obj, indent=1, default=str).encode()

    put_json("metrics/snapshot_start.json", start_snap)
    put_json("metrics/snapshot_end.json", end_snap)
    files["metrics/prometheus.txt"] = (await agent._prom_text()).encode()
    put_json("slo.json", await agent._slo(None))
    put_json("traces.json", tracer.traces(200))
    put_json("flight.json", await agent._flight(None))
    put_json("raft/telemetry.json", raftstats.telemetry(
        getattr(agent.server, "raft", None), local=agent.local))
    from consul_tpu.agent.reconcile import reconstats
    rc = reconstats.wire()
    leader = getattr(agent.server, "leader_duties", None)
    rc["reconciler_armed"] = bool(
        leader is not None and getattr(leader, "reconciler", None))
    put_json("reconcile/telemetry.json", rc)
    put_json("journey/telemetry.json", await agent._journey(None))
    put_json("device/telemetry.json", await agent._device(None))
    put_json("autotune/verdict.json", await agent._autotune(None))
    files["tasks.txt"] = debug.task_dump().encode()
    put_json("config.json", redacted_config(agent.config))
    put_json("manifest.json", {
        "created": time.time(),
        "seconds": seconds,
        "node": agent.config.node_name,
        "version": VERSION,
        "sections": list(SECTIONS),
        "files": sorted(files) + ["manifest.json"],
    })

    buf = io.BytesIO()
    now = int(time.time())
    with tarfile.open(fileobj=buf, mode="w:gz") as tar:
        for name in sorted(files):
            data = files[name]
            info = tarfile.TarInfo(name)
            info.size = len(data)
            info.mtime = now
            tar.addfile(info, io.BytesIO(data))
    return buf.getvalue()
