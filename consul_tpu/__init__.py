"""consul_tpu — a TPU-native service-discovery / health / KV framework.

A brand-new framework with the capability surface of HashiCorp Consul
v0.5.2 (the reference, see SURVEY.md), built TPU-first:

- **Gossip plane (TPU / JAX).**  SWIM failure detection and epidemic
  dissemination run as one jit-compiled, batched message-passing round
  step over HBM-resident membership arrays (``consul_tpu.gossip``),
  sharded over a `jax.sharding.Mesh`.  The same kernel backs the real
  agent's membership layer and a million-node simulator.
- **Control plane (host / Python + C++).**  Raft-replicated state
  machine, MVCC state store with blocking-query watches, RPC mesh with
  forwarding, HTTP/DNS/CLI edge interfaces, ACLs, sessions/locks — the
  strongly-consistent side of the system (``consul_tpu.server``,
  ``consul_tpu.state``, ``consul_tpu.agent``).

Layer map and parity citations: SURVEY.md §1-§2; each module's docstring
cites the reference file:line it matches.
"""

from consul_tpu.version import VERSION, PROTOCOL_VERSION

__version__ = VERSION
__all__ = ["VERSION", "PROTOCOL_VERSION"]
