/* Hash-routed single-page UI over the agent's HTTP API.
 *
 * Screens mirror the reference Ember app (ui/ in the reference tree):
 *   #/services            -> /v1/internal/ui/services
 *   #/services/<name>     -> /v1/health/service/<name>
 *   #/nodes               -> /v1/internal/ui/nodes
 *   #/nodes/<name>        -> /v1/internal/ui/node/<name>
 *   #/kv[/prefix/]        -> /v1/kv/<prefix>?keys&separator=/
 */
"use strict";

const view = document.getElementById("view");

async function api(path, opts) {
  const r = await fetch(path, opts);
  if (!r.ok) throw new Error(`${r.status} ${await r.text()}`);
  const text = await r.text();
  return text ? JSON.parse(text) : null;
}

function el(tag, attrs = {}, ...children) {
  const e = document.createElement(tag);
  for (const [k, v] of Object.entries(attrs)) {
    if (k === "class") e.className = v;
    else if (k.startsWith("on")) e.addEventListener(k.slice(2), v);
    else e.setAttribute(k, v);
  }
  for (const c of children.flat()) {
    e.append(c instanceof Node ? c : document.createTextNode(String(c)));
  }
  return e;
}

/* base64 <-> UTF-8 text (atob alone mangles non-ASCII values). */
function b64decode(v) {
  return new TextDecoder().decode(
    Uint8Array.from(atob(v), c => c.charCodeAt(0)));
}

/* KV keys may contain ?, #, %… — escape each path segment, keep '/'. */
function kvPath(key) {
  return key.split("/").map(encodeURIComponent).join("/");
}

function goKV(hash) {
  if (location.hash === hash) route();  // hashchange won't fire
  else location.hash = hash;            // fires route() once
}

function badge(n, cls) {
  return el("span", { class: `badge ${n ? cls : "zero"}` }, n);
}

function setActiveTab(tab) {
  document.querySelectorAll("nav a").forEach(a =>
    a.classList.toggle("active", a.dataset.tab === tab));
}

function render(...nodes) {
  view.replaceChildren(...nodes);
}

function fail(e) {
  render(el("p", { class: "err" }, `Request failed: ${e.message}`));
}

/* -- services ------------------------------------------------------------ */

async function showServices() {
  setActiveTab("services");
  const rows = await api("/v1/internal/ui/services");
  render(
    el("h2", {}, "Services"),
    el("table", {},
      el("thead", {}, el("tr", {},
        el("th", {}, "Service"), el("th", {}, "Health"),
        el("th", {}, "Nodes"))),
      el("tbody", {}, rows.map(s =>
        el("tr", { class: "rowlink",
                   onclick: () => location.hash = `#/services/${encodeURIComponent(s.Name)}` },
          el("td", {}, s.Name),
          el("td", {},
            badge(s.ChecksPassing, "pass"),
            badge(s.ChecksWarning, "warn"),
            badge(s.ChecksCritical, "crit")),
          el("td", {}, s.Nodes.length))))));
  if (!rows.length) view.append(el("p", { class: "muted" }, "No services registered."));
}

async function showService(name) {
  setActiveTab("services");
  const insts = await api(`/v1/health/service/${encodeURIComponent(name)}`);
  render(
    el("p", { class: "back" }, el("a", { href: "#/services" }, "← Services")),
    el("h2", {}, el("span", { class: "crumb" }, "service / "), name),
    el("table", {},
      el("thead", {}, el("tr", {},
        el("th", {}, "Node"), el("th", {}, "Address"),
        el("th", {}, "Port"), el("th", {}, "Checks"))),
      el("tbody", {}, insts.map(i =>
        el("tr", { class: "rowlink",
                   onclick: () => location.hash = `#/nodes/${encodeURIComponent(i.Node.Node)}` },
          el("td", {}, i.Node.Node),
          el("td", {}, i.Service.Address || i.Node.Address),
          el("td", {}, i.Service.Port),
          el("td", {}, i.Checks.map(c =>
            el("div", {}, el("span", { class: `status ${c.Status}` }, c.Status),
              ` ${c.Name}`))))))));
}

/* -- nodes --------------------------------------------------------------- */

function checkCounts(checks) {
  const c = { passing: 0, warning: 0, critical: 0 };
  for (const ch of checks) c[ch.Status] = (c[ch.Status] || 0) + 1;
  return c;
}

async function showNodes() {
  setActiveTab("nodes");
  const nodes = await api("/v1/internal/ui/nodes");
  render(
    el("h2", {}, "Nodes"),
    el("table", {},
      el("thead", {}, el("tr", {},
        el("th", {}, "Node"), el("th", {}, "Address"),
        el("th", {}, "Health"), el("th", {}, "Services"))),
      el("tbody", {}, nodes.map(n => {
        const c = checkCounts(n.Checks || []);
        return el("tr", { class: "rowlink",
                          onclick: () => location.hash = `#/nodes/${encodeURIComponent(n.Node)}` },
          el("td", {}, n.Node),
          el("td", {}, n.Address),
          el("td", {}, badge(c.passing, "pass"), badge(c.warning, "warn"),
            badge(c.critical, "crit")),
          el("td", {}, (n.Services || []).map(s => s.Service).join(", ")));
      }))));
}

async function showNode(name) {
  setActiveTab("nodes");
  const n = await api(`/v1/internal/ui/node/${encodeURIComponent(name)}`);
  render(
    el("p", { class: "back" }, el("a", { href: "#/nodes" }, "← Nodes")),
    el("h2", {}, el("span", { class: "crumb" }, "node / "), n.Node,
      el("span", { class: "muted" }, `  (${n.Address})`)),
    el("h2", {}, "Services"),
    el("table", {},
      el("thead", {}, el("tr", {},
        el("th", {}, "Service"), el("th", {}, "ID"),
        el("th", {}, "Port"), el("th", {}, "Tags"))),
      el("tbody", {}, (n.Services || []).map(s =>
        el("tr", {},
          el("td", {}, s.Service), el("td", {}, s.ID || s.Service),
          el("td", {}, s.Port), el("td", {}, (s.Tags || []).join(", ")))))),
    el("h2", { style: "margin-top:20px" }, "Checks"),
    el("table", {},
      el("thead", {}, el("tr", {},
        el("th", {}, "Check"), el("th", {}, "Status"),
        el("th", {}, "Output"))),
      el("tbody", {}, (n.Checks || []).map(c =>
        el("tr", {},
          el("td", {}, c.Name),
          el("td", {}, el("span", { class: `status ${c.Status}` }, c.Status)),
          el("td", { class: "muted" }, c.Output || ""))))));
}

/* -- key/value ----------------------------------------------------------- */

function kvEditor(key, value, { fresh }) {
  const keyInput = el("input", { type: "text", value: key,
                                 placeholder: "key (folders end with /)" });
  if (!fresh) keyInput.setAttribute("disabled", "");
  const valInput = el("textarea", {}, value);
  const save = async () => {
    const k = keyInput.value.trim();
    if (!k) return;
    await api(`/v1/kv/${kvPath(k)}`, { method: "PUT", body: valInput.value });
    goKV(`#/kv/${k.slice(0, k.lastIndexOf("/") + 1)}`);
  };
  const row = el("div", { class: "row" }, el("button", { onclick: save }, fresh ? "Create" : "Save"));
  if (!fresh) {
    row.append(el("button", {
      class: "danger",
      onclick: async () => {
        await api(`/v1/kv/${kvPath(key)}`, { method: "DELETE" });
        goKV(`#/kv/${key.slice(0, key.lastIndexOf("/") + 1)}`);
      },
    }, "Delete"));
  }
  return el("div", { class: "editor" }, keyInput,
            el("div", { class: "row" }, valInput), row);
}

async function showKV(prefix) {
  setActiveTab("kv");
  if (prefix && !prefix.endsWith("/")) {
    // leaf: show the editor for one key
    const ents = await api(`/v1/kv/${kvPath(prefix)}`).catch(() => null);
    const val = ents && ents[0] && ents[0].Value ? b64decode(ents[0].Value) : "";
    render(
      el("p", { class: "back" },
        el("a", { href: `#/kv/${prefix.slice(0, prefix.lastIndexOf("/") + 1)}` },
          "← Back")),
      el("h2", {}, el("span", { class: "crumb" }, "kv / "), prefix),
      kvEditor(prefix, val, { fresh: !ents }));
    return;
  }
  let keys = [];
  try {
    keys = await api(`/v1/kv/${kvPath(prefix)}?keys&separator=/`) || [];
  } catch (e) { /* 404 = empty prefix */ }
  const crumbs = el("h2", {}, el("a", { href: "#/kv" }, "kv"), " / ");
  let acc = "";
  for (const part of prefix.split("/").filter(Boolean)) {
    acc += part + "/";
    crumbs.append(el("a", { href: `#/kv/${acc}` }, part), " / ");
  }
  render(
    crumbs,
    el("table", {},
      el("tbody", {}, keys.map(k =>
        el("tr", { class: "rowlink",
                   onclick: () => { location.hash = `#/kv/${k}`; } },
          el("td", {}, k.endsWith("/") ? `📁 ${k.slice(prefix.length)}`
                                       : k.slice(prefix.length)))))),
    keys.length ? "" : el("p", { class: "muted" }, "No keys under this prefix."),
    el("h2", { style: "margin-top:22px" }, "Create key"),
    kvEditor(prefix, "", { fresh: true }));
}

/* -- shell --------------------------------------------------------------- */

async function whoami() {
  try {
    const me = await api("/v1/agent/self");
    document.getElementById("whoami").textContent =
      `${me.Config.NodeName} · ${me.Config.Datacenter}` +
      (me.Config.Server ? " · server" : " · client");
  } catch (e) { /* non-fatal */ }
}

function route() {
  const h = location.hash || "#/services";
  const m = h.slice(2).split("/");
  const go = {
    services: () => m[1] ? showService(decodeURIComponent(m[1])) : showServices(),
    nodes: () => m[1] ? showNode(decodeURIComponent(m[1])) : showNodes(),
    kv: () => showKV(m.slice(1).join("/")),
  }[m[0]] || showServices;
  go().catch(fail);
}

window.addEventListener("hashchange", route);
whoami();
route();
