"""CLI-RPC (IPC): msgpack seq-based request/response + streaming.

Parity target: ``command/agent/rpc.go`` (701 LoC) + ``rpc_client.go``
(473) — the agent-side command socket the CLI talks to.
"""

from consul_tpu.ipc.server import IPCServer
from consul_tpu.ipc.client import IPCClient, IPCError

__all__ = ["IPCServer", "IPCClient", "IPCError"]
