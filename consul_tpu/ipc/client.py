"""CLI-side IPC client (sync).

Parity target: ``command/agent/rpc_client.go`` (473 LoC): dial,
handshake, seq-matched request/response, and the monitor stream
(a handler receives out-of-band log records until stopped).
"""

from __future__ import annotations

import socket
import threading
from typing import Any, Callable, Dict, List, Optional

import msgpack


class IPCError(Exception):
    pass


class IPCClient:
    def __init__(self, addr: str, timeout: float = 10.0) -> None:
        self._timeout = timeout
        if addr.startswith("unix://"):
            # Unix-socket IPC address (command/rpc.go + util_unix.go).
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._sock.settimeout(timeout)
            self._sock.connect(addr[len("unix://"):])
        else:
            host, _, port = addr.rpartition(":")
            self._sock = socket.create_connection((host or "127.0.0.1",
                                                   int(port)), timeout=timeout)
        self._unpacker = msgpack.Unpacker(raw=False)
        self._seq = 0
        self._lock = threading.Lock()
        self._monitor_handler: Optional[Callable[[str], None]] = None
        self._monitor_seq: Optional[int] = None
        self._old_monitor_seqs: set = set()  # stopped monitors still draining
        self._handshake()

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "IPCClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- wire helpers -------------------------------------------------------

    def _next_obj(self) -> Any:
        while True:
            try:
                return next(self._unpacker)
            except StopIteration:
                data = self._sock.recv(4096)
                if not data:
                    raise IPCError("connection closed")
                self._unpacker.feed(data)

    def _send(self, *objs: Any) -> None:
        buf = b"".join(msgpack.packb(o, use_bin_type=True) for o in objs)
        self._sock.sendall(buf)

    def _read_response(self, want_seq: int, has_body: bool) -> Any:
        """Read headers until ours arrives; dispatch monitor records that
        interleave (rpc_client.go listen/seq-matching)."""
        while True:
            header = self._next_obj()
            seq = header.get("Seq")
            err = header.get("Error", "")
            if seq == self._monitor_seq and seq != want_seq:
                body = self._next_obj()
                if self._monitor_handler and "Log" in body:
                    self._monitor_handler(body["Log"])
                continue
            if seq != want_seq:
                if seq in self._old_monitor_seqs:
                    # In-flight record from a stopped monitor: its {Log}
                    # body MUST be consumed or the stream desyncs.
                    self._next_obj()
                continue
            if err:
                raise IPCError(err)
            return self._next_obj() if has_body else None

    def _call(self, command: str, body: Any = None,
              has_resp_body: bool = False) -> Any:
        with self._lock:
            self._seq += 1
            seq = self._seq
            objs: List[Any] = [{"Command": command, "Seq": seq}]
            if body is not None:
                objs.append(body)
            self._send(*objs)
            return self._read_response(seq, has_resp_body)

    def _handshake(self) -> None:
        self._call("handshake", {"Version": 1})

    # -- commands -----------------------------------------------------------

    def join(self, addrs: List[str], wan: bool = False) -> int:
        resp = self._call("join", {"Existing": addrs, "WAN": wan},
                          has_resp_body=True)
        return resp.get("Num", 0)

    def members_lan(self) -> List[Dict[str, Any]]:
        return self._call("members-lan", None,
                          has_resp_body=True).get("Members", [])

    def members_wan(self) -> List[Dict[str, Any]]:
        return self._call("members-wan", None,
                          has_resp_body=True).get("Members", [])

    def stats(self) -> Dict[str, Dict[str, str]]:
        return self._call("stats", None, has_resp_body=True)

    def leave(self) -> None:
        self._call("leave")

    def force_leave(self, node: str) -> None:
        self._call("force-leave", {"Node": node})

    def reload(self) -> None:
        self._call("reload")

    def monitor(self, handler: Callable[[str], None],
                log_level: str = "INFO") -> int:
        """Start streaming logs to handler; returns the monitor seq for
        stop()."""
        with self._lock:
            self._seq += 1
            seq = self._seq
            self._send({"Command": "monitor", "Seq": seq},
                       {"LogLevel": log_level})
            self._monitor_handler = handler
            self._monitor_seq = seq
            self._read_response(seq, has_body=False)
        return seq

    def pump(self, timeout: Optional[float] = None) -> bool:
        """Process one incoming record (monitor logs); returns False on
        timeout."""
        if timeout is not None:
            self._sock.settimeout(timeout)
        try:
            header = self._next_obj()
        except socket.timeout:
            return False
        finally:
            self._sock.settimeout(self._timeout)
        if header.get("Seq") == self._monitor_seq:
            body = self._next_obj()
            if self._monitor_handler and "Log" in body:
                self._monitor_handler(body["Log"])
        return True

    def stop_monitor(self, seq: int) -> None:
        self._old_monitor_seqs.add(seq)
        self._monitor_handler = None
        self._monitor_seq = None
        self._call("stop", {"Stop": seq})

    def keyring(self, op: str, key: str = "") -> Dict[str, Any]:
        cmd = {"install": "install-key", "use": "use-key",
               "remove": "remove-key", "list": "list-keys"}[op]
        return self._call(cmd, {"Key": key}, has_resp_body=True)
