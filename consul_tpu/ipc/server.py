"""Agent-side IPC server.

Parity target: ``command/agent/rpc.go``: msgpack request/response
with client-assigned sequence numbers over TCP (or a unix socket),
version handshake, and the command set at :45-59 —
handshake, join, members-lan, members-wan, monitor, stop, leave,
force-leave, stats, reload, keyring ops.  ``monitor`` subscribes the
connection to the agent's log stream; records flow as out-of-band
{Seq: <monitor seq>} headers + log body until ``stop``.
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, Optional

import msgpack

MIN_IPC_VERSION = 1
MAX_IPC_VERSION = 1

COMMANDS = ("handshake", "join", "members-lan", "members-wan", "monitor",
            "stop", "leave", "force-leave", "stats", "reload",
            "install-key", "use-key", "remove-key", "list-keys", "serve")


class IPCServer:
    def __init__(self, agent) -> None:
        self.agent = agent
        self._server: Optional[asyncio.AbstractServer] = None
        self.addr: Optional[tuple] = None
        self.unix_path: Optional[str] = None

    async def start(self, host: str = "127.0.0.1", port: int = 8400,
                    unix_path: Optional[str] = None) -> None:
        if unix_path:
            # Unix-socket IPC address (rpc.go unix support via
            # command/agent/config.go UnixSockets); stale socket files
            # are unlinked before bind, as the reference does.
            import os
            try:
                os.unlink(unix_path)
            except FileNotFoundError:
                pass
            self._server = await asyncio.start_unix_server(self._serve,
                                                           unix_path)
            self.unix_path = unix_path
        else:
            self._server = await asyncio.start_server(self._serve, host, port)
            self.addr = self._server.sockets[0].getsockname()[:2]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        conn = _Conn(self.agent, reader, writer)
        try:
            await conn.run()
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            # A dropped monitor client must not leak its log sink.
            for sink in conn._monitors.values():
                self.agent.log_sink_remove(sink)
            conn._monitors.clear()
            writer.close()


class _Conn:
    def __init__(self, agent, reader, writer) -> None:
        self.agent = agent
        self.reader = reader
        self.writer = writer
        self.unpacker = msgpack.Unpacker(raw=False)
        self.did_handshake = False
        self._monitors: Dict[int, Any] = {}  # monitor seq -> log sink
        self._drains: set = set()  # anchor drain tasks against GC

    async def _next_obj(self) -> Any:
        while True:
            try:
                return next(self.unpacker)
            except StopIteration:
                data = await self.reader.read(4096)
                if not data:
                    raise ConnectionError("client closed")
                self.unpacker.feed(data)

    def _send(self, *objs: Any) -> None:
        for obj in objs:
            self.writer.write(msgpack.packb(obj, use_bin_type=True))

    async def run(self) -> None:
        while True:
            header = await self._next_obj()
            command = header.get("Command", "")
            seq = header.get("Seq", 0)
            if command != "handshake" and not self.did_handshake:
                self._send({"Seq": seq, "Error": "Handshake required"})
                await self.writer.drain()
                continue
            handler = getattr(self, "_cmd_" + command.replace("-", "_"), None)
            if handler is None:
                self._send({"Seq": seq, "Error": f"Unknown command: {command}"})
            else:
                try:
                    await handler(seq)
                except Exception as e:
                    self._send({"Seq": seq, "Error": str(e)})
            await self.writer.drain()

    # -- commands -----------------------------------------------------------

    async def _cmd_handshake(self, seq: int) -> None:
        req = await self._next_obj()
        version = req.get("Version", 0)
        if not (MIN_IPC_VERSION <= version <= MAX_IPC_VERSION):
            self._send({"Seq": seq,
                        "Error": f"Unsupported version: {version}"})
            return
        self.did_handshake = True
        self._send({"Seq": seq, "Error": ""})

    async def _cmd_join(self, seq: int) -> None:
        req = await self._next_obj()
        addrs = req.get("Existing", [])
        n = await self.agent.join(addrs, wan=req.get("WAN", False))
        self._send({"Seq": seq, "Error": ""}, {"Num": n})

    async def _cmd_members_lan(self, seq: int) -> None:
        members = self.agent.lan_members()
        self._send({"Seq": seq, "Error": ""}, {"Members": members})

    async def _cmd_members_wan(self, seq: int) -> None:
        members = self.agent.wan_members()
        self._send({"Seq": seq, "Error": ""}, {"Members": members})

    async def _cmd_stats(self, seq: int) -> None:
        stats = dict(self.agent.server.stats())
        stats.update(self.agent.gossip_stats())
        # gossip_backend=tpu: surface the plane's kernel-session
        # counters as their own `consul info` section (the serf.Stats()
        # role for the on-device substrate).
        pool = getattr(self.agent, "lan_pool", None)
        if hasattr(pool, "plane_stats"):
            ps = await pool.plane_stats(timeout=2.0)
            if ps:
                k = ps.get("kernel", {})
                m = ps.get("members", {})
                stats["gossip_plane"] = {
                    "round": str(ps.get("round", 0)),
                    "capacity": str(ps.get("capacity", 0)),
                    "sim_nodes": str(ps.get("sim_nodes", 0)),
                    "alive": str(m.get("alive", 0)),
                    "failed": str(m.get("failed", 0)),
                    "left": str(m.get("left", 0)),
                    "pending_joins": str(ps.get("pending_joins", 0)),
                    "event_slots_live": str(ps.get("event_slots_live", 0)),
                    "detected": str(k.get("n_detected", 0)),
                    "refuted": str(k.get("n_refuted", 0)),
                    "false_dead": str(k.get("n_false_dead", 0)),
                    "slot_drops": str(k.get("drops", 0)),
                }
        self._send({"Seq": seq, "Error": ""}, stats)

    async def _cmd_leave(self, seq: int) -> None:
        self._send({"Seq": seq, "Error": ""})
        await self.writer.drain()
        await self.agent.graceful_leave()

    async def _cmd_force_leave(self, seq: int) -> None:
        req = await self._next_obj()
        await self.agent.force_leave(req.get("Node", ""))
        self._send({"Seq": seq, "Error": ""})

    async def _cmd_reload(self, seq: int) -> None:
        await self.agent.reload()
        self._send({"Seq": seq, "Error": ""})

    async def _cmd_monitor(self, seq: int) -> None:
        req = await self._next_obj()
        level = req.get("LogLevel", "INFO")

        def sink(line: str) -> None:
            try:
                self._send({"Seq": seq, "Error": ""}, {"Log": line})
                loop = asyncio.get_event_loop()
                task = loop.create_task(_drain(self.writer))
                self._drains.add(task)
                task.add_done_callback(self._drains.discard)
            except Exception:  # noqa: E02 — monitor client died mid-stream
                pass

        # Ack FIRST: the client reads one header as the command response;
        # replayed ring lines must come after it or the stream desyncs.
        self._send({"Seq": seq, "Error": ""})
        await self.writer.drain()
        self._monitors[seq] = sink
        self.agent.log_sink_add(sink, level)

    async def _cmd_serve(self, seq: int) -> None:
        """Worker-gateway request (agent/workers.py): run one hot op
        (agent/hotpath.py) against the in-process server core and ship
        the precomputed (status, headers, content_type, body) quadruple
        back as a single msgpack frame.

        Unlike the admin commands, serve requests are CONCURRENT: the
        body is read inline (keeping the request stream in sync) and
        the op runs in a spawned task, so a blocking op never stalls
        the next request on the same connection.  Replies are matched
        by Seq; _send writes header+body with no await in between, so
        interleaved task replies can't tear each other's frames."""
        req = await self._next_obj()
        op = req.get("Op", "")
        args = dict(req.get("Args") or {})
        if "token" in args and args["token"] is None:
            # Default-token resolution happens agent-side so workers
            # never need ACL material in their own config.
            args["token"] = self.agent.config.acl_token
        task = asyncio.get_event_loop().create_task(
            self._serve_one(seq, op, args))
        self._drains.add(task)
        task.add_done_callback(self._drains.discard)

    async def _serve_one(self, seq: int, op: str, args: Dict[str, Any]) -> None:
        import time

        from consul_tpu.agent import hotpath
        from consul_tpu.obs.reqstats import reqstats
        t0 = time.monotonic()
        try:
            status, hdrs, ct, body = await hotpath.handle(
                self.agent.server, op, args)
            self._send({"Seq": seq, "Error": ""},
                       {"Status": status, "Hdrs": hdrs, "CT": ct,
                        "Body": body})
        except Exception as e:  # noqa: E02 — reply channel of last resort
            self._send({"Seq": seq, "Error": str(e)})
        finally:
            # Gateway ops land in the same per-endpoint stats registry
            # the edge handlers feed, under their hot-op name.
            reqstats.record(op, (time.monotonic() - t0) * 1000)
        await _drain(self.writer)

    async def _cmd_stop(self, seq: int) -> None:
        req = await self._next_obj()
        target = req.get("Stop", 0)
        sink = self._monitors.pop(target, None)
        if sink is not None:
            self.agent.log_sink_remove(sink)
        self._send({"Seq": seq, "Error": ""})

    # -- keyring ops (wired to the gossip keyring when it lands) ------------

    async def _keyring(self, seq: int, op: str) -> None:
        req = await self._next_obj()
        result = await self.agent.keyring_operation(op, req.get("Key", ""))
        self._send({"Seq": seq, "Error": ""}, result)

    async def _cmd_install_key(self, seq: int) -> None:
        await self._keyring(seq, "install")

    async def _cmd_use_key(self, seq: int) -> None:
        await self._keyring(seq, "use")

    async def _cmd_remove_key(self, seq: int) -> None:
        await self._keyring(seq, "remove")

    async def _cmd_list_keys(self, seq: int) -> None:
        await self._keyring(seq, "list")


async def _drain(writer: asyncio.StreamWriter) -> None:
    try:
        await writer.drain()
    except ConnectionError:
        pass
