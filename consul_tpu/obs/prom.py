"""Prometheus text-format exposition of the telemetry registry.

Renders the ``utils.telemetry`` inmem snapshot (the same data
``/v1/agent/metrics`` serves as JSON) in the Prometheus text format
(version 0.0.4): counters summed across retained intervals, gauges
last-write-wins, timer samples as a summary pair (``_count``/``_sum``
in seconds) plus ``_min``/``_max`` gauges.  Every family gets a
``# HELP`` + ``# TYPE`` pair and label values are escaped per the
format spec.  Served by the agent at
``/v1/agent/metrics?format=prometheus``.

Flight-recorder series ride along automatically: the FlightRecorder
folds drained kernel rows into the registry as ``consul.flight.*``,
which render here as ``consul_flight_*``.  The detection-latency
observatory banks (obs/hist.py) render as CUMULATIVE histogram
families via the ``histograms`` parameter
(``consul_swim_detection_latency_rounds_bucket{le="..."}`` etc).
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize(name: str) -> str:
    """Metric name -> valid Prometheus identifier (dots and other
    separators become underscores; leading digit gets a prefix)."""
    out = _BAD_CHARS.sub("_", name)
    if not _NAME_OK.match(out):
        out = "_" + out
    return out


def escape_label_value(v: Any) -> str:
    """Escape a label value per the text format: backslash, double
    quote, and newline."""
    return (str(v).replace("\\", r"\\").replace('"', r"\"")
            .replace("\n", r"\n"))


def _esc_help(v: Any) -> str:
    """HELP text escaping: backslash and newline (quotes stay)."""
    return str(v).replace("\\", r"\\").replace("\n", r"\n")


def _fmt(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _family(lines: List[str], name: str, kind: str, help_text: str) -> None:
    lines.append(f"# HELP {name} {_esc_help(help_text)}")
    lines.append(f"# TYPE {name} {kind}")


def render_prometheus(snapshot: List[Dict[str, Any]],
                      histograms: Optional[List[Dict[str, Any]]] = None,
                      summaries: Optional[List[Dict[str, Any]]] = None,
                      labeled_counters: Optional[List[Dict[str, Any]]] = None,
                      labeled_gauges: Optional[List[Dict[str, Any]]] = None
                      ) -> str:
    """Telemetry snapshot (list of interval dicts, oldest first) ->
    Prometheus text format, one block per family with HELP/TYPE lines.

    ``histograms``: optional list of cumulative histogram families
    (obs.hist ``HistRecorder.families()`` shape: ``name``, ``help``,
    ``buckets`` as ascending ``(le, cumulative_count)`` pairs, ``sum``,
    ``count``); rendered with the mandatory ``+Inf`` bucket.

    ``summaries``: optional quantile summary families (serving-plane
    p50/p99, obs.reqstats): ``name``, ``help``, ``labels`` dict,
    ``quantiles`` as ``(q, value)`` pairs, ``sum``, ``count``.
    Labelset variants share one HELP/TYPE block per name.

    ``labeled_counters``: optional labeled counter families:
    ``name``, ``help``, ``rows`` as ``(labels_dict, value)`` pairs.

    ``labeled_gauges``: same rows shape as ``labeled_counters`` but
    rendered with ``# TYPE ... gauge`` (per-peer replication lag and
    contact-age series from obs.raftstats)."""
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    samples: Dict[str, Dict[str, float]] = {}
    for iv in snapshot:
        for k, c in iv.get("Counters", {}).items():
            counters[k] = counters.get(k, 0.0) + float(c["sum"])
        for k, g in iv.get("Gauges", {}).items():
            gauges[k] = float(g)
        for k, s in iv.get("Samples", {}).items():
            agg = samples.setdefault(
                k, {"count": 0.0, "sum": 0.0,
                    "min": float("inf"), "max": float("-inf")})
            agg["count"] += float(s["count"])
            agg["sum"] += float(s["sum"])
            agg["min"] = min(agg["min"], float(s["min"]))
            agg["max"] = max(agg["max"], float(s["max"]))
    lines: List[str] = []
    emitted: set = set()
    for k in sorted(counters):
        n = sanitize(k)
        if n in emitted:
            continue
        emitted.add(n)
        _family(lines, n, "counter", f"Telemetry counter {k}.")
        lines.append(f"{n} {_fmt(counters[k])}")
    for k in sorted(gauges):
        n = sanitize(k)
        # A name can land in the registry as BOTH counter and gauge
        # when the gossip plane shares the agent's process (the plane's
        # FlightRecorder counts consul.flight.* while the agent's
        # scrape-time fold_summary mirrors the same names as gauges).
        # One family per name: the counter wins, the mirror is dropped.
        if n in emitted:
            continue
        emitted.add(n)
        _family(lines, n, "gauge", f"Telemetry gauge {k}.")
        lines.append(f"{n} {_fmt(gauges[k])}")
    for k in sorted(samples):
        agg = samples[k]
        n = sanitize(k)
        # Timer samples are milliseconds in the registry; expose
        # base-unit seconds per Prometheus convention.
        _family(lines, f"{n}_seconds", "summary",
                f"Telemetry timer {k} in seconds.")
        lines.append(f"{n}_seconds_count {_fmt(agg['count'])}")
        lines.append(f"{n}_seconds_sum {repr(agg['sum'] / 1000.0)}")
        _family(lines, f"{n}_seconds_min", "gauge",
                f"Minimum retained {k} sample in seconds.")
        lines.append(f"{n}_seconds_min {repr(agg['min'] / 1000.0)}")
        _family(lines, f"{n}_seconds_max", "gauge",
                f"Maximum retained {k} sample in seconds.")
        lines.append(f"{n}_seconds_max {repr(agg['max'] / 1000.0)}")
    hist_seen: set = set()
    for fam in histograms or []:
        n = sanitize(fam["name"])
        # One HELP/TYPE block per family name: scenario-labeled
        # variants (obs.hist families with a "labels" dict) share the
        # name with their unlabeled aggregate and must not repeat the
        # header — Prometheus parsers reject duplicate TYPE lines.
        if n not in hist_seen:
            hist_seen.add(n)
            _family(lines, n, "histogram", fam.get("help", ""))
        labels = fam.get("labels") or {}
        pre = "".join(f'{sanitize(str(k))}="{escape_label_value(v)}",'
                      for k, v in sorted(labels.items()))
        tail = "{" + pre[:-1] + "}" if pre else ""
        for le, cum in fam.get("buckets", []):
            lines.append(
                f'{n}_bucket{{{pre}le="{escape_label_value(le)}"}} '
                f'{_fmt(cum)}')
        lines.append(f'{n}_bucket{{{pre}le="+Inf"}} {_fmt(fam["count"])}')
        lines.append(f"{n}_sum{tail} {_fmt(fam['sum'])}")
        lines.append(f"{n}_count{tail} {_fmt(fam['count'])}")
    for kind, fams in (("counter", labeled_counters),
                       ("gauge", labeled_gauges)):
        for fam in fams or []:
            n = sanitize(fam["name"])
            if n in emitted:
                continue
            emitted.add(n)
            _family(lines, n, kind, fam.get("help", ""))
            for labels, value in fam.get("rows", []):
                body = ",".join(
                    f'{sanitize(str(k))}="{escape_label_value(v)}"'
                    for k, v in sorted(labels.items()))
                tail = f"{{{body}}}" if body else ""
                lines.append(f"{n}{tail} {_fmt(value)}")
    sum_seen: set = set()
    for fam in summaries or []:
        n = sanitize(fam["name"])
        if n in emitted:
            continue
        if n not in sum_seen:
            sum_seen.add(n)
            _family(lines, n, "summary", fam.get("help", ""))
        labels = fam.get("labels") or {}
        pre = "".join(f'{sanitize(str(k))}="{escape_label_value(v)}",'
                      for k, v in sorted(labels.items()))
        tail = "{" + pre[:-1] + "}" if pre else ""
        for q, v in fam.get("quantiles", []):
            lines.append(
                f'{n}{{{pre}quantile="{escape_label_value(q)}"}} {_fmt(v)}')
        lines.append(f"{n}_sum{tail} {_fmt(fam['sum'])}")
        lines.append(f"{n}_count{tail} {_fmt(fam['count'])}")
    return "\n".join(lines) + "\n" if lines else ""
