"""Prometheus text-format exposition of the telemetry registry.

Renders the ``utils.telemetry`` inmem snapshot (the same data
``/v1/agent/metrics`` serves as JSON) in the Prometheus text format
(version 0.0.4): counters summed across retained intervals, gauges
last-write-wins, timer samples as a summary pair (``_count``/``_sum``
in seconds) plus ``_min``/``_max`` gauges.  Served by the agent at
``/v1/agent/metrics?format=prometheus``.

Flight-recorder series ride along automatically: the FlightRecorder
folds drained kernel rows into the registry as ``consul.flight.*``,
which render here as ``consul_flight_*``.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize(name: str) -> str:
    """Metric name -> valid Prometheus identifier (dots and other
    separators become underscores; leading digit gets a prefix)."""
    out = _BAD_CHARS.sub("_", name)
    if not _NAME_OK.match(out):
        out = "_" + out
    return out


def _fmt(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render_prometheus(snapshot: List[Dict[str, Any]]) -> str:
    """Telemetry snapshot (list of interval dicts, oldest first) ->
    Prometheus text format, one block per family with a TYPE line."""
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    samples: Dict[str, Dict[str, float]] = {}
    for iv in snapshot:
        for k, c in iv.get("Counters", {}).items():
            counters[k] = counters.get(k, 0.0) + float(c["sum"])
        for k, g in iv.get("Gauges", {}).items():
            gauges[k] = float(g)
        for k, s in iv.get("Samples", {}).items():
            agg = samples.setdefault(
                k, {"count": 0.0, "sum": 0.0,
                    "min": float("inf"), "max": float("-inf")})
            agg["count"] += float(s["count"])
            agg["sum"] += float(s["sum"])
            agg["min"] = min(agg["min"], float(s["min"]))
            agg["max"] = max(agg["max"], float(s["max"]))
    lines: List[str] = []
    for k in sorted(counters):
        n = sanitize(k)
        lines.append(f"# TYPE {n} counter")
        lines.append(f"{n} {_fmt(counters[k])}")
    for k in sorted(gauges):
        n = sanitize(k)
        lines.append(f"# TYPE {n} gauge")
        lines.append(f"{n} {_fmt(gauges[k])}")
    for k in sorted(samples):
        agg = samples[k]
        n = sanitize(k)
        # Timer samples are milliseconds in the registry; expose
        # base-unit seconds per Prometheus convention.
        lines.append(f"# TYPE {n}_seconds summary")
        lines.append(f"{n}_seconds_count {_fmt(agg['count'])}")
        lines.append(f"{n}_seconds_sum {repr(agg['sum'] / 1000.0)}")
        lines.append(f"# TYPE {n}_seconds_min gauge")
        lines.append(f"{n}_seconds_min {repr(agg['min'] / 1000.0)}")
        lines.append(f"# TYPE {n}_seconds_max gauge")
        lines.append(f"{n}_seconds_max {repr(agg['max'] / 1000.0)}")
    return "\n".join(lines) + "\n" if lines else ""
